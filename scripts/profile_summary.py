"""Capture a jax.profiler trace of one benchmark window and print the
top device ops by total duration.

Measurement harness for BASELINE.md's profiler-trace notes (not a test).
Usage:
    python scripts/profile_summary.py resnet50 [--batch 512]
    python scripts/profile_summary.py deepfm [--batch 8192]
"""

from __future__ import annotations

import argparse
import glob
import os
import tempfile
from collections import defaultdict

import numpy as np


def summarize_xplane(logdir: str, top: int = 25):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(logdir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        print("no xplane files under", logdir)
        return
    for path in paths:
        with open(path, "rb") as f:
            space = xplane_pb2.XSpace.FromString(f.read())
        for plane in space.planes:
            if "TPU" not in plane.name and "tpu" not in plane.name.lower():
                continue
            metadata = {m_id: m.name for m_id, m in plane.event_metadata.items()}
            totals = defaultdict(float)
            counts = defaultdict(int)
            for line in plane.lines:
                # XLA op lines carry the per-op device activity.
                for event in line.events:
                    name = metadata.get(event.metadata_id, "?")
                    totals[name] += event.duration_ps / 1e9  # -> ms
                    counts[name] += 1
            if not totals:
                continue
            print(f"\n== plane: {plane.name} (lines: {len(plane.lines)}) ==")
            ranked = sorted(totals.items(), key=lambda kv: -kv[1])
            total_ms = sum(totals.values())
            print(f"total device-event time {total_ms:.1f} ms (double-counts nested lines)")
            for name, ms in ranked[:top]:
                print(f"  {ms:9.2f} ms  x{counts[name]:<5d} {name[:110]}")


def run_resnet(batch: int, logdir: str, norm_bf16: bool = True):
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.resnet50 import resnet50_subclass as zoo

    model = zoo.ResNet50(
        dtype=jnp.bfloat16,
        norm_dtype=jnp.bfloat16 if norm_bf16 else jnp.float32,
    )
    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(model, zoo.loss, zoo.optimizer(), mesh)
    rng = np.random.RandomState(0)
    batches = [
        (
            rng.rand(batch, 224, 224, 3).astype(np.float32),
            rng.randint(0, 1000, size=batch).astype(np.int32),
            np.ones((batch,), np.float32),
        )
        for _ in range(4)
    ]
    window = trainer.stage_window(batches)
    np.asarray(trainer.train_window(window))  # compile + warm
    np.asarray(trainer.train_window(window))
    with jax.profiler.trace(logdir):
        np.asarray(trainer.train_window(window))


def run_deepfm(batch: int, logdir: str, steps: int = 40):
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    vocab = 100_000
    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=vocab),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    rng = np.random.RandomState(0)

    def make_batch():
        return (
            {
                "dense": rng.rand(batch, zoo.NUM_DENSE).astype(np.float32),
                "cat": rng.randint(0, vocab, size=(batch, zoo.NUM_CAT)).astype(
                    np.int32
                ),
            },
            rng.randint(0, 2, size=batch).astype(np.int32),
            np.ones((batch,), np.float32),
        )

    first = make_batch()
    trainer.ensure_initialized(first[0])
    window = trainer.stage_window([make_batch() for _ in range(steps)])
    np.asarray(trainer.train_window(window))
    np.asarray(trainer.train_window(window))
    with jax.profiler.trace(logdir):
        np.asarray(trainer.train_window(window))


def run_transformer(batch: int, logdir: str, steps: int = 8):
    """The tracked transformer bench config (bench.TRANSFORMER_BENCH) —
    the round-5 MFU probe: what are the top NON-attention device ops,
    and does any exceed its roofline cost?  (VERDICT round-4 #8)."""
    import jax

    import bench
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.transformer import transformer_lm as zoo

    cfg = bench.TRANSFORMER_BENCH
    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(
        zoo.custom_model(
            vocab=cfg["vocab"], d_model=cfg["d_model"],
            num_heads=cfg["num_heads"], num_layers=cfg["num_layers"],
            max_len=cfg["seq_len"],
        ),
        zoo.loss,
        zoo.optimizer(),
        mesh,
    )
    rng = np.random.RandomState(0)
    batches = [
        (
            rng.randint(0, cfg["vocab"], size=(batch, cfg["seq_len"]))
            .astype(np.int32),
            rng.randint(0, cfg["vocab"], size=(batch, cfg["seq_len"]))
            .astype(np.int32),
            np.ones((batch,), np.float32),
        )
        for _ in range(steps)
    ]
    window = trainer.stage_window(batches)
    np.asarray(trainer.train_window(window))
    np.asarray(trainer.train_window(window))
    with jax.profiler.trace(logdir):
        np.asarray(trainer.train_window(window))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "workload", choices=["resnet50", "deepfm", "transformer"]
    )
    parser.add_argument("--batch", type=int, default=0)
    parser.add_argument("--logdir", default="")
    parser.add_argument("--norm_f32", action="store_true")
    args = parser.parse_args()
    logdir = args.logdir or tempfile.mkdtemp(prefix=f"trace_{args.workload}_")
    if args.workload == "resnet50":
        run_resnet(args.batch or 512, logdir, norm_bf16=not args.norm_f32)
    elif args.workload == "transformer":
        run_transformer(args.batch or 16, logdir)
    else:
        run_deepfm(args.batch or 8192, logdir)
    print("trace dir:", logdir)
    summarize_xplane(logdir)


if __name__ == "__main__":
    main()
