"""Convergence A/B: strict (W=1) vs windowed sparse apply (W>1).

The question this answers (round-4 VERDICT item #1): the 26M-row
north-star throughput headline uses `--sparse_apply_every=16` — the
async-PS-style staleness relaxation (ps_trainer._train_chunk_impl) —
and nothing measured whether W=16 trains models as well as strict
per-step mode.  This script runs the controlled experiment:

- ONE synthetic-Criteo distribution (model_zoo.datasets.
  synthetic_ctr_columns): fixed ground-truth weights, Bernoulli labels
  (Bayes AUC ~0.84), Zipf id draws by default — hot rows are touched
  many times per window, the ADVERSARIAL case for windowed apply (a hot
  row gets one summed-gradient Adam update per window instead of W
  sequential ones).  Uniform draws, and larger vocabs where each row is
  touched less than once per window, are strictly easier.
- Same train stream (same seed, same batch order), same model init
  (trainer seed), same dense optimizer for every config; the ONLY
  variable is `sparse_apply_every` (plus one anchor run with the
  default per-row-bias Adam to tie the A/B to the strict golden
  contract).
- Held-out eval (same ground truth, different draw seed) after every
  epoch: AUC + logloss.

Each config runs in its OWN subprocess (`--all`): two trainers in one
process OOM the 16 GB chip, and process isolation also resets the
tunnel/backend state between runs.  Within a config, train windows are
staged to the device ONCE and replayed across epochs — the id pattern
per window is huge (~10^7 draws), and identical streams across configs
is exactly what the A/B wants.

Results land as JSON lines; `--all` prints the aggregated table.  The
round-4 BASELINE.md "Windowed-apply convergence" section records the
outcome; tests/test_sparse_window.py pins a tiny-config version as a
regression test.

Usage:
    python scripts/convergence_ab.py --all --out /tmp/conv_ab.jsonl
    python scripts/convergence_ab.py --w 16 --bias global   # one config
    # round-5 seed replication (3 seeds x the 3 shipped configs):
    python scripts/convergence_ab.py --all --sweep-seeds 0,1,2 --out f.jsonl
    # round-6 fused-kernel gates (ops/sparse_embedding.py):
    python scripts/convergence_ab.py --smoke             # CPU, make test-sparse
    python scripts/convergence_ab.py --all --sparse-kernel fused   # chip
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _logloss(logits: np.ndarray, labels: np.ndarray) -> float:
    z = logits.astype(np.float64)
    s = 2.0 * labels.astype(np.float64) - 1.0
    return float(np.mean(np.logaddexp(0.0, -s * z)))


def _auc(logits: np.ndarray, labels: np.ndarray) -> float:
    from model_zoo.wide_and_deep.wide_and_deep import _auc as rank_auc

    return float(rank_auc(logits, labels))


def run_config(args) -> dict:
    from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo import datasets
    from model_zoo.deepfm import deepfm_functional_api as zoo

    n_train = args.batch * args.steps_per_epoch
    # Seed replication (round-5 VERDICT weak #3): --seed offsets the DRAW
    # seeds and the trainer INIT seed but keeps weights_seed=0 — every
    # seed trains on the same ground-truth task, so within a seed the
    # configs share identical data/init (the controlled pairwise A/B) and
    # across seeds the peak-AUC spread is the error bar.  seed=0
    # reproduces the round-4 runs bit-for-bit.
    dense, cats, labels = datasets.synthetic_ctr_columns(
        n_train,
        num_dense=zoo.NUM_DENSE,
        num_categorical=zoo.NUM_CAT,
        vocab_size=args.vocab,
        weights_seed=0,
        draw_seed=1 + 1000 * args.seed,
        zipf_s=args.zipf,
    )
    e_dense, e_cats, e_labels = datasets.synthetic_ctr_columns(
        args.eval_examples,
        num_dense=zoo.NUM_DENSE,
        num_categorical=zoo.NUM_CAT,
        vocab_size=args.vocab,
        weights_seed=0,
        draw_seed=2 + 1000 * args.seed,
        zipf_s=args.zipf,
    )

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        # Same rule as bench.py: the model's per-mode table layout must
        # see the SAME apply mode AND kernel the trainer runs, or a
        # headline-scale A/B would validate a layout/engine the
        # headline never uses.
        zoo.custom_model(
            vocab_size=args.vocab, sparse_apply_every=args.w,
            sparse_kernel=args.sparse_kernel,
        ),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=sparse_optim.adam(
            args.emb_lr, bias_correction=args.bias
        ),
        sparse_apply_every=args.w,
        sparse_kernel=args.sparse_kernel,
        seed=args.seed,
    )
    mask = np.ones((args.batch,), np.float32)

    def batch(i: int):
        lo, hi = i * args.batch, (i + 1) * args.batch
        return (
            {"dense": dense[lo:hi], "cat": cats[lo:hi]},
            labels[lo:hi],
            mask,
        )

    trainer.ensure_initialized(batch(0)[0])
    assert args.steps_per_epoch % args.window == 0
    # A window that is not a multiple of W would end each window with a
    # short tail chunk — the labeled W would overstate the actual applied
    # staleness, which is the very thing under measurement.
    assert args.window % args.w == 0, (args.window, args.w)
    windows = [
        trainer.stage_window(
            [batch(w * args.window + i) for i in range(args.window)]
        )
        for w in range(args.steps_per_epoch // args.window)
    ]

    def evaluate() -> tuple[float, float]:
        outs = []
        for lo in range(0, args.eval_examples, args.batch):
            feats = {
                "dense": e_dense[lo : lo + args.batch],
                "cat": e_cats[lo : lo + args.batch],
            }
            outs.append(np.asarray(trainer.eval_step(feats)))
        logits = np.concatenate(outs)
        return _auc(logits, e_labels), _logloss(logits, e_labels)

    epochs = []
    train_s = 0.0
    for _ in range(args.epochs):
        start = time.perf_counter()
        losses = None
        for win in windows:
            losses = trainer.train_window(win)
        final = np.asarray(losses)  # completion fence (see bench.py)
        assert np.isfinite(final).all()
        train_s += time.perf_counter() - start
        auc, ll = evaluate()
        epochs.append({"auc": round(auc, 5), "logloss": round(ll, 5)})

    result = {
        "w": args.w,
        "bias": args.bias,
        "sparse_kernel": args.sparse_kernel,
        "seed": args.seed,
        "emb_lr": args.emb_lr,
        "vocab": args.vocab,
        "zipf": args.zipf,
        "epochs": epochs,
        "peak_auc": max(e["auc"] for e in epochs),
        "min_logloss": min(e["logloss"] for e in epochs),
        "final_auc": epochs[-1]["auc"],
        "final_logloss": epochs[-1]["logloss"],
        "train_samples_per_sec": round(
            args.epochs * n_train / train_s, 1
        ),
    }
    return result


CONFIGS = [
    (1, "per_row"),   # strict golden default — the anchor
    (1, "global"),    # strict, headline-table optimizer
    (4, "global"),
    (8, "global"),
    (16, "global"),   # the 26M headline configuration
    (32, "global"),
]


# The seed-replication grid (round-5 VERDICT weak #3): the strict golden
# anchor and the two windowed configs the headline metrics actually use,
# each replicated across 3 draw/init seeds.  The full W sweep stays
# single-seed in CONFIGS (the ordering question only matters for the
# shipped configs).
SEED_CONFIGS = [(1, "per_row"), (16, "global"), (32, "global")]


def run_all(args) -> None:
    if args.sweep_seeds:
        grid = [
            (w, bias, seed)
            for seed in [int(s) for s in args.sweep_seeds.split(",")]
            for (w, bias) in SEED_CONFIGS
        ]
    else:
        grid = [(w, bias, args.seed) for (w, bias) in CONFIGS]
    rows = []
    for w, bias, seed in grid:
        cmd = [
            sys.executable, __file__,
            "--w", str(w), "--bias", bias, "--seed", str(seed),
            "--vocab", str(args.vocab), "--batch", str(args.batch),
            "--steps-per-epoch", str(args.steps_per_epoch),
            "--epochs", str(args.epochs),
            "--eval-examples", str(args.eval_examples),
            "--window", str(args.window), "--zipf", str(args.zipf),
            "--emb-lr", str(args.emb_lr),
            "--sparse-kernel", args.sparse_kernel,
        ]
        print(f"=== W={w} bias={bias} seed={seed} ===", flush=True)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            # A diverging config (NaN losses tripping the child's isfinite
            # assert) IS a result — record it and keep sweeping; the other
            # configs and the summary table must still come out.
            print(proc.stdout[-4000:], file=sys.stderr)
            print(proc.stderr[-4000:], file=sys.stderr)
            result = {"w": w, "bias": bias, "seed": seed, "status": "failed"}
        else:
            result = json.loads(proc.stdout.strip().splitlines()[-1])
        rows.append(result)
        line = json.dumps(result)
        print(line, flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(line + "\n")
    print("\n| W | bias | seed | peak AUC | min logloss | samples/s |")
    print("|---|------|------|----------|-------------|-----------|")
    for r in rows:
        if r.get("status") == "failed":
            print(f"| {r['w']} | {r['bias']} | {r.get('seed', '?')} "
                  f"| FAILED | FAILED | — |")
            continue
        print(
            f"| {r['w']} | {r['bias']} | {r['seed']} "
            f"| {r['peak_auc']:.5f} | {r['min_logloss']:.5f} "
            f"| {r['train_samples_per_sec']:,.0f} |"
        )
    if args.sweep_seeds:
        print("\n| W | bias | peak AUC mean ± half-range | n seeds |")
        print("|---|------|----------------------------|---------|")
        for w, bias in SEED_CONFIGS:
            aucs = [
                r["peak_auc"] for r in rows
                if r.get("status") != "failed"
                and (r["w"], r["bias"]) == (w, bias)
            ]
            if not aucs:
                continue
            mid = (max(aucs) + min(aucs)) / 2
            half = (max(aucs) - min(aucs)) / 2
            print(
                f"| {w} | {bias} | {np.mean(aucs):.5f} ± {half:.5f} "
                f"(mid {mid:.5f}) | {len(aucs)} |"
            )


def run_smoke(args) -> int:
    """The `make test-sparse` convergence gate: a tiny CPU config of
    the SAME controlled A/B, run for both sparse kernels in-process
    (interpret-mode Pallas on CPU), asserting the fused engine trains
    the model as well as the xla engine — losses finite, held-out AUC
    within a coarse bound of each other and above chance.  Minutes of
    CPU, no chip; the full-scale fused A/B
    (`--all --sparse-kernel fused`) is queued chip work."""
    import copy

    results = {}
    for kernel in ("xla", "fused"):
        cfg = copy.copy(args)
        cfg.sparse_kernel = kernel
        cfg.w = 1
        cfg.bias = "per_row"
        cfg.vocab = 500
        cfg.batch = 256
        cfg.steps_per_epoch = 24
        cfg.epochs = 2
        cfg.eval_examples = 2048
        cfg.window = 8
        results[kernel] = run_config(cfg)
        print(json.dumps(results[kernel]), flush=True)
    auc_x = results["xla"]["peak_auc"]
    auc_f = results["fused"]["peak_auc"]
    assert auc_x > 0.55 and auc_f > 0.55, (
        f"smoke configs failed to learn: xla {auc_x} fused {auc_f}"
    )
    assert abs(auc_x - auc_f) < 0.02, (
        f"fused kernel trains differently from xla: "
        f"peak AUC {auc_f} vs {auc_x}"
    )
    print(
        f"convergence smoke OK: peak AUC xla {auc_x:.4f} vs fused "
        f"{auc_f:.4f} (|delta| < 0.02)", flush=True,
    )
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--all", action="store_true")
    p.add_argument(
        "--smoke", action="store_true",
        help="tiny CPU fused-vs-xla convergence gate (make test-sparse)",
    )
    p.add_argument(
        "--sparse-kernel", choices=["xla", "fused"], default="xla",
        dest="sparse_kernel",
        help="sparse-path engine under test (ops/sparse_embedding.py); "
        "the fused A/B at headline scale is the chip-side gate for "
        "--sparse_kernel=fused",
    )
    p.add_argument(
        "--sweep-seeds", default="",
        help="comma-separated seed list; with --all, runs SEED_CONFIGS "
             "x seeds instead of the single-seed CONFIGS sweep",
    )
    p.add_argument("--w", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bias", choices=["per_row", "global"], default="global")
    p.add_argument("--vocab", type=int, default=100_000)
    p.add_argument("--batch", type=int, default=8192)
    p.add_argument("--steps-per-epoch", type=int, default=480)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--eval-examples", type=int, default=262_144)
    # 96 is a multiple of every swept W (1/4/8/16/32) — see the assert in
    # run_config; 480 steps/epoch = 5 staged windows.
    p.add_argument("--window", type=int, default=96)
    p.add_argument("--zipf", type=float, default=1.1)
    # Embedding-table Adam lr.  A window contributes ONE Adam-normalized
    # update where strict mode contributes W, so scaling this with W is
    # the natural knob for closing the windowed warmup gap (measured in
    # the r04 A/B follow-up).
    p.add_argument("--emb-lr", type=float, default=0.001)
    p.add_argument("--out", default="")
    args = p.parse_args()
    if args.smoke:
        sys.exit(run_smoke(args))
    if args.all:
        run_all(args)
    else:
        print(json.dumps(run_config(args)), flush=True)


if __name__ == "__main__":
    main()
