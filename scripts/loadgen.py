#!/usr/bin/env python
"""Deterministic load generator for the serving plane.

    # open loop: paced arrivals at a target rate (the SLO-honest mode —
    # arrival times do not depend on response times, so queueing delay
    # is measured, not hidden)
    python scripts/loadgen.py --serve_dir /srv/fleet --mode open \
        --qps 200 --duration_s 30 --batch_rows 8

    # closed loop: N workers issue back-to-back (throughput probe)
    python scripts/loadgen.py --addr 127.0.0.1:40001 --mode closed \
        --requests 500 --concurrency 4

    python scripts/loadgen.py --selftest   # the `make serving-gates` gate

Determinism: the request stream is seeded — request i of a run with
seed S is the same features every time, including the hot-key skew
(a small ``hot_fraction`` of the vocab receives ``hot_share`` of the
categorical ids — real CTR traffic is Zipf-ish, and a cache-friendly
uniform stream would flatter every latency number).  Replays reproduce.

Targets are discovered from the serve dir (`live_replicas` — survives
SIGKILL relaunches, replica ids are never reused) or given with
``--addr``; multiple targets round-robin.  Output is a latency summary
(p50/p90/p99, qps, served/shed/deadline/error counts) printed as JSON
and optionally written with ``--output``.  tests/test_serving.py drives
the same `run_open_loop`/`run_closed_loop` library functions in its
acceptance e2e.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

# Repo-root invocation: scripts/ is not a package.
if __package__ in (None, ""):
    import os as _os

    sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )


# ---------------------------------------------------------------------------
# Deterministic request stream (hot-key skew)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StreamConfig:
    seed: int = 0
    batch_rows: int = 8
    vocab_size: int = 100
    num_dense: int = 13
    num_cat: int = 26
    #: Fraction of the vocab that is "hot" and the share of categorical
    #: ids drawn from it (0.1/0.8 ~ an aggressive production skew).
    hot_fraction: float = 0.1
    hot_share: float = 0.8


class RequestStream:
    """Seeded feature-dict generator: `request(i)` is a pure function of
    (config, i), so two streams with the same config agree element-wise
    and a failed run replays exactly."""

    def __init__(self, config: StreamConfig = StreamConfig()):
        self.config = config
        self._n_hot = max(1, int(config.vocab_size * config.hot_fraction))

    def request(self, i: int) -> Dict[str, np.ndarray]:
        cfg = self.config
        rng = np.random.default_rng((cfg.seed, i))
        dense = rng.standard_normal(
            (cfg.batch_rows, cfg.num_dense)
        ).astype(np.float32)
        hot = rng.random((cfg.batch_rows, cfg.num_cat)) < cfg.hot_share
        hot_ids = rng.integers(
            0, self._n_hot, (cfg.batch_rows, cfg.num_cat)
        )
        cold_ids = rng.integers(
            self._n_hot, cfg.vocab_size, (cfg.batch_rows, cfg.num_cat)
        )
        cat = np.where(hot, hot_ids, cold_ids).astype(np.int32)
        return {"dense": dense, "cat": cat}


# ---------------------------------------------------------------------------
# Request tracing (client half)
# ---------------------------------------------------------------------------


def trace_id_for(seed: int, i: int) -> str:
    """Deterministic wire trace id for request i of a seeded run: pure
    in (seed, i), so a replay regenerates the SAME ids and a journal
    from run A can be queried with ids computed offline."""
    return f"lg{seed}-{i:08d}"


class ClientTracer:
    """Client half of request-level tracing: mints the deterministic
    trace id for each request, keeps the per-request latency record,
    and journals one ``client.predict`` ROOT span per request
    (span_id == trace_id — the replica's rpc.predict parents under it
    via the gRPC metadata, common/grpc_utils.py).

    With ``journal_dir`` set the spans land in the serve dir's SHARED
    events.jsonl, so ``obs.trace <serve_dir>`` merges client and
    replica spans into one waterfall with a ``loadgen`` pid row."""

    def __init__(self, seed: int = 0, journal_dir: str = ""):
        self.seed = seed
        self._lock = threading.Lock()
        self._records: List[dict] = []  # guarded-by: _lock
        self._tracing = None
        if journal_dir:
            from elasticdl_tpu import obs
            from elasticdl_tpu.obs import tracing

            obs.init_journal(journal_dir)
            tracing.set_process("loadgen")
            self._tracing = tracing

    def trace_id(self, i: int) -> str:
        return trace_id_for(self.seed, i)

    def record(self, i: int, outcome: str, start_wall: float,
               latency_s: float):
        trace_id = self.trace_id(i)
        with self._lock:
            self._records.append({
                "i": i,
                "trace_id": trace_id,
                "outcome": outcome,
                "latency_ms": round(latency_s * 1e3, 3),
            })
        if self._tracing is not None:
            self._tracing.record_span(
                "client.predict", start_wall, latency_s,
                trace_id=trace_id, span_id=trace_id, root=True,
                outcome=outcome,
            )

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def slowest(self, n: int) -> List[dict]:
        return sorted(
            self.records(), key=lambda r: -r["latency_ms"]
        )[:max(0, n)]


#: --slowest waterfall: phase order on the wire and one glyph per phase
#: (the bar is proportional — `qqqqqbxr` reads as queue-dominated).
_WATERFALL_PHASES = ("queue", "batch", "execute", "respond")
_PHASE_GLYPHS = {"queue": "q", "batch": "b", "execute": "x", "respond": "r"}


def render_slowest(
    records: List[dict],
    events: Optional[List[dict]] = None,
    top: int = 5,
    width: int = 40,
) -> str:
    """The ``--slowest N`` table: trace ids + latency for the N slowest
    requests, each with a phase waterfall joined from the journal's
    sampled ``request_trace`` events when available.  The server-side
    sampler (serving/ledger.py) journals EVERY request above its tail
    threshold, so genuinely slow rows nearly always join; head-sampled
    fast rows may not — the line still prints, without the bar."""
    events = events or []
    by_trace: Dict[str, dict] = {}
    for event in events:
        if event.get("event") == "request_trace" and event.get("trace_id"):
            by_trace[str(event["trace_id"])] = event
    ranked = sorted(records, key=lambda r: -r["latency_ms"])[:max(0, top)]
    lines = [f"slowest {len(ranked)} request(s):"]
    joined_any = False
    for rec in ranked:
        lines.append(
            f"  {rec['latency_ms']:>9.1f}ms  trace {rec['trace_id']}  "
            f"[{rec['outcome']}]"
        )
        joined = by_trace.get(rec["trace_id"])
        phases = joined.get("phases") if joined else None
        if not isinstance(phases, dict) or not phases:
            continue
        joined_any = True
        known = {
            p: float(phases[p])
            for p in _WATERFALL_PHASES
            if isinstance(phases.get(p), (int, float)) and phases[p] >= 0
        }
        total = sum(known.values()) or 1.0
        bar = "".join(
            _PHASE_GLYPHS[p] * max(1, int(round(width * known[p] / total)))
            for p in _WATERFALL_PHASES
            if known.get(p)
        )
        split = " ".join(f"{p}={known[p]:.1f}ms" for p in known)
        dominant = joined.get("dominant_phase", "")
        lines.append(
            f"             |{bar:<{width}.{width}}|  {split}"
            + (f"  <- {dominant}" if dominant else "")
        )
    if ranked and not joined_any:
        lines.append(
            "  (no request_trace events joined — phase waterfalls need "
            "the serve-dir journal written by the replicas' sampler)"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Latency accounting
# ---------------------------------------------------------------------------

OUTCOMES = ("served", "shed", "deadline", "error")


class LatencyHistogram:
    """Exact latency record for a bounded run (a loadgen run is minutes,
    not days — keeping every sample beats bucket-resolution arguments)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latencies: List[float] = []  # guarded-by: _lock

    def record(self, seconds: float):
        with self._lock:
            self._latencies.append(seconds)

    def percentile_ms(self, pct: float) -> float:
        with self._lock:
            lat = sorted(self._latencies)
        if not lat:
            return 0.0
        rank = min(len(lat) - 1, int(round(pct / 100.0 * (len(lat) - 1))))
        return lat[rank] * 1e3

    def count(self) -> int:
        with self._lock:
            return len(self._latencies)

    def summary(self) -> dict:
        return {
            "count": self.count(),
            "p50_ms": round(self.percentile_ms(50.0), 3),
            "p90_ms": round(self.percentile_ms(90.0), 3),
            "p99_ms": round(self.percentile_ms(99.0), 3),
            "max_ms": round(self.percentile_ms(100.0), 3),
        }


@dataclass
class LoadResult:
    mode: str
    requests: int = 0
    outcomes: Dict[str, int] = field(
        default_factory=lambda: {o: 0 for o in OUTCOMES}
    )
    elapsed_s: float = 0.0
    histogram: LatencyHistogram = field(default_factory=LatencyHistogram)
    #: Open loop only: requests that could not be issued on schedule
    #: because the issuing side fell behind (loadgen saturation — a
    #: result with nonzero lag understates server queueing).
    schedule_lag: int = 0

    def summary(self) -> dict:
        served = self.outcomes["served"]
        return {
            "mode": self.mode,
            "requests": self.requests,
            **self.outcomes,
            "elapsed_s": round(self.elapsed_s, 3),
            "qps": round(served / self.elapsed_s, 2) if self.elapsed_s else 0.0,
            "availability_ratio": (
                round(served / self.requests, 6) if self.requests else 1.0
            ),
            "schedule_lag": self.schedule_lag,
            "latency": self.histogram.summary(),
        }


def classify_error(exc: BaseException) -> str:
    """Bounded outcome from a predict failure.  gRPC status codes map
    RESOURCE_EXHAUSTED -> shed (the server's explicit backpressure) and
    DEADLINE_EXCEEDED -> deadline; QueueFullError/TimeoutError cover the
    in-process path the e2e drives."""
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            name = code().name
        except Exception:
            name = ""
        if name == "RESOURCE_EXHAUSTED":
            return "shed"
        if name == "DEADLINE_EXCEEDED":
            return "deadline"
    if type(exc).__name__ == "QueueFullError":
        return "shed"
    if isinstance(exc, TimeoutError):
        return "deadline"
    return "error"


# ---------------------------------------------------------------------------
# The two loops
# ---------------------------------------------------------------------------


def _issue(predict_fn, stream: RequestStream, i: int, result: LoadResult,
           lock: threading.Lock, clock=time.monotonic,
           trace: Optional[ClientTracer] = None):
    features = stream.request(i)
    trace_id = trace.trace_id(i) if trace is not None else ""
    start_wall = time.time()
    t0 = clock()
    try:
        if trace_id:
            # The client span IS the trace root: span_id == trace_id
            # rides the metadata so the server parents under it.
            predict_fn(features, trace_id=trace_id, span_id=trace_id)
        else:
            predict_fn(features)
        outcome = "served"
    except Exception as exc:  # outcome-classified, never fatal
        outcome = classify_error(exc)
    latency = clock() - t0
    with lock:
        result.requests += 1
        result.outcomes[outcome] += 1
    if outcome == "served":
        result.histogram.record(latency)
    if trace is not None:
        trace.record(i, outcome, start_wall, latency)


def run_closed_loop(
    predict_fn: Callable[[Dict[str, np.ndarray]], object],
    stream: RequestStream,
    num_requests: int,
    concurrency: int = 1,
    clock=time.monotonic,
    trace: Optional[ClientTracer] = None,
) -> LoadResult:
    """`concurrency` workers issue back-to-back until `num_requests`
    total have been sent.  Request indices are deterministic per worker
    (worker w sends i = w, w+C, w+2C, ...)."""
    result = LoadResult(mode="closed")
    lock = threading.Lock()
    t_start = clock()

    def worker(w: int):
        for i in range(w, num_requests, concurrency):
            _issue(predict_fn, stream, i, result, lock, clock, trace)

    threads = [
        threading.Thread(target=worker, args=(w,),
                         name=f"loadgen-closed-{w}", daemon=True)
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    result.elapsed_s = clock() - t_start
    return result


def run_open_loop(
    predict_fn: Callable[[Dict[str, np.ndarray]], object],
    stream: RequestStream,
    target_qps: float,
    duration_s: float,
    max_outstanding: int = 256,
    clock=time.monotonic,
    sleep=time.sleep,
    trace: Optional[ClientTracer] = None,
) -> LoadResult:
    """Paced arrivals: request i is issued at t_start + i/target_qps on
    its own thread (arrivals independent of completions).  If more than
    `max_outstanding` requests are in flight the arrival is counted as
    `schedule_lag` and skipped — the loadgen refuses to become an
    unbounded thread pile when the server is saturated."""
    if target_qps <= 0:
        raise ValueError(f"target_qps must be > 0, got {target_qps}")
    result = LoadResult(mode="open")
    lock = threading.Lock()
    outstanding = threading.Semaphore(max_outstanding)
    threads: List[threading.Thread] = []
    total = int(target_qps * duration_s)
    t_start = clock()

    def issue_one(i: int):
        try:
            _issue(predict_fn, stream, i, result, lock, clock, trace)
        finally:
            outstanding.release()

    for i in range(total):
        due = t_start + i / target_qps
        delay = due - clock()
        if delay > 0:
            sleep(delay)
        if not outstanding.acquire(blocking=False):
            with lock:
                result.requests += 1
                result.schedule_lag += 1
                result.outcomes["shed"] += 1
            continue
        t = threading.Thread(
            target=issue_one, args=(i,), name=f"loadgen-open-{i}",
            daemon=True,
        )
        threads.append(t)
        t.start()
    for t in threads:
        t.join(timeout=60)
    result.elapsed_s = clock() - t_start
    return result


# ---------------------------------------------------------------------------
# Delayed-label replay (the quality plane's feedback half)
# ---------------------------------------------------------------------------


def label_mapping(
    stream: RequestStream, indices: Sequence[int]
) -> Optional[Dict[str, np.ndarray]]:
    """Ground-truth labels for the given request indices, keyed by the
    trace ids the predictions carried — the replica's label-join ledger
    matches them against its pending predictions.  Labels come from the
    same pure rule as the training stream (data/stream.click_label_rule
    via feedback_labels), so the `stream.labels` fault site applies here
    too: poisoned feeds flip, outages return None for the whole group.
    """
    from elasticdl_tpu.data.stream import feedback_labels

    mapping: Dict[str, np.ndarray] = {}
    for i in indices:
        labels = feedback_labels(stream.request(i))
        if labels is None:
            return None  # label-feed outage: the group is lost
        mapping[trace_id_for(stream.config.seed, i)] = labels
    return mapping


def run_label_feed(
    send_fns: Sequence[Callable[[Dict[str, np.ndarray]], dict]],
    stream: RequestStream,
    num_requests: int,
    group: int = 32,
    delay_s: float = 0.0,
    sleep=time.sleep,
) -> dict:
    """Replay delayed labels for requests [0, num_requests) in groups.
    Each group is BROADCAST to every send_fn — the feed does not know
    which replica served a given prediction, so every replica sees every
    label and the non-owners record orphans (bounded, and exactly what a
    production at-least-once label bus does).  Returns the feed summary;
    send failures and outages are counted, never raised."""
    stats = {
        "groups": 0, "outages": 0, "send_errors": 0,
        "labels_sent": 0, "received": 0, "joined": 0,
    }
    for start in range(0, num_requests, max(1, group)):
        if delay_s > 0:
            sleep(delay_s)
        indices = range(start, min(start + max(1, group), num_requests))
        mapping = label_mapping(stream, indices)
        stats["groups"] += 1
        if mapping is None:
            stats["outages"] += 1
            continue
        stats["labels_sent"] += len(mapping)
        for send_fn in send_fns:
            try:
                reply = send_fn(mapping)
            except Exception:  # feed keeps going; the gate degrades
                stats["send_errors"] += 1
                continue
            if isinstance(reply, dict):
                stats["received"] += int(reply.get("received", 0))
                stats["joined"] += int(reply.get("joined", 0))
    return stats


def round_robin_predict(predict_fns: Sequence[Callable]) -> Callable:
    """One predict_fn spreading requests across replicas."""
    if not predict_fns:
        raise ValueError("no predict targets")
    counter = {"i": 0}
    lock = threading.Lock()

    def predict(features, **kwargs):
        with lock:
            i = counter["i"]
            counter["i"] += 1
        return predict_fns[i % len(predict_fns)](features, **kwargs)

    return predict


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _selftest(slowest: int = 0) -> int:
    """No-server sanity: stream determinism + skew, outcome
    classification, a closed+open loop against a fake backend, and the
    request-tracing client half (deterministic trace ids, per-request
    records, the --slowest waterfall join)."""
    cfg = StreamConfig(seed=7, batch_rows=4, vocab_size=50)
    a, b = RequestStream(cfg), RequestStream(cfg)
    for i in (0, 1, 99):
        ra, rb = a.request(i), b.request(i)
        if not (np.array_equal(ra["dense"], rb["dense"])
                and np.array_equal(ra["cat"], rb["cat"])):
            print("selftest FAILED: stream not deterministic",
                  file=sys.stderr)
            return 1
    if np.array_equal(a.request(0)["cat"], a.request(1)["cat"]):
        print("selftest FAILED: distinct requests identical", file=sys.stderr)
        return 1
    # Hot-key skew: the hot prefix of the vocab must dominate.
    ids = np.concatenate([a.request(i)["cat"].ravel() for i in range(50)])
    n_hot = max(1, int(cfg.vocab_size * cfg.hot_fraction))
    hot_share = float(np.mean(ids < n_hot))
    if not 0.6 < hot_share < 0.95:
        print(f"selftest FAILED: hot share {hot_share}", file=sys.stderr)
        return 1

    class _Shed(Exception):
        def code(self):
            class _C:
                name = "RESOURCE_EXHAUSTED"
            return _C()

    if classify_error(_Shed()) != "shed" or \
            classify_error(TimeoutError()) != "deadline" or \
            classify_error(RuntimeError()) != "error":
        print("selftest FAILED: outcome classification", file=sys.stderr)
        return 1

    calls = {"n": 0}

    def fake_predict(features, **kwargs):
        calls["n"] += 1
        if calls["n"] % 5 == 0:
            raise _Shed()
        return np.zeros(features["dense"].shape[0], np.float32)

    closed = run_closed_loop(fake_predict, a, num_requests=50, concurrency=4)
    if closed.requests != 50 or closed.outcomes["served"] != 40 \
            or closed.outcomes["shed"] != 10:
        print(f"selftest FAILED: closed loop {closed.summary()}",
              file=sys.stderr)
        return 1
    calls["n"] = 0
    opened = run_open_loop(fake_predict, a, target_qps=500, duration_s=0.2)
    if opened.requests != 100 or opened.histogram.count() \
            != opened.outcomes["served"]:
        print(f"selftest FAILED: open loop {opened.summary()}",
              file=sys.stderr)
        return 1
    summary = opened.summary()
    if summary["latency"]["p99_ms"] < summary["latency"]["p50_ms"]:
        print("selftest FAILED: percentile ordering", file=sys.stderr)
        return 1

    # Request tracing: trace ids are pure in (seed, i); a traced run
    # records every request; the --slowest table joins phase splits.
    if trace_id_for(7, 3) != trace_id_for(7, 3) or \
            trace_id_for(7, 3) == trace_id_for(8, 3) or \
            trace_id_for(7, 3) == trace_id_for(7, 4):
        print("selftest FAILED: trace ids not deterministic/distinct",
              file=sys.stderr)
        return 1
    calls["n"] = 0
    tracer = ClientTracer(seed=7)
    run_closed_loop(fake_predict, a, num_requests=20, concurrency=2,
                    trace=tracer)
    records = tracer.records()
    if len(records) != 20 or {r["trace_id"] for r in records} != {
            trace_id_for(7, i) for i in range(20)}:
        print(f"selftest FAILED: traced records {len(records)}",
              file=sys.stderr)
        return 1
    outcomes = {r["outcome"] for r in records}
    if not {"served", "shed"} <= outcomes:
        print(f"selftest FAILED: traced outcomes {outcomes}",
              file=sys.stderr)
        return 1
    top = slowest or 3
    slow = tracer.slowest(top)
    if len(slow) != top or \
            slow[0]["latency_ms"] < slow[-1]["latency_ms"]:
        print(f"selftest FAILED: slowest ordering {slow}", file=sys.stderr)
        return 1
    joined_events = [{
        "ts": 0.0, "event": "request_trace",
        "trace_id": slow[0]["trace_id"], "outcome": slow[0]["outcome"],
        "sampled_by": "tail", "latency_ms": slow[0]["latency_ms"],
        "phases": {"queue": 61.0, "batch": 2.0, "execute": 12.0,
                   "respond": 2.0},
        "dominant_phase": "queue",
    }]
    table = render_slowest(records, joined_events, top=top)
    if slow[0]["trace_id"] not in table or "<- queue" not in table \
            or "qqqq" not in table:
        print(f"selftest FAILED: --slowest table\n{table}", file=sys.stderr)
        return 1
    print(f"loadgen selftest OK (--slowest {top} table joined)")
    return 0


def _selftest_labels() -> int:
    """No-server sanity of the delayed-label replay half: labels are
    pure in (seed, i) with the training stream's positive rate, groups
    broadcast to every target with join accounting, and a label-feed
    outage (`stream.labels:truncate`) loses groups without raising."""
    from elasticdl_tpu.common import faults

    faults.clear()
    cfg = StreamConfig(seed=7, batch_rows=4, vocab_size=50)
    stream = RequestStream(cfg)
    a = label_mapping(stream, range(8))
    b = label_mapping(stream, range(8))
    if a is None or set(a) != {trace_id_for(7, i) for i in range(8)} or \
            not all(np.array_equal(a[k], b[k]) for k in a):
        print("label selftest FAILED: mapping not deterministic",
              file=sys.stderr)
        return 1
    rate = float(np.mean(np.concatenate(list(a.values()))))
    if not 0.05 < rate < 0.65:
        print(f"label selftest FAILED: positive rate {rate}",
              file=sys.stderr)
        return 1

    deliveries: List[int] = []

    def send_ok(mapping):
        deliveries.append(len(mapping))
        return {"received": len(mapping), "joined": len(mapping) - 1,
                "enabled": True}

    def send_broken(mapping):
        raise RuntimeError("replica gone")

    stats = run_label_feed(
        [send_ok, send_broken], stream, num_requests=20, group=8,
        sleep=lambda s: None,
    )
    if stats["groups"] != 3 or stats["labels_sent"] != 20 or \
            stats["send_errors"] != 3 or stats["received"] != 20 or \
            stats["joined"] != 17 or deliveries != [8, 8, 4]:
        print(f"label selftest FAILED: feed stats {stats}", file=sys.stderr)
        return 1
    # Outage: the second group's fetch returns None (site fires once per
    # request in the group; group 2 starts at call 9).
    faults.install("stream.labels:truncate@9")
    try:
        stats = run_label_feed(
            [send_ok], stream, num_requests=24, group=8,
            sleep=lambda s: None,
        )
    finally:
        faults.clear()
    if stats["outages"] != 1 or stats["labels_sent"] != 16 or \
            stats["groups"] != 3:
        print(f"label selftest FAILED: outage stats {stats}",
              file=sys.stderr)
        return 1
    print(
        f"loadgen label selftest OK (rate {rate:.2f}, outage lost 1 "
        "group, send errors non-fatal)"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Deterministic serving load generator."
    )
    parser.add_argument("--serve_dir", default="",
                        help="discover live replicas from this serve dir")
    parser.add_argument("--addr", action="append", default=[],
                        help="explicit replica addr host:port (repeatable)")
    parser.add_argument("--mode", choices=("open", "closed"), default="open")
    parser.add_argument("--qps", type=float, default=100.0,
                        help="open loop: target arrival rate")
    parser.add_argument("--duration_s", type=float, default=10.0,
                        help="open loop: run length")
    parser.add_argument("--requests", type=int, default=200,
                        help="closed loop: total requests")
    parser.add_argument("--concurrency", type=int, default=4,
                        help="closed loop: worker threads")
    parser.add_argument("--deadline_s", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--batch_rows", type=int, default=8)
    parser.add_argument("--vocab_size", type=int, default=100)
    parser.add_argument("--hot_fraction", type=float, default=0.1)
    parser.add_argument("--hot_share", type=float, default=0.8)
    parser.add_argument("--output", default="",
                        help="also write the JSON summary here")
    parser.add_argument("--slowest", type=int, default=0,
                        help="print trace ids + phase waterfalls of the N "
                             "slowest requests (joined from the serve-dir "
                             "journal's sampled request_trace events)")
    parser.add_argument("--no_trace", action="store_true",
                        help="do not attach trace ids / journal client "
                             "spans (pre-tracing wire behaviour)")
    parser.add_argument("--labels", action="store_true",
                        help="after the run, replay delayed ground-truth "
                             "labels (keyed by the requests' trace ids) to "
                             "every target's labels RPC — feeds the "
                             "replicas' online label-join quality ledger")
    parser.add_argument("--label_delay_s", type=float, default=0.0,
                        help="pause before each label group (simulated "
                             "feedback delay)")
    parser.add_argument("--label_group", type=int, default=32,
                        help="labels delivered per replay group")
    parser.add_argument("--selftest", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        return _selftest_labels() if args.labels else _selftest(args.slowest)

    addrs = list(args.addr)
    if args.serve_dir:
        from elasticdl_tpu.serving.replica_main import live_replicas

        addrs += [
            f"127.0.0.1:{r['port']}" for r in live_replicas(args.serve_dir)
        ]
    if not addrs:
        print("no targets: pass --serve_dir or --addr", file=sys.stderr)
        return 2

    from elasticdl_tpu.serving.frontend import PredictClient

    clients = [PredictClient(a, deadline_s=args.deadline_s) for a in addrs]
    predict = round_robin_predict([c.predict for c in clients])
    stream = RequestStream(StreamConfig(
        seed=args.seed, batch_rows=args.batch_rows,
        vocab_size=args.vocab_size, hot_fraction=args.hot_fraction,
        hot_share=args.hot_share,
    ))
    tracer = None
    if not args.no_trace:
        tracer = ClientTracer(seed=args.seed, journal_dir=args.serve_dir)
    if args.mode == "open":
        result = run_open_loop(predict, stream, args.qps, args.duration_s,
                               trace=tracer)
    else:
        result = run_closed_loop(
            predict, stream, args.requests, args.concurrency, trace=tracer
        )
    summary = {"targets": addrs, **result.summary()}
    if args.labels:
        if args.no_trace:
            print("--labels needs trace ids; drop --no_trace",
                  file=sys.stderr)
            return 2
        summary["label_feed"] = run_label_feed(
            [c.send_labels for c in clients], stream, result.requests,
            group=args.label_group, delay_s=args.label_delay_s,
        )
    if tracer is not None and args.slowest:
        summary["slowest"] = tracer.slowest(args.slowest)
    text = json.dumps(summary, indent=2)
    print(text)
    if tracer is not None and args.slowest:
        events: List[dict] = []
        journal_path = os.path.join(args.serve_dir, "events.jsonl") \
            if args.serve_dir else ""
        if journal_path and os.path.exists(journal_path):
            with open(journal_path, "r", encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        events.append(rec)
        print(render_slowest(tracer.records(), events, top=args.slowest),
              file=sys.stderr)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    for c in clients:
        c.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
