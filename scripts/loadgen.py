#!/usr/bin/env python
"""Deterministic load generator for the serving plane.

    # open loop: paced arrivals at a target rate (the SLO-honest mode —
    # arrival times do not depend on response times, so queueing delay
    # is measured, not hidden)
    python scripts/loadgen.py --serve_dir /srv/fleet --mode open \
        --qps 200 --duration_s 30 --batch_rows 8

    # closed loop: N workers issue back-to-back (throughput probe)
    python scripts/loadgen.py --addr 127.0.0.1:40001 --mode closed \
        --requests 500 --concurrency 4

    python scripts/loadgen.py --selftest   # the `make serving-gates` gate

Determinism: the request stream is seeded — request i of a run with
seed S is the same features every time, including the hot-key skew
(a small ``hot_fraction`` of the vocab receives ``hot_share`` of the
categorical ids — real CTR traffic is Zipf-ish, and a cache-friendly
uniform stream would flatter every latency number).  Replays reproduce.

Targets are discovered from the serve dir (`live_replicas` — survives
SIGKILL relaunches, replica ids are never reused) or given with
``--addr``; multiple targets round-robin.  Output is a latency summary
(p50/p90/p99, qps, served/shed/deadline/error counts) printed as JSON
and optionally written with ``--output``.  tests/test_serving.py drives
the same `run_open_loop`/`run_closed_loop` library functions in its
acceptance e2e.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

# Repo-root invocation: scripts/ is not a package.
if __package__ in (None, ""):
    import os as _os

    sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )


# ---------------------------------------------------------------------------
# Deterministic request stream (hot-key skew)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StreamConfig:
    seed: int = 0
    batch_rows: int = 8
    vocab_size: int = 100
    num_dense: int = 13
    num_cat: int = 26
    #: Fraction of the vocab that is "hot" and the share of categorical
    #: ids drawn from it (0.1/0.8 ~ an aggressive production skew).
    hot_fraction: float = 0.1
    hot_share: float = 0.8


class RequestStream:
    """Seeded feature-dict generator: `request(i)` is a pure function of
    (config, i), so two streams with the same config agree element-wise
    and a failed run replays exactly."""

    def __init__(self, config: StreamConfig = StreamConfig()):
        self.config = config
        self._n_hot = max(1, int(config.vocab_size * config.hot_fraction))

    def request(self, i: int) -> Dict[str, np.ndarray]:
        cfg = self.config
        rng = np.random.default_rng((cfg.seed, i))
        dense = rng.standard_normal(
            (cfg.batch_rows, cfg.num_dense)
        ).astype(np.float32)
        hot = rng.random((cfg.batch_rows, cfg.num_cat)) < cfg.hot_share
        hot_ids = rng.integers(
            0, self._n_hot, (cfg.batch_rows, cfg.num_cat)
        )
        cold_ids = rng.integers(
            self._n_hot, cfg.vocab_size, (cfg.batch_rows, cfg.num_cat)
        )
        cat = np.where(hot, hot_ids, cold_ids).astype(np.int32)
        return {"dense": dense, "cat": cat}


# ---------------------------------------------------------------------------
# Latency accounting
# ---------------------------------------------------------------------------

OUTCOMES = ("served", "shed", "deadline", "error")


class LatencyHistogram:
    """Exact latency record for a bounded run (a loadgen run is minutes,
    not days — keeping every sample beats bucket-resolution arguments)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latencies: List[float] = []  # guarded-by: _lock

    def record(self, seconds: float):
        with self._lock:
            self._latencies.append(seconds)

    def percentile_ms(self, pct: float) -> float:
        with self._lock:
            lat = sorted(self._latencies)
        if not lat:
            return 0.0
        rank = min(len(lat) - 1, int(round(pct / 100.0 * (len(lat) - 1))))
        return lat[rank] * 1e3

    def count(self) -> int:
        with self._lock:
            return len(self._latencies)

    def summary(self) -> dict:
        return {
            "count": self.count(),
            "p50_ms": round(self.percentile_ms(50.0), 3),
            "p90_ms": round(self.percentile_ms(90.0), 3),
            "p99_ms": round(self.percentile_ms(99.0), 3),
            "max_ms": round(self.percentile_ms(100.0), 3),
        }


@dataclass
class LoadResult:
    mode: str
    requests: int = 0
    outcomes: Dict[str, int] = field(
        default_factory=lambda: {o: 0 for o in OUTCOMES}
    )
    elapsed_s: float = 0.0
    histogram: LatencyHistogram = field(default_factory=LatencyHistogram)
    #: Open loop only: requests that could not be issued on schedule
    #: because the issuing side fell behind (loadgen saturation — a
    #: result with nonzero lag understates server queueing).
    schedule_lag: int = 0

    def summary(self) -> dict:
        served = self.outcomes["served"]
        return {
            "mode": self.mode,
            "requests": self.requests,
            **self.outcomes,
            "elapsed_s": round(self.elapsed_s, 3),
            "qps": round(served / self.elapsed_s, 2) if self.elapsed_s else 0.0,
            "availability_ratio": (
                round(served / self.requests, 6) if self.requests else 1.0
            ),
            "schedule_lag": self.schedule_lag,
            "latency": self.histogram.summary(),
        }


def classify_error(exc: BaseException) -> str:
    """Bounded outcome from a predict failure.  gRPC status codes map
    RESOURCE_EXHAUSTED -> shed (the server's explicit backpressure) and
    DEADLINE_EXCEEDED -> deadline; QueueFullError/TimeoutError cover the
    in-process path the e2e drives."""
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            name = code().name
        except Exception:
            name = ""
        if name == "RESOURCE_EXHAUSTED":
            return "shed"
        if name == "DEADLINE_EXCEEDED":
            return "deadline"
    if type(exc).__name__ == "QueueFullError":
        return "shed"
    if isinstance(exc, TimeoutError):
        return "deadline"
    return "error"


# ---------------------------------------------------------------------------
# The two loops
# ---------------------------------------------------------------------------


def _issue(predict_fn, stream: RequestStream, i: int, result: LoadResult,
           lock: threading.Lock, clock=time.monotonic):
    features = stream.request(i)
    t0 = clock()
    try:
        predict_fn(features)
        outcome = "served"
    except Exception as exc:  # outcome-classified, never fatal
        outcome = classify_error(exc)
    latency = clock() - t0
    with lock:
        result.requests += 1
        result.outcomes[outcome] += 1
    if outcome == "served":
        result.histogram.record(latency)


def run_closed_loop(
    predict_fn: Callable[[Dict[str, np.ndarray]], object],
    stream: RequestStream,
    num_requests: int,
    concurrency: int = 1,
    clock=time.monotonic,
) -> LoadResult:
    """`concurrency` workers issue back-to-back until `num_requests`
    total have been sent.  Request indices are deterministic per worker
    (worker w sends i = w, w+C, w+2C, ...)."""
    result = LoadResult(mode="closed")
    lock = threading.Lock()
    t_start = clock()

    def worker(w: int):
        for i in range(w, num_requests, concurrency):
            _issue(predict_fn, stream, i, result, lock, clock)

    threads = [
        threading.Thread(target=worker, args=(w,),
                         name=f"loadgen-closed-{w}", daemon=True)
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    result.elapsed_s = clock() - t_start
    return result


def run_open_loop(
    predict_fn: Callable[[Dict[str, np.ndarray]], object],
    stream: RequestStream,
    target_qps: float,
    duration_s: float,
    max_outstanding: int = 256,
    clock=time.monotonic,
    sleep=time.sleep,
) -> LoadResult:
    """Paced arrivals: request i is issued at t_start + i/target_qps on
    its own thread (arrivals independent of completions).  If more than
    `max_outstanding` requests are in flight the arrival is counted as
    `schedule_lag` and skipped — the loadgen refuses to become an
    unbounded thread pile when the server is saturated."""
    if target_qps <= 0:
        raise ValueError(f"target_qps must be > 0, got {target_qps}")
    result = LoadResult(mode="open")
    lock = threading.Lock()
    outstanding = threading.Semaphore(max_outstanding)
    threads: List[threading.Thread] = []
    total = int(target_qps * duration_s)
    t_start = clock()

    def issue_one(i: int):
        try:
            _issue(predict_fn, stream, i, result, lock, clock)
        finally:
            outstanding.release()

    for i in range(total):
        due = t_start + i / target_qps
        delay = due - clock()
        if delay > 0:
            sleep(delay)
        if not outstanding.acquire(blocking=False):
            with lock:
                result.requests += 1
                result.schedule_lag += 1
                result.outcomes["shed"] += 1
            continue
        t = threading.Thread(
            target=issue_one, args=(i,), name=f"loadgen-open-{i}",
            daemon=True,
        )
        threads.append(t)
        t.start()
    for t in threads:
        t.join(timeout=60)
    result.elapsed_s = clock() - t_start
    return result


def round_robin_predict(predict_fns: Sequence[Callable]) -> Callable:
    """One predict_fn spreading requests across replicas."""
    if not predict_fns:
        raise ValueError("no predict targets")
    counter = {"i": 0}
    lock = threading.Lock()

    def predict(features):
        with lock:
            i = counter["i"]
            counter["i"] += 1
        return predict_fns[i % len(predict_fns)](features)

    return predict


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _selftest() -> int:
    """No-server sanity: stream determinism + skew, outcome
    classification, and a closed+open loop against a fake backend."""
    cfg = StreamConfig(seed=7, batch_rows=4, vocab_size=50)
    a, b = RequestStream(cfg), RequestStream(cfg)
    for i in (0, 1, 99):
        ra, rb = a.request(i), b.request(i)
        if not (np.array_equal(ra["dense"], rb["dense"])
                and np.array_equal(ra["cat"], rb["cat"])):
            print("selftest FAILED: stream not deterministic",
                  file=sys.stderr)
            return 1
    if np.array_equal(a.request(0)["cat"], a.request(1)["cat"]):
        print("selftest FAILED: distinct requests identical", file=sys.stderr)
        return 1
    # Hot-key skew: the hot prefix of the vocab must dominate.
    ids = np.concatenate([a.request(i)["cat"].ravel() for i in range(50)])
    n_hot = max(1, int(cfg.vocab_size * cfg.hot_fraction))
    hot_share = float(np.mean(ids < n_hot))
    if not 0.6 < hot_share < 0.95:
        print(f"selftest FAILED: hot share {hot_share}", file=sys.stderr)
        return 1

    class _Shed(Exception):
        def code(self):
            class _C:
                name = "RESOURCE_EXHAUSTED"
            return _C()

    if classify_error(_Shed()) != "shed" or \
            classify_error(TimeoutError()) != "deadline" or \
            classify_error(RuntimeError()) != "error":
        print("selftest FAILED: outcome classification", file=sys.stderr)
        return 1

    calls = {"n": 0}

    def fake_predict(features):
        calls["n"] += 1
        if calls["n"] % 5 == 0:
            raise _Shed()
        return np.zeros(features["dense"].shape[0], np.float32)

    closed = run_closed_loop(fake_predict, a, num_requests=50, concurrency=4)
    if closed.requests != 50 or closed.outcomes["served"] != 40 \
            or closed.outcomes["shed"] != 10:
        print(f"selftest FAILED: closed loop {closed.summary()}",
              file=sys.stderr)
        return 1
    calls["n"] = 0
    opened = run_open_loop(fake_predict, a, target_qps=500, duration_s=0.2)
    if opened.requests != 100 or opened.histogram.count() \
            != opened.outcomes["served"]:
        print(f"selftest FAILED: open loop {opened.summary()}",
              file=sys.stderr)
        return 1
    summary = opened.summary()
    if summary["latency"]["p99_ms"] < summary["latency"]["p50_ms"]:
        print("selftest FAILED: percentile ordering", file=sys.stderr)
        return 1
    print("loadgen selftest OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Deterministic serving load generator."
    )
    parser.add_argument("--serve_dir", default="",
                        help="discover live replicas from this serve dir")
    parser.add_argument("--addr", action="append", default=[],
                        help="explicit replica addr host:port (repeatable)")
    parser.add_argument("--mode", choices=("open", "closed"), default="open")
    parser.add_argument("--qps", type=float, default=100.0,
                        help="open loop: target arrival rate")
    parser.add_argument("--duration_s", type=float, default=10.0,
                        help="open loop: run length")
    parser.add_argument("--requests", type=int, default=200,
                        help="closed loop: total requests")
    parser.add_argument("--concurrency", type=int, default=4,
                        help="closed loop: worker threads")
    parser.add_argument("--deadline_s", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--batch_rows", type=int, default=8)
    parser.add_argument("--vocab_size", type=int, default=100)
    parser.add_argument("--hot_fraction", type=float, default=0.1)
    parser.add_argument("--hot_share", type=float, default=0.8)
    parser.add_argument("--output", default="",
                        help="also write the JSON summary here")
    parser.add_argument("--selftest", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        return _selftest()

    addrs = list(args.addr)
    if args.serve_dir:
        from elasticdl_tpu.serving.replica_main import live_replicas

        addrs += [
            f"127.0.0.1:{r['port']}" for r in live_replicas(args.serve_dir)
        ]
    if not addrs:
        print("no targets: pass --serve_dir or --addr", file=sys.stderr)
        return 2

    from elasticdl_tpu.serving.frontend import PredictClient

    clients = [PredictClient(a, deadline_s=args.deadline_s) for a in addrs]
    predict = round_robin_predict([c.predict for c in clients])
    stream = RequestStream(StreamConfig(
        seed=args.seed, batch_rows=args.batch_rows,
        vocab_size=args.vocab_size, hot_fraction=args.hot_fraction,
        hot_share=args.hot_share,
    ))
    if args.mode == "open":
        result = run_open_loop(predict, stream, args.qps, args.duration_s)
    else:
        result = run_closed_loop(
            predict, stream, args.requests, args.concurrency
        )
    summary = {"targets": addrs, **result.summary()}
    text = json.dumps(summary, indent=2)
    print(text)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    for c in clients:
        c.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
