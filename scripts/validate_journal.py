#!/usr/bin/env python
"""Schema-check a control-plane event journal (JSONL).

    python scripts/validate_journal.py /logs/job1/events.jsonl [...]
    python scripts/validate_journal.py --selftest

Exit status: 0 when every record validates, 1 on any malformed record,
2 on usage errors.  Wired into ``make test-obs`` (via --selftest plus
the subprocess tests in tests/test_telemetry.py) so the journal the
tooling (obs.top, chaos-test reconstruction, post-mortem grep) depends
on can't silently drift from the documented schema
(docs/observability.md "Event journal").

Every record must be a JSON object with a numeric ``ts`` and a
non-empty string ``event``; events named in ``EVENT_REQUIRED_FIELDS``
must additionally carry their listed fields.  Unknown event types pass
(the journal is open for extension) — malformed JSON, wrong-typed
envelope fields, or missing required fields fail.  Stdlib only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import List, Tuple

#: Required fields per documented event type (docs/observability.md).
#: Extension stays cheap: add the event name + its load-bearing fields.
EVENT_REQUIRED_FIELDS = {
    "master_start": ("job_name",),
    "rendezvous": ("rendezvous_id", "world_size"),
    "task_dispatch": ("task_id", "worker_id", "trace_id"),
    "task_done": ("task_id", "trace_id"),
    "task_requeue": ("reason",),
    "task_failed_permanently": ("task_id",),
    "worker_churn": ("workers", "exit_codes"),
    "hung_worker_kill": ("worker_id",),
    "worker_telemetry": ("worker_id",),
    "straggler_detected": ("worker_id", "metric"),
    "straggler_cleared": ("worker_id",),
    "scale": ("old_size", "new_size"),
    "scale_up": ("old_size", "new_size"),
    "span": ("name", "duration_s"),
    "job_failed": ("reason",),
}


def validate_record(record: object) -> List[str]:
    """Schema errors for one parsed record ([] when valid)."""
    errors = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, not an object"]
    ts = record.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        errors.append(f"'ts' must be a number, got {ts!r}")
    event = record.get("event")
    if not isinstance(event, str) or not event:
        errors.append(f"'event' must be a non-empty string, got {event!r}")
        return errors
    for field in EVENT_REQUIRED_FIELDS.get(event, ()):
        if field not in record:
            errors.append(f"event '{event}' missing required field '{field}'")
    return errors


def validate_file(path: str) -> List[Tuple[int, str]]:
    """(line number, message) for every invalid line in a journal file."""
    problems: List[Tuple[int, str]] = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError as exc:
                problems.append((lineno, f"invalid JSON: {exc}"))
                continue
            for message in validate_record(record):
                problems.append((lineno, message))
    return problems


def _selftest() -> int:
    """Generate a known-good and a known-bad journal and verify this
    validator tells them apart — the `make test-obs` sanity gate."""
    good = [
        {"ts": 1.0, "event": "master_start", "job_name": "j", "port": 1},
        {"ts": 2.0, "event": "rendezvous", "rendezvous_id": 1,
         "world_size": 2, "workers": [0, 1]},
        {"ts": 3.0, "event": "task_dispatch", "task_id": 1, "worker_id": 0,
         "trace_id": "t-1-1"},
        {"ts": 4.0, "event": "worker_telemetry", "worker_id": 0,
         "step_p50_s": 0.01},
        {"ts": 5.0, "event": "straggler_detected", "worker_id": 1,
         "metric": "step_time", "value": 1.0},
        {"ts": 6.0, "event": "task_done", "task_id": 1, "trace_id": "t-1-1"},
        {"ts": 7.0, "event": "some_future_event", "anything": "goes"},
    ]
    bad_lines = [
        '{"ts": 1.0, "event": "task_requeue"}',        # missing reason
        '{"event": "rendezvous", "rendezvous_id": 1, "world_size": 1}',  # no ts
        '{"ts": "yesterday", "event": "span", "name": "x", "duration_s": 1}',
        '{"ts": 2.0}',                                  # no event
        '{"ts": 3.0, "event": "task_done", "task_id"',  # truncated JSON
        '[1, 2, 3]',                                    # not an object
    ]
    with tempfile.TemporaryDirectory(prefix="journal_selftest_") as tmp:
        good_path = os.path.join(tmp, "good.jsonl")
        with open(good_path, "w", encoding="utf-8") as f:
            for record in good:
                f.write(json.dumps(record) + "\n")
        bad_path = os.path.join(tmp, "bad.jsonl")
        with open(bad_path, "w", encoding="utf-8") as f:
            f.write("\n".join(bad_lines) + "\n")
        good_problems = validate_file(good_path)
        bad_problems = validate_file(bad_path)
    if good_problems:
        print("selftest FAILED: valid journal flagged:", file=sys.stderr)
        for lineno, message in good_problems:
            print(f"  line {lineno}: {message}", file=sys.stderr)
        return 1
    if len({lineno for lineno, _ in bad_problems}) != len(bad_lines):
        print(
            f"selftest FAILED: expected every one of {len(bad_lines)} bad "
            f"lines flagged, got {bad_problems}",
            file=sys.stderr,
        )
        return 1
    print("validate_journal selftest OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Schema-check control-plane event journals (JSONL).",
    )
    parser.add_argument("paths", nargs="*", help="journal files to check")
    parser.add_argument(
        "--quiet", action="store_true", help="suppress per-line messages"
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="validate a generated good/bad pair and exit",
    )
    args = parser.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2
    failed = False
    for path in args.paths:
        if not os.path.exists(path):
            print(f"{path}: no such file", file=sys.stderr)
            failed = True
            continue
        problems = validate_file(path)
        if problems:
            failed = True
            if not args.quiet:
                for lineno, message in problems:
                    print(f"{path}:{lineno}: {message}", file=sys.stderr)
            print(
                f"{path}: {len(problems)} problem(s)", file=sys.stderr
            )
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
