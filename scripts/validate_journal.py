#!/usr/bin/env python
"""Schema-check a control-plane event journal (JSONL).

    python scripts/validate_journal.py /logs/job1/events.jsonl [...]
    python scripts/validate_journal.py --selftest

Exit status: 0 when every record validates, 1 on any malformed record,
2 on usage errors.  Wired into ``make test-obs`` (via --selftest plus
the subprocess tests in tests/test_telemetry.py) so the journal the
tooling (obs.top, chaos-test reconstruction, post-mortem grep) depends
on can't silently drift from the documented schema
(docs/observability.md "Event journal").

Every record must be a JSON object with a numeric ``ts`` and a
non-empty string ``event``; events named in ``EVENT_REQUIRED_FIELDS``
must additionally carry their listed fields.  Unknown event types pass
(the journal is open for extension) — malformed JSON, wrong-typed
envelope fields, or missing required fields fail.  Stdlib only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
from typing import List, Tuple

#: Required fields per documented event type (docs/observability.md).
#: Extension stays cheap: add the event name + its load-bearing fields.
EVENT_REQUIRED_FIELDS = {
    "master_start": ("job_name",),
    "rendezvous": ("rendezvous_id", "world_size"),
    "task_dispatch": ("task_id", "worker_id", "trace_id"),
    "task_done": ("task_id", "trace_id"),
    "task_requeue": ("reason",),
    "task_failed_permanently": ("task_id",),
    "worker_churn": ("workers", "exit_codes"),
    "hung_worker_kill": ("worker_id",),
    "worker_telemetry": ("worker_id",),
    "straggler_detected": ("worker_id", "metric"),
    "straggler_cleared": ("worker_id",),
    "scale": ("old_size", "new_size"),
    "scale_up": ("old_size", "new_size"),
    "span": ("name", "duration_s"),
    "job_failed": ("reason",),
    # Goodput ledger (obs/goodput.py — docs/observability.md "Goodput").
    "phase_transition": ("from", "to", "seconds"),
    "rescale_cost": (
        "cause", "total_s", "detection_s", "rendezvous_s", "redo_s",
    ),
    "goodput_summary": ("goodput_ratio", "wall_s", "phases"),
    # Elastic policy engine (master/policy.py — docs/observability.md
    # "Policy decisions"): scale_up/scale_down/evict/hold + evidence.
    "policy_decision": ("action", "reason"),
    # Step anatomy (obs/stepstats.py — docs/observability.md "Step
    # anatomy"): per-worker compute-plane phase decomposition.
    "step_anatomy": ("worker_id",),
    # StepProfiler trace windows (common/profiler.py): lets obs.report
    # point at the TensorBoard trace covering an anomalous window.
    "profile_window": ("worker_id", "action", "trace_dir"),
    # Bench regression gate (scripts/bench_regress.py): per-metric
    # verdicts of a bench.py run vs the recorded baseline spread.
    "bench_regress": ("verdict", "metrics_total", "regressed"),
    # Sparse-path engine decision (parallel/ps_trainer.py init): which
    # lookup/apply engine (xla vs the fused Pallas kernels) a training
    # run's numbers were measured on — and, for the fused engine, which
    # dispatch route it took (`route`: single_device pallas_call vs
    # shard_map over the mesh; 'xla' for the SPMD-partitioned engine) —
    # postmortems and bench audits must not have to guess
    # (docs/design.md "Fused sparse kernels").
    "sparse_kernel_selected": ("kernel",),
    # Declarative compile layer (parallel/compile.py): one event per
    # compiled entry point — trainer identity, pjit-vs-shard_map
    # strategy, rule-table hit/miss counts, donated argnums — so a
    # postmortem can always answer "what placement did this job
    # actually compile?" (docs/design.md "Declarative sharding").
    "compile_plan": ("trainer", "strategy"),
    # Distributed tracing plane (obs/tracing.py + obs/trace.py —
    # docs/observability.md "Distributed tracing").  `span` above stays
    # backward-compatible (name + duration_s); tracing-plane spans add
    # span_id/trace_id/parent_span_id/start_ts as optional fields.
    # `clock_probe` is the worker-journal half of clock alignment:
    # wall stamps around the telemetry-carrying heartbeat RPC, paired
    # with the master's worker_telemetry event by (worker_id, probe_ts
    # == worker_ts) for the midpoint offset estimate.
    "clock_probe": ("worker_id", "probe_ts", "t_send", "t_recv"),
    # Crash flight recorder (tracing.flush_flight_record): the final
    # bounded metrics dump a SIGTERM'd process leaves next to its
    # flushed open spans.
    "registry_snapshot": ("reason",),
    # Serving plane (serving/ — docs/serving.md).  `model_swap` is the
    # hot-swap commit record (new generation + the training step it was
    # exported at; old_generation/drained_inflight ride as optional
    # evidence).  `request_shed` is the explicit load-shed record
    # (reason: queue_full at admission, deadline in queue).
    # `serving_telemetry` is the per-replica periodic rollup — replica
    # id is unbounded, so qps/p50/p99/queue-depth/generation ride the
    # journal, never metric labels.  Serving requests reuse
    # `phase_transition` with the REQUEST_PHASES taxonomy
    # (queue/batch/execute/respond — obs/stepstats.py).
    "model_swap": ("generation", "step"),
    "request_shed": ("reason",),
    "serving_telemetry": ("replica_id",),
    "serving_replica_start": ("replica_id", "port"),
    "serving_fleet_start": ("replicas",),
    # Continuous train->serve loop (master/stream.py, checkpoint/delta.py,
    # obs/freshness.py — docs/design.md "Continuous training").
    # `stream_watermark` records every advance of the trained-offset
    # frontier (the journal-backed resume point for a SIGKILLed master);
    # `delta_checkpoint`/`delta_compaction` are the chain's commit
    # records; `freshness_slo` fires on breach/clear TRANSITIONS only,
    # with the lag attributed to the owning stage.
    "stream_watermark": ("stream", "offset"),
    "delta_checkpoint": ("step", "base_step"),
    "delta_compaction": ("step",),
    "freshness_slo": ("state", "lag_s", "slo_s"),
    # SLO plane (obs/slo.py — docs/observability.md "SLO plane").
    # `slo_status` is the rate-limited per-tick rollup of one SLO's
    # error budget; `slo_alert` is the edge-triggered multi-window
    # burn-rate fire/clear with its evidence (per-window burn rates,
    # budget remaining, offending series).
    "slo_status": ("slo", "budget_remaining_ratio"),
    "slo_alert": ("slo", "state"),
    # Request-level tracing exemplars (serving/ledger.py ExemplarSampler
    # — docs/observability.md "Request tracing & exemplars").  Journaled
    # only for sampled requests (deterministic head samples, over-SLO
    # tails, and every non-served outcome), so exemplar volume is
    # O(sampled), never O(requests); the trace id is journal-only per
    # the cardinality rule.
    "request_trace": ("trace_id", "outcome", "sampled_by"),
    # Model-quality plane (obs/quality.py — docs/observability.md
    # "Model quality").  `quality_window` is the periodic online-metric
    # rollup of the label-join ledger (AUC/logloss/calibration ride as
    # optional fields — a window can be labelless); `quality_drift`
    # fires on train-serve divergence breach/clear EDGES only;
    # `quality_gate` records every canary-gate verdict on a delta link
    # (outcome passed|held|forced, with the shadow-eval evidence).
    "quality_window": ("joined", "origin"),
    "quality_drift": ("state", "divergence", "origin"),
    "quality_gate": ("outcome", "step", "origin"),
}

#: Every event type the repo is ALLOWED to emit.  Journal FILES stay
#: open for extension (unknown events in a file pass — an old validator
#: must not reject a newer master's journal), but the repo's own call
#: sites must register here: ``--check-sources`` runs the analyzer's
#: AST ``journal-schema`` rule over the source tree and fails on any
#: emission whose event name is missing from this set, so schema drift
#: can't recur silently.
KNOWN_EVENTS = frozenset(EVENT_REQUIRED_FIELDS) | {
    "task_progress_resume",
    "train_epoch_done",
    "job_complete",
    "pod_create_failed",
    "pod_pending_timeout",
    "checkpoint_saved",
    "checkpoint_restored",
    "checkpoint_quarantined",
}

#: Optional fields per event: everything a call site may carry BESIDE
#: the required fields and the ts/event envelope.  This is the
#: field-level half of the source contract — the analyzer's
#: ``journal-schema`` rule flags any literal kwarg/dict key at an
#: emission site that is in neither the required nor the optional set,
#: which is how a misspelled field (``generaton=...``) gets caught at
#: lint time instead of at post-mortem grep time.  Journal-FILE
#: validation stays permissive (extra fields in a file always pass).
#: Every KNOWN_EVENTS entry appears here, even when empty, so adding a
#: field is an explicit one-line registration.
EVENT_OPTIONAL_FIELDS = {
    "master_start": ("port", "metrics_port"),
    "rendezvous": ("coordinator", "workers"),
    "task_dispatch": ("type", "shard", "start", "end", "epoch"),
    "task_done": ("worker_id", "type", "duration_s"),
    "task_requeue": (
        "task_id", "task_ids", "worker_id", "trace_id", "trace_ids",
        "retry", "records", "timeout_s",
    ),
    "task_failed_permanently": (
        "trace_id", "retries", "shard", "start", "end",
    ),
    "task_progress_resume": (
        "stream", "epoch", "todo", "finished_records", "next_offset",
        "watermark", "completed_above_watermark",
    ),
    "train_epoch_done": ("epoch", "next_epoch"),
    "job_complete": ("restarts_used",),
    "job_failed": (),
    "worker_churn": ("old_size", "restarts_used", "budget_left"),
    "hung_worker_kill": ("silent_s",),
    "worker_telemetry": (
        "worker_ts", "step", "step_p50_s", "step_p95_s", "examples_s",
        "data_wait_s", "host",
    ),
    "straggler_detected": ("value", "threshold", "median"),
    "straggler_cleared": ("metric",),
    "scale": ("direction",),
    "scale_up": ("direction",),
    "pod_create_failed": ("pod", "error"),
    "pod_pending_timeout": ("pod", "timeout_s"),
    "span": (
        "trace_id", "span_id", "parent_span_id", "start_ts", "proc",
        "task_id", "worker_id", "error", "steps",
        # Serving request spans (rpc.predict / serve.queue /
        # serve.execute / serve.respond) and the shared serve.batch span
        # every member request links to via `batch_span_id`.
        "rows", "outcome", "batch_rows", "bucket", "generation",
        "requests", "batch_span_id", "addr",
    ),
    "phase_transition": ("cause",),
    "rescale_cost": (
        "seq", "old_size", "new_size", "rendezvous_id", "redo_tasks",
        "redo_records", "superseded",
    ),
    "goodput_summary": (
        "outcome", "rescales", "records_done", "records_redone",
    ),
    "policy_decision": (
        "worker_id", "flag_streak_ticks", "kill_budget_remaining",
        "evidence", "old_size", "new_size",
        # SLO advisory evidence (note_slo_alert -> _hold): which SLOs
        # were fired while the engine decided, plus the fire evidence.
        "slo_advisory", "slo", "grade", "burn_rates",
        "budget_remaining_ratio", "offending", "origin",
    ),
    "step_anatomy": (
        "totals", "fractions", "steps", "examples", "retraces", "bound",
        "dominant_phase", "overlap_s",
    ),
    "profile_window": ("step_start", "step_end"),
    "bench_regress": ("details", "baseline"),
    "sparse_kernel_selected": (
        "requested", "route", "optimizer", "tables", "table_rows",
    ),
    "compile_plan": (
        "name", "rule_table", "rule_hits", "rule_misses",
        "donated_argnums", "devices",
    ),
    "clock_probe": ("rtt_s",),
    "registry_snapshot": ("proc", "metrics"),
    "model_swap": (
        "old_generation", "old_step", "model_dir", "drained_inflight",
        "undrained", "kind", "outcome", "reason", "event_time",
    ),
    "request_shed": (
        "queue_depth", "queue_limit", "rows", "waited_s",
    ),
    "serving_telemetry": (
        "generation", "step", "inflight", "queue_depth", "qps",
        "p50_ms", "p99_ms", "availability_ratio", "served", "dropped",
        "shed", "errors", "model_event_time",
        # Per-phase p99 split (queue/batch/execute/respond — the
        # obs.top --serving QU/BA/EX/RE columns) and the slowest recent
        # exemplar ({trace_id, latency_ms, dominant_phase}).
        "queue_p99_ms", "batch_p99_ms", "execute_p99_ms",
        "respond_p99_ms", "exemplar",
    ),
    "serving_replica_start": ("model_dir", "generation"),
    "serving_fleet_start": ("model_dir", "serve_dir"),
    "stream_watermark": ("event_time", "next_offset", "pending_ranges"),
    "delta_checkpoint": ("rows", "tables", "event_time"),
    "delta_compaction": ("deltas_folded", "event_time"),
    "freshness_slo": ("stage", "generation", "step"),
    "slo_status": (
        "kind", "objective", "window_s", "bad_fraction", "burn_rates",
        "alerting", "grade", "offending", "origin",
    ),
    "slo_alert": (
        "grade", "burn_rates", "budget_remaining_ratio", "offending",
        "windows", "origin", "objective",
        # Up-to-K exemplar trace ids from the serving ExemplarSampler:
        # the offending-REQUEST evidence beside the offending-series
        # string (resolvable in the assembled obs.trace output).
        "exemplars",
    ),
    "request_trace": (
        "latency_ms", "phases", "dominant_phase", "rows", "replica_id",
        "generation", "bucket",
    ),
    "checkpoint_saved": ("step", "kind", "n_processes", "event_time"),
    "checkpoint_restored": ("step", "kind"),
    "checkpoint_quarantined": ("path", "reason"),
    "quality_window": (
        "window", "pending", "expired", "orphans", "auc", "logloss",
        "calibration_error", "prediction_mean", "label_mean", "entropy",
    ),
    "quality_drift": ("threshold",),
    "quality_gate": (
        "delta_dir", "reason", "rows", "quality", "baseline_logloss",
        "candidate_logloss", "baseline_auc", "candidate_auc",
    ),
}
assert set(EVENT_OPTIONAL_FIELDS) == set(KNOWN_EVENTS), (
    "EVENT_OPTIONAL_FIELDS must carry an entry (possibly empty) for "
    "every known event"
)


def validate_record(record: object) -> List[str]:
    """Schema errors for one parsed record ([] when valid)."""
    errors = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, not an object"]
    ts = record.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        errors.append(f"'ts' must be a number, got {ts!r}")
    event = record.get("event")
    if not isinstance(event, str) or not event:
        errors.append(f"'event' must be a non-empty string, got {event!r}")
        return errors
    for field in EVENT_REQUIRED_FIELDS.get(event, ()):
        if field not in record:
            errors.append(f"event '{event}' missing required field '{field}'")
    return errors


def validate_file(path: str) -> List[Tuple[int, str]]:
    """(line number, message) for every invalid line in a journal file."""
    problems: List[Tuple[int, str]] = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError as exc:
                problems.append((lineno, f"invalid JSON: {exc}"))
                continue
            for message in validate_record(record):
                problems.append((lineno, message))
    return problems


#: ``--check-sources`` is an alias for the analyzer's AST
#: ``journal-schema`` rule (elasticdl_tpu/analysis/protocol_rules.py).
#: The old regex scanner matched event NAMES only; the AST rule also
#: checks every literal field at each ``journal.record(...)`` /
#: ``record_span(...)`` / ``dict(event=...)`` site against
#: EVENT_REQUIRED_FIELDS / EVENT_OPTIONAL_FIELDS above, so a misspelled
#: field now fails the gate where the grep passed it.
_UNKNOWN_EVENT_RE = re.compile(r"unknown journal event '([^']+)'")


def _analysis_scan(root: str):
    """One journal-schema pass of the analyzer over `root`."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from elasticdl_tpu.analysis.core import scan
    from elasticdl_tpu.analysis.protocol_rules import check_journal_schema

    return scan([root], [check_journal_schema])


def scan_sources(root: str) -> List[Tuple[str, int, str]]:
    """(path, line, event) for every journal emission whose event type is
    not registered in KNOWN_EVENTS.  Scans the package source tree —
    tests journal arbitrary demo events and are deliberately excluded."""
    unknown: List[Tuple[str, int, str]] = []
    for violation in _analysis_scan(root).violations:
        match = _UNKNOWN_EVENT_RE.search(violation.message)
        if match:
            unknown.append((violation.path, violation.line, match.group(1)))
    return unknown


def scan_sources_counted(root: str) -> Tuple[List[Tuple[str, int, str]], int]:
    """All journal-schema findings as (path, line, message), plus the
    scanned-file count (zero means the gate looked at nothing)."""
    report = _analysis_scan(root)
    problems = [
        (violation.path, violation.line, violation.message)
        for violation in report.violations
    ]
    return problems, len(report.files)


def _check_sources(root: str) -> int:
    if not os.path.isdir(root) and not (
        os.path.isfile(root) and root.endswith(".py")
    ):
        # A gate that scanned nothing must not pass (same rule as the
        # analysis CLI's zero-file-scan exit): a wrong cwd or a moved
        # tree would otherwise silently disable drift detection.
        print(
            f"check-sources: no .py files under {root!r} — wrong "
            "directory? (run from the repo root)", file=sys.stderr,
        )
        return 2
    problems, scanned = scan_sources_counted(root)
    if scanned == 0:
        print(
            f"check-sources: no .py files under {root!r} — wrong "
            "directory? (run from the repo root)", file=sys.stderr,
        )
        return 2
    if problems:
        print(
            "journal schema drift (event names and fields are checked "
            "against scripts/validate_journal.py registries by the "
            "analyzer's journal-schema rule):", file=sys.stderr,
        )
        for path, line, message in sorted(problems):
            print(f"  {path}:{line}: {message}", file=sys.stderr)
        return 1
    print(
        f"check-sources OK ({root}: {scanned} files, every emission "
        "site matches the registered event + field schema)"
    )
    return 0


def _selftest() -> int:
    """Generate a known-good and a known-bad journal and verify this
    validator tells them apart — the `make test-obs` sanity gate."""
    good = [
        {"ts": 1.0, "event": "master_start", "job_name": "j", "port": 1},
        {"ts": 2.0, "event": "rendezvous", "rendezvous_id": 1,
         "world_size": 2, "workers": [0, 1]},
        {"ts": 3.0, "event": "task_dispatch", "task_id": 1, "worker_id": 0,
         "trace_id": "t-1-1"},
        {"ts": 4.0, "event": "worker_telemetry", "worker_id": 0,
         "step_p50_s": 0.01},
        {"ts": 5.0, "event": "straggler_detected", "worker_id": 1,
         "metric": "step_time", "value": 1.0},
        {"ts": 6.0, "event": "task_done", "task_id": 1, "trace_id": "t-1-1"},
        {"ts": 6.2, "event": "phase_transition", "from": "idle",
         "to": "training", "cause": "task_dispatch", "seconds": 1.5},
        {"ts": 6.4, "event": "rescale_cost", "seq": 1,
         "cause": "worker_churn", "total_s": 3.0, "detection_s": 0.5,
         "rendezvous_s": 1.5, "redo_s": 1.0, "redo_records": 64},
        {"ts": 6.6, "event": "goodput_summary", "goodput_ratio": 0.87,
         "wall_s": 41.0, "phases": {"training": 35.7}},
        {"ts": 6.8, "event": "policy_decision", "action": "evict",
         "reason": "persistent_straggler", "worker_id": 1,
         "flag_streak_ticks": 3, "kill_budget_remaining": 0},
        # overlap_s rides BESIDE the exclusive phase totals (async
        # staging credit, obs/stepstats.py): fractions still sum to 1.0
        # over serialized time and overlap_s reports the hidden work.
        {"ts": 6.85, "event": "step_anatomy", "worker_id": 0,
         "totals": {"data_wait": 1.2, "execute": 4.0}, "steps": 64,
         "examples": 4096, "retraces": 1, "bound": "host",
         "fractions": {"data_wait": 0.23, "execute": 0.77},
         "dominant_phase": "execute", "overlap_s": 0.8},
        {"ts": 6.9, "event": "profile_window", "worker_id": 2,
         "action": "open", "step_start": 100, "step_end": 120,
         "trace_dir": "/logs/job1/profile/worker_2"},
        {"ts": 6.95, "event": "bench_regress", "verdict": "regressed",
         "metrics_total": 8, "regressed": 1,
         "details": [{"metric": "deepfm", "ratio": 0.8}]},
        {"ts": 6.97, "event": "sparse_kernel_selected", "kernel": "fused",
         "requested": "fused", "route": "shard_map", "optimizer": "adam",
         "tables": 1, "table_rows": 26000000},
        {"ts": 6.98, "event": "compile_plan", "trainer": "ps_trainer",
         "name": "ps_train_step", "strategy": "pjit",
         "rule_table": "ps-fused", "rule_hits": 3, "rule_misses": 0,
         "donated_argnums": [0], "devices": 8},
        # Tracing-plane span: the legacy envelope (name + duration_s)
        # plus the span-tree fields the assembler keys on.
        {"ts": 7.02, "event": "span", "name": "task.lifetime",
         "duration_s": 9.01, "start_ts": 6.99, "span_id": "t-1-1",
         "trace_id": "t-1-1", "proc": "master", "task_id": 1},
        {"ts": 7.04, "event": "span", "name": "step.data_wait",
         "duration_s": 2.0, "start_ts": 7.0, "span_id": "s-abc-3",
         "parent_span_id": "s-abc-2", "trace_id": "t-1-1",
         "proc": "worker_0"},
        {"ts": 7.06, "event": "clock_probe", "worker_id": 0,
         "probe_ts": 7.001, "t_send": 7.001, "t_recv": 7.041,
         "rtt_s": 0.04},
        {"ts": 7.08, "event": "registry_snapshot", "reason": "shutdown",
         "proc": "worker_0", "metrics": {"elasticdl_rpc_calls_total": 5}},
        # Serving plane (docs/serving.md).
        {"ts": 7.12, "event": "model_swap", "generation": 2, "step": 4096,
         "old_generation": 1, "old_step": 2048,
         "model_dir": "/exports/gen2", "drained_inflight": 3,
         "undrained": 0},
        {"ts": 7.14, "event": "request_shed", "reason": "queue_full",
         "queue_depth": 256, "queue_limit": 256, "rows": 8},
        {"ts": 7.16, "event": "serving_telemetry", "replica_id": 7,
         "generation": 2, "step": 4096, "inflight": 1, "queue_depth": 4,
         "qps": 812.5, "p50_ms": 3.1, "p99_ms": 11.8,
         "availability_ratio": 0.998, "served": 51233, "dropped": 14,
         "shed": 88, "errors": 0},
        {"ts": 7.18, "event": "serving_replica_start", "replica_id": 7,
         "port": 40001, "model_dir": "/exports/gen2", "generation": 1},
        {"ts": 7.2, "event": "serving_fleet_start", "replicas": 4,
         "model_dir": "/exports/gen2", "serve_dir": "/srv/fleet"},
        # A serving request's phase record rides the same
        # phase_transition envelope with the REQUEST_PHASES taxonomy.
        {"ts": 7.22, "event": "phase_transition", "from": "queue",
         "to": "execute", "cause": "batch_formed", "seconds": 0.0021},
        # Continuous train->serve loop.
        {"ts": 7.24, "event": "stream_watermark", "stream": "clicks",
         "offset": 81920, "event_time": 204.8, "next_offset": 86016,
         "pending_ranges": 2},
        {"ts": 7.25, "event": "delta_checkpoint", "step": 4160,
         "base_step": 4096, "rows": 1812, "tables": 2,
         "event_time": 204.8},
        {"ts": 7.26, "event": "delta_compaction", "step": 4288,
         "deltas_folded": 3, "event_time": 211.2},
        {"ts": 7.27, "event": "freshness_slo", "state": "breach",
         "lag_s": 12.4, "slo_s": 10.0, "stage": "serving",
         "generation": 2, "step": 4160},
        {"ts": 7.28, "event": "model_swap", "kind": "delta",
         "outcome": "rolled_back", "generation": 2, "step": 4160,
         "old_generation": 2, "old_step": 4160,
         "model_dir": "/pub/delta_000000004160_000000004224",
         "reason": "ValueError('corrupt delta')"},
        # SLO plane (obs/slo.py): the rate-limited status rollup and a
        # fire/clear alert pair with its burn-rate evidence.
        {"ts": 7.32, "event": "slo_status", "slo": "serving_latency",
         "kind": "threshold", "objective": 0.99, "window_s": 3600.0,
         "bad_fraction": 0.004, "budget_remaining_ratio": 0.6,
         "burn_rates": {"fast_short": 0.4, "fast_long": 0.3,
                        "slow_short": 0.3, "slow_long": 0.2},
         "alerting": False, "grade": "", "origin": "replica_0"},
        {"ts": 7.34, "event": "slo_alert", "slo": "serving_latency",
         "state": "fire", "grade": "page",
         "burn_rates": {"fast_short": 33.3, "fast_long": 18.2,
                        "slow_short": 18.2, "slow_long": 3.3},
         "budget_remaining_ratio": 0.12,
         "offending": "elasticdl_serving_latency_p99_ms",
         "origin": "replica_0"},
        {"ts": 7.36, "event": "slo_alert", "slo": "serving_latency",
         "state": "clear", "grade": "page",
         "burn_rates": {"fast_short": 0.0, "fast_long": 0.1,
                        "slow_short": 0.1, "slow_long": 1.1},
         "budget_remaining_ratio": 0.11, "offending": "",
         "origin": "replica_0"},
        # Request tracing & exemplars (PR 19): a latency slo_alert
        # carrying exemplar trace ids, the shared serve.batch span, a
        # member request's phase span linking to it, and the sampler's
        # request_trace records (a tail exemplar + a minimal shed one).
        {"ts": 7.38, "event": "slo_alert", "slo": "serving_latency",
         "state": "fire", "grade": "page",
         "burn_rates": {"fast_short": 20.1, "fast_long": 15.0,
                        "slow_short": 15.0, "slow_long": 2.8},
         "budget_remaining_ratio": 0.4,
         "offending": "elasticdl_serving_latency_p99_ms",
         "origin": "replica_1", "exemplars": ["lg7-00000102"]},
        {"ts": 7.4, "event": "span", "name": "serve.batch",
         "duration_s": 0.004, "start_ts": 7.39, "span_id": "s-b-1",
         "proc": "replica_0", "batch_rows": 24, "bucket": 32,
         "generation": 2, "requests": 3},
        {"ts": 7.42, "event": "span", "name": "serve.execute",
         "duration_s": 0.003, "start_ts": 7.391, "span_id": "s-e-1",
         "parent_span_id": "s-b-1", "trace_id": "lg7-00000102",
         "proc": "replica_0", "rows": 8, "batch_span_id": "s-b-1"},
        {"ts": 7.44, "event": "request_trace", "trace_id": "lg7-00000102",
         "outcome": "served", "sampled_by": "tail", "latency_ms": 81.2,
         "phases": {"queue": 63.1, "batch": 0.8, "execute": 17.1,
                    "respond": 0.2},
         "dominant_phase": "queue", "rows": 8, "replica_id": 0,
         "generation": 2, "bucket": 32},
        {"ts": 7.46, "event": "request_trace", "trace_id": "lg7-00000140",
         "outcome": "shed", "sampled_by": "outcome"},
        {"ts": 7.48, "event": "serving_telemetry", "replica_id": 0,
         "generation": 2, "qps": 410.0, "p99_ms": 81.2,
         "queue_p99_ms": 63.1, "batch_p99_ms": 0.9,
         "execute_p99_ms": 17.4, "respond_p99_ms": 0.3,
         "exemplar": {"trace_id": "lg7-00000102", "latency_ms": 81.2,
                      "dominant_phase": "queue"}},
        # Model-quality plane (PR 20): the windowed online-eval rollup, a
        # drift breach edge, and a canary-gate hold with its shadow-eval
        # evidence (docs/observability.md "Model quality").
        {"ts": 7.5, "event": "quality_window", "joined": 512,
         "origin": "replica_0", "window": 512, "pending": 9, "expired": 3,
         "orphans": 1, "auc": 0.71, "logloss": 0.48,
         "calibration_error": 0.04, "prediction_mean": 0.31,
         "label_mean": 0.3, "entropy": 0.58},
        {"ts": 7.52, "event": "quality_drift", "state": "breach",
         "divergence": 0.41, "threshold": 0.25, "origin": "replica_0"},
        {"ts": 7.54, "event": "quality_gate", "outcome": "held",
         "step": 4224, "origin": "replica_0",
         "delta_dir": "/pub/delta_000000004160_000000004224",
         "reason": "logloss_regress:0.3120", "rows": 192,
         "quality": "known", "baseline_logloss": 0.48,
         "candidate_logloss": 0.79, "baseline_auc": 0.71,
         "candidate_auc": 0.55},
        {"ts": 7.3, "event": "some_future_event", "anything": "goes"},
    ]
    bad_lines = [
        '{"ts": 1.0, "event": "task_requeue"}',        # missing reason
        '{"ts": 1.2, "event": "policy_decision", "action": "hold"}',  # no reason
        '{"ts": 1.3, "event": "step_anatomy", "totals": {}}',  # no worker_id
        '{"ts": 1.35, "event": "profile_window", "worker_id": 1}',  # no action
        '{"ts": 1.4, "event": "bench_regress", "verdict": "ok"}',  # no counts
        '{"ts": 1.45, "event": "sparse_kernel_selected"}',  # no kernel
        '{"ts": 1.47, "event": "compile_plan", "trainer": "dp"}',  # no strategy
        '{"ts": 1.48, "event": "clock_probe", "worker_id": 0}',  # no stamps
        '{"ts": 1.49, "event": "registry_snapshot"}',           # no reason
        '{"ts": 1.491, "event": "model_swap", "generation": 2}',  # no step
        '{"ts": 1.492, "event": "request_shed", "rows": 8}',    # no reason
        '{"ts": 1.493, "event": "serving_telemetry", "qps": 1}',  # no replica
        '{"ts": 1.494, "event": "serving_replica_start", "replica_id": 1}',
        '{"ts": 1.495, "event": "serving_fleet_start"}',        # no replicas
        '{"ts": 1.496, "event": "stream_watermark", "stream": "clicks"}',
        '{"ts": 1.497, "event": "delta_checkpoint", "step": 4160}',  # no base
        '{"ts": 1.498, "event": "delta_compaction"}',           # no step
        '{"ts": 1.499, "event": "freshness_slo", "state": "breach"}',
        '{"ts": 1.4995, "event": "slo_status", "slo": "goodput"}',  # no budget
        '{"ts": 1.4996, "event": "slo_alert", "slo": "goodput"}',   # no state
        '{"ts": 1.4997, "event": "slo_alert", "state": "fire"}',    # no slo
        '{"ts": 1.4998, "event": "request_trace", "trace_id": "t",'
        ' "outcome": "served"}',                        # no sampled_by
        '{"ts": 1.4999, "event": "request_trace", "outcome": "shed",'
        ' "sampled_by": "outcome"}',                    # no trace_id
        '{"ts": 1.49991, "event": "quality_window", "auc": 0.7}',  # no joined
        '{"ts": 1.49992, "event": "quality_drift", "state": "breach"}',
        '{"ts": 1.49993, "event": "quality_gate", "step": 4224,'
        ' "origin": "replica_0"}',                      # no outcome
        '{"ts": 1.5, "event": "phase_transition", "from": "idle"}',  # no to
        '{"ts": 1.6, "event": "rescale_cost", "cause": "scale"}',  # no costs
        '{"event": "rendezvous", "rendezvous_id": 1, "world_size": 1}',  # no ts
        '{"ts": "yesterday", "event": "span", "name": "x", "duration_s": 1}',
        '{"ts": 2.0}',                                  # no event
        '{"ts": 3.0, "event": "task_done", "task_id"',  # truncated JSON
        '[1, 2, 3]',                                    # not an object
    ]
    with tempfile.TemporaryDirectory(prefix="journal_selftest_") as tmp:
        good_path = os.path.join(tmp, "good.jsonl")
        with open(good_path, "w", encoding="utf-8") as f:
            for record in good:
                f.write(json.dumps(record) + "\n")
        bad_path = os.path.join(tmp, "bad.jsonl")
        with open(bad_path, "w", encoding="utf-8") as f:
            f.write("\n".join(bad_lines) + "\n")
        good_problems = validate_file(good_path)
        bad_problems = validate_file(bad_path)
    if good_problems:
        print("selftest FAILED: valid journal flagged:", file=sys.stderr)
        for lineno, message in good_problems:
            print(f"  line {lineno}: {message}", file=sys.stderr)
        return 1
    if len({lineno for lineno, _ in bad_problems}) != len(bad_lines):
        print(
            f"selftest FAILED: expected every one of {len(bad_lines)} bad "
            f"lines flagged, got {bad_problems}",
            file=sys.stderr,
        )
        return 1
    print("validate_journal selftest OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Schema-check control-plane event journals (JSONL).",
    )
    parser.add_argument("paths", nargs="*", help="journal files to check")
    parser.add_argument(
        "--quiet", action="store_true", help="suppress per-line messages"
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="validate a generated good/bad pair and exit",
    )
    parser.add_argument(
        "--check-sources", nargs="?", const="elasticdl_tpu",
        default=None, metavar="DIR",
        help="run the analyzer's AST journal-schema rule over the source "
        "tree (default: elasticdl_tpu) and fail on unregistered event "
        "types or unregistered/missing fields",
    )
    args = parser.parse_args(argv)
    if args.check_sources is not None:
        status = _check_sources(args.check_sources)
        if status or not (args.selftest or args.paths):
            return status
    if args.selftest:
        return _selftest()
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2
    failed = False
    for path in args.paths:
        if not os.path.exists(path):
            print(f"{path}: no such file", file=sys.stderr)
            failed = True
            continue
        problems = validate_file(path)
        if problems:
            failed = True
            if not args.quiet:
                for lineno, message in problems:
                    print(f"{path}:{lineno}: {message}", file=sys.stderr)
            print(
                f"{path}: {len(problems)} problem(s)", file=sys.stderr
            )
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
