#!/usr/bin/env python
"""Per-rule summary table for the invariant analyzer's JSON output.

Usage (what `make lint` runs)::

    python -m elasticdl_tpu.analysis elasticdl_tpu model_zoo \
        --format json > findings.json
    python scripts/invariant_report.py findings.json

Reads the analyzer's ``--format json`` document (from a file argument,
``-``, or stdin) and prints one row per rule: surviving findings and
suppressed (noqa'd / baselined) findings.  Exit status is always 0 —
the analyzer's own exit code is the gate; this is the human-readable
chaser.  Stdlib-only, like the analyzer.
"""

from __future__ import annotations

import json
import sys


def render(data: dict) -> str:
    findings = data.get("findings", [])
    suppressed_by_rule = data.get("suppressed_by_rule", {})
    rules = data.get("rules", [])
    counts: dict = {}
    for finding in findings:
        rule = finding.get("rule", "?")
        counts[rule] = counts.get(rule, 0) + 1
    names = list(rules)
    for name in sorted(set(counts) | set(suppressed_by_rule)):
        if name not in names:
            names.append(name)
    width = max([len(name) for name in names] + [len("rule")]) + 2
    lines = [f"{'rule'.ljust(width)}{'findings':>9}{'suppressed':>12}"]
    for name in names:
        lines.append(
            f"{name.ljust(width)}{counts.get(name, 0):>9}"
            f"{suppressed_by_rule.get(name, 0):>12}"
        )
    lines.append(
        f"{'total'.ljust(width)}{len(findings):>9}"
        f"{data.get('suppressed', 0):>12}"
        f"   ({data.get('files_scanned', 0)} files scanned)"
    )
    # Cost visibility (make lint): where the analyzer's wall time goes,
    # rule family by rule family, plus the size of the cross-module
    # graph the whole-program rules reasoned over.
    timing = data.get("timing", {})
    if timing:
        total_s = sum(timing.values())
        slowest = sorted(timing.items(), key=lambda kv: -kv[1])
        lines.append(
            "timing: "
            + ", ".join(f"{name} {seconds:.2f}s" for name, seconds in slowest)
            + f"   (total {total_s:.2f}s)"
        )
    graph = data.get("graph", {})
    if graph:
        lines.append(
            "program graph: {modules} modules, {edges} edges, "
            "{fixpoint_iterations} fixpoint iteration(s)".format(**graph)
        )
    # The counts alone don't locate anything: repeat each finding in the
    # analyzer's text format so `make lint` output stays actionable.
    if findings:
        lines.append("")
        for finding in findings:
            lines.append(
                f"{finding.get('path', '?')}:{finding.get('line', 0)}:"
                f"{finding.get('col', 0)}: [{finding.get('rule', '?')}] "
                f"{finding.get('message', '')}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        if argv and argv[0] not in ("-",):
            with open(argv[0], "r", encoding="utf-8") as f:
                data = json.load(f)
        else:
            data = json.load(sys.stdin)
    except (OSError, ValueError) as exc:
        # An empty/missing findings file means the analyzer itself
        # failed before producing JSON (usage error, bad path); its
        # stderr already explains why — don't bury it under a traceback.
        print(f"invariant_report: no findings JSON ({exc}); "
              "see the analyzer's own error above")
        return 0
    print(render(data))
    return 0


if __name__ == "__main__":
    sys.exit(main())
