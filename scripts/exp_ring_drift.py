"""Diagnose the ring tracked metric's -3% drift (round-5 VERDICT #4).

BENCH_r04 recorded `ring_attention_tokens_per_sec_per_chip` at
vs_baseline 0.97 with 0.3% within-run spread — ten times its own noise.
The kernel did not change between the baseline recording and the driver
run; what DID differ is process context: in `bench.py main()` the ring
bench runs THIRD, after the transformer and ResNet-50 trainers have
initialized, allocated, and stepped on the same chip, while the
baseline was recorded by calling bench_ring_engine in a fresh process.

This script measures exactly that variable on one chip:

  A. bench_ring_engine in a FRESH process (subprocess), nothing else
     has touched the chip;
  B. bench_ring_engine after bench_transformer() + bench_resnet50()
     in the same process (the driver's execution context).

Each arm repeats `--arms` times (alternating) so tunnel weather shows
up as within-arm scatter rather than between-arm bias.  If B sits ~3%
below A, the drift is predecessor-state (HBM layout/fragmentation or
residual allocations), not a kernel regression — re-baseline with the
reason recorded in BASELINE.md, or report the ring row from a fresh
subprocess in main().

Usage: python scripts/exp_ring_drift.py [--arms 3]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_arm(predecessors: bool) -> dict:
    """One subprocess measurement of bench_ring_engine.  Arm A
    (predecessors=False): the chip is untouched — the context the
    baseline was recorded in.  Arm B (True): bench_transformer +
    bench_resnet50 run first in the same process — the driver's
    execution context.  One code template so the arms can't drift."""
    pred = (
        "bench.bench_transformer()\nbench.bench_resnet50()\n"
        if predecessors else ""
    )
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import json, bench\n"
        "%s"
        "rate, spread = bench.bench_ring_engine()\n"
        "print(json.dumps({'rate': rate, 'spread': spread}))\n"
    ) % (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        pred,
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        check=True,
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arms", type=int, default=3)
    args = p.parse_args()
    rows = []
    for i in range(args.arms):
        for arm, predecessors in (("fresh", False), ("after_pred", True)):
            r = _run_arm(predecessors)
            r["arm"] = arm
            r["i"] = i
            rows.append(r)
            print(json.dumps(r), flush=True)
    for arm in ("fresh", "after_pred"):
        rates = [r["rate"] for r in rows if r["arm"] == arm]
        mid = sum(rates) / len(rates)
        half = (max(rates) - min(rates)) / 2
        print(f"{arm}: mean {mid:,.0f} ± {half:,.0f} tokens/s "
              f"({len(rates)} runs)")
    fresh = [r["rate"] for r in rows if r["arm"] == "fresh"]
    after = [r["rate"] for r in rows if r["arm"] == "after_pred"]
    delta = (sum(after) / len(after)) / (sum(fresh) / len(fresh)) - 1
    print(f"after_pred vs fresh: {delta:+.2%}")


if __name__ == "__main__":
    main()
