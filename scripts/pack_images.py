"""Pack an image-classification directory tree into ETRF image shards.

The offline half of the round-5 image data plane (data/image.py): JPEG/
PNG decode + resize happen ONCE here, so the training hot path streams
fixed-width raw uint8 records at memcpy-grade rates instead of paying
per-epoch decode (the classic host-bound trap for TPU input pipelines).

Input layout: the standard class-per-subdirectory tree
(`root/<class_name>/<image file>`, ImageNet-style); class names map to
integer labels by sorted order, written alongside as labels.json.

Each image is resized so its SHORTER side equals --size, center-cropped
square, and stored as [size, size, 3] uint8 — the record-cache
equivalent of the usual train transform, leaving room for the training
random crop (e.g. store 256, train 224).  Output is one or more .etrf
shard files (--records-per-shard); a shard directory feeds
`ImageRecordReader` (model_zoo/resnet50) directly and each file becomes
one shard in the master's dynamic-sharding queue.

Usage:
    python scripts/pack_images.py /data/imagenet/train out_dir \
        --size 256 --records-per-shard 50000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

IMAGE_SUFFIXES = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def list_dataset(root: str):
    classes = sorted(
        name for name in os.listdir(root)
        if os.path.isdir(os.path.join(root, name))
    )
    if not classes:
        raise ValueError(f"no class subdirectories under {root}")
    items = []
    for label, cls in enumerate(classes):
        for name in sorted(os.listdir(os.path.join(root, cls))):
            if name.lower().endswith(IMAGE_SUFFIXES):
                items.append((os.path.join(root, cls, name), label))
    if not items:
        raise ValueError(f"no image files under {root}")
    return classes, items


def decode_resize(path: str, size: int) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as img:
        img = img.convert("RGB")
        w, h = img.size
        scale = size / min(w, h)
        img = img.resize(
            (max(size, round(w * scale)), max(size, round(h * scale))),
            Image.BILINEAR,
        )
        w, h = img.size
        left, top = (w - size) // 2, (h - size) // 2
        img = img.crop((left, top, left + size, top + size))
        return np.asarray(img, np.uint8)


def pack(root: str, out_dir: str, size: int, records_per_shard: int,
         seed: int = 0) -> int:
    from elasticdl_tpu.data import recordfile
    from elasticdl_tpu.data.image import image_record_layout

    classes, items = list_dataset(root)
    # One global shuffle at packing time so every shard is an unbiased
    # sample — sequential shard tasks then see mixed classes even
    # before the per-task training permutation.
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(items))
    layout = image_record_layout(size)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "labels.json"), "w") as f:
        json.dump(classes, f)

    n_shards = max(1, -(-len(items) // records_per_shard))
    written = 0
    for shard in range(n_shards):
        lo = shard * records_per_shard
        chunk = order[lo:lo + records_per_shard]
        path = os.path.join(out_dir, f"images-{shard:05d}.etrf")

        def records():
            for idx in chunk:
                file_path, label = items[idx]
                image = decode_resize(file_path, size)
                yield layout.pack(
                    image=image.reshape(-1),
                    label=np.int32(label),
                )

        recordfile.write_records(path, records())
        written += len(chunk)
        print(f"{path}: {len(chunk)} records", flush=True)
    print(
        f"packed {written} images, {len(classes)} classes -> "
        f"{n_shards} shard(s) in {out_dir}",
        flush=True,
    )
    return written


def main():
    p = argparse.ArgumentParser()
    p.add_argument("input", help="class-per-subdirectory image tree")
    p.add_argument("output", help="output directory for .etrf shards")
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--records-per-shard", type=int, default=50_000)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    pack(args.input, args.output, args.size, args.records_per_shard,
         args.seed)


if __name__ == "__main__":
    main()
