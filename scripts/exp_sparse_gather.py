"""Perf experiment: can ANY engine beat the XLA row gather that bounds
the sparse embedding path?  (VERDICT round-3 #7, extended round 6 into
the xla-vs-fused kernel microbench.)

The 26M-row probe spends ~5.5 ms/step in lookup-gather + row ops and
~2.7 ms in the grad scatter — count-bound at ~25 ns per touched row
(BASELINE.md).  Round 3 measured the incumbents plus a Pallas
scalar-prefetch gather; round 6 adds the shipped fused kernel family
(ops/sparse_embedding.py) so each stage of the sparse path has an
xla-vs-fused ns/row number:

  lookup:   raw storage-row gather / pk.lookup (gather + one-hot
            select) / fused_lookup (gather-and-lane-select kernel);
  dedup:    packed.dedup_representatives alone (the sort-free
            segment-combine both scatter and fused modes share);
  apply:    the full sparse-adam update — dedup + scatter_apply's
            gather/update/scatter trips (xla) vs fused_dedup_apply's
            one-kernel pass;
  scatter:  pk.scatter_add (the raw write side, context).

Compare against the arithmetic floors: 213k rows x 512 B = 109 MB moved
twice (read + write) = ~0.27 ms at 819 GB/s IF the access were
sequential — the gap between that and the measured rate is random-access
row granularity, which no kernel formulation removes.

`--selftest` runs a tiny CPU configuration through every engine in
Pallas interpret mode and asserts the fused results against the xla
references — the `make test-sparse` gate that keeps this harness (and
the kernels it measures) runnable without a chip.

`--shard_map` (round 7) is the MULTI-DEVICE mode: tables shard their
storage blocks over the mesh's `model` axis and every fused kernel
dispatches per-shard bodies through shard_map
(ops/sparse_embedding.py "Sharded dispatch").  It tables ns/row AND
ns/row/shard (each shard owns 1/Nth of the touched rows — the number
that must hold flat as the mesh grows for the fused path to survive
scale-out).  `--shard_map --selftest` forces a 4-virtual-device CPU
mesh and asserts the sharded routes against the single-device xla
references in interpret mode — the `make test-compile` gate.

Usage: python scripts/exp_sparse_gather.py [n_ids] [vocab_rows]
       python scripts/exp_sparse_gather.py --shard_map [n_ids] [vocab]
       python scripts/exp_sparse_gather.py --selftest
       python scripts/exp_sparse_gather.py --shard_map --selftest
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

INNER = 32


def _time(fn, *args) -> float:
    import jax

    jit_fn = jax.jit(fn)

    def once():
        start = time.perf_counter()
        out = jit_fn(*args)
        np.asarray(jax.tree.leaves(out)[0].ravel()[0])
        return time.perf_counter() - start

    once()
    once()
    times = [once() for _ in range(5)]
    return sorted(times)[2] / INNER


def _loop(body):
    import jax
    import jax.numpy as jnp

    def fn(*args):
        def step(i, tot):
            return tot + body(i, *args)

        return jax.lax.fori_loop(0, INNER, step, jnp.float32(0))

    return fn


def _row(label: str, t: float, n_ids: int):
    print(f"{label:<20} {t * 1e3:7.3f} ms  {t / n_ids * 1e9:6.1f} ns/row",
          flush=True)


def main(n_ids: int, vocab: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from elasticdl_tpu.ops import sparse_embedding as ske
    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel import sparse_optim
    from elasticdl_tpu.parallel.packed import PackedSpec

    spec = PackedSpec(vocab, 16)  # dim 16: one row per 128-lane block
    rng = np.random.RandomState(0)
    # Generate directly in packed shape (a logical->packed relayout at
    # 26M rows crashes the TPU compiler — BASELINE.md dead ends).
    table = jnp.asarray(
        rng.rand(*spec.packed_shape).astype(np.float32)
    )
    ids = jnp.asarray(
        rng.randint(0, vocab, size=n_ids).astype(np.int32)
    )
    grads = jnp.asarray(rng.rand(n_ids, spec.dim).astype(np.float32))
    print(
        f"table {spec.packed_shape} ({table.nbytes / 2**30:.2f} GiB), "
        f"{n_ids} ids", flush=True,
    )

    # -- lookup engines --------------------------------------------------

    # 1. raw storage-row gather (what jnp.take lowers to).
    t = _time(
        _loop(lambda i, tb, ix: jnp.sum(jnp.take(tb, ix + i, axis=0))),
        table, ids // spec.rows_per_block,
    )
    _row("raw row gather:", t, n_ids)

    # 2. full packed lookup (gather + slot-select einsum) — what the
    # xla model path pays.
    t = _time(
        _loop(lambda i, tb, ix: jnp.sum(pk.lookup(spec, tb, ix + i))),
        table, ids,
    )
    _row("pk.lookup (xla):", t, n_ids)

    # 3. fused gather-and-lane-select kernel (the shipped engine).
    t = _time(
        _loop(
            lambda i, tb, ix: jnp.sum(ske.fused_lookup(spec, tb, ix + i))
        ),
        table, ids,
    )
    _row("fused_lookup:", t, n_ids)

    # 4. the round-3 Pallas scalar-prefetch one-row-per-step gather,
    # kept as the historical formulation floor probe: each step fetches
    # the aligned 8-row block CONTAINING the target row — 8x the useful
    # bytes, but the per-step rate measures what a one-row-per-grid-step
    # engine could ever achieve.
    def gather_kernel(ids_ref, rows_ref, out_ref):
        out_ref[...] = rows_ref[...].reshape(out_ref.shape)

    def pallas_gather(tb, block_ix):
        n = block_ix.shape[0]
        return pl.pallas_call(
            gather_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n,),
                in_specs=[
                    pl.BlockSpec(
                        (8, spec.block_width),
                        lambda i, ids_pref: (ids_pref[i], 0),
                    ),
                ],
                out_specs=pl.BlockSpec(
                    (1, 8, spec.block_width), lambda i, ids_pref: (i, 0, 0)
                ),
            ),
            out_shape=jax.ShapeDtypeStruct(
                (n, 8, spec.block_width), tb.dtype
            ),
        )(block_ix, tb)

    try:
        t = _time(
            _loop(lambda i, tb, ix: jnp.sum(pallas_gather(tb, ix + i))),
            table, ids // spec.rows_per_block // 8,
        )
        _row("pallas sp gather:", t, n_ids)
    except Exception as e:  # noqa: BLE001 — record the failure mode
        print(f"pallas sp gather:    FAILED ({type(e).__name__}: "
              f"{str(e)[:200]})", flush=True)

    # -- dedup + apply engines -------------------------------------------

    # 5. the sort-free segment-combine alone (shared by scatter + fused).
    t = _time(
        _loop(
            lambda i, ix, g: jnp.sum(
                pk.dedup_representatives(spec, ix + i, g)[1]
            )
        ),
        ids, grads,
    )
    _row("dedup (both):", t, n_ids)

    # 6/7. full sparse-adam apply: xla scatter path vs fused kernel.
    opt_x = sparse_optim.adam(0.001, mode="scatter",
                              bias_correction="global")
    opt_f = sparse_optim.adam(0.001, mode="fused",
                              bias_correction="global")
    slots = opt_x.init_slots(spec, table)

    def apply_body(opt):
        def body(i, tb, sl, ix, g):
            new_tb, new_sl = opt.apply(spec, tb, sl, ix + i, g)
            return jnp.sum(new_tb[0])

        return body

    t = _time(_loop(apply_body(opt_x)), table, slots, ids, grads)
    _row("adam apply (xla):", t, n_ids)
    t = _time(_loop(apply_body(opt_f)), table, slots, ids, grads)
    _row("adam apply (fused):", t, n_ids)

    # 8. grad scatter-add (the raw write side, context).
    t = _time(
        _loop(
            lambda i, tb, ix, g: jnp.sum(
                pk.scatter_add(spec, tb, ix + i, g)[0]
            )
        ),
        table, ids, grads,
    )
    _row("pk.scatter_add:", t, n_ids)

    bw_floor_ms = 2 * n_ids * spec.block_width * 4 / 819e9 * 1e3
    print(f"sequential-BW floor: {bw_floor_ms:7.3f} ms  "
          f"{bw_floor_ms / n_ids * 1e6:6.1f} ns/row", flush=True)


def _shard_mesh():
    """(mesh, n_shards) over every visible device: data=1, model=N —
    the fused multi-chip layout (tables block-shard over `model`)."""
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh

    n = len(jax.devices())
    return build_mesh(MeshConfig(data=1, model=n)), n


def main_shard_map(n_ids: int, vocab: int):
    """xla-vs-fused ns/row with the fused engines dispatched through
    shard_map over a multi-device mesh.  The per-shard column divides by
    the shard count: each model-axis shard owns 1/Nth of the touched
    rows, so flat ns/row/shard across mesh sizes is the scale-out win
    condition."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops import sparse_embedding as ske
    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel import sparse_optim
    from elasticdl_tpu.parallel.packed import PackedSpec

    mesh, n_shards = _shard_mesh()
    spec = PackedSpec(vocab, 16)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.rand(*spec.packed_shape).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, size=n_ids).astype(np.int32))
    grads = jnp.asarray(rng.rand(n_ids, spec.dim).astype(np.float32))
    print(
        f"table {spec.packed_shape} sharded over {n_shards} model-axis "
        f"shard(s), {n_ids} ids", flush=True,
    )

    def _row_per_shard(label, t):
        _row(label, t, n_ids)
        print(
            f"{'':<20} {'':>10}  "
            f"{t / (n_ids / n_shards) * 1e9:6.1f} ns/row/shard",
            flush=True,
        )

    t = _time(
        _loop(lambda i, tb, ix: jnp.sum(pk.lookup(spec, tb, ix + i))),
        table, ids,
    )
    _row("pk.lookup (xla):", t, n_ids)
    t = _time(
        _loop(
            lambda i, tb, ix: jnp.sum(
                ske.fused_lookup(spec, tb, ix + i, mesh=mesh)
            )
        ),
        table, ids,
    )
    _row_per_shard("fused_lookup (sm):", t)

    opt_x = sparse_optim.adam(0.001, mode="scatter",
                              bias_correction="global")
    opt_f = sparse_optim.adam(0.001, mode="fused",
                              bias_correction="global", mesh=mesh)
    slots = opt_x.init_slots(spec, table)

    def apply_body(opt):
        def body(i, tb, sl, ix, g):
            new_tb, _new_sl = opt.apply(spec, tb, sl, ix + i, g)
            return jnp.sum(new_tb[0])

        return body

    t = _time(_loop(apply_body(opt_x)), table, slots, ids, grads)
    _row("adam apply (xla):", t, n_ids)
    t = _time(_loop(apply_body(opt_f)), table, slots, ids, grads)
    _row_per_shard("adam apply (sm):", t)


def selftest_shard_map() -> int:
    """CPU interpret-mode gate of the SHARDED dispatch: on a forced
    4-virtual-device mesh, the shard_map'd fused lookup is bit-exact vs
    pk.lookup and the shard_map'd fused adam apply matches the xla
    scatter path within the documented 1-ulp tolerance."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops import sparse_embedding as ske
    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel import sparse_optim
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS
    from elasticdl_tpu.parallel.packed import PackedSpec

    mesh, n_shards = _shard_mesh()
    assert n_shards > 1, (
        "shard_map selftest needs >1 device (forced virtual CPUs)"
    )
    rng = np.random.RandomState(0)
    spec = PackedSpec(320, 16)
    assert ske.table_partition_axis(spec.num_blocks, mesh) == MODEL_AXIS
    table = jnp.asarray(rng.rand(*spec.packed_shape).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, 320, size=64).astype(np.int32))
    grads = jnp.asarray(rng.rand(64, spec.dim).astype(np.float32))

    ref = np.asarray(pk.lookup(spec, table, ids))
    got = np.asarray(ske.fused_lookup(spec, table, ids, mesh=mesh))
    assert np.array_equal(ref, got), "shard_map fused_lookup != pk.lookup"

    opt_x = sparse_optim.adam(0.001, mode="scatter")
    opt_f = sparse_optim.adam(0.001, mode="fused", mesh=mesh)
    slots = opt_x.init_slots(spec, table)
    tx, sx = opt_x.apply(spec, table, slots, ids, grads)
    tf, sf = opt_f.apply(spec, table, slots, ids, grads)
    np.testing.assert_allclose(
        np.asarray(tf), np.asarray(tx), rtol=3e-7, atol=1e-7,
        err_msg="shard_map fused adam table",
    )
    for key in sx:
        np.testing.assert_allclose(
            np.asarray(sf[key]), np.asarray(sx[key]), rtol=3e-7,
            atol=1e-7, err_msg=f"shard_map fused adam slot {key}",
        )
    print(
        f"exp_sparse_gather shard_map selftest OK ({n_shards}-shard "
        "mesh: fused lookup bit-exact, fused adam apply within 1 ulp, "
        "interpret mode)"
    )
    return 0


def selftest() -> int:
    """CPU interpret-mode gate: every engine this harness measures runs
    and the fused results match the xla references (bit-exact for the
    lookup — pure data movement — and within the documented 1-ulp FMA
    tolerance for the adam apply)."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops import sparse_embedding as ske
    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel import sparse_optim
    from elasticdl_tpu.parallel.packed import PackedSpec

    rng = np.random.RandomState(0)
    spec = PackedSpec(300, 16)
    table = jnp.asarray(rng.rand(*spec.packed_shape).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, 300, size=64).astype(np.int32))
    grads = jnp.asarray(rng.rand(64, spec.dim).astype(np.float32))

    ref = np.asarray(pk.lookup(spec, table, ids))
    got = np.asarray(ske.fused_lookup(spec, table, ids))
    assert np.array_equal(ref, got), "fused_lookup != pk.lookup"

    opt_x = sparse_optim.adam(0.001, mode="scatter")
    opt_f = sparse_optim.adam(0.001, mode="fused")
    slots = opt_x.init_slots(spec, table)
    tx, sx = opt_x.apply(spec, table, slots, ids, grads)
    tf, sf = opt_f.apply(spec, table, slots, ids, grads)
    np.testing.assert_allclose(
        np.asarray(tf), np.asarray(tx), rtol=3e-7, atol=1e-7,
        err_msg="fused adam table",
    )
    for key in sx:
        np.testing.assert_allclose(
            np.asarray(sf[key]), np.asarray(sx[key]), rtol=3e-7, atol=1e-7,
            err_msg=f"fused adam slot {key}",
        )
    print("exp_sparse_gather selftest OK "
          "(fused lookup + adam apply match xla, interpret mode)")
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("n_ids", nargs="?", type=int, default=212_992)
    parser.add_argument("vocab", nargs="?", type=int, default=26_000_000)
    parser.add_argument("--selftest", action="store_true")
    parser.add_argument(
        "--shard_map", action="store_true",
        help="multi-device mode: fused engines dispatched through "
        "shard_map over a (1, n_devices) mesh (ns/row per shard)",
    )
    args = parser.parse_args()
    if args.shard_map and args.selftest:
        # Force the virtual multi-device CPU world BEFORE jax's backend
        # initializes (the selftest must run on a 1-device CI box).
        from elasticdl_tpu.parallel.mesh import force_virtual_cpu_devices

        force_virtual_cpu_devices(4)
        sys.exit(selftest_shard_map())
    if args.selftest:
        sys.exit(selftest())
    if args.shard_map:
        main_shard_map(args.n_ids, args.vocab)
    else:
        main(args.n_ids, args.vocab)
