"""Perf experiment: can ANY engine beat the XLA row gather that bounds
the sparse embedding path?  (VERDICT round-3 #7, time-boxed.)

The 26M-row probe spends ~5.5 ms/step in lookup-gather + row ops and
~2.7 ms in the grad scatter — count-bound at ~25 ns per touched row
(BASELINE.md).  The only hypothesized path below that floor was a fused
Pallas lookup/scatter engine.  This harness measures, on the real chip:

  1. the raw XLA storage-row gather (pk.lookup minus the slot-select
     einsum) — the incumbent;
  2. full pk.lookup (gather + one-hot slot select) — what the model pays;
  3. a Pallas scalar-prefetch gather: grid over ids, each step DMAs one
     512 B storage row HBM->VMEM->HBM with the id stream scalar-prefetched
     so the pipeline emitter double-buffers the row fetches.  This is the
     idiomatic TPU formulation of a "coalesced DMA" gather (the round-3
     experiment issued EXPLICIT per-row async copies instead and measured
     a 0.3 us/row issue-bound floor);
  4. the packed grad scatter-add (pk.scatter_add) — the write side.

Compare against the arithmetic floors: 213k rows x 512 B = 109 MB moved
twice (read + write) = ~0.27 ms at 819 GB/s IF the access were
sequential — the gap between that and the measured rate is random-access
row granularity, which no kernel formulation removes.

Usage: python scripts/exp_sparse_gather.py [n_ids] [vocab_rows]
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

INNER = 32


def _time(fn, *args) -> float:
    import jax

    jit_fn = jax.jit(fn)

    def once():
        start = time.perf_counter()
        out = jit_fn(*args)
        np.asarray(jax.tree.leaves(out)[0].ravel()[0])
        return time.perf_counter() - start

    once()
    once()
    times = [once() for _ in range(5)]
    return sorted(times)[2] / INNER


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel.packed import PackedSpec

    n_ids = int(sys.argv[1]) if len(sys.argv) > 1 else 212_992
    vocab = int(sys.argv[2]) if len(sys.argv) > 2 else 26_000_000
    spec = PackedSpec(vocab, 16)  # dim 16: one row per 128-lane block
    rng = np.random.RandomState(0)
    # Generate directly in packed shape (a logical->packed relayout at
    # 26M rows crashes the TPU compiler — BASELINE.md dead ends).
    table = jnp.asarray(
        rng.rand(*spec.packed_shape).astype(np.float32)
    )
    ids = jnp.asarray(
        rng.randint(0, vocab, size=n_ids).astype(np.int32)
    )
    grads = jnp.asarray(rng.rand(n_ids, spec.dim).astype(np.float32))
    print(
        f"table {spec.packed_shape} ({table.nbytes / 2**30:.2f} GiB), "
        f"{n_ids} ids", flush=True,
    )

    def loop(body):
        def fn(*args):
            def step(i, tot):
                return tot + body(i, *args)

            return jax.lax.fori_loop(0, INNER, step, jnp.float32(0))

        return fn

    # 1. raw storage-row gather (what jnp.take lowers to).
    t = _time(
        loop(lambda i, tb, ix: jnp.sum(jnp.take(tb, ix + i, axis=0))),
        table, ids // spec.rows_per_block,
    )
    print(f"raw row gather:      {t * 1e3:7.3f} ms  "
          f"{t / n_ids * 1e9:6.1f} ns/row", flush=True)

    # 2. full packed lookup (gather + slot-select einsum).
    t = _time(
        loop(lambda i, tb, ix: jnp.sum(pk.lookup(spec, tb, ix + i))),
        table, ids,
    )
    print(f"pk.lookup:           {t * 1e3:7.3f} ms  "
          f"{t / n_ids * 1e9:6.1f} ns/row", flush=True)

    # 3. Pallas scalar-prefetch gather: one DMA per grid step, the id
    # stream scalar-prefetched so the pipeline emitter double-buffers
    # the fetches.  Pallas TPU requires (8, 128)-aligned blocks, so each
    # step fetches the aligned 8-row block CONTAINING the target row —
    # 8x the useful bytes, but the per-step rate measures exactly what a
    # one-row-per-step engine could ever achieve (a (1, 128) block is
    # not lowerable; the per-useful-row cost of this engine is the
    # per-step cost).
    def gather_kernel(ids_ref, rows_ref, out_ref):
        out_ref[...] = rows_ref[...].reshape(out_ref.shape)

    def pallas_gather(tb, block_ix):
        n = block_ix.shape[0]
        return pl.pallas_call(
            gather_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n,),
                in_specs=[
                    pl.BlockSpec(
                        (8, spec.block_width),
                        lambda i, ids_pref: (ids_pref[i], 0),
                    ),
                ],
                out_specs=pl.BlockSpec(
                    (1, 8, spec.block_width), lambda i, ids_pref: (i, 0, 0)
                ),
            ),
            out_shape=jax.ShapeDtypeStruct(
                (n, 8, spec.block_width), tb.dtype
            ),
        )(block_ix, tb)

    try:
        t = _time(
            loop(lambda i, tb, ix: jnp.sum(pallas_gather(tb, ix + i))),
            table, ids // spec.rows_per_block // 8,
        )
        print(f"pallas sp gather:    {t * 1e3:7.3f} ms  "
              f"{t / n_ids * 1e9:6.1f} ns/row", flush=True)
    except Exception as e:  # noqa: BLE001 — record the failure mode
        print(f"pallas sp gather:    FAILED ({type(e).__name__}: "
              f"{str(e)[:200]})", flush=True)

    # 4. grad scatter-add (the write side of the sparse path).
    t = _time(
        loop(
            lambda i, tb, ix, g: jnp.sum(
                pk.scatter_add(spec, tb, ix + i, g)[0]
            )
        ),
        table, ids, grads,
    )
    print(f"pk.scatter_add:      {t * 1e3:7.3f} ms  "
          f"{t / n_ids * 1e9:6.1f} ns/row", flush=True)

    bw_floor_ms = 2 * n_ids * spec.block_width * 4 / 819e9 * 1e3
    print(f"sequential-BW floor: {bw_floor_ms:7.3f} ms  "
          f"{bw_floor_ms / n_ids * 1e6:6.1f} ns/row", flush=True)


if __name__ == "__main__":
    main()
