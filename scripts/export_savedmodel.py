"""Convert an elasticdl_tpu serving artifact to a TensorFlow SavedModel.

The docs/design.md "Serving artifact" decision: this framework's native
export is a self-contained signature + streamed per-table memmaps
(serving/export.py) — no TF dependency on the serving side.  This tool
is the documented converter for operators with an existing TF-Serving
fleet (the reference's deployment path, †common/model_handler.py →
SavedModel): it wraps the artifact's forward function with
`jax.experimental.jax2tf`, stores every variable (embedding tables
included) as a `tf.Variable`, and writes a SavedModel whose
serving_default signature takes the model's named feature tensors with
a polymorphic batch dimension.

Parity contract: the SavedModel's outputs match the native
`ServingModel.predict` to float tolerance on the same inputs
(tests/test_savedmodel_export.py re-runs the test_serving parity case
through TF).

Scale caveat: `tf.Variable` materializes each packed table in host
memory during conversion (the native artifact streams; SavedModel's
variable format cannot).  Fine through tens of millions of rows; for
tables beyond host memory, serve the native artifact instead.

Usage:
    python scripts/export_savedmodel.py <artifact_dir> <out_dir> \
        [--model_zoo PATH] [--batch N]

`--batch` sets the example batch used to trace the conversion; the
saved signature itself is batch-polymorphic.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _example_features(serving_model, batch: int, model_zoo: str = ""):
    """Synthesize a feature pytree matching the model's input signature
    from the zoo module's synthetic reader (every zoo config has one).
    `model_zoo` overrides the artifact's recorded path (same contract as
    load_for_serving — artifacts move between machines)."""
    from elasticdl_tpu.common.model_utils import load_module

    sig = serving_model.signature
    module = load_module(
        model_zoo or sig["model_zoo"] or "model_zoo", sig["model_def"]
    )
    reader_fn = getattr(module, "custom_data_reader", None)
    if reader_fn is None:
        raise ValueError(
            f"{sig['model_def']} has no custom_data_reader to synthesize "
            "an example batch from; pass --sample <npz> instead"
        )
    reader = reader_fn(f"synthetic://sample?n={batch}")
    records = list(
        reader.read_records(type("T", (), {"start": 0, "end": batch}))
    )
    feats = [r[0] if isinstance(r, tuple) else r for r in records]
    if isinstance(feats[0], dict):
        return {
            key: np.stack([f[key] for f in feats]) for key in feats[0]
        }
    return np.stack(feats)


def convert(
    artifact_dir: str,
    out_dir: str,
    model_zoo: str = "",
    batch: int = 4,
    sample: str = "",
):
    import jax
    import tensorflow as tf
    from jax.experimental import jax2tf

    from elasticdl_tpu.serving import load_for_serving
    from elasticdl_tpu.worker.trainer import _model_apply

    served = load_for_serving(artifact_dir, model_zoo=model_zoo, mmap=True)
    if sample:
        loaded = np.load(sample)
        features = (
            {k: loaded[k] for k in loaded.files}
            if len(loaded.files) > 1
            else loaded[loaded.files[0]]
        )
    else:
        features = _example_features(served, batch, model_zoo=model_zoo)

    # Materialize variables (mmap'd packed tables included) as numpy —
    # tf.Variable needs concrete buffers.
    variables = jax.tree.map(np.asarray, served.variables)
    leaves, treedef = jax.tree.flatten(variables)
    model = served._model

    def forward(leaves_, feats):
        vars_ = jax.tree.unflatten(treedef, list(leaves_))
        outputs, _ = _model_apply(
            model, vars_, feats, train=False, mutable=False
        )
        return outputs

    def poly(leaf):
        trailing = ", ".join(str(d) for d in np.shape(leaf)[1:])
        return f"(b, {trailing})" if trailing else "(b,)"

    feat_poly = jax.tree.map(poly, features)
    tf_forward = jax2tf.convert(
        forward,
        polymorphic_shapes=[None, feat_poly],
        with_gradient=False,
    )

    class Servable(tf.Module):
        pass

    servable = Servable()
    servable.model_variables = [
        tf.Variable(leaf, trainable=False) for leaf in leaves
    ]

    def spec(leaf, name):
        # Named specs give the SavedModel signature the model's feature
        # names as its tensor kwargs (dense=..., cat=...).
        return tf.TensorSpec(
            (None,) + tuple(np.shape(leaf)[1:]), leaf.dtype, name=name
        )

    if isinstance(features, dict):
        input_signature = [
            {key: spec(value, key) for key, value in features.items()}
        ]
    else:
        input_signature = [spec(features, "input")]

    @tf.function(input_signature=input_signature)
    def serving_fn(feats):
        return {"outputs": tf_forward(servable.model_variables, feats)}

    servable.serving_fn = serving_fn
    tf.saved_model.save(
        servable, out_dir, signatures={"serving_default": serving_fn}
    )

    # Parity gate: the SavedModel must reproduce the native artifact's
    # predictions on the example batch before the conversion counts.
    reloaded = tf.saved_model.load(out_dir)
    tf_in = (
        {k: tf.constant(np.asarray(v)) for k, v in features.items()}
        if isinstance(features, dict)
        else {"input": tf.constant(np.asarray(features))}
    )
    got = reloaded.signatures["serving_default"](**tf_in)[
        "outputs"
    ].numpy()
    want = np.asarray(served.predict(features))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    print(
        f"SavedModel written to {out_dir} "
        f"(parity vs native artifact: max|diff| "
        f"{np.max(np.abs(got - want)):.3g} on batch {len(want)})"
    )
    return out_dir


def main():
    p = argparse.ArgumentParser()
    p.add_argument("artifact_dir")
    p.add_argument("out_dir")
    p.add_argument("--model_zoo", default="")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--sample", default="", help=".npz of example features")
    args = p.parse_args()
    convert(
        args.artifact_dir, args.out_dir,
        model_zoo=args.model_zoo, batch=args.batch, sample=args.sample,
    )


if __name__ == "__main__":
    main()
