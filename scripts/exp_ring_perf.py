"""Perf experiment: ring-attention per-step engines on the real chip.

Not part of the test suite — the measurement harness behind BASELINE.md's
"Ring-attention Pallas engine" table (round 3) and the round-4 carry-
fusion work (VERDICT #2).  Methodology: single-chip-equivalent A/B — the
per-device compute of ONE ring member, R sequential worst-case
(fully-unmasked) KV-block steps run inside one jit (RTT-amortized), bf16
inputs, H=8 D=128.  The ppermute transfers are deliberately absent: on
real multi-chip hardware they overlap the next step's compute under
XLA's scheduler; what this harness isolates is the per-step BLOCK-ENGINE
cost the VERDICT targets.

Usage:
    python scripts/exp_ring_perf.py fwd t2048_b4_xla t2048_b4_pallas
    python scripts/exp_ring_perf.py grad t2048_b4_pallas_bq1024
    python scripts/exp_ring_perf.py fwd profile_t2048_b4_pallas

Variant tokens (joined by `_`): tN = T_local, bN = batch,
xla|pallas = engine, bqN/bkN = kernel block sizes, rN = ring steps
(default 4), `profile` prefix captures a jax.profiler trace to
/tmp/ring_prof.
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

H, D = 8, 128
REPEATS = 5


def parse(spec: str):
    cfg = dict(t=2048, b=4, engine="pallas", bq=None, bk=None, r=4,
               profile=False, inner=INNER)
    for tok in spec.split("_"):
        if tok == "profile":
            cfg["profile"] = True
        elif tok in ("xla", "pallas"):
            cfg["engine"] = tok
        elif tok.startswith("bq"):
            cfg["bq"] = int(tok[2:])
        elif tok.startswith("i") and tok[1:].isdigit():
            cfg["inner"] = int(tok[1:])
        elif tok.startswith("bk"):
            cfg["bk"] = int(tok[2:])
        elif tok.startswith("t"):
            cfg["t"] = int(tok[1:])
        elif tok.startswith("b"):
            cfg["b"] = int(tok[1:])
        elif tok.startswith("r"):
            cfg["r"] = int(tok[1:])
        else:
            raise ValueError(f"unknown token {tok!r}")
    return cfg


def build_step_fn(cfg, mode):
    """fn(q, ks [R,...], vs [R,...]) -> scalar; R INDEPENDENT worst-case
    ring-step invocations, results summed.  Independent — not chained
    through the (acc, lse) carry — because on the tunneled backend a
    dependent-kernel chain serializes and reads ~5-10x slow (the
    carry-chain artifact in the repo's benchmarking notes); the real
    multi-chip ring overlaps each step with the next KV ppermute, which
    independent iterations model far better than an artificial serial
    chain.  This matches the round-3 table's methodology."""
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.ops.flash_attention import (
        NEG_INF,
        flash_ring_step_bwd,
        flash_ring_step_carry,
    )
    from elasticdl_tpu.parallel.ring_attention import (
        _attn_block,
        _finalize,
    )

    t, scale = cfg["t"], 1.0 / D ** 0.5
    kb = dict(causal=True, scale=scale)
    if cfg["bq"]:
        kb["block_q"] = cfg["bq"]
    if cfg["bk"]:
        kb["block_k"] = cfg["bk"]
    # Worst-case unmasked steps: q rows are globally LAST (positions in
    # the final T rows), every KV block earlier -> causal mask never
    # trims work, matching the round-3 table's "fully-unmasked" steps.
    q_pos = jnp.arange((cfg["r"]) * t, (cfg["r"] + 1) * t)
    k_pos_per_step = [jnp.arange(i * t, (i + 1) * t) for i in range(cfg["r"])]

    if cfg["engine"] == "pallas":

        def fwd(q, ks, vs):
            # KV arrive in KERNEL layout [R,B,H,T,D]: production rotates
            # KV pre-transposed (one transpose outside the ring scan,
            # round 4), so the per-step engine cost excludes relayout.
            qk = q.transpose(0, 2, 1, 3)
            acc0 = jnp.zeros(
                (cfg["r"],) + qk.shape, jnp.float32
            )
            lse0 = jnp.full(
                (cfg["r"],) + qk.shape[:3] + (1,), NEG_INF, jnp.float32
            )
            total = jnp.float32(0)
            for i in range(cfg["r"]):
                acc, lse = flash_ring_step_carry(
                    qk, ks[i], vs[i],
                    acc0[i], lse0[i], q_pos, k_pos_per_step[i], **kb,
                )
                total = total + jnp.sum(acc) + jnp.sum(lse)
            return total

        if mode == "fwd":
            return fwd

        def grad_fn(q, ks, vs):
            # R independent bwd-step invocations (the step kernels are
            # stateless by design: they take the FINAL lse/delta).
            qk = q.transpose(0, 2, 1, 3)
            do = jnp.ones_like(qk, jnp.float32)
            lse = jnp.zeros(qk.shape[:3] + (1,), jnp.float32)
            delta = jnp.zeros_like(lse)
            total = jnp.float32(0)
            for i in range(cfg["r"]):
                dq_i, dk_i, dv_i = flash_ring_step_bwd(
                    qk, ks[i], vs[i], do, lse, delta,
                    q_pos, k_pos_per_step[i], causal=True, scale=scale,
                )
                total = total + jnp.sum(dq_i) + jnp.sum(dk_i) + jnp.sum(dv_i)
            return total

        return grad_fn

    # XLA block engine: independent _attn_block invocations from fresh
    # (m, l, acc) — the same per-step work the ring's scan body does.
    def fwd_xla_step(q, k, v, k_pos):
        acc = jnp.zeros_like(q, jnp.float32)
        l = acc[..., 0].transpose(0, 2, 1)
        m = NEG_INF + l
        m, l, acc = _attn_block(
            q, k, v, scale, q_pos, k_pos, True, m, l, acc
        )
        return _finalize(m, l, acc, q.dtype)

    def fwd_xla(q, ks, vs):
        total = jnp.float32(0)
        for i in range(cfg["r"]):
            total = total + jnp.sum(
                fwd_xla_step(q, ks[i], vs[i], k_pos_per_step[i]).astype(
                    jnp.float32
                )
            )
        return total

    if mode == "fwd":
        return fwd_xla

    def grad_xla(q, ks, vs):
        dq, dks, dvs = jax.grad(fwd_xla, argnums=(0, 1, 2))(q, ks, vs)
        return (
            jnp.sum(dq.astype(jnp.float32))
            + jnp.sum(dks.astype(jnp.float32))
            + jnp.sum(dvs.astype(jnp.float32))
        )

    return grad_xla


INNER = 8  # step-group repetitions inside one jit — the per-dispatch
# host RTT over the tunnel (10-90 ms observed) would otherwise swamp the
# group cost being measured (repo benchmarking notes).


def run_variant(spec: str, mode: str):
    import jax
    import jax.numpy as jnp

    cfg = parse(spec)
    rng = np.random.RandomState(0)
    shape = (cfg["b"], cfg["t"], H, D)
    q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    ks = jnp.asarray(rng.randn(cfg["r"], *shape), jnp.bfloat16)
    vs = jnp.asarray(rng.randn(cfg["r"], *shape), jnp.bfloat16)
    if cfg["engine"] == "pallas":
        # Kernel layout, once, outside the timed region (see build_step_fn).
        ks = ks.transpose(0, 1, 3, 2, 4)
        vs = vs.transpose(0, 1, 3, 2, 4)

    group = build_step_fn(cfg, mode)

    def looped(q, ks, vs):
        # Outer repetitions are independent (an iteration-scaled q, no
        # carry into the attention inputs) so the device pipelines them;
        # a dependent chain serializes ~5-10x slow on this backend.
        def body(j, tot):
            return tot + group(q * (1 + 1e-6 * j), ks, vs)

        return jax.lax.fori_loop(0, cfg["inner"], body, jnp.float32(0))

    fn = jax.jit(looped)

    def once():
        start = time.perf_counter()
        out = fn(q, ks, vs)
        np.asarray(out)  # fence: device->host copy
        return time.perf_counter() - start

    once()
    once()
    if cfg["profile"]:
        with jax.profiler.trace("/tmp/ring_prof"):
            times = [once() for _ in range(3)]
    else:
        times = [once() for _ in range(REPEATS)]
    ms = sorted(times)[len(times) // 2] * 1e3 / cfg["inner"]
    print(
        f"{mode} {spec}: {ms:.2f} ms/group of {cfg['r']} steps "
        f"(per step {ms / cfg['r']:.2f})",
        flush=True,
    )
    return ms


def main():
    mode = sys.argv[1]
    assert mode in ("fwd", "grad")
    for spec in sys.argv[2:]:
        run_variant(spec, mode)


if __name__ == "__main__":
    main()
