"""Perf experiment: ResNet-50 step-time knobs on the real chip.

Not part of the test suite — a measurement harness for BASELINE.md numbers.
Usage: python scripts/exp_resnet_perf.py b512_w4 b512_w8_bf16in ...

Variant tokens (joined by `_`): bN = batch, wN = steps/window,
`bf16in` = stage images as bfloat16, `normf32` = f32 BN compute.
"""

from __future__ import annotations

import sys
import time

import jax.numpy as jnp
import ml_dtypes
import numpy as np


def run_variant(spec: str):
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.resnet50 import resnet50_subclass as zoo

    batch, steps, in_dtype, norm_dtype = 512, 4, np.float32, jnp.bfloat16
    for tok in spec.split("_"):
        if tok.startswith("b") and tok[1:].isdigit():
            batch = int(tok[1:])
        elif tok.startswith("w") and tok[1:].isdigit():
            steps = int(tok[1:])
        elif tok == "bf16in":
            in_dtype = ml_dtypes.bfloat16
        elif tok == "normf32":
            norm_dtype = jnp.float32
        else:
            raise SystemExit(f"unknown token {tok} in {spec}")

    model = zoo.ResNet50(dtype=jnp.bfloat16, norm_dtype=norm_dtype)
    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(model, zoo.loss, zoo.optimizer(), mesh)
    rng = np.random.RandomState(0)

    def make_batch():
        images = rng.rand(batch, 224, 224, 3).astype(in_dtype)
        labels = rng.randint(0, 1000, size=batch).astype(np.int32)
        return images, labels, np.ones((batch,), np.float32)

    window = trainer.stage_window([make_batch() for _ in range(steps)])

    def run():
        start = time.perf_counter()
        losses = trainer.train_window(window)
        np.asarray(losses)
        return time.perf_counter() - start

    run(); run()
    times = [run() for _ in range(5)]
    rates = sorted(batch * steps / t for t in times)
    med = rates[len(rates) // 2]
    spread = (rates[-1] - rates[0]) / med
    print(f"{spec}: {med:,.0f} img/s (spread {spread:.1%})", flush=True)


def main():
    for spec in sys.argv[1:] or ["b512_w4"]:
        try:
            run_variant(spec)
        except Exception as e:
            print(f"{spec}: FAILED {type(e).__name__}: {str(e)[:200]}",
                  flush=True)


if __name__ == "__main__":
    main()
