#!/usr/bin/env python
"""Bench regression gate: compare a ``bench.py`` run against the
recorded baseline, journal the verdict, fail loud on regressions.

    make bench-regress                      # runs `python bench.py`
    python scripts/bench_regress.py                 # same
    python scripts/bench_regress.py --input run.jsonl
    python scripts/bench_regress.py --selftest      # CPU-only gate test
    python scripts/bench_regress.py --synthetic regress   # exits 1

ROADMAP item 5's second half: perf becomes a *gated, journaled* signal
instead of a per-round ritual.  Each tracked metric of a bench run is
compared against BASELINE.md's recorded value (``bench.SELF_BASELINE``
— the single source both bench.py's ``vs_baseline`` field and this gate
read) within that metric's recorded run-to-run spread
(``ALLOWED_SPREAD`` below, transcribed from BASELINE.md's measured
spreads with a safety floor).  The result journals through the obs
plane as a schema-registered ``bench_regress`` event
(scripts/validate_journal.py) carrying per-metric verdicts, so every
future speed PR lands with its number attached and attributable.

Verdicts: ``ok`` (within spread), ``improved`` (above it — update
BASELINE.md!), ``regressed`` (below it — the gate exits non-zero).
Rows bench.py flags ``tracked: false`` (tunnel-weather-bound coupled
metrics) are reported but never gate.  ``--selftest`` exercises the
gate on synthetic bench output with no accelerator (the tier-1 path);
``--synthetic ok|regress`` drives the FULL pipeline on synthetic rows
so the exit-code contract itself is testable end to end.

Exit status: 0 = no tracked regression, 1 = regression (or a selftest
failure), 2 = usage / unparsable input.  Stdlib only (bench.py itself
needs jax, but --input/--selftest/--synthetic paths never import it).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# `python scripts/bench_regress.py` puts scripts/ (not the repo root) on
# sys.path; the gate needs the package (obs journal) and its sibling
# validate_journal either way it is invoked.
for _path in (REPO_ROOT, os.path.join(REPO_ROOT, "scripts")):
    if _path not in sys.path:
        sys.path.insert(0, _path)

#: Allowed relative shortfall per tracked metric before the gate trips:
#: BASELINE.md's recorded run-to-run spreads (device rows measure
#: 0.04-1 % — see the table and the "Steadiness" note) widened to a
#: floor that absorbs chip/tunnel weather without hiding a real
#: regression; host-pipeline rows ride a 1-core CI box that halves
#: under load, so their recorded spread is wider.
DEFAULT_ALLOWED_SPREAD = 0.05
ALLOWED_SPREAD: Dict[str, float] = {
    # Host-side rows: BASELINE.md records 60 % outlier windows on the
    # shared core (trimmed to ~2-15 % spread); gate at 15 %.
    "deepfm_e2e_host_pipeline_records_per_sec": 0.15,
    # Staged for the async staging engine row (round 8): emitted
    # tracked:false until a multi-core driver host replaces the
    # provisional sync-row anchor (on the 1-core CI box the parse pool
    # degenerates to one worker); host-side shared-core row, so the
    # host floor applies once it flips tracked.
    "deepfm_e2e_host_pipeline_async_records_per_sec": 0.15,
    "resnet50_e2e_host_pipeline_images_per_sec": 0.15,
    # 26M-row table rows recorded at 0.5-1.0 % spread; 5 % floor.
    "deepfm_26m_table_samples_per_sec_per_chip": 0.05,
    "deepfm_26m_strict_samples_per_sec_per_chip": 0.05,
    # Fused-kernel headline row: bench.py emits it tracked:false until
    # chip-verified (the flag, not this table, is what defers gating);
    # once the driver records a number and flips it tracked, it gates
    # at the device-row floor.
    "deepfm_train_fused_samples_per_sec_per_chip": 0.05,
    # Staged for the shard_map'd multi-chip fused row (round 7): also
    # emitted tracked:false until a real multi-chip driver run; the
    # entry here is ready for the flip.
    "deepfm_train_fused_multichip_samples_per_sec_per_chip": 0.05,
    # Staged for the serving-plane QPS row (round 13): emitted
    # tracked:false until a driver run replaces the provisional CI-host
    # anchor; host-side shared-core row, so the host floor applies.
    # deepfm_serve_p99_ms deliberately has NO entry: it is
    # lower-is-better and the ratio gate's direction would invert —
    # it lives in UNTRACKED below instead.
    "deepfm_serve_qps_per_replica": 0.15,
}

#: Metrics that never gate even when present (mirrors bench.py's
#: ``tracked: false`` rows — tunnel-H2D-bound coupled numbers).
UNTRACKED = frozenset(
    {
        "deepfm_e2e_samples_per_sec_per_chip",
        # Parse-pool scaling ratio: 1.0 by construction on the 1-core
        # CI host, so the ratio gate would be noise-gating the pool's
        # fixed overhead — permanently report-only; the async RATE row
        # above is the one that flips tracked with driver evidence.
        "deepfm_e2e_parse_pool_scaling_x",
        "resnet50_e2e_images_per_sec_per_chip",
        # Lower-is-better tail latency: the ratio gate reads shortfall
        # as value/baseline < 1-spread, which would treat a LATENCY
        # IMPROVEMENT as a regression — permanently report-only.
        "deepfm_serve_p99_ms",
        # Quality-plane math anchor (bench_deepfm_online_auc_window):
        # a synthetic fixed-separation scorer, so the value measures
        # the ledger's window math, never model quality — permanently
        # report-only.
        "deepfm_online_auc_window",
        "bench_backend_probe",
    }
)


def load_baseline() -> Dict[str, float]:
    """bench.py's SELF_BASELINE (the one recorded-value table BOTH the
    bench's vs_baseline field and this gate read), imported by path so
    the import never initializes jax."""
    path = os.path.join(REPO_ROOT, "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_baseline", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return dict(module.SELF_BASELINE)


def parse_rows(lines) -> List[dict]:
    """Metric rows out of a bench.py run's stdout (non-JSON lines —
    logging, mesh banners — skip silently)."""
    rows = []
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and "metric" in row and "value" in row:
            rows.append(row)
    return rows


def judge(rows: List[dict], baseline: Dict[str, float]) -> dict:
    """Per-metric verdicts + the run verdict.

    A metric gates iff it is baseline-recorded, not flagged untracked,
    and its row doesn't carry ``tracked: false``.  The reverse check
    also gates: every tracked baseline metric MUST appear in the run —
    a silently-dropped metric can never regress otherwise (the exact
    judge-reading-prose failure mode this gate exists to prevent)."""
    details = []
    regressed = improved = 0
    for row in rows:
        metric = row["metric"]
        if metric in UNTRACKED or metric not in baseline:
            continue
        tracked = row.get("tracked", True)
        allowed = ALLOWED_SPREAD.get(metric, DEFAULT_ALLOWED_SPREAD)
        ratio = float(row["value"]) / float(baseline[metric])
        if not tracked:
            verdict = "untracked"
        elif ratio < 1.0 - allowed:
            verdict = "regressed"
            regressed += 1
        elif ratio > 1.0 + allowed:
            verdict = "improved"
            improved += 1
        else:
            verdict = "ok"
        details.append(
            {
                "metric": metric,
                "value": float(row["value"]),
                "baseline": float(baseline[metric]),
                "ratio": round(ratio, 4),
                "allowed_spread": allowed,
                "spread": row.get("spread"),
                "verdict": verdict,
            }
        )
    seen = {row["metric"] for row in rows}
    missing = 0
    for metric in sorted(baseline):
        if metric in UNTRACKED or metric in seen:
            continue
        missing += 1
        details.append(
            {
                "metric": metric,
                "baseline": float(baseline[metric]),
                "verdict": "missing",
            }
        )
    return {
        "verdict": "regressed" if (regressed or missing) else "ok",
        "metrics_total": len(details),
        "regressed": regressed,
        "missing": missing,
        "improved": improved,
        "details": details,
    }


def journal_verdict(result: dict, journal_dir: str = "") -> dict:
    """Record the ``bench_regress`` event through the obs plane (and to
    ``<journal_dir>/events.jsonl`` when a directory is given).  The
    record is schema-checked against scripts/validate_journal.py BEFORE
    being trusted — a gate whose own audit trail drifts from the schema
    registry must fail itself."""
    from elasticdl_tpu import obs

    if journal_dir:
        obs.init_journal(journal_dir)
    record = obs.journal().record(
        "bench_regress",
        verdict=result["verdict"],
        metrics_total=result["metrics_total"],
        regressed=result["regressed"],
        missing=result.get("missing", 0),
        improved=result["improved"],
        bench_exit_code=result.get("bench_exit_code", 0),
        details=result["details"],
    )
    import validate_journal

    errors = validate_journal.validate_record(record)
    if errors:
        raise AssertionError(
            f"bench_regress journal record failed its own schema: {errors}"
        )
    return record


def render(result: dict) -> str:
    lines = []
    for detail in result["details"]:
        if detail["verdict"] == "missing":
            lines.append(
                f"  missing    {detail['metric']}: tracked in the "
                "baseline but never emitted by this run"
            )
            continue
        lines.append(
            f"  {detail['verdict']:<10} {detail['metric']}: "
            f"{detail['value']:,.1f} vs baseline "
            f"{detail['baseline']:,.1f} (ratio {detail['ratio']}, "
            f"allowed -{detail['allowed_spread'] * 100:.0f}%)"
        )
    lines.append(
        f"bench-regress: {result['verdict'].upper()} — "
        f"{result['metrics_total']} gated metric(s), "
        f"{result['regressed']} regressed, "
        f"{result.get('missing', 0)} missing, "
        f"{result['improved']} improved"
    )
    if result.get("bench_exit_code"):
        lines.append(
            f"  bench command itself exited "
            f"{result['bench_exit_code']} — the run is not trustworthy "
            "even where emitted rows look healthy"
        )
    if result["improved"] and not result["regressed"]:
        lines.append(
            "  (improvement beyond spread: update BASELINE.md + "
            "bench.SELF_BASELINE so the gain is locked in)"
        )
    return "\n".join(lines)


def synthetic_rows(kind: str, baseline: Dict[str, float]) -> List[dict]:
    """A fake bench run: every tracked metric at baseline, except under
    ``regress`` where the flagship drops far beyond any spread."""
    rows = []
    for metric, value in sorted(baseline.items()):
        if metric in UNTRACKED:
            continue
        rows.append(
            {"metric": metric, "value": value, "unit": "synthetic",
             "spread": 0.0}
        )
    if kind == "regress":
        rows[-1] = dict(rows[-1])
        rows[-1]["value"] = rows[-1]["value"] * 0.5  # far beyond spread
    return rows


def run_bench(cmd: str, timeout_s: int):
    """(stdout lines, exit code).  A non-zero bench exit FAILS the gate
    even when rows were emitted before the crash — a bench that died
    mid-run must not publish its partial output as a passing claim."""
    proc = subprocess.run(
        cmd, shell=True, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout_s,
    )
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(
            f"bench-regress: bench command {cmd!r} exited "
            f"{proc.returncode}", file=sys.stderr,
        )
    return proc.stdout.splitlines(), proc.returncode


def selftest() -> int:
    """The tier-1 gate over the gate: on synthetic output (no
    accelerator), a within-spread run passes, a beyond-spread regression
    trips — and the journaled event schema-validates either way."""
    baseline = load_baseline()
    good = judge(synthetic_rows("ok", baseline), baseline)
    bad = judge(synthetic_rows("regress", baseline), baseline)
    problems = []
    if good["verdict"] != "ok" or good["regressed"]:
        problems.append(f"within-spread run misjudged: {good['verdict']}")
    if not good["metrics_total"]:
        problems.append("no metrics gated — baseline table unreadable?")
    if bad["verdict"] != "regressed" or bad["regressed"] != 1:
        problems.append(
            f"beyond-spread regression not caught: {bad['verdict']} "
            f"({bad['regressed']} regressed)"
        )
    # Fail-closed checks: a tracked metric DROPPED from the run must
    # gate (a metric that stops being emitted can never regress
    # otherwise), and a crashed bench must not publish partial rows.
    dropped = judge(synthetic_rows("ok", baseline)[:-1], baseline)
    if dropped["verdict"] != "regressed" or dropped["missing"] != 1:
        problems.append(
            f"dropped tracked metric not caught: {dropped['verdict']} "
            f"({dropped['missing']} missing)"
        )
    crashed_lines, crashed_rc = run_bench(
        f"{sys.executable} -c \"import json; "
        "print(json.dumps({'metric': 'm', 'value': 1.0})); exit(3)\"",
        timeout_s=60,
    )
    if crashed_rc != 3 or not parse_rows(crashed_lines):
        problems.append("bench-crash harness misbehaved in selftest")
    with tempfile.TemporaryDirectory(prefix="bench_regress_self_") as tmp:
        record = journal_verdict(bad, journal_dir=tmp)
        if record.get("verdict") != "regressed":
            problems.append(f"journaled verdict wrong: {record}")
        import validate_journal

        journal_path = os.path.join(tmp, "events.jsonl")
        if not os.path.exists(journal_path):
            problems.append("bench_regress event never reached the journal")
        elif validate_journal.validate_file(journal_path):
            problems.append("journaled bench_regress file fails the schema")
    if problems:
        print("bench_regress selftest FAILED:", file=sys.stderr)
        for problem in problems:
            print(f"  {problem}", file=sys.stderr)
        return 1
    print(
        f"bench_regress selftest OK ({good['metrics_total']} gated "
        "metrics; synthetic regression trips, dropped-metric trips, "
        "crashed-bench rc propagates, journal schema-valid)"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Gate a bench.py run against BASELINE.md's recorded "
        "value±spread; journal a bench_regress event; exit non-zero on "
        "beyond-spread regressions.",
    )
    parser.add_argument(
        "--input", default="",
        help="read bench.py JSONL output from this file ('-' = stdin) "
        "instead of running the bench",
    )
    parser.add_argument(
        "--cmd", default=f"{sys.executable} bench.py",
        help="bench command to run when no --input is given",
    )
    parser.add_argument(
        "--timeout", type=int, default=3600,
        help="bench command timeout in seconds",
    )
    parser.add_argument(
        "--journal-dir", default="",
        help="also append the bench_regress event to "
        "<dir>/events.jsonl (e.g. the job's --tensorboard_log_dir)",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="exercise the gate on synthetic output (no accelerator)",
    )
    parser.add_argument(
        "--synthetic", choices=("ok", "regress"), default="",
        help="run the full pipeline on a synthetic bench run "
        "(exit-code contract test)",
    )
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    baseline = load_baseline()
    bench_rc = 0
    if args.synthetic:
        rows = synthetic_rows(args.synthetic, baseline)
    elif args.input == "-":
        rows = parse_rows(sys.stdin)
    elif args.input:
        try:
            with open(args.input, "r", encoding="utf-8") as f:
                rows = parse_rows(f)
        except OSError as exc:
            print(f"{args.input}: {exc}", file=sys.stderr)
            return 2
    else:
        lines, bench_rc = run_bench(args.cmd, args.timeout)
        rows = parse_rows(lines)
    if not rows:
        print(
            "bench-regress: no metric rows found — nothing gated "
            "(bench failed before emitting, or wrong --input?)",
            file=sys.stderr,
        )
        return 2
    result = judge(rows, baseline)
    if bench_rc:
        # Fail-closed: partial rows from a crashed bench never publish
        # as a passing perf claim.
        result["bench_exit_code"] = bench_rc
        result["verdict"] = "bench_error"
    journal_verdict(result, journal_dir=args.journal_dir)
    print(render(result))
    return 1 if result["verdict"] != "ok" else 0


if __name__ == "__main__":
    sys.exit(main())
