#!/usr/bin/env bash
# Regenerate the checked-in protobuf module from elasticdl.proto.
# Parity: the reference's scripts/gen_protobuf.sh (protoc for py + go);
# here only the Python codec is needed (gRPC servicer/stub glue is
# hand-written in elasticdl_tpu/proto/service.py to avoid a grpcio-tools
# build dependency).
set -euo pipefail
cd "$(dirname "$0")/.."
if ! command -v protoc >/dev/null 2>&1; then
    # No protoc on this box (the CI/dev container ships only the protobuf
    # runtime): additive schema changes go through the descriptor-patching
    # fallback instead.
    echo "protoc not found; falling back to scripts/regen_proto.py" >&2
    exec python scripts/regen_proto.py
fi
protoc --proto_path=elasticdl_tpu/proto \
       --python_out=elasticdl_tpu/proto \
       elasticdl_tpu/proto/elasticdl.proto
echo "regenerated elasticdl_tpu/proto/elasticdl_pb2.py"
