"""Benchmark entrypoint: prints ONE JSON line with the headline metric.

Headline: DeepFM (the BASELINE north-star, config 4) training throughput in
samples/sec/chip through the full ParameterServerStrategy step — sharded
embedding lookup, FM + deep tower, sparse scatter update — on whatever
accelerator is visible (the driver provides one real TPU chip).  The
reference publishes no numbers (BASELINE.md), so vs_baseline compares
against this framework's own recorded round-1 value.
"""

from __future__ import annotations

import json
import time

import numpy as np

# Self-established baselines (samples/sec/chip) recorded on the driver's
# TPU chip in round 1 (batch 8192, vocab 100k x 26 fields, adam); see
# BASELINE.md.
SELF_BASELINE = {
    "deepfm_train_samples_per_sec_per_chip": 87_639.0,
}


def bench_deepfm(batch_size: int = 8192, vocab: int = 100_000, steps: int = 30):
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=vocab),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    rng = np.random.RandomState(0)
    features = {
        "dense": rng.rand(batch_size, zoo.NUM_DENSE).astype(np.float32),
        "cat": rng.randint(
            0, vocab, size=(batch_size, zoo.NUM_CAT)
        ).astype(np.int32),
    }
    labels = rng.randint(0, 2, size=batch_size).astype(np.int32)

    # Warmup / compile.
    loss = trainer.train_step(features, labels)
    jax.block_until_ready(loss)

    start = time.perf_counter()
    for _ in range(steps):
        loss = trainer.train_step(features, labels)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start
    n_chips = max(1, len(jax.devices()))
    return batch_size * steps / elapsed / n_chips


def main():
    samples_per_sec = bench_deepfm()
    metric = "deepfm_train_samples_per_sec_per_chip"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(
                    samples_per_sec / SELF_BASELINE[metric], 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
