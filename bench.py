"""Benchmark entrypoint: one JSON line per headline metric.

Measured on whatever accelerator is visible (the driver provides one
real TPU chip), ten metrics:

- `transformer_lm_tokens_per_sec_per_chip` (net-new long-context scope):
  causal-LM train step, T=2048, Pallas flash-attention kernel.
- `resnet50_images_per_sec_per_chip` (config 5): ResNet-50 ImageNet
  train step (bf16 convs + BN compute, f32 stats/params) through the
  AllReduce-mode DataParallelTrainer.
- `resnet50_e2e_host_pipeline_images_per_sec` +
  `resnet50_e2e_images_per_sec_per_chip` (round 5): the vision data
  plane — ETRF uint8 image records -> view parse -> crop/flip -> uint8
  staging -> train_window (the coupled row is tunnel-bound here,
  tracked=false).
- `ring_attention_tokens_per_sec_per_chip`: the context-parallel path's
  Pallas per-step block engine (round 4).
- `deepfm_e2e_host_pipeline_records_per_sec` +
  `deepfm_e2e_samples_per_sec_per_chip`: the production data-to-device
  pipeline (the coupled number is tunnel-bound here, tracked=false).
- `deepfm_e2e_host_pipeline_async_records_per_sec` +
  `deepfm_e2e_parse_pool_scaling_x` (round 8): the SAME host pipeline
  through the async staging engine (data/pipeline.ParsePool fanning
  parse_buffer over host cores at a 16 MB chunk budget), plus the
  pool-vs-chunked-serial scaling ratio.  Both degenerate on the 1-core
  CI host (pool of one), so they emit tracked:false until a multi-core
  driver host records them.
- `deepfm_26m_table_samples_per_sec_per_chip`: the north-star TABLE
  scale (26M resident rows, windowed sparse apply W=32 — the
  convergence-validated large-table config).
- `deepfm_26m_strict_samples_per_sec_per_chip`: strict per-step apply
  at the same 26M scale (the golden contract under the auto split
  layout — tracked from round 5).
- `deepfm_train_fused_samples_per_sec_per_chip` (round 6): the
  headline config on the fused Pallas sparse kernels
  (`--sparse_kernel=fused`, ops/sparse_embedding.py) — tracked:false
  until the first driver measurement (BASELINE.md queued chip work).
- `deepfm_train_fused_multichip_samples_per_sec_per_chip` (round 7):
  the same fused config dispatched through shard_map over EVERY
  visible device (tables block-sharded over `model`) — per-chip rate,
  tracked:false until multi-chip driver evidence; the scale-out
  survival row of the fused win.
- `deepfm_train_samples_per_sec_per_chip` (config 4, printed LAST — the
  flagship headline, strict per-step golden contract): full
  ParameterServerStrategy step — packed sharded embedding lookup, FM +
  deep tower, streaming sparse-Adam.  The final line also carries an
  `all: {metric: row, ...}` field with every metric of the run, so the
  driver's BENCH artifact (which preserves only the parsed final line)
  reconstructs the whole round.

Every row carries a roofline field (mfu vs the 197 TF/s v5e bf16 peak,
bw_frac vs 819 GB/s HBM, or ns-per-row vs the measured 25 ns/row sparse
floor) so drift vs silicon is visible, not just drift vs last round.

The reference publishes no numbers (BASELINE.md), so vs_baseline compares
against this framework's own recorded round-1 values (resnet50 had no
round-1 measurement; its vs_baseline is against the round-2 recorded
baseline once set).

Methodology (round-2 steadiness fixes, VERDICT weak #1):
- distinct pre-generated batches staged to the device as stacked windows
  (trainer.stage_window) OUTSIDE the timed region, then timed via
  trainer.train_window — K compiled train steps per dispatch (lax.scan).
  Staging is excluded because this harness reaches the chip over a
  tunnel whose host->device path is both slow (~25-70 ms/MB) and wildly
  variable (3x run-to-run) — it would swamp and randomize the framework
  number being measured.  BASELINE.md records the separately-measured
  staging cost and the production prefetch path.
- TWO warmup windows (compile + first-touch, then post-compile
  caches/power settle — the first post-compile window is consistently
  the slow outlier), then `repeats` timed windows replaying one staged
  window (within-window batch variety is high — hundreds of distinct
  batches — and staging dominates bench wall time over the tunnel);
- reports the MEDIAN window and the max relative spread across windows,
  so a wobbly host shows up as spread instead of silently moving the
  headline.
"""

from __future__ import annotations

import json
import shutil
import time

import numpy as np


def _median_spread(times, work_per_run):
    """Median rate + min-max relative spread over timed runs (the shared
    steadiness methodology — see the module docstring)."""
    rates = sorted(work_per_run / t for t in times)
    median = rates[len(rates) // 2]
    return median, (rates[-1] - rates[0]) / median


def _trimmed_median_spread(times, work_per_run):
    """_median_spread over the timed runs with the single fastest and
    slowest dropped.  For HOST-side measurements on the 1-core CI
    machine: a background process landing inside one repeat produced
    60% min-max spreads (BENCH_r03 host-pipeline row, VERDICT round-3
    weak #4) that said nothing about the pipeline; trimming one outlier
    each side restores a regression-detecting spread while the median
    stays honest.  Device-side metrics keep the untrimmed spread."""
    assert len(times) >= 5, "trimming needs >= 5 repeats"
    return _median_spread(sorted(times)[1:-1], work_per_run)

# Self-established baselines (samples/sec/chip) recorded on the driver's
# TPU chip; see BASELINE.md. Round 1: 87,639 (column-major tables, sorted
# dedup adam). Round 2 rebuilt the embedding engine (packed layout +
# streaming adam).
SELF_BASELINE = {
    "deepfm_train_samples_per_sec_per_chip": 87_639.0,
    # Fused Pallas sparse kernels at the headline config (round 6, code
    # complete; chip number queued — BASELINE.md).  PROVISIONAL anchor =
    # the round-4 xla-strict measurement of the SAME config, so
    # vs_baseline reads directly as the fused-vs-incumbent speedup; the
    # row stays tracked:false until a driver bench verifies it.
    "deepfm_train_fused_samples_per_sec_per_chip": 972_913.0,
    # Fused kernels dispatched through shard_map over every visible
    # device (round 7, tables block-sharded over `model`): per-chip
    # throughput against the same provisional xla-strict anchor, so
    # vs_baseline ~1.0 means the fused win SURVIVES scale-out.
    # tracked:false until a multi-chip driver run records evidence.
    "deepfm_train_fused_multichip_samples_per_sec_per_chip": 972_913.0,
    # The production data plane, file -> device-ready batches, one host
    # core (first measured round 3; the coupled e2e number is tracked
    # with a wide documented spread — tunnel-transfer-bound, BASELINE.md
    # "End-to-end pipeline" section).
    "deepfm_e2e_host_pipeline_records_per_sec": 990_000.0,
    # Async staging engine (round 8, PROVISIONAL): the same host
    # pipeline with parse_buffer fanned over data/pipeline.ParsePool at
    # a 16 MB chunk budget.  Anchor = the sync row's recorded rate, so
    # vs_baseline reads directly as the async-vs-sync speedup; on the
    # 1-core CI box the pool degenerates to one worker, so the row
    # emits tracked:false until a multi-core driver host measures it.
    "deepfm_e2e_host_pipeline_async_records_per_sec": 990_000.0,
    # (deepfm_e2e_parse_pool_scaling_x carries NO baseline entry on
    # purpose: it is a ratio, not an anchored rate — 1.0 by
    # construction on one core, permanently report-only in
    # scripts/bench_regress.py UNTRACKED, and SELF_BASELINE's contract
    # is "every entry has a roofline anchor" (tests/test_bench_meta.py).)
    # Tunnel-transfer-bound: observed 165k-330k across runs (H2D weather,
    # see BASELINE.md) — baseline is the observed midpoint and vs_baseline
    # swings with the recorded spread, by design.
    "deepfm_e2e_samples_per_sec_per_chip": 250_000.0,
    # North-star table scale (BASELINE.json: Criteo-1TB rows on chip):
    # vocab 1M x 26 fields = 26M resident rows.  Round-2 measured 192,513
    # samples/s here (the streaming sparse-adam cliff, VERDICT round 2
    # item #1); vs_baseline tracks the recovery against that number.
    "deepfm_26m_table_samples_per_sec_per_chip": 192_513.0,
    # Strict per-step semantics at the 26M table scale (round-4 recovery:
    # auto split layout + global bias, BASELINE.md table-scale probe).
    # Tracked from round 5 (VERDICT round-4 weak #4: the round-3
    # 192k->157k strict regression was caught by a judge reading prose,
    # not by the bench); vs_baseline tracks the round-4 measurement.
    "deepfm_26m_strict_samples_per_sec_per_chip": 272_953.0,
    # Online serving plane (round 13, PROVISIONAL): per-replica request
    # throughput and client-observed p99 through the exported-artifact ->
    # ServingReplica -> MicroBatcher path, closed loop of 8 clients at 8
    # rows/request.  Anchors are the first CI-host (CPU) harness
    # measurement — no chip number exists yet; both rows are emitted
    # tracked:false (and the p99 row must STAY untracked: lower-is-
    # better inverts the regression gate's ratio direction).
    "deepfm_serve_qps_per_replica": 12_479.0,
    "deepfm_serve_p99_ms": 1.0,
    # First measured in round 2 (no earlier number exists); vs_baseline
    # therefore tracks drift against the round-2 recording in BASELINE.md.
    "resnet50_images_per_sec_per_chip": 1_524.0,
    # The vision data plane, file -> staged uint8 batches, one host core
    # (first measured round 5: 2,464 img/s on an idle CI host after the
    # size-dispatched CRC (zlib >= 512 B payloads), no-copy parse, fused
    # permute+crop+in-loop-flip, and whole-task single-chunk reads —
    # BASELINE.md image data plane section; halves under heavy
    # concurrent load on the 1-core box).
    "resnet50_e2e_host_pipeline_images_per_sec": 2_464.0,
    # Coupled file->device rate. PROVISIONAL: the tunnel was down for
    # the whole round-5 build window, so no chip measurement exists yet;
    # vs_baseline is meaningful from the first driver bench run.
    # Tunnel-transfer-bound like the deepfm coupled row (untracked).
    "resnet50_e2e_images_per_sec_per_chip": 1_000.0,
    # Net-new scope (no reference counterpart, BASELINE.md long-context
    # section): Pallas flash-attention transformer LM, recorded round 2
    # at batch_size=8.  The shipped default is now batch_size=16 (~245k);
    # the bench runs B=16, so expect a standing ~+1.5% vs_baseline offset
    # (config drift, not regression — see BASELINE.md).
    "transformer_lm_tokens_per_sec_per_chip": 241_046.0,
    # Ring-attention per-step engine (round 4, BASELINE.md ring table):
    # block-attended q-tokens/s through 4 worst-case ring steps (fwd +
    # full bwd, Pallas step kernels, T_local=2048 B=4 H=8 D=128) —
    # tracks the kernel engine the context-parallel path runs on, which
    # until round 4 was only manually tabled.  Work per group =
    # B x T_local x R q-block-attends; baseline recorded at the bench's
    # own config (inner=32; spread 0.4%).  The deeper-amortized research
    # numbers (inner=64-128, BASELINE.md) run ~13% higher — the delta is
    # residual per-dispatch RTT, constant across rounds at fixed inner.
    "ring_attention_tokens_per_sec_per_chip": 1_977_558.0,
}


def bench_deepfm(
    batch_size: int = 8192,
    vocab: int = 100_000,
    steps_per_window: int = 800,  # amortizes per-dispatch host gap: 40
    repeats: int = 5,             # -> 668k, 400 -> 827k, 800 -> 839k
    embedding_optimizer=None,
    sparse_apply_every: int = 1,
    sparse_kernel=None,
    mesh_config=None,
):
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(mesh_config or MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        # The model's per-mode table layout must see the SAME apply mode
        # AND kernel the trainer runs (merged table under windowed apply
        # or the fused kernels, split under strict-xla at >10M rows —
        # model_zoo/deepfm SPLIT_TABLE_ROWS), and the mesh routes the
        # fused kernels' dispatch (shard_map on multi-device).
        zoo.custom_model(
            vocab_size=vocab, sparse_apply_every=sparse_apply_every,
            sparse_kernel=sparse_kernel, mesh=mesh,
        ),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=embedding_optimizer or zoo.embedding_optimizer(),
        sparse_apply_every=sparse_apply_every,
        sparse_kernel=sparse_kernel,
    )
    rng = np.random.RandomState(0)

    def make_batch():
        features = {
            "dense": rng.rand(batch_size, zoo.NUM_DENSE).astype(np.float32),
            "cat": rng.randint(
                0, vocab, size=(batch_size, zoo.NUM_CAT)
            ).astype(np.int32),
        }
        labels = rng.randint(0, 2, size=batch_size).astype(np.int32)
        mask = np.ones((batch_size,), np.float32)
        return features, labels, mask

    first = make_batch()
    trainer.ensure_initialized(first[0])
    # ONE device-resident window: at 800 distinct batches (170M id draws
    # over a 2.6M-row id space) the id pattern within a single window is
    # already far beyond any cache's reach, so replaying it across timed
    # windows costs nothing in realism — and halving the staged bytes
    # keeps the driver's bench wall time bounded (the tunnel's H2D path
    # is the slow part; see the methodology note).
    window = trainer.stage_window(
        [make_batch() for _ in range(steps_per_window)]
    )

    def run_window() -> float:
        start = time.perf_counter()
        losses = trainer.train_window(window)
        # Force with a device->host COPY, not block_until_ready: on the
        # tunneled backend block_until_ready has been observed to return
        # milliseconds into a multi-hundred-ms program (both on single
        # leaves and whole pytrees); materializing the losses on host
        # cannot lie — the program must have finished to produce them.
        host_losses = np.asarray(losses)
        assert np.isfinite(host_losses).all()
        return time.perf_counter() - start

    run_window()  # warmup: compile + first-touch
    run_window()  # second warmup: post-compile caches/power settle
    times = [run_window() for _ in range(repeats)]
    median, spread = _median_spread(times, batch_size * steps_per_window)
    n_chips = max(1, len(jax.devices()))
    return median / n_chips, spread


def bench_deepfm_fused():
    """The headline config (strict per-step, 2.6M rows) on the FUSED
    Pallas sparse kernels (--sparse_kernel=fused, ops/sparse_embedding):
    gather-and-lane-select lookup, one-pass dedup+apply, and the
    DeepFM FM-interaction kernel — the ROADMAP-4 attack on the
    `bound: sparse-row-count` wall.  Emitted tracked:false until a
    driver run verifies the number on the chip (BASELINE.md round-6
    queued chip work); the provisional baseline is the xla-strict
    round-4 measurement, so vs_baseline > 1.0 IS the fused speedup."""
    return bench_deepfm(sparse_kernel="fused")


def bench_deepfm_fused_multichip():
    """The fused headline config with the kernels dispatched through
    shard_map over EVERY visible device (round 7: the multi-chip fused
    path — tables block-shard over the mesh's `model` axis, ids route
    to their owning shard, combine is a psum;
    ops/sparse_embedding.py "Sharded dispatch").  On a single-device
    host this degenerates to the single-chip fused number (the
    `devices` field says which was measured); the row stays
    tracked:false until a real multi-chip driver run records the
    per-chip evidence (BASELINE.md queued chip work)."""
    import jax

    from elasticdl_tpu.parallel import MeshConfig

    n = max(1, len(jax.devices()))
    return bench_deepfm(
        sparse_kernel="fused", mesh_config=MeshConfig(data=1, model=n)
    )


def bench_deepfm_online_auc_window(
    rows: int = 256, batches: int = 4, rounds: int = 5, vocab: int = 1000,
):
    """Windowed online AUC through the REAL label-join path: synthetic
    click batches scored by a deterministic fixed-separation scorer,
    predictions noted into a QualityLedger keyed by trace id, delayed
    labels (the training stream's pure click_label_rule) joined against
    them, and the windowed rank-based AUC read off the ledger snapshot.
    The row anchors the ledger's window math in the bench artifact —
    join bookkeeping plus online==offline AUC — NOT model quality, so
    it stays tracked:false (scripts/bench_regress.py UNTRACKED)."""
    from elasticdl_tpu.data.stream import (
        click_label_rule,
        synthetic_click_batch,
    )
    from elasticdl_tpu.obs.quality import QualityLedger

    values = []
    for r in range(rounds):
        ledger = QualityLedger(
            window_size=rows * batches, join_window_s=60.0
        )
        rng = np.random.RandomState(17 + r)
        for b in range(batches):
            lo = r * 100_000 + b * rows
            feats = synthetic_click_batch(lo, lo + rows, vocab)
            labels = click_label_rule(feats)
            preds = np.clip(
                0.5 + 0.25 * (2.0 * labels - 1.0)
                + 0.3 * rng.randn(rows),
                1e-3, 1.0 - 1e-3,
            ).astype(np.float32)
            trace_id = f"bench-{r}-{b}"
            ledger.note_prediction(trace_id, preds, now=float(b))
            ledger.note_label(trace_id, labels, now=float(b) + 0.5)
        snapshot = ledger.snapshot()
        assert snapshot["joined"] == rows * batches, snapshot
        values.append(float(snapshot["auc"]))
    return float(np.mean(values)), float(np.max(values) - np.min(values))


def bench_deepfm_serve(
    vocab: int = 100_000,
    request_rows: int = 8,
    requests_per_round: int = 200,
    rounds: int = 5,
    concurrency: int = 8,
    max_batch_size: int = 64,
):
    """Per-replica serving throughput + client-observed tail latency
    through the REAL online path: exported artifact -> ServingReplica
    (CompilePlan'd serve_step) -> MicroBatcher (padded power-of-two
    buckets under a 2 ms budget), driven by a closed loop of
    `concurrency` clients issuing `request_rows`-row requests
    back-to-back (in-process — the gRPC hop is deliberately excluded so
    the row tracks the compute path, not loopback weather).  QPS counts
    served REQUESTS for one replica; p99 includes queueing + batching +
    execute.  p99 is LOWER-is-better — the regression gate's ratio
    direction assumes higher-is-better, so that row must stay
    tracked:false even after a chip anchor lands (bench_regress.py)."""
    import shutil
    import tempfile
    import threading

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from elasticdl_tpu.serving.batcher import BatcherConfig, MicroBatcher
    from elasticdl_tpu.serving.export import export_model
    from elasticdl_tpu.serving.runtime import ServingReplica
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=vocab),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    rng = np.random.RandomState(0)

    def make_features(rows):
        return {
            "dense": rng.rand(rows, zoo.NUM_DENSE).astype(np.float32),
            "cat": rng.randint(
                0, vocab, size=(rows, zoo.NUM_CAT)
            ).astype(np.int32),
        }

    trainer.ensure_initialized(make_features(request_rows))
    model_dir = tempfile.mkdtemp(prefix="bench_serve_")
    try:
        export_model(
            trainer, model_dir,
            model_zoo="model_zoo",
            model_def="deepfm.deepfm_functional_api",
            model_params=f"vocab_size={vocab}",
        )
        replica = ServingReplica(model_dir, model_zoo="model_zoo")
        batcher = MicroBatcher(
            replica.execute,
            BatcherConfig(max_batch_size=max_batch_size, max_wait_us=2000,
                          queue_limit=512),
        ).start()
        try:
            replica.warmup(make_features(1), batcher.buckets)
            pool = [make_features(request_rows) for _ in range(64)]

            def run_round():
                latencies = []
                lat_lock = threading.Lock()

                def client(w):
                    for i in range(w, requests_per_round, concurrency):
                        t0 = time.perf_counter()
                        batcher.predict(pool[i % len(pool)])
                        dt = time.perf_counter() - t0
                        with lat_lock:
                            latencies.append(dt)

                threads = [
                    threading.Thread(target=client, args=(w,),
                                     name=f"bench-serve-{w}", daemon=True)
                    for w in range(concurrency)
                ]
                start = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                elapsed = time.perf_counter() - start
                latencies.sort()
                p99 = latencies[min(len(latencies) - 1,
                                    int(round(0.99 * (len(latencies) - 1))))]
                return elapsed, p99 * 1e3

            run_round()  # warmup the full concurrent path
            measured = [run_round() for _ in range(rounds)]
            qps, qps_spread = _median_spread(
                [elapsed for elapsed, _ in measured], requests_per_round
            )
            p99s = sorted(p99 for _, p99 in measured)
            p99_median = p99s[len(p99s) // 2]
            p99_spread = (p99s[-1] - p99s[0]) / p99_median
            return qps, qps_spread, p99_median, p99_spread
        finally:
            batcher.stop()
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)


def bench_deepfm_table_scale():
    """DeepFM at the NORTH-STAR table scale (BASELINE.json: 26M+ hot rows)
    in the production-recommended large-table configuration:
    --sparse_apply_every=32 (one windowed sparse apply per 32 steps — the
    reference's async-PS staleness contract, see ps_trainer) and adam
    bias_correction='global' (what the reference's Go Adam does).

    W=32 is the round-4 "largest safe W": the convergence A/B measured
    its peak held-out AUC WITHIN NOISE of the strict golden anchor at
    both 2.6M rows (0.7351 vs 0.7352) and the true 26M scale (0.7346 vs
    strict-global 0.7281 — nominally above, but single-seed differences
    of this size carry no ordering claim; round-5 seed replication in
    BASELINE.md), with the measurable cost confined to first-epoch
    warmup — see BASELINE.md "Windowed-apply convergence".
    Strict per-step semantics at this scale are benchmarked in
    BASELINE.md's table-scale probe table; the headline `bench_deepfm`
    stays strict."""
    from elasticdl_tpu.parallel import sparse_optim

    return bench_deepfm(
        vocab=1_000_000,  # x 26 fields = 26M resident rows on the chip
        steps_per_window=96,
        repeats=3,
        embedding_optimizer=sparse_optim.adam(
            0.001, bias_correction="global"
        ),
        sparse_apply_every=32,
    )


def bench_deepfm_table_scale_strict():
    """Strict per-step apply (`--sparse_apply_every=1`, the golden
    contract) at the same 26M-row scale — the round-4 split-layout
    recovery (157k -> 273k, BASELINE.md table-scale probe).  Tracked
    from round 5 so a strict-mode regression at north-star scale trips
    the bench instead of relying on prose (VERDICT round-4 weak #4).
    DeepFM's per-mode layout auto-splits the merged table here
    (SPLIT_TABLE_ROWS); global bias because strict per-row `t` slots
    exceed HBM at this scale outright."""
    from elasticdl_tpu.parallel import sparse_optim

    return bench_deepfm(
        vocab=1_000_000,
        steps_per_window=96,
        repeats=3,
        embedding_optimizer=sparse_optim.adam(
            0.001, bias_correction="global"
        ),
        sparse_apply_every=1,
    )


def _write_criteo_etrf(path: str, n: int, vocab: int, seed: int = 0):
    """Vectorized ETRF generation (bench fixture, excluded from timing):
    build the fixed-width record image columnar-side and split to rows."""
    from elasticdl_tpu.data import recordfile
    from model_zoo.deepfm import deepfm_functional_api as zoo

    rng = np.random.RandomState(seed)
    dense = rng.rand(n, zoo.NUM_DENSE).astype(np.float32)
    cat = rng.randint(0, vocab, size=(n, zoo.NUM_CAT)).astype(np.int32)
    label = rng.randint(0, 2, size=(n, 1)).astype(np.uint8)
    buf = np.concatenate(
        [
            np.ascontiguousarray(dense).view(np.uint8),
            np.ascontiguousarray(cat).view(np.uint8),
            label,
        ],
        axis=1,
    )
    recordfile.write_records(path, (row.tobytes() for row in buf))


def bench_deepfm_e2e(
    batch_size: int = 8192,
    vocab: int = 100_000,
    steps_per_window: int = 96,
    repeats: int = 3,
):
    """The PRODUCTION data-to-device pipeline, timed as one loop: ETRF
    file -> read_range_buffers -> RecordLayout.parse_buffer ->
    columnar_dataset_fn (vectorized shuffle) -> row-view batches ->
    stage_window -> train_window.  Unlike the synthetic benches, every
    timed window INCLUDES reading + parsing + batch assembly + the
    host->device transfer — the integrated hot loop of the reference's
    worker (SURVEY §3.3, †worker/worker.py task loop over †data/reader/).
    On this harness the transfer rides a tunnel (~25-70 ms/MB, 3x
    run-to-run — BASELINE.md methodology note), so the coupled number is
    transfer-bound; BASELINE.md records the host-pipeline-only rate
    alongside."""
    import tempfile

    n = batch_size * steps_per_window
    tmp = tempfile.mkdtemp(prefix="bench_e2e_")
    try:
        return _bench_deepfm_e2e_body(
            tmp, n, batch_size, vocab, steps_per_window, repeats
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_deepfm_e2e_body(tmp, n, batch_size, vocab, steps_per_window, repeats):
    import jax

    from elasticdl_tpu.data.columnar import materialize_columnar_task
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    path = f"{tmp}/criteo.etrf"
    _write_criteo_etrf(path, n, vocab)

    reader = zoo.CriteoRecordReader(path)

    class _Task:
        start, end = 0, n

    mask = np.ones((batch_size,), np.float32)

    def host_pipeline():
        """File -> staged-window-ready batch list (all host work)."""
        columnar = materialize_columnar_task(
            reader, _Task, zoo.columnar_dataset_fn, "training", None
        )
        return [
            (*columnar.slice(i * batch_size, (i + 1) * batch_size), mask)
            for i in range(steps_per_window)
        ]

    # Host pipeline alone (file -> batch views, warm page cache): the
    # data-plane capacity claim.  Measured BEFORE the trainer/backend
    # exists in this process: the tunneled device client's service
    # threads steal ~60% of the 1-core CI host (isolated 2026-07-31 —
    # 415k rec/s at 15% spread with the trainer resident vs 935-986k
    # clean), which is a harness artifact, not a property of the data
    # plane (production worker hosts are not 1-core and don't share
    # that core with a tunnel).  7 repeats, one outlier trimmed each
    # side (_trimmed_median_spread) against background-process noise.
    host_pipeline()  # warm the page cache
    host_times = []
    for _ in range(max(7, repeats)):
        start = time.perf_counter()
        host_pipeline()
        host_times.append(time.perf_counter() - start)
    host_median, host_spread = _trimmed_median_spread(host_times, n)

    # Async host pipeline (data/pipeline.py, round 8): the same file ->
    # batch pipeline with parse_buffer fanned over a ParsePool.  The
    # pool needs multiple chunks to overlap, so this leg caps the
    # columnar chunk budget at 16 MB (~8 chunks for this task); the
    # workers=0 leg re-measures the CHUNKED-serial rate so the scaling
    # ratio compares like against like — chunk-concat overhead sits in
    # both legs and the pool is the only variable.  Also measured
    # before the device client exists (same stolen-core caveat as the
    # sync row above).
    import os

    from elasticdl_tpu.data.pipeline import ParsePool

    chunked_reader = zoo.CriteoRecordReader(path)
    chunked_reader.columnar_chunk_bytes = 16 << 20

    def host_pipeline_async(pool):
        columnar = materialize_columnar_task(
            chunked_reader, _Task, zoo.columnar_dataset_fn, "training",
            None, parse_pool=pool,
        )
        return [
            (*columnar.slice(i * batch_size, (i + 1) * batch_size), mask)
            for i in range(steps_per_window)
        ]

    def _timed_async(pool):
        host_pipeline_async(pool)  # warm
        async_times = []
        for _ in range(max(7, repeats)):
            start = time.perf_counter()
            host_pipeline_async(pool)
            async_times.append(time.perf_counter() - start)
        return _trimmed_median_spread(async_times, n)

    pool_workers = max(1, os.cpu_count() or 1)
    serial_rate, _ = _timed_async(None)
    with ParsePool(pool_workers) as pool:
        async_rate, async_spread = _timed_async(pool)
    scaling_x = async_rate / serial_rate

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=vocab),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    first = host_pipeline()
    trainer.ensure_initialized(first[0][0])

    def run_epoch(n_windows: int) -> float:
        """n_windows full passes, ONE completion fence at the end — like
        the production worker, nothing blocks per window, so host parse
        of window k+1 overlaps device compute and transfer of window k."""
        start = time.perf_counter()
        losses = None
        for _ in range(n_windows):
            batches = host_pipeline()
            window = trainer.stage_window(batches)
            losses = trainer.train_window(window)
        host_losses = np.asarray(losses)  # fence (see bench_deepfm)
        assert np.isfinite(host_losses).all()
        return time.perf_counter() - start

    run_epoch(1)  # warmup: compile + first-touch
    run_epoch(1)
    times = [run_epoch(2) for _ in range(repeats)]
    median, spread = _median_spread(times, 2 * n)
    n_chips = max(1, len(jax.devices()))
    return (
        (host_median, host_spread),
        (async_rate, async_spread, pool_workers, scaling_x),
        (median / n_chips, spread),
    )


def _write_imagenet_etrf(path: str, n: int, store: int, seed: int = 0):
    """Bench fixture (excluded from timing): n random [store,store,3]
    uint8 images + labels packed with the data/image.py layout."""
    from elasticdl_tpu.data import image as image_plane

    rng = np.random.default_rng(seed)
    images = rng.integers(
        0, 256, size=(n, store, store, 3), dtype=np.uint8
    )
    labels = rng.integers(0, 1000, size=n).astype(np.int32)
    image_plane.write_image_etrf(path, images, labels)


def bench_resnet_e2e(
    batch_size: int = 128,
    store: int = 256,     # stored record size; random-crops to 224
    steps_per_window: int = 16,
    repeats: int = 3,
):
    """The vision data plane, file -> device (round-5 VERDICT #1 — the
    last BASELINE config without a file->device proof): ETRF of DECODED
    fixed-size uint8 images -> read_range_buffers ->
    RecordLayout.parse_buffer (one numpy view) -> permutation +
    uint8 random-crop/flip (data/image.py) -> uint8 staging ->
    train_window.  Normalization runs on DEVICE (the zoo model's
    `normalize` head), so the host does zero per-pixel float math and
    stages 1 byte/pixel.

    Reported like bench_deepfm_e2e: the HOST-PIPELINE rate (file ->
    staged-window-ready uint8 batches, the data-plane capacity claim —
    tracked) and the coupled rate (includes the tunnel-bound transfer —
    untracked on this harness).  The host row's roofline anchor is the
    chip's own 2,665 img/s: host/device >= 1 means one host core
    sustains one chip."""
    import tempfile

    n = batch_size * steps_per_window
    tmp = tempfile.mkdtemp(prefix="bench_img_e2e_")
    try:
        return _bench_resnet_e2e_body(
            tmp, n, batch_size, store, steps_per_window, repeats
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_resnet_e2e_body(tmp, n, batch_size, store, steps_per_window,
                           repeats):
    import jax

    from elasticdl_tpu.data.columnar import materialize_columnar_task
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.resnet50 import resnet50_subclass as zoo

    path = f"{tmp}/imagenet.etrf"
    _write_imagenet_etrf(path, n, store)

    reader = zoo.ImageRecordReader(path)

    class _Task:
        start, end = 0, n

    mask = np.ones((batch_size,), np.float32)

    def host_pipeline():
        """File -> staged-window-ready uint8 batch list (all host work:
        read + view-parse + permute + crop/flip)."""
        columnar = materialize_columnar_task(
            reader, _Task, zoo.columnar_dataset_fn, "training", None
        )
        return [
            (*columnar.slice(i * batch_size, (i + 1) * batch_size), mask)
            for i in range(steps_per_window)
        ]

    host_pipeline()  # warm the page cache
    host_times = []
    for _ in range(max(7, repeats)):
        start = time.perf_counter()
        host_pipeline()
        host_times.append(time.perf_counter() - start)
    host_median, host_spread = _trimmed_median_spread(host_times, n)

    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh
    )
    first = host_pipeline()
    trainer.ensure_initialized(first[0][0])

    def run_epoch(n_windows: int) -> float:
        start = time.perf_counter()
        losses = None
        for _ in range(n_windows):
            batches = host_pipeline()
            window = trainer.stage_window(batches)
            losses = trainer.train_window(window)
        host_losses = np.asarray(losses)  # completion fence
        assert np.isfinite(host_losses).all()
        return time.perf_counter() - start

    run_epoch(1)  # warmup: compile + first-touch
    run_epoch(1)
    times = [run_epoch(2) for _ in range(repeats)]
    median, spread = _median_spread(times, 2 * n)
    n_chips = max(1, len(jax.devices()))
    return (host_median, host_spread), (median / n_chips, spread)


def bench_resnet50(
    batch_size: int = 128,  # scanned sweet spot on one v5e chip:
    image_size: int = 224,  # 64->2411, 128->2628, 192->2415, 256->2527,
    steps_per_window: int = 96,  # 384->2379, 512->2301 img/s (BASELINE.md)
    repeats: int = 5,  # windows: 64 -> 2628-2642, 96 -> 2661 (0% spread),
    # 128 -> 2676 but 4% spread (HBM pressure jitter); 96 wins on
    # steadiness.
):
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.resnet50 import resnet50_subclass as zoo

    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh
    )
    rng = np.random.RandomState(0)

    def make_batch():
        # Images stage as RAW uint8 (the round-5 production contract:
        # the model normalizes 0-255 inputs on device) — half the staged
        # window bytes of the old bf16 staging, which both shortens the
        # tunnel transfer and doubles the window length that fits.
        images = rng.randint(
            0, 256, size=(batch_size, image_size, image_size, 3)
        ).astype(np.uint8)
        labels = rng.randint(0, zoo.NUM_CLASSES, size=batch_size).astype(
            np.int32
        )
        return images, labels, np.ones((batch_size,), np.float32)

    # ONE staged window (unlike deepfm's alternating pair): conv compute
    # is data-independent, so window replay is cost-identical — and image
    # staging over the tunnel dominates bench wall time (96 steps x 128 x
    # 224^2 x 3 uint8 images ~= 1.85 GB/window).
    window = trainer.stage_window(
        [make_batch() for _ in range(steps_per_window)]
    )

    def run_window() -> float:
        start = time.perf_counter()
        losses = trainer.train_window(window)
        # Device->host copy as the completion fence (see bench_deepfm).
        host_losses = np.asarray(losses)
        assert np.isfinite(host_losses).all()
        return time.perf_counter() - start

    run_window()  # warmup: compile + first-touch
    run_window()  # second warmup: post-compile caches/power settle
    times = [run_window() for _ in range(repeats)]
    median, spread = _median_spread(times, batch_size * steps_per_window)
    n_chips = max(1, len(jax.devices()))
    return median / n_chips, spread


def bench_transformer(
    batch_size: int = 16,  # B=8 -> 241k, B=16 -> 245k tokens/sec
    steps_per_window: int = 20,
    repeats: int = 5,
):
    """Long-context config (net-new vs the reference): TRANSFORMER_BENCH
    causal LM, Pallas flash-attention kernel (ops/flash_attention.py)."""
    import jax

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from model_zoo.transformer import transformer_lm as zoo

    cfg = TRANSFORMER_BENCH
    vocab, seq_len = cfg["vocab"], cfg["seq_len"]
    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(
        zoo.custom_model(
            vocab=vocab, d_model=cfg["d_model"],
            num_heads=cfg["num_heads"], num_layers=cfg["num_layers"],
            max_len=seq_len,
        ),
        zoo.loss,
        zoo.optimizer(),
        mesh,
    )
    rng = np.random.RandomState(0)

    def make_batch():
        return (
            rng.randint(0, vocab, size=(batch_size, seq_len)).astype(
                np.int32
            ),
            rng.randint(0, vocab, size=(batch_size, seq_len)).astype(
                np.int32
            ),
            np.ones((batch_size,), np.float32),
        )

    window = trainer.stage_window(
        [make_batch() for _ in range(steps_per_window)]
    )

    def run_window() -> float:
        start = time.perf_counter()
        losses = trainer.train_window(window)
        host_losses = np.asarray(losses)  # completion fence (see deepfm)
        assert np.isfinite(host_losses).all()
        return time.perf_counter() - start

    run_window()
    run_window()
    times = [run_window() for _ in range(repeats)]
    median, spread = _median_spread(
        times, batch_size * seq_len * steps_per_window
    )
    n_chips = max(1, len(jax.devices()))
    return median / n_chips, spread


# -- roofline accounting (VERDICT round-3 #5) ---------------------------
#
# Every tracked metric also reports where it sits against the CHIP's
# capability, not just against last round's number, so perf drift vs
# silicon is visible in the bench artifact itself.  Ceilings:
# - 197 TF/s: v5e bf16 peak — mfu follows the standard
#   fraction-of-peak definition.  (The round-2 "118 TF/s sustained"
#   reference was itself RTT-diluted: the round-4 ring kernels measure
#   149 TF/s on pure matmul chains, so peak is the honest denominator.)
# - 819 GB/s: v5e HBM bandwidth (the ResNet roofline analysis).
# - 25 ns/row: measured count-bound floor of the sparse embedding path
#   (lookup-gather + grad-scatter per touched row, BASELINE.md).
# - 4.52M rec/s: measured single-core ETRF parse ceiling (data plane;
#   see HOST_PARSE_CEILING_RPS below for the history).
PEAK_BF16_FLOPS = 197e12
HBM_BYTES_PER_SEC = 819e9
SPARSE_FLOOR_NS_PER_ROW = 25.0
# Vectorized ETRF read+parse ceiling for Criteo-shaped records on one
# host core.  Round 3 measured 1.94M rec/s; the round-5 slicing-by-8
# CRC-32 (native recordfile.cc) re-measured it at 4.52M rec/s — the
# byte-at-a-time CRC was the binding cost (BASELINE.md data plane).
HOST_PARSE_CEILING_RPS = 4.52e6
# The chip's own measured ResNet-50 train rate (the tracked device
# metric) — the anchor the image HOST pipeline is judged against.
RESNET_DEVICE_IMG_PER_SEC = 2_665.0


# ONE definition of the transformer bench's model shape, consumed by
# both bench_transformer (builds the model) and the roofline accounting
# (computes FLOPs/token) — divergent copies would silently break the
# emitted mfu.
TRANSFORMER_BENCH = dict(
    vocab=32768, d_model=512, num_heads=8, num_layers=4, seq_len=2048,
    mlp_ratio=4,
)

# Same single-definition rule for the ring-engine bench shape, consumed
# by bench_ring_engine (drives the harness) and the roofline accounting
# (FLOPs per ring group).  heads/d are pinned by exp_ring_perf's variant
# grid (H=8, D=128) — recorded here because the FLOP formula needs them.
RING_BENCH = dict(
    t_local=2048, batch=4, heads=8, d=128, r=4, inner=32, repeats=3,
)


def _transformer_flops_per_token() -> float:
    """Analytic fwd FLOPs/token for TRANSFORMER_BENCH (causal);
    train = 3x fwd.  2*m*n per [m,n] matmul contraction; causal
    attention touches T/2 keys on average."""
    cfg = TRANSFORMER_BENCH
    d, layers = cfg["d_model"], cfg["num_layers"]
    per_layer = (
        8 * d * d                          # qkv (6d^2) + output proj (2d^2)
        + 4 * cfg["mlp_ratio"] * d * d     # mlp up + down
        + 4 * d * (cfg["seq_len"] / 2)     # QK^T + PV, T/2 causal keys
    )
    return 2 * d * cfg["vocab"] + layers * per_layer


def _roofline_fields(metric: str, value: float) -> dict:
    if metric == "transformer_lm_tokens_per_sec_per_chip":
        achieved = value * 3 * _transformer_flops_per_token()
        return {
            "flops_per_sec": round(achieved, -9),
            "mfu": round(achieved / PEAK_BF16_FLOPS, 3),
        }
    if metric == "resnet50_images_per_sec_per_chip":
        # 12.3 GFLOP/image train (3x the 4.1 GFLOP fwd); ~168 MB/image
        # HBM traffic (BASELINE.md: ~21.5 GB/step at batch 128 — the
        # binding roofline; this workload is bandwidth-bound, not MXU-
        # bound, so bw_frac is the headroom signal and mfu is context).
        achieved_flops = value * 12.3e9
        achieved_bytes = value * 21.5e9 / 128
        return {
            "mfu": round(achieved_flops / PEAK_BF16_FLOPS, 3),
            "bytes_per_sec": round(achieved_bytes, -9),
            "bw_frac": round(achieved_bytes / HBM_BYTES_PER_SEC, 3),
            "bound": "hbm",
        }
    if metric == "deepfm_26m_strict_samples_per_sec_per_chip":
        # Strict mode's binding resource at 26M rows is the PER-STEP
        # full-table streaming pass (params+moments read/write every
        # apply — BASELINE.md table-scale probe), not the touched-row
        # count; ns_per_row/floor_frac are kept for cross-row
        # comparability, `bound` names the actual wall.
        ns_per_row = 1e9 / (value * 26)
        return {
            "ns_per_row": round(ns_per_row, 1),
            "floor_frac": round(SPARSE_FLOOR_NS_PER_ROW / ns_per_row, 3),
            "bound": "table-stream",
        }
    if metric in (
        "deepfm_train_samples_per_sec_per_chip",
        "deepfm_train_fused_samples_per_sec_per_chip",
        "deepfm_train_fused_multichip_samples_per_sec_per_chip",
        "deepfm_26m_table_samples_per_sec_per_chip",
        "deepfm_e2e_samples_per_sec_per_chip",
    ):
        # Count-bound workload: the binding resource is per-touched-row
        # sparse work (26 rows/sample), floor ~25 ns/row on this chip.
        ns_per_row = 1e9 / (value * 26)
        return {
            "ns_per_row": round(ns_per_row, 1),
            "floor_frac": round(SPARSE_FLOOR_NS_PER_ROW / ns_per_row, 3),
            "bound": "sparse-row-count",
        }
    if metric == "ring_attention_tokens_per_sec_per_chip":
        # 8 block-matmuls of 2*B*H*T*T*D FLOPs per ring step (fwd 2 +
        # bwd 6), RING_BENCH["r"] steps/group over B*T*R q-tokens.
        rb = RING_BENCH
        flops_per_group = (
            8 * 2 * rb["batch"] * rb["heads"]
            * rb["t_local"] * rb["t_local"] * rb["d"] * rb["r"]
        )
        groups_per_sec = value / (rb["batch"] * rb["t_local"] * rb["r"])
        achieved = groups_per_sec * flops_per_group
        return {
            "flops_per_sec": round(achieved, -9),
            "mfu": round(achieved / PEAK_BF16_FLOPS, 3),
        }
    if metric == "deepfm_serve_qps_per_replica":
        # Forward-only sparse work: 8 samples/request x 26 touched
        # rows/sample (bench_deepfm_serve defaults).  The provisional
        # CPU-host anchor is bound by per-request dispatch, not the
        # chip's sparse floor — floor_frac says how far the number sits
        # from row-count-bound serving.
        ns_per_row = 1e9 / (value * 8 * 26)
        return {
            "ns_per_row": round(ns_per_row, 1),
            "floor_frac": round(SPARSE_FLOOR_NS_PER_ROW / ns_per_row, 3),
            "bound": "host-dispatch",
        }
    if metric == "deepfm_serve_p99_ms":
        # Latency row: the anchor is the device floor for one full
        # 64-row bucket (64 x 26 rows at the sparse floor) as a
        # fraction of the observed p99 — everything above the fraction
        # is queue/batch/dispatch, the batcher's tunable share.
        floor_ms = 64 * 26 * SPARSE_FLOOR_NS_PER_ROW / 1e6
        return {
            "floor_frac": round(floor_ms / value, 3),
            "bound": "host-dispatch",
        }
    if metric in (
        "deepfm_e2e_host_pipeline_records_per_sec",
        "deepfm_e2e_host_pipeline_async_records_per_sec",
    ):
        return {
            "host_parse_frac": round(value / HOST_PARSE_CEILING_RPS, 3),
            "bound": "host-core",
        }
    if metric == "resnet50_e2e_host_pipeline_images_per_sec":
        # Anchor = the chip's own measured train rate: device_frac is
        # what fraction of ONE chip this ONE host core feeds;
        # cores_per_chip is the host cores needed to saturate it (a v5e
        # host has ~28 cores per chip — BASELINE.md image plane).
        return {
            "device_frac": round(value / RESNET_DEVICE_IMG_PER_SEC, 3),
            "cores_per_chip": round(RESNET_DEVICE_IMG_PER_SEC / value, 1),
            "bound": "host-core",
        }
    if metric == "resnet50_e2e_images_per_sec_per_chip":
        return {
            "device_frac": round(value / RESNET_DEVICE_IMG_PER_SEC, 3),
            "bound": "tunnel-transfer",
        }
    return {}


def bench_ring_engine(t_local=None, batch=None, r=None,
                      inner=None, repeats=None):
    """The context-parallel path's per-step block engine (Pallas ring
    kernels): R worst-case (fully-unmasked) ring steps, forward + full
    backward, timed via scripts/exp_ring_perf.py's harness (independent
    step invocations looped `inner` times inside one jit — the tunnel's
    per-dispatch RTT would otherwise swamp the group cost).  Returns
    block-attended q-tokens/s = batch * t_local * r / group_time."""
    import importlib.util
    import os

    # Defaults come from RING_BENCH — the same dict _roofline_fields
    # computes the FLOP accounting from, so a caller overriding a shape
    # arg diverges VISIBLY (the override shows in the harness variant
    # name) instead of silently emitting a wrong mfu for the default.
    t_local = RING_BENCH["t_local"] if t_local is None else t_local
    batch = RING_BENCH["batch"] if batch is None else batch
    r = RING_BENCH["r"] if r is None else r
    inner = RING_BENCH["inner"] if inner is None else inner
    repeats = RING_BENCH["repeats"] if repeats is None else repeats

    spec = importlib.util.spec_from_file_location(
        "exp_ring_perf",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scripts", "exp_ring_perf.py"),
    )
    harness = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(harness)
    variant = f"t{t_local}_b{batch}_r{r}_pallas_i{inner}"
    times = []
    for _ in range(repeats):
        fwd_ms = harness.run_variant(variant, "fwd")
        grad_ms = harness.run_variant(variant, "grad")
        times.append((fwd_ms + grad_ms) / 1e3)
    work = batch * t_local * r
    rates = sorted(work / t for t in times)
    median = rates[len(rates) // 2]
    return median, (rates[-1] - rates[0]) / median


# Every row _emit prints, keyed by metric — the FINAL line re-emits the
# whole set under "all" so the driver's BENCH_r{N}.json (which preserves
# only the parsed final line) reconstructs every metric of the round.
# Round-4 VERDICT weak #1: the transformer and ResNet values of round 4
# were already lost from the artifact because only prose recorded them.
_EMITTED: dict = {}


def _emit(metric: str, value: float, unit: str, spread: float,
          final: bool = False, **extra):
    row = {
        "metric": metric,
        # Rates are O(1e3..1e6) and read fine at 1 decimal; ratio rows
        # (parse_pool_scaling_x) are O(1) and need the precision.
        "value": round(value, 3 if abs(value) < 10 else 1),
        "unit": unit,
        # Ratio rows (parse_pool_scaling_x) have no recorded anchor:
        # the value IS the comparison, so vs_baseline is omitted.
        **(
            {"vs_baseline": round(value / SELF_BASELINE[metric], 3)}
            if metric in SELF_BASELINE else {}
        ),
        "spread": round(spread, 4),
        **_roofline_fields(metric, value),
        **extra,
    }
    _EMITTED[metric] = {k: v for k, v in row.items() if k != "metric"}
    if final:
        row["all"] = dict(_EMITTED)
    print(json.dumps(row), flush=True)


def _require_live_backend(timeout_s: int = 180, probe_fn=None):
    """Fail FAST if the accelerator backend is unreachable.  The axon
    tunnel can die mid-session (observed round 5: ~5 h outage), and a
    dead tunnel makes the first jax.devices() block FOREVER inside the
    PJRT client init — turning the driver's bench run into an unbounded
    hang instead of a recorded failure.  A Python signal handler can't
    fire during a hung C call (the interpreter never regains control),
    so the escape is faulthandler's C-level watchdog thread: it dumps
    the stack and hard-exits without needing the GIL.

    `probe_fn` overrides the real device probe (host-side tests must
    not initialize the live backend)."""
    import faulthandler
    import sys

    print(
        json.dumps({
            "metric": "bench_backend_probe",
            "value": 0,
            "unit": "none",
            "note": (
                f"probing the accelerator backend (timeout {timeout_s}s)"
                " — if this run's output ENDS here with a dumped stack,"
                " the backend/tunnel was unreachable and no metrics were"
                " measured"
            ),
        }),
        flush=True,
    )
    sys.stderr.flush()
    faulthandler.dump_traceback_later(timeout_s, exit=True)
    try:
        n = (probe_fn or _device_count)()
    finally:
        faulthandler.cancel_dump_traceback_later()
    print(f"# backend live: {n} device(s)", flush=True)


def _device_count() -> int:
    import jax

    return len(jax.devices())


def main():
    _require_live_backend()
    tokens_per_sec, t_spread = bench_transformer()
    _emit(
        "transformer_lm_tokens_per_sec_per_chip",
        tokens_per_sec,
        "tokens/sec/chip",
        t_spread,
    )
    images_per_sec, r_spread = bench_resnet50()
    _emit(
        "resnet50_images_per_sec_per_chip",
        images_per_sec,
        "images/sec/chip",
        r_spread,
    )
    ring_rate, ring_spread = bench_ring_engine()
    _emit(
        "ring_attention_tokens_per_sec_per_chip",
        ring_rate,
        "tokens/sec/chip",
        ring_spread,
    )
    (img_host, ih_spread), (img_e2e, ie_spread) = bench_resnet_e2e()
    _emit(
        "resnet50_e2e_host_pipeline_images_per_sec",
        img_host,
        "images/sec/host-core",
        ih_spread,
    )
    _emit(
        "resnet50_e2e_images_per_sec_per_chip",
        img_e2e,
        "images/sec/chip",
        ie_spread,
        tracked=False,
        untracked_reason="tunnel-H2D-bound (same as the deepfm coupled row)",
    )
    (
        (host_rate, h_spread),
        (async_rate, a_spread, pool_workers, scaling_x),
        (e2e_rate, e_spread),
    ) = bench_deepfm_e2e()
    _emit(
        "deepfm_e2e_host_pipeline_records_per_sec",
        host_rate,
        "records/sec/host",
        h_spread,
        pipeline="sync",
    )
    # pipeline=async dimension of the same row (round 8): the shared
    # staging engine's parse pool.  On the 1-core CI host the pool is a
    # pool of one, so the number reads as pool OVERHEAD, not the win —
    # the row (and its scaling companion) stays untracked until a
    # multi-core driver host measures it; the regression gate's
    # ALLOWED_SPREAD entry is staged for the flip.
    _emit(
        "deepfm_e2e_host_pipeline_async_records_per_sec",
        async_rate,
        "records/sec/host",
        a_spread,
        pipeline="async",
        parse_workers=pool_workers,
        tracked=False,
        untracked_reason=(
            "parse pool degenerates to one worker on the 1-core CI "
            "host; provisional anchor = the sync row — flips tracked "
            "with the first multi-core driver measurement (BASELINE.md "
            "queued chip work)"
        ),
    )
    _emit(
        "deepfm_e2e_parse_pool_scaling_x",
        scaling_x,
        "x vs chunked-serial",
        a_spread,
        parse_workers=pool_workers,
        tracked=False,
        untracked_reason=(
            "1.0 by construction on one core (scripts/bench_regress.py "
            "keeps this row permanently report-only)"
        ),
    )
    # The coupled number on THIS harness is bound by the tunnel's H2D
    # path (25-70 ms/MB, 3x run-to-run — BASELINE.md e2e section), so
    # its vs_baseline swings with tunnel weather, not the framework:
    # reported with its spread for visibility, but flagged untracked —
    # regression judgment rides the host-pipeline row plus the staged
    # device metrics, which bracket it from both sides.
    _emit(
        "deepfm_e2e_samples_per_sec_per_chip",
        e2e_rate,
        "samples/sec/chip",
        e_spread,
        tracked=False,
        untracked_reason="tunnel-H2D-bound (BASELINE.md e2e decomposition)",
    )
    table_samples_per_sec, ts_spread = bench_deepfm_table_scale()
    _emit(
        "deepfm_26m_table_samples_per_sec_per_chip",
        table_samples_per_sec,
        "samples/sec/chip",
        ts_spread,
    )
    strict_samples_per_sec, ss_spread = bench_deepfm_table_scale_strict()
    _emit(
        "deepfm_26m_strict_samples_per_sec_per_chip",
        strict_samples_per_sec,
        "samples/sec/chip",
        ss_spread,
    )
    fused_samples_per_sec, f_spread = bench_deepfm_fused()
    _emit(
        "deepfm_train_fused_samples_per_sec_per_chip",
        fused_samples_per_sec,
        "samples/sec/chip",
        f_spread,
        tracked=False,
        untracked_reason=(
            "fused kernels not yet chip-verified (BASELINE.md round-6 "
            "queued chip work); flips tracked with the first driver "
            "measurement"
        ),
    )
    fmc_samples_per_sec, fmc_spread = bench_deepfm_fused_multichip()
    _emit(
        "deepfm_train_fused_multichip_samples_per_sec_per_chip",
        fmc_samples_per_sec,
        "samples/sec/chip",
        fmc_spread,
        tracked=False,
        devices=_device_count(),
        untracked_reason=(
            "shard_map'd fused dispatch awaits multi-chip driver "
            "evidence (BASELINE.md queued chip work); on 1 device this "
            "degenerates to the single-chip fused number"
        ),
    )
    serve_qps, sq_spread, serve_p99, sp_spread = bench_deepfm_serve()
    _emit(
        "deepfm_serve_qps_per_replica",
        serve_qps,
        "requests/sec/replica",
        sq_spread,
        tracked=False,
        untracked_reason=(
            "provisional CI-host anchor, no chip measurement yet "
            "(BASELINE.md serving plane); flips tracked with the first "
            "driver recording"
        ),
    )
    _emit(
        "deepfm_serve_p99_ms",
        serve_p99,
        "ms",
        sp_spread,
        tracked=False,
        untracked_reason=(
            "lower-is-better: the regression gate's ratio direction "
            "assumes higher-is-better, so this row reports but must "
            "never gate (scripts/bench_regress.py)"
        ),
    )
    auc_value, auc_spread = bench_deepfm_online_auc_window()
    _emit(
        "deepfm_online_auc_window",
        auc_value,
        "auc",
        auc_spread,
        tracked=False,
        untracked_reason=(
            "anchors the label-join ledger's windowed-AUC math on a "
            "synthetic fixed-separation scorer, not model quality; "
            "flips meaningful only when a trained chip model feeds "
            "the ledger (obs/quality.py)"
        ),
    )
    # The north-star headline prints LAST (the driver parses the final
    # line); final=True folds every metric of the run into its "all"
    # field so the artifact alone reconstructs the round.
    samples_per_sec, d_spread = bench_deepfm()
    _emit(
        "deepfm_train_samples_per_sec_per_chip",
        samples_per_sec,
        "samples/sec/chip",
        d_spread,
        final=True,
    )


if __name__ == "__main__":
    main()
