"""Benchmark entrypoint: prints ONE JSON line with the headline metric.

Runs on whatever accelerator is visible (the driver provides one real TPU
chip).  Headline: flagship-model training throughput in samples/sec/chip.
The reference publishes no numbers (BASELINE.md), so vs_baseline compares
against this framework's own recorded round-1 target.
"""

from __future__ import annotations

import json
import time


# Self-established target (samples/sec/chip) to compare across rounds; see
# BASELINE.md — the reference publishes no benchmark numbers.
SELF_BASELINE = {"mnist_dnn_train_samples_per_sec_per_chip": 13_800_000.0}


def bench_mnist_dnn(batch_size: int = 1024, steps: int = 50):
    import jax
    import jax.numpy as jnp
    import optax
    from model_zoo.mnist import mnist_functional_api as zoo

    model = zoo.custom_model()
    tx = zoo.optimizer()
    rng = jax.random.PRNGKey(0)
    images = jax.random.uniform(rng, (batch_size, 28, 28), jnp.float32)
    labels = jax.random.randint(rng, (batch_size,), 0, 10, jnp.int32)
    params = model.init(rng, images)["params"]
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, images, labels):
        def compute_loss(p):
            return zoo.loss(labels, model.apply({"params": p}, images))

        loss, grads = jax.value_and_grad(compute_loss)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    # Warmup/compile.
    params, opt_state, loss = train_step(params, opt_state, images, labels)
    jax.block_until_ready(loss)

    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, images, labels)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start
    return batch_size * steps / elapsed


def main():
    samples_per_sec = bench_mnist_dnn()
    metric = "mnist_dnn_train_samples_per_sec_per_chip"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(samples_per_sec / SELF_BASELINE[metric], 3),
            }
        )
    )


if __name__ == "__main__":
    main()
