"""Fused Pallas sparse-embedding kernels vs the XLA reference paths.

The numeric gate of the fused sparse engine (ops/sparse_embedding.py,
ISSUE 9): every kernel runs here in Pallas INTERPRET mode on CPU — the
real kernel bodies, not a shadow implementation — and is held to the
documented exactness contract against the packed XLA formulation:

- fused_lookup == packed.lookup BIT-FOR-BIT for in-vocab ids (and
  bit-identical through the Embedding layer for OOV/padding batches,
  where the validity mask owns out-of-range semantics);
- fused_dedup_apply == dedup_representatives + scatter_apply for all
  four optimizers over duplicate-heavy / OOV / pad-row /
  vocab%rows_per_block!=0 batches, table + every slot, to the
  documented <= 1-ulp tolerance (rtol 3e-7): the kernel replays the
  scatter path's arithmetic operation-for-operation, but XLA may FMA-
  fuse a mul-feeding-an-add (single rounding) on either side;
- fused_lookup_fm's activations == the XLA twin bit-for-bit, its FM
  partial sums within reduction-order tolerance, and its custom-VJP
  gradient (the perturbation capture) matches the unfused formulation;
- the compiled fused train step materializes NO [n, block_width] f32
  intermediate — the HBM round-trip the kernels exist to remove —
  while the xla step demonstrably does (the HLO-structure assertion).
"""

import re

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.ops import sparse_embedding as ske
from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer

# The documented apply tolerance: XLA may fuse any mul-feeding-an-add
# into an FMA (single rounding) on either side of the comparison; 1 ulp
# of f32 (see fused_dedup_apply's docstring).
ULP_RTOL = 3e-7


def _edge_ids(rng, vocab, n):
    """duplicates + padding + OOB-high + a zero-sum duplicate pair."""
    ids = rng.randint(0, vocab, size=n).astype(np.int32)
    ids[0] = ids[1]          # duplicate pair
    ids[2] = -1              # padding
    ids[3] = vocab + 1000    # OOB high
    return ids


# ---------------------------------------------------------------------------
# fused lookup
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "vocab,dim",
    [(64, 8), (100, 4), (33, 5), (16, 200)],  # 33,5: vocab % r != 0
)
def test_fused_lookup_bit_exact(vocab, dim):
    spec = PackedSpec(vocab, dim)
    rng = np.random.RandomState(0)
    table = rng.randn(vocab, dim).astype(np.float32)
    packed = pk.pack(spec, jnp.asarray(table))
    ids = rng.randint(0, vocab, size=77).astype(np.int32)
    ids[5] = ids[6] = ids[7]  # duplicate-heavy
    ref = np.asarray(pk.lookup(spec, packed, jnp.asarray(ids)))
    got = np.asarray(ske.fused_lookup(spec, packed, jnp.asarray(ids)))
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(got, table[ids])


def test_fused_lookup_through_embedding_layer_with_oov_and_padding():
    """The layer owns out-of-range semantics (safe ids + validity
    mask); under it the two kernels are bit-identical even for OOV and
    padding batches."""
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 40, size=(8, 5)).astype(np.int32)
    ids[0, 0] = -1        # padding
    ids[1, 2] = 40 + 7    # OOV
    ids[2, 3] = 10**6     # far OOV
    outs = {}
    for kernel in ("xla", "fused"):
        layer = Embedding(40, 8, sparse_kernel=kernel)
        variables = layer.init(jax.random.PRNGKey(0), ids)
        outs[kernel] = np.asarray(layer.apply(variables, ids))
    np.testing.assert_array_equal(outs["fused"], outs["xla"])
    # Invalid positions really are zeroed.
    assert not outs["fused"][0, 0].any()
    assert not outs["fused"][1, 2].any()


def test_fused_lookup_table_gradient_matches_xla():
    """Dense-autodiff mode (Local/AllReduce trainers): the custom VJP's
    segment-sum cotangent equals autodiff through the packed lookup."""
    spec = PackedSpec(20, 4)
    rng = np.random.RandomState(2)
    packed = pk.pack(spec, jnp.asarray(rng.randn(20, 4).astype(np.float32)))
    ids = jnp.asarray(np.array([1, 1, 5, 19, 3], np.int32))

    def loss(lookup_fn, p):
        return jnp.sum(lookup_fn(spec, p, ids) ** 2)

    g_fused = jax.grad(lambda p: loss(ske.fused_lookup, p))(packed)
    g_xla = jax.grad(lambda p: loss(pk.lookup, p))(packed)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_xla), rtol=1e-6, atol=1e-6
    )


# ---------------------------------------------------------------------------
# fused dedup + apply
# ---------------------------------------------------------------------------

_OPTS = {
    "sgd": lambda mode: sparse_optim.sgd(0.1, mode=mode),
    "momentum": lambda mode: sparse_optim.momentum(0.1, mu=0.9, mode=mode),
    "nesterov": lambda mode: sparse_optim.momentum(
        0.1, mu=0.9, nesterov=True, mode=mode
    ),
    "adagrad": lambda mode: sparse_optim.adagrad(0.1, mode=mode),
    "adam": lambda mode: sparse_optim.adam(0.01, mode=mode),
    "adam_global": lambda mode: sparse_optim.adam(
        0.01, mode=mode, bias_correction="global"
    ),
}


@pytest.mark.parametrize("name", sorted(_OPTS))
@pytest.mark.parametrize("vocab,dim", [(64, 8), (33, 5)])
def test_fused_apply_matches_scatter_path(name, vocab, dim):
    """Multi-step fused vs scatter equivalence with every edge batch:
    duplicates, zero-sum cancellation, padding, OOB, and (33, 5) the
    vocab % rows_per_block != 0 layout."""
    spec = PackedSpec(vocab, dim)
    rng = np.random.RandomState(7)
    table0 = rng.randn(vocab, dim).astype(np.float32)

    results = {}
    for mode in ("scatter", "fused"):
        opt = _OPTS[name](mode)
        packed = pk.pack(spec, jnp.asarray(table0))
        slots = opt.init_slots(spec, packed)
        for step in range(3):
            srng = np.random.RandomState(100 + step)
            ids = _edge_ids(srng, vocab, 20)
            grads = srng.randn(20, dim).astype(np.float32)
            ids[4] = ids[5] = 7
            grads[5] = -grads[4]  # row 7 sums to zero -> untouched
            if name == "sgd" and mode == "scatter":
                # sgd has no scatter/dedup path (linear => plain
                # scatter-add); the dedup-equivalent reference is
                # apply_acc on the accumulated gradient.
                acc = pk.grad_accumulate(
                    spec, packed, jnp.asarray(ids), jnp.asarray(grads)
                )
                packed, slots = opt.apply_acc(spec, packed, slots, acc)
            else:
                packed, slots = opt.apply(
                    spec, packed, slots, jnp.asarray(ids), jnp.asarray(grads)
                )
        results[mode] = (
            np.asarray(packed),
            {k: np.asarray(v) for k, v in slots.items()},
        )

    t_ref, s_ref = results["scatter"]
    t_fused, s_fused = results["fused"]
    np.testing.assert_allclose(t_fused, t_ref, rtol=ULP_RTOL, atol=1e-7)
    assert sorted(s_ref) == sorted(s_fused)
    for key in s_ref:
        np.testing.assert_allclose(
            s_fused[key], s_ref[key], rtol=ULP_RTOL, atol=1e-7,
            err_msg=f"slot {key}",
        )


def test_fused_apply_zero_sum_and_pad_rows_untouched():
    """The touched contract survives the kernel: zero-summed rows keep
    their moments (no decay), padding/OOB ids never write."""
    spec = PackedSpec(32, 8)
    rng = np.random.RandomState(3)
    table0 = rng.randn(32, 8).astype(np.float32)
    opt = sparse_optim.adam(0.01, mode="fused")
    packed = pk.pack(spec, jnp.asarray(table0))
    slots = opt.init_slots(spec, packed)
    ids = np.array([4, 4, -1, 200, 9], np.int32)
    grads = rng.randn(5, 8).astype(np.float32)
    grads[1] = -grads[0]  # row 4 cancels exactly
    new_packed, new_slots = opt.apply(
        spec, packed, slots, jnp.asarray(ids), jnp.asarray(grads)
    )
    logical = np.asarray(pk.unpack(spec, new_packed))
    np.testing.assert_array_equal(logical[4], table0[4])
    t = np.asarray(pk.unpack(spec, new_slots["t"]))[:, 0]
    assert t[9] == 1 and t.sum() == 1  # exactly one touched row


def test_fused_apply_under_jit_and_scan():
    """The kernel path must trace inside the PS train step's scan."""
    spec = PackedSpec(64, 8)
    opt = sparse_optim.adam(0.01, mode="fused")
    packed = pk.pack(
        spec, jnp.asarray(np.random.RandomState(3).randn(64, 8), jnp.float32)
    )
    slots = opt.init_slots(spec, packed)
    ids = jnp.asarray(
        np.random.RandomState(4).randint(0, 64, (3, 10)).astype(np.int32)
    )
    grads = jnp.asarray(
        np.random.RandomState(5).randn(3, 10, 8).astype(np.float32)
    )

    @jax.jit
    def window(packed, slots, ids, grads):
        def body(carry, xs):
            p, s = carry
            p, s = opt.apply(spec, p, s, xs[0], xs[1])
            return (p, s), jnp.sum(p)

        return jax.lax.scan(body, (packed, slots), (ids, grads))

    (new_packed, _), sums = window(packed, slots, ids, grads)
    assert np.isfinite(np.asarray(new_packed)).all()
    assert sums.shape == (3,)


def test_select_mode_and_resolution():
    """'fused' is opt-in: auto keeps the measured stream/scatter
    crossover and resolve_kernel('auto') stays on xla until
    AUTO_FUSED_READY flips with chip evidence."""
    spec_small = PackedSpec(1000, 8)
    spec_large = PackedSpec(2_000_000, 8)
    assert sparse_optim.select_mode(spec_small, 256, "auto") == "stream"
    assert sparse_optim.select_mode(spec_large, 256, "auto") == "scatter"
    assert sparse_optim.select_mode(spec_small, 256, "fused") == "fused"
    with pytest.raises(ValueError):
        sparse_optim.select_mode(spec_small, 256, "bogus")
    assert ske.resolve_kernel("xla") == "xla"
    assert ske.resolve_kernel("fused") == "fused"
    assert ske.resolve_kernel("auto") == (
        "fused" if ske.AUTO_FUSED_READY else "xla"
    )
    with pytest.raises(ValueError):
        ske.resolve_kernel("bogus")
    # remake: the trainer's hook to force fused on a spec-built optimizer.
    opt = sparse_optim.adam(0.01, bias_correction="global")
    fused = opt.remake("fused")
    assert fused.name == "adam"
    assert fused.hyperparams == opt.hyperparams


# ---------------------------------------------------------------------------
# fused lookup -> FM interaction
# ---------------------------------------------------------------------------


def _fm_fixture(batch=12, fields=6, per_field_vocab=30, dim=9, seed=0):
    rng = np.random.RandomState(seed)
    vocab = per_field_vocab * fields
    spec = PackedSpec(vocab, dim)
    table = rng.randn(vocab, dim).astype(np.float32)
    packed = pk.pack(spec, jnp.asarray(table))
    ids = (
        rng.randint(0, per_field_vocab, (batch, fields))
        + np.arange(fields)[None, :] * per_field_vocab
    ).astype(np.int32)
    ids[0, 0] = -1            # padding
    ids[1, 1] = vocab + 3     # OOV
    valid = (ids >= 0) & (ids < vocab)
    safe = np.where(valid, ids, 0).astype(np.int32)
    return spec, packed, ids, safe, valid


def test_fused_lookup_fm_matches_xla_twin():
    spec, packed, ids, safe, valid = _fm_fixture()
    bet = jnp.zeros(ids.shape + (spec.dim,), jnp.float32)
    acts, first, sum_v, sum_sq = ske.fused_lookup_fm(
        spec, packed, bet, jnp.asarray(safe), jnp.asarray(valid)
    )
    ref_acts = np.asarray(
        pk.lookup(spec, packed, jnp.asarray(safe.reshape(-1)))
    ).reshape(ids.shape + (spec.dim,)) * valid[..., None]
    np.testing.assert_array_equal(np.asarray(acts), ref_acts)
    rf, rsv, rss = ske.fm_stats_xla(jnp.asarray(ref_acts))
    # Reduction-order tolerance: the kernel sums fields sequentially,
    # jnp.sum reduces pairwise (documented in the op docstring).
    np.testing.assert_allclose(first, rf, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(sum_v, rsv, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(sum_sq, rss, rtol=1e-6, atol=1e-5)


def test_fused_lookup_fm_gradient_matches_unfused():
    """The bet cotangent — the sparse gradient the PS trainer captures
    — must match autodiff through the unfused formulation, including
    the FM partial sums' jacobian and the validity mask."""
    spec, packed, ids, safe, valid = _fm_fixture()
    valid_f = jnp.asarray(valid)[..., None].astype(jnp.float32)

    def loss_fused(bet):
        acts, first, sv, ss = ske.fused_lookup_fm(
            spec, packed, bet, jnp.asarray(safe), jnp.asarray(valid)
        )
        second = 0.5 * jnp.sum(sv * sv - ss, axis=-1)
        return jnp.sum(first + second) + jnp.sum(acts * acts)

    def loss_ref(bet):
        acts = (
            pk.lookup(spec, packed, jnp.asarray(safe.reshape(-1))).reshape(
                ids.shape + (spec.dim,)
            )
            + bet
        ) * valid_f
        first, sv, ss = ske.fm_stats_xla(acts)
        second = 0.5 * jnp.sum(sv * sv - ss, axis=-1)
        return jnp.sum(first + second) + jnp.sum(acts * acts)

    bet = jnp.zeros(ids.shape + (spec.dim,), jnp.float32)
    g_fused = np.asarray(jax.grad(loss_fused)(bet))
    g_ref = np.asarray(jax.grad(loss_ref)(bet))
    np.testing.assert_allclose(g_fused, g_ref, rtol=1e-5, atol=1e-5)
    # Padding/OOV positions carry zero gradient either way.
    assert not g_fused[0, 0].any() and not g_fused[1, 1].any()


def test_embedding_fm_interaction_layer_modes_agree():
    """The Embedding layer's fm_interaction surface returns the same
    quadruple under both kernels (acts bit-exact, stats to reduction
    tolerance)."""
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 60, size=(8, 5)).astype(np.int32)
    ids[3, 0] = -1
    outs = {}
    for kernel in ("xla", "fused"):
        layer = Embedding(
            60, 9, sparse_kernel=kernel, fm_interaction=True
        )
        variables = layer.init(jax.random.PRNGKey(0), ids)
        outs[kernel] = layer.apply(variables, ids)
    a_x, f_x, sv_x, ss_x = (np.asarray(o) for o in outs["xla"])
    a_f, f_f, sv_f, ss_f = (np.asarray(o) for o in outs["fused"])
    np.testing.assert_array_equal(a_f, a_x)
    np.testing.assert_allclose(f_f, f_x, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(sv_f, sv_x, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ss_f, ss_x, rtol=1e-6, atol=1e-5)


# ---------------------------------------------------------------------------
# trainer integration + HLO structure
# ---------------------------------------------------------------------------

VOCAB, DIM = 256, 8  # block_width 128 -> the [n, 128] shape is unambiguous


class _SparseModel(nn.Module):
    kernel: str = "xla"
    mesh: object = None  # fused dispatch mesh (shard_map on multi-device)

    @nn.compact
    def __call__(self, ids):
        x = Embedding(
            VOCAB, DIM, combiner="sum", name="emb",
            sparse_kernel=self.kernel, mesh=self.mesh,
        )(ids)
        return nn.Dense(4, name="head")(x)


def _loss(labels, outputs):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, labels.astype(jnp.int32)
    ).mean()


def _one_device_trainer(kernel):
    mesh = build_mesh(MeshConfig(), devices=jax.devices()[:1])
    return ShardedEmbeddingTrainer(
        _SparseModel(kernel=kernel),
        _loss,
        optax.sgd(0.1),
        mesh,
        embedding_optimizer=sparse_optim.adam(0.01),
        sparse_kernel=kernel,
    )


def test_ps_trainer_fused_matches_xla_end_to_end():
    rng = np.random.RandomState(0)
    batches = [
        (
            rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32),
            rng.randint(0, 4, size=16).astype(np.int32),
        )
        for _ in range(4)
    ]
    results = {}
    for kernel in ("xla", "fused"):
        trainer = _one_device_trainer(kernel)
        losses = [
            float(trainer.train_step(ids, labels)) for ids, labels in batches
        ]
        results[kernel] = (losses, trainer.get_variables_numpy())
    l_x, v_x = results["xla"]
    l_f, v_f = results["fused"]
    np.testing.assert_allclose(l_f, l_x, rtol=1e-5, atol=1e-6)
    for key in v_x:
        np.testing.assert_allclose(
            v_f[key], v_x[key], rtol=1e-5, atol=1e-6, err_msg=key
        )


def test_fused_train_step_hlo_has_no_row_batch_intermediates():
    """The HLO-structure assertion of ISSUE 9: the compiled fused train
    step contains NO [n, block_width] f32 tensor — the gathered-rows /
    expanded-updates HBM round-trip the kernels exist to remove — while
    the xla step demonstrably materializes it (gather rows for the
    lookup/slot reads, tiled+masked rows for every scatter)."""
    n = 16 * 3  # flattened ids per step

    def step_hlo(kernel):
        trainer = _one_device_trainer(kernel)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=16).astype(np.int32)
        trainer.ensure_initialized(ids)
        staged = trainer.stage_batch(
            ids, labels, np.ones((16,), np.float32)
        )
        return trainer._train_step.lower(trainer.state, *staged).compile(
        ).as_text()

    row_batch = re.compile(rf"f32\[{n},128\]")
    xla_hits = len(row_batch.findall(step_hlo("xla")))
    fused_hits = len(row_batch.findall(step_hlo("fused")))
    assert xla_hits > 0, "xla step no longer materializes row batches?"
    assert fused_hits == 0, (
        f"fused step materializes {fused_hits} [n, block_width] "
        "intermediate(s) — the kernel fusion regressed"
    )


def test_trainer_journals_kernel_selection_and_dispatch_route():
    """The journal names WHICH engine a run's numbers were measured on
    AND (round 7) which dispatch route the fused kernels took —
    single_device pallas_call vs shard_map over the mesh (the v1
    multi-device config ERROR is gone: shard_map IS the partitioning
    rule pallas_call lacked)."""
    from elasticdl_tpu import obs

    trainer = _one_device_trainer("fused")
    ids = np.random.RandomState(0).randint(0, VOCAB, size=(8, 3)).astype(
        np.int32
    )
    trainer.ensure_initialized(ids)
    events = [
        e for e in obs.journal().tail(50)
        if e.get("event") == "sparse_kernel_selected"
    ]
    assert events and events[-1]["kernel"] == "fused"
    assert events[-1]["requested"] == "fused"
    assert events[-1]["tables"] == 1
    assert events[-1]["route"] == "single_device"
    # Multi-device mesh: fused now CONSTRUCTS and journals the
    # shard_map route (the model threads the mesh so its Embedding
    # layers dispatch per-shard kernel bodies).
    mesh = build_mesh(MeshConfig(data=4, model=2))
    multi = ShardedEmbeddingTrainer(
        _SparseModel(kernel="fused", mesh=mesh),
        _loss,
        optax.sgd(0.1),
        mesh,
        embedding_optimizer=sparse_optim.adam(0.01),
        sparse_kernel="fused",
    )
    multi.ensure_initialized(
        np.random.RandomState(0).randint(0, VOCAB, size=(16, 3)).astype(
            np.int32
        )
    )
    events = [
        e for e in obs.journal().tail(50)
        if e.get("event") == "sparse_kernel_selected"
    ]
    assert events[-1]["kernel"] == "fused"
    assert events[-1]["route"] == "shard_map"
    # The xla engine journals its own route tag.
    xla = _one_device_trainer("xla")
    xla.ensure_initialized(ids)
    events = [
        e for e in obs.journal().tail(50)
        if e.get("event") == "sparse_kernel_selected"
    ]
    assert events[-1]["kernel"] == "xla"
    assert events[-1]["route"] == "xla"


def test_deepfm_layout_merges_under_fused_kernel():
    """Satellite: the combined 1+dim table is the default layout; the
    split layout survives only as the measured strict-xla->10M-row
    exception and the checkpoint-compat flag."""
    from model_zoo.deepfm import deepfm_functional_api as zoo

    big_vocab = zoo.SPLIT_TABLE_ROWS // zoo.NUM_CAT + 1
    total = big_vocab * zoo.NUM_CAT
    # The measured xla exception is preserved...
    strict_big_xla = zoo.custom_model(
        vocab_size=big_vocab, sparse_apply_every=1, sparse_kernel="xla"
    )
    assert strict_big_xla._split(total) is True
    # ...but the fused engine keeps the merged table at every scale.
    strict_big_fused = zoo.custom_model(
        vocab_size=big_vocab, sparse_apply_every=1, sparse_kernel="fused"
    )
    assert strict_big_fused._split(total) is False
    # Compat flag: checkpoints saved under split tables still load.
    pinned = zoo.custom_model(
        vocab_size=big_vocab, sparse_kernel="fused", split_tables=True
    )
    assert pinned._split(total) is True


# ---------------------------------------------------------------------------
# multi-device shard_map dispatch (ISSUE 10: the fused path multi-chip)
# ---------------------------------------------------------------------------


def _multi_device_trainer(kernel, mesh):
    return ShardedEmbeddingTrainer(
        _SparseModel(kernel=kernel, mesh=mesh if kernel == "fused" else None),
        _loss,
        optax.sgd(0.1),
        mesh,
        embedding_optimizer=sparse_optim.adam(0.01),
        sparse_kernel=kernel,
    )


def test_multi_device_fused_requires_mesh_aware_remake():
    """A user-supplied optimizer with a pre-mesh remake hook (mode-only
    signature) is a loud config ERROR on a multi-device mesh: silently
    dropping the mesh would run a single-device pallas apply over
    model-sharded tables while the journal reports route=shard_map."""
    mesh = build_mesh(MeshConfig(data=4, model=2))
    base = sparse_optim.adam(0.01)
    legacy = sparse_optim.SparseOptimizer(
        base.name, base.init_slots, base.apply, base.hyperparams,
        base.apply_acc,
        remake=lambda mode: sparse_optim.adam(0.01, mode=mode),
    )
    with pytest.raises(ValueError, match="remake hook accepts mesh"):
        ShardedEmbeddingTrainer(
            _SparseModel(kernel="fused", mesh=mesh),
            _loss,
            optax.sgd(0.1),
            mesh,
            embedding_optimizer=legacy,
            sparse_kernel="fused",
        )
    # On a single device the mode-only hook stays supported.
    one = build_mesh(MeshConfig(), devices=jax.devices()[:1])
    ShardedEmbeddingTrainer(
        _SparseModel(kernel="fused"),
        _loss,
        optax.sgd(0.1),
        one,
        embedding_optimizer=legacy,
        sparse_kernel="fused",
    )


def test_multi_device_fused_matches_xla_end_to_end():
    """The acceptance gate: on the 8-device dryrun mesh the fused
    engine (shard_map dispatch, tables block-sharded over `model`)
    trains to numerical equivalence with the xla engine within the PR 9
    documented tolerances — the headline speedup no longer evaporates
    at scale-out."""
    mesh = build_mesh(MeshConfig(data=4, model=2))
    rng = np.random.RandomState(0)
    batches = [
        (
            rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32),
            rng.randint(0, 4, size=16).astype(np.int32),
        )
        for _ in range(4)
    ]
    results = {}
    for kernel in ("xla", "fused"):
        trainer = _multi_device_trainer(kernel, mesh)
        losses = [
            float(trainer.train_step(ids, labels)) for ids, labels in batches
        ]
        results[kernel] = (losses, trainer.get_variables_numpy())
    # Precondition: the fused table really is model-axis-sharded (NOT
    # silently replicated) while xla keeps the whole-mesh block layout.
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    fused_trainer = _multi_device_trainer("fused", mesh)
    fused_trainer.ensure_initialized(batches[0][0])
    t = fused_trainer.state.tables["emb/embedding"]
    assert t.sharding.spec == P(MODEL_AXIS, None)
    xla_trainer = _multi_device_trainer("xla", mesh)
    xla_trainer.ensure_initialized(batches[0][0])
    t = xla_trainer.state.tables["emb/embedding"]
    assert t.sharding.spec == P((DATA_AXIS, MODEL_AXIS), None)

    l_x, v_x = results["xla"]
    l_f, v_f = results["fused"]
    np.testing.assert_allclose(l_f, l_x, rtol=1e-5, atol=1e-6)
    for key in v_x:
        np.testing.assert_allclose(
            v_f[key], v_x[key], rtol=1e-5, atol=1e-6, err_msg=key
        )


def test_multi_device_fused_hlo_no_row_batch_intermediates_per_shard():
    """PR 9's zero-[n, block_width]-intermediates HLO assertion,
    extended to the 8-device dryrun mesh: the compiled (SPMD-
    partitioned) fused step shows NO f32 row-batch buffer at the global
    flattened-id count OR the per-data-shard count, while the xla step
    still materializes row batches."""
    mesh_shape = (4, 2)
    n_global = 16 * 3
    n_shard = n_global // mesh_shape[0]

    def step_hlo(kernel):
        mesh = build_mesh(MeshConfig(*mesh_shape))
        trainer = _multi_device_trainer(kernel, mesh)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=16).astype(np.int32)
        trainer.ensure_initialized(ids)
        staged = trainer.stage_batch(
            ids, labels, np.ones((16,), np.float32)
        )
        return trainer._train_step.lower(trainer.state, *staged).compile(
        ).as_text()

    row_batch = re.compile(rf"f32\[({n_global}|{n_shard}),128\]")
    xla_hits = len(row_batch.findall(step_hlo("xla")))
    fused_hits = len(row_batch.findall(step_hlo("fused")))
    assert xla_hits > 0, "xla step no longer materializes row batches?"
    assert fused_hits == 0, (
        f"multi-device fused step materializes {fused_hits} "
        "[n, block_width] intermediate(s) per shard — the shard_map "
        "kernel dispatch regressed"
    )


def test_multi_device_fused_windowed_apply_matches_xla():
    """The windowed relaxation (sparse_apply_every > 1: ONE deferred
    fused apply per chunk, inside lax.scan) composes with the shard_map
    dispatch — all_gather + shard_map inside scan inside the jitted
    window step."""
    mesh = build_mesh(MeshConfig(data=4, model=2))
    results = {}
    for kernel in ("xla", "fused"):
        trainer = ShardedEmbeddingTrainer(
            _SparseModel(
                kernel=kernel, mesh=mesh if kernel == "fused" else None
            ),
            _loss,
            optax.sgd(0.1),
            mesh,
            embedding_optimizer=sparse_optim.adam(0.01),
            sparse_kernel=kernel,
            sparse_apply_every=2,
        )
        batches = []
        for i in range(4):
            r = np.random.RandomState(i)
            batches.append((
                r.randint(0, VOCAB, (16, 3)).astype(np.int32),
                r.randint(0, 4, 16).astype(np.int32),
                np.ones((16,), np.float32),
            ))
        trainer.ensure_initialized(batches[0][0])
        window = trainer.stage_window(batches)
        results[kernel] = np.asarray(trainer.train_window(window))
    np.testing.assert_allclose(
        results["fused"], results["xla"], rtol=1e-5, atol=1e-6
    )


def test_deepfm_fused_multichip_matches_xla():
    """DeepFM (merged 1+d table, FM kernel) fused-vs-xla on the
    8-device mesh — the full acceptance config: block-sharded table
    (vocab chosen so blocks divide the model axis), FM partial sums
    psum-combined, fused dedup+apply through the optimizer remake
    path."""
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig(data=4, model=2))
    rng = np.random.RandomState(0)
    B, vocab = 16, 64  # 64*26=1664 rows -> 208 blocks, divides model=2

    def batch(i):
        r = np.random.RandomState(100 + i)
        return (
            {
                "dense": r.rand(B, zoo.NUM_DENSE).astype(np.float32),
                "cat": r.randint(0, vocab, (B, zoo.NUM_CAT)).astype(np.int32),
            },
            r.randint(0, 2, B).astype(np.int32),
        )

    results = {}
    for kernel in ("xla", "fused"):
        trainer = ShardedEmbeddingTrainer(
            zoo.custom_model(
                vocab_size=vocab, sparse_kernel=kernel,
                mesh=mesh if kernel == "fused" else None,
            ),
            zoo.loss,
            zoo.optimizer(),
            mesh,
            embedding_optimizer=sparse_optim.adam(0.001),
            sparse_kernel=kernel,
            seed=0,
        )
        losses = []
        for i in range(5):
            feats, labels = batch(i)
            losses.append(float(trainer.train_step(feats, labels)))
        results[kernel] = losses
        if kernel == "fused":
            spec = trainer._table_specs["fm_embedding/embedding"]
            assert ske.table_partition_axis(
                spec.num_blocks, mesh
            ) == MODEL_AXIS
    np.testing.assert_allclose(
        results["fused"], results["xla"], rtol=1e-4, atol=1e-5
    )
    assert results["fused"][-1] < results["fused"][0], "no learning"


def test_deepfm_fused_trains_and_matches_xla():
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    rng = np.random.RandomState(0)
    B, vocab = 16, 50

    def batch(i):
        r = np.random.RandomState(100 + i)
        return (
            {
                "dense": r.rand(B, zoo.NUM_DENSE).astype(np.float32),
                "cat": r.randint(0, vocab, (B, zoo.NUM_CAT)).astype(np.int32),
            },
            r.randint(0, 2, B).astype(np.int32),
        )

    results = {}
    for kernel in ("xla", "fused"):
        trainer = ShardedEmbeddingTrainer(
            zoo.custom_model(vocab_size=vocab, sparse_kernel=kernel),
            zoo.loss,
            zoo.optimizer(),
            build_mesh(MeshConfig(), devices=jax.devices()[:1]),
            embedding_optimizer=sparse_optim.adam(0.001),
            sparse_kernel=kernel,
            seed=0,
        )
        losses = []
        for i in range(5):
            feats, labels = batch(i)
            losses.append(float(trainer.train_step(feats, labels)))
        results[kernel] = losses
    np.testing.assert_allclose(
        results["fused"], results["xla"], rtol=1e-4, atol=1e-5
    )
    assert results["fused"][-1] < results["fused"][0], "no learning"
