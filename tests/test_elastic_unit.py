"""Unit tests for elastic membership, checkpointing, and lockstep batching."""

import os

import numpy as np
import pytest

from elasticdl_tpu.checkpoint import CheckpointSaver
from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.parallel.elastic import WorldInfo
from elasticdl_tpu.proto import elasticdl_pb2 as pb


class TestRendezvous:
    def test_rank_assignment_and_bump(self):
        rdv = ElasticRendezvous(coordinator_port_fn=lambda host: 5000)
        rid = rdv.set_worker_hosts([(2, "hostb"), (0, "hosta")])
        assert rid == 1
        resp = rdv.get_comm_rank(0)
        assert resp.rank_id == 0 and resp.world_size == 2
        assert resp.coordinator_addr == "hosta:5000"
        assert rdv.get_comm_rank(2).rank_id == 1
        # Unknown worker: rank -1 (not in this world).
        assert rdv.get_comm_rank(7).rank_id == -1
        # Churn: new world, new id; old member evicted.
        rid2 = rdv.set_worker_hosts([(3, "hostc")])
        assert rid2 == 2
        assert rdv.get_comm_rank(0).rank_id == -1
        assert rdv.get_comm_rank(3).rank_id == 0

    def test_liveness_reports_stale_world(self):
        rdv = ElasticRendezvous(coordinator_port_fn=lambda host: 5000)
        rid = rdv.set_worker_hosts([(0, "h")])
        assert rdv.report_liveness(0, "h", rid) is False
        rdv.set_worker_hosts([(0, "h"), (1, "h")])
        assert rdv.report_liveness(0, "h", rid) is True  # stale rendezvous

    def test_deferred_hosts_resolve_via_liveness(self):
        """Kubernetes worlds: hosts unknown at declaration; the coordinator
        resolves only once rank 0 advertises its IP over liveness, on a
        deterministic per-world port (master cannot bind-probe a remote
        pod's netns)."""
        from elasticdl_tpu.master.rendezvous_server import (
            remote_coordinator_port,
        )

        def boom(host):
            raise AssertionError("must not bind-probe with unknown hosts")

        rdv = ElasticRendezvous(coordinator_port_fn=boom)
        rid = rdv.set_worker_hosts([(0, ""), (1, "")])
        # No coordinator yet: workers keep polling instead of joining.
        resp = rdv.get_comm_rank(1, "10.0.0.2")
        assert resp.rank_id == 1 and resp.coordinator_addr == ""
        # Rank 1 advertising (above) does not resolve the coordinator;
        # rank 0 advertising does.
        resp = rdv.get_comm_rank(0, "10.0.0.1")
        expected_port = remote_coordinator_port(rid)
        assert resp.coordinator_addr == f"10.0.0.1:{expected_port}"
        assert list(resp.worker_hosts) == ["10.0.0.1", "10.0.0.2"]
        # Advertising rides the rank poll, NOT the heartbeat channel: both
        # workers are still 'never heartbeated', so staleness is judged
        # against the (long) startup grace, not the liveness timeout.
        assert rdv.stale_workers(timeout_s=0.0, startup_grace_s=60.0) == []
        # A re-declared world discards advertised hosts and defers again,
        # with a different coordinator port (stragglers can't reconnect).
        rid2 = rdv.set_worker_hosts([(2, ""), (3, "")])
        assert rdv.get_comm_rank(2).coordinator_addr == ""
        addr2 = rdv.get_comm_rank(2, "10.0.0.9").coordinator_addr
        assert addr2 == f"10.0.0.9:{remote_coordinator_port(rid2)}"
        assert remote_coordinator_port(rid2) != expected_port


class TestCheckpointSaver:
    def test_save_load_roundtrip_and_gc(self, tmp_path):
        saver = CheckpointSaver(str(tmp_path), keep_max=2)
        assert saver.load_latest() == (None, 0)
        for step in (10, 20, 30):
            saver.save({"w": np.full((3,), step)}, step)
        assert saver.steps() == [20, 30]  # keep_max trimmed step 10
        state, step = saver.load_latest()
        assert step == 30
        np.testing.assert_array_equal(state["w"], [30, 30, 30])

    def test_corrupt_latest_falls_back(self, tmp_path):
        saver = CheckpointSaver(str(tmp_path), keep_max=3)
        saver.save({"w": np.ones(2)}, 1)
        saver.save({"w": np.ones(2) * 2}, 2)
        # Corrupt the newest snapshot.
        with open(tmp_path / "step_000000000002" / "state.pkl", "wb") as f:
            f.write(b"garbage")
        state, step = saver.load_latest()
        assert step == 1


class TestTaskBroadcastEncoding:
    def test_roundtrip(self):
        shard_names = ["a", "b"]
        task = pb.Task(
            task_id=7, shard_name="b", start=5, end=25, type=pb.EVALUATION,
            model_version=3, epoch=1,
        )
        arr = elastic._encode_task(task, shard_names)
        back = elastic._decode_task(arr, shard_names)
        assert back == task

    def test_none_encodes_no_task(self):
        arr = elastic._encode_task(None, ["a"])
        back = elastic._decode_task(arr, ["a"])
        assert back.task_id == -1 and back.shard_name == ""


class TestLockstepBatching:
    def test_even_split(self):
        world = WorldInfo(rank=1, world_size=2, rendezvous_id=1, coordinator_addr="")
        ranges = list(elastic.iter_local_batch_ranges(0, 16, 4, world))
        # Global batches of 8: [0,8) and [8,16); rank 1 takes second halves.
        assert ranges == [(4, 8, 8), (12, 16, 8)]

    def test_ragged_tail_same_step_count_across_ranks(self):
        # 18 records, per-rank batch 4, world 2 -> global batch 8 -> 3 steps.
        for rank in (0, 1):
            world = WorldInfo(rank=rank, world_size=2, rendezvous_id=1,
                              coordinator_addr="")
            ranges = list(elastic.iter_local_batch_ranges(100, 118, 4, world))
            assert len(ranges) == 3
        r0 = list(elastic.iter_local_batch_ranges(100, 118, 4,
                  WorldInfo(0, 2, 1, "")))
        r1 = list(elastic.iter_local_batch_ranges(100, 118, 4,
                  WorldInfo(1, 2, 1, "")))
        # Tail global batch holds records [116,118): rank0 gets both, rank1 none.
        assert r0[-1] == (116, 118, 2)
        assert r1[-1] == (118, 118, 2)
        # Together the ranks cover the task exactly once.
        covered = []
        for (lo, hi, _), (lo1, hi1, _) in zip(r0, r1):
            covered.extend(range(lo, hi))
            covered.extend(range(lo1, hi1))
        assert sorted(covered) == list(range(100, 118))

    def test_per_rank_real_counts(self):
        assert elastic.per_rank_real_counts(8, 4, 2) == [4, 4]
        assert elastic.per_rank_real_counts(5, 4, 2) == [4, 1]
        assert elastic.per_rank_real_counts(2, 4, 2) == [2, 0]


class TestHungWorkerDetection:
    def test_stale_heartbeat_triggers_churn(self):
        """A worker that never heartbeats gets killed and the churn path
        runs (here: budget 0, world 1 -> job fails rather than hangs)."""
        import sys
        import time

        from elasticdl_tpu.master.pod_manager import LocalProcessManager

        rdv = ElasticRendezvous(coordinator_port_fn=lambda host: 5000)
        manager = LocalProcessManager(
            num_workers=1,
            worker_argv_fn=lambda wid: [sys.executable, "-c",
                                        "import time; time.sleep(600)"],
            rendezvous=rdv,
            max_restarts=0,
            liveness_timeout_s=0.5,
            poll_interval_s=0.1,
        )
        try:
            manager.start()
            ok = manager.wait(timeout=30)
            assert ok is False
            assert "restart budget" in manager.failed_reason
        finally:
            manager.stop()

    def test_monitor_crash_unblocks_wait(self):
        import sys

        from elasticdl_tpu.master.pod_manager import LocalProcessManager

        class BoomRendezvous(ElasticRendezvous):
            def stale_workers(self, timeout_s):
                raise RuntimeError("boom")

        manager = LocalProcessManager(
            num_workers=1,
            worker_argv_fn=lambda wid: [sys.executable, "-c",
                                        "import time; time.sleep(600)"],
            rendezvous=BoomRendezvous(coordinator_port_fn=lambda host: 5000),
            max_restarts=0,
            liveness_timeout_s=1.0,
            poll_interval_s=0.1,
        )
        try:
            manager.start()
            ok = manager.wait(timeout=30)
            assert ok is False and "crashed" in manager.failed_reason
        finally:
            manager.stop()


class TestElasticCheckpointDefaults:
    """Elastic jobs must never churn without a checkpoint to restore
    (VERDICT weak #3): job_runner fills in safe defaults and warns."""

    def _args(self, extra=()):
        from elasticdl_tpu.common.args import parse_master_args

        return parse_master_args([
            "--model_zoo=model_zoo",
            "--model_def=mnist.mnist_functional_api",
            "--training_data=synthetic://mnist?n=64",
            "--distribution_strategy=AllreduceStrategy",
            *extra,
        ])

    def test_defaults_applied_when_unset(self):
        from elasticdl_tpu.common.constants import Mode
        from elasticdl_tpu.master.job_runner import (
            _ensure_elastic_checkpointing,
        )

        args = self._args()
        assert args.checkpoint_dir == "" and args.checkpoint_steps == 0
        _ensure_elastic_checkpointing(args, Mode.TRAINING)
        assert args.checkpoint_dir
        assert os.path.isdir(args.checkpoint_dir)
        assert args.checkpoint_steps > 0

    def test_explicit_settings_untouched(self, tmp_path):
        from elasticdl_tpu.common.constants import Mode
        from elasticdl_tpu.master.job_runner import (
            _ensure_elastic_checkpointing,
        )

        args = self._args([f"--checkpoint_dir={tmp_path}",
                           "--checkpoint_steps=7"])
        _ensure_elastic_checkpointing(args, Mode.TRAINING)
        assert args.checkpoint_dir == str(tmp_path)
        assert args.checkpoint_steps == 7

    def test_eval_mode_and_no_elasticity_skip_defaults(self):
        from elasticdl_tpu.common.constants import Mode
        from elasticdl_tpu.master.job_runner import (
            _ensure_elastic_checkpointing,
        )

        args = self._args()
        _ensure_elastic_checkpointing(args, Mode.EVALUATION)
        assert args.checkpoint_dir == ""
        args = self._args(["--need_elasticity=false"])
        _ensure_elastic_checkpointing(args, Mode.TRAINING)
        assert args.checkpoint_dir == ""
