"""Vectorized data-plane tests: RecordLayout round-trips and the
buffer-level ETRF read path (native codec and Python fallback produce
identical chunks; parse_buffer matches per-record parsing)."""

import pytest

# Tier-1 fast gate runs `-m 'not slow'` (see Makefile test-fast).
pytestmark = pytest.mark.slow

import numpy as np
import pytest

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data.vectorized import RecordLayout

LAYOUT = RecordLayout([
    ("dense", np.float32, 13),
    ("cat", np.int32, 26),
    ("label", np.uint8, 1),
])


def _records(n, seed=0):
    rng = np.random.RandomState(seed)
    return [
        LAYOUT.pack(
            dense=rng.rand(13).astype(np.float32),
            cat=rng.randint(0, 1 << 20, size=26),
            label=[i % 2],
        )
        for i in range(n)
    ]


def test_pack_parse_roundtrip():
    recs = _records(32, seed=1)
    cols = LAYOUT.parse_batch(recs)
    assert cols["dense"].shape == (32, 13)
    assert cols["cat"].shape == (32, 26)
    np.testing.assert_array_equal(cols["label"][:, 0], np.arange(32) % 2)
    # Field values survive bit-exactly.
    one = LAYOUT.parse_batch([recs[7]])
    np.testing.assert_array_equal(one["cat"][0], cols["cat"][7])
    np.testing.assert_array_equal(one["dense"][0], cols["dense"][7])


def test_parse_batch_rejects_ragged():
    with pytest.raises(ValueError, match="fixed-width"):
        LAYOUT.parse_batch([b"short"])


def test_read_range_buffers_matches_per_record(tmp_path):
    recs = _records(300, seed=2)
    path = str(tmp_path / "v.etrf")
    recordfile.write_records(path, recs)

    per_record = list(recordfile.read_range(path, 25, 275))
    chunks = list(recordfile.read_range_buffers(path, 25, 275))
    assert sum(len(lengths) for _, lengths in chunks) == 250
    joined = b"".join(bytes(buf) for buf, _ in chunks)
    assert joined == b"".join(per_record)

    # Columnar parse over the buffer chunks == per-record parse.
    cols = [LAYOUT.parse_buffer(buf, lengths) for buf, lengths in chunks]
    cat = np.concatenate([c["cat"] for c in cols])
    ref = LAYOUT.parse_batch(per_record)
    np.testing.assert_array_equal(cat, ref["cat"])


def test_read_range_buffers_python_fallback(tmp_path, monkeypatch):
    recs = _records(100, seed=3)
    path = str(tmp_path / "f.etrf")
    recordfile.write_records(path, recs)
    native = list(recordfile.read_range_buffers(path, 0, 100))
    monkeypatch.setattr(recordfile, "_native", lambda: None)
    fallback = list(recordfile.read_range_buffers(path, 0, 100))
    assert b"".join(bytes(b) for b, _ in native) == b"".join(
        bytes(b) for b, _ in fallback
    )
    assert np.concatenate([l for _, l in native]).tolist() == (
        np.concatenate([l for _, l in fallback]).tolist()
    )


def test_read_range_buffers_max_bytes_budget(tmp_path, monkeypatch):
    """`max_bytes` (round 5): the native codec honors a whole-task
    budget (one chunk) and splits under a small one; the Python
    fallback deliberately caps at its default streaming bound (memory —
    see recordfile.read_range_buffers) — both yield identical DATA at
    any budget."""
    recs = _records(100, seed=5)
    path = str(tmp_path / "g.etrf")
    recordfile.write_records(path, recs)
    rec_bytes = len(recs[0])

    whole = list(recordfile.read_range_buffers(path, 0, 100,
                                               max_bytes=1 << 30))
    assert len(whole) == 1  # native: whole task, one chunk
    small = list(recordfile.read_range_buffers(path, 0, 100,
                                               max_bytes=10 * rec_bytes))
    assert len(small) > 1  # budget smaller than the task splits

    def payload(chunks):
        return b"".join(bytes(b) for b, _ in chunks)

    assert payload(whole) == payload(small)
    monkeypatch.setattr(recordfile, "_native", lambda: None)
    for budget in (1 << 30, 10 * rec_bytes, 0):
        fallback = list(recordfile.read_range_buffers(path, 0, 100,
                                                      max_bytes=budget))
        assert payload(fallback) == payload(whole)
        assert np.concatenate([l for _, l in fallback]).tolist() == (
            np.concatenate([l for _, l in whole]).tolist()
        )


def test_parse_buffer_length_validation():
    recs = _records(4)
    buf = np.frombuffer(b"".join(recs), np.uint8)
    with pytest.raises(ValueError, match="fixed-width"):
        LAYOUT.parse_buffer(buf, lengths=[1, 2, 3, 4])
    with pytest.raises(ValueError, match="multiple"):
        LAYOUT.parse_buffer(buf[:-1])


def test_deepfm_trains_from_criteo_etrf_file(tmp_path):
    """Binary-file ingestion e2e: a Criteo-layout ETRF file trains the
    DeepFM config through the real CLI Local path via the vectorized
    reader (loss decreases => parsing wired features correctly)."""
    import subprocess
    import sys

    from model_zoo.deepfm.deepfm_functional_api import (
        NUM_CAT,
        NUM_DENSE,
        criteo_record_layout,
    )

    layout = criteo_record_layout()
    rng = np.random.RandomState(0)
    n = 512
    # Learnable structure: label depends on dense[0] and cat[0] parity.
    recs = []
    for _ in range(n):
        dense = rng.rand(NUM_DENSE).astype(np.float32)
        cat = rng.randint(0, 100, size=NUM_CAT).astype(np.int32)
        label = int(dense[0] + 0.3 * (cat[0] % 2) > 0.65)
        recs.append(layout.pack(dense=dense, cat=cat, label=[label]))
    path = str(tmp_path / "criteo.etrf")
    recordfile.write_records(path, recs)

    proc = subprocess.run(
        [
            sys.executable, "-m", "elasticdl_tpu.client.main", "train",
            "--distribution_strategy=Local",
            "--model_zoo=model_zoo",
            "--model_def=deepfm.deepfm_functional_api",
            "--model_params=vocab_size=100",
            f"--training_data={path}",
            "--records_per_task=128",
            "--num_epochs=4",
            "--minibatch_size=32",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
            "ELASTICDL_FORCE_PLATFORM": "cpu",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    import re

    losses = [
        float(m) for m in re.findall(r"loss=([0-9.]+)", proc.stderr)
    ]
    assert len(losses) >= 8
    assert losses[-1] < losses[0] * 0.9, (losses[:2], losses[-2:])


def test_criteo_reader_implements_reader_surface(tmp_path):
    """The collective worker needs shard_names()/metadata (AbstractDataReader
    surface) — the reader must not be Local-only."""
    from model_zoo.deepfm.deepfm_functional_api import CriteoRecordReader

    path = str(tmp_path / "s.etrf")
    recordfile.write_records(path, _records(10))
    reader = CriteoRecordReader(path)
    assert reader.shard_names() == [path]
    assert reader.create_shards() == {path: 10}
    assert hasattr(reader, "metadata")
