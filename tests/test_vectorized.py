"""Vectorized data-plane tests: RecordLayout round-trips and the
buffer-level ETRF read path (native codec and Python fallback produce
identical chunks; parse_buffer matches per-record parsing)."""

import numpy as np
import pytest

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data.vectorized import RecordLayout

LAYOUT = RecordLayout([
    ("dense", np.float32, 13),
    ("cat", np.int32, 26),
    ("label", np.uint8, 1),
])


def _records(n, seed=0):
    rng = np.random.RandomState(seed)
    return [
        LAYOUT.pack(
            dense=rng.rand(13).astype(np.float32),
            cat=rng.randint(0, 1 << 20, size=26),
            label=[i % 2],
        )
        for i in range(n)
    ]


def test_pack_parse_roundtrip():
    recs = _records(32, seed=1)
    cols = LAYOUT.parse_batch(recs)
    assert cols["dense"].shape == (32, 13)
    assert cols["cat"].shape == (32, 26)
    np.testing.assert_array_equal(cols["label"][:, 0], np.arange(32) % 2)
    # Field values survive bit-exactly.
    one = LAYOUT.parse_batch([recs[7]])
    np.testing.assert_array_equal(one["cat"][0], cols["cat"][7])
    np.testing.assert_array_equal(one["dense"][0], cols["dense"][7])


def test_parse_batch_rejects_ragged():
    with pytest.raises(ValueError, match="fixed-width"):
        LAYOUT.parse_batch([b"short"])


def test_read_range_buffers_matches_per_record(tmp_path):
    recs = _records(300, seed=2)
    path = str(tmp_path / "v.etrf")
    recordfile.write_records(path, recs)

    per_record = list(recordfile.read_range(path, 25, 275))
    chunks = list(recordfile.read_range_buffers(path, 25, 275))
    assert sum(len(lengths) for _, lengths in chunks) == 250
    joined = b"".join(bytes(buf) for buf, _ in chunks)
    assert joined == b"".join(per_record)

    # Columnar parse over the buffer chunks == per-record parse.
    cols = [LAYOUT.parse_buffer(buf, lengths) for buf, lengths in chunks]
    cat = np.concatenate([c["cat"] for c in cols])
    ref = LAYOUT.parse_batch(per_record)
    np.testing.assert_array_equal(cat, ref["cat"])


def test_read_range_buffers_python_fallback(tmp_path, monkeypatch):
    recs = _records(100, seed=3)
    path = str(tmp_path / "f.etrf")
    recordfile.write_records(path, recs)
    native = list(recordfile.read_range_buffers(path, 0, 100))
    monkeypatch.setattr(recordfile, "_native", lambda: None)
    fallback = list(recordfile.read_range_buffers(path, 0, 100))
    assert b"".join(bytes(b) for b, _ in native) == b"".join(
        bytes(b) for b, _ in fallback
    )
    assert np.concatenate([l for _, l in native]).tolist() == (
        np.concatenate([l for _, l in fallback]).tolist()
    )


def test_parse_buffer_length_validation():
    recs = _records(4)
    buf = np.frombuffer(b"".join(recs), np.uint8)
    with pytest.raises(ValueError, match="fixed-width"):
        LAYOUT.parse_buffer(buf, lengths=[1, 2, 3, 4])
    with pytest.raises(ValueError, match="multiple"):
        LAYOUT.parse_buffer(buf[:-1])
