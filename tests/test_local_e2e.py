"""Local-mode end-to-end: `elasticdl train` on MNIST DNN (BASELINE config 1).

Parity: the reference's local-mode CI smoke test (SURVEY.md §4) — master +
worker in one process, real gRPC, loss must decrease and eval must report.
"""

import numpy as np
import pytest

from elasticdl_tpu.client import api
from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.common.model_utils import load_model_spec


def _train_args(tmp_path, extra=()):
    return parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--distribution_strategy", "Local",
            "--training_data", "synthetic://mnist?n=640",
            "--validation_data", "synthetic://mnist?n=256&seed=9",
            "--records_per_task", "320",
            "--minibatch_size", "32",
            "--num_epochs", "1",
            "--output", str(tmp_path / "model"),
            *extra,
        ]
    )


def test_local_train_end_to_end(tmp_path):
    args = _train_args(tmp_path)
    losses = []

    # Wrap the trainer step to observe the loss trajectory.
    from elasticdl_tpu.worker import trainer as trainer_mod

    original = trainer_mod.Trainer.train_step

    def spy(self, features, labels):
        loss = original(self, features, labels)
        losses.append(float(loss))
        return loss

    trainer_mod.Trainer.train_step = spy
    try:
        assert api._run_local(args, mode="training") == 0
    finally:
        trainer_mod.Trainer.train_step = original

    assert len(losses) == 20  # 640 records / 32 batch
    # Loss decreases substantially on the learnable synthetic task.
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7

    # --output produced a servable artifact a fresh loader can predict from.
    from elasticdl_tpu.serving import load_for_serving

    served = load_for_serving(str(tmp_path / "model"))
    out = np.asarray(served.predict(np.zeros((2, 28, 28, 1), np.float32)))
    assert out.shape == (2, 10) and np.isfinite(out).all()


def test_mnist_subclass_variant_trains(tmp_path):
    """The setup()-style CNN variant (reference: mnist_subclass) runs the
    same contract end to end."""
    args = parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_subclass",
            "--distribution_strategy", "Local",
            "--training_data", "synthetic://mnist?n=256",
            "--validation_data", "synthetic://mnist?n=64&seed=1",
            "--records_per_task", "128",
            "--minibatch_size", "32",
            "--num_epochs", "1",
        ]
    )
    assert api._run_local(args, mode="training") == 0


def test_local_evaluate_only(tmp_path):
    args = parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--distribution_strategy", "Local",
            "--validation_data", "synthetic://mnist?n=128",
            "--records_per_task", "64",
            "--minibatch_size", "32",
        ]
    )
    assert api._run_local(args, mode="evaluation") == 0


def test_model_spec_loading():
    args = parse_master_args(
        ["--model_zoo", "model_zoo", "--model_def", "mnist.mnist_functional_api"]
    )
    spec = load_model_spec(args)
    model = spec.build_model()
    assert model.hidden_dim == 128
    assert spec.eval_metrics_fn is not None
    assert spec.custom_data_reader is not None


def test_model_params_passthrough():
    args = parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--model_params", "hidden_dim=32",
        ]
    )
    spec = load_model_spec(args)
    assert spec.build_model().hidden_dim == 32


def test_per_epoch_eval_and_train_end_callbacks(tmp_path, monkeypatch):
    """evaluation_steps=0 evaluates at each epoch boundary; zoo callbacks()
    run via the TRAIN_END_CALLBACK task."""
    from model_zoo.mnist import mnist_functional_api as zoo

    ran = []
    monkeypatch.setattr(
        zoo, "callbacks", lambda: [lambda worker: ran.append(worker)], raising=False
    )
    from elasticdl_tpu.master import evaluation_service as es_mod

    rounds = []
    original = es_mod.EvaluationService.trigger_evaluation

    def spy(self, model_version):
        rounds.append(model_version)
        return original(self, model_version)

    monkeypatch.setattr(es_mod.EvaluationService, "trigger_evaluation", spy)

    args = parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--distribution_strategy", "Local",
            "--training_data", "synthetic://mnist?n=256",
            "--validation_data", "synthetic://mnist?n=64&seed=9",
            "--records_per_task", "128",
            "--minibatch_size", "32",
            "--num_epochs", "3",
        ]
    )
    assert api._run_local(args, mode="training") == 0
    # 2 epoch boundaries (after epochs 0 and 1) + 1 final round.
    assert len(rounds) == 3
    assert len(ran) == 1  # train-end callback ran exactly once


def test_eval_tasks_read_from_validation_reader():
    """EVALUATION tasks must read the validation dataset, not re-read the
    training shards that happen to share names."""
    import numpy as np

    from elasticdl_tpu.data.reader import NumpyDataReader
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.worker.worker import Worker

    train_reader = NumpyDataReader(
        np.zeros((8, 2), np.float32), np.zeros(8, np.int32), shard_name="d"
    )
    val_reader = NumpyDataReader(
        np.ones((8, 2), np.float32), np.ones(8, np.int32), shard_name="d"
    )

    class Spec:
        dataset_fn = staticmethod(lambda ds, mode, meta: ds)

    worker = Worker.__new__(Worker)  # wire only what _get_batches needs
    from elasticdl_tpu.data.task_data_service import TaskDataService

    worker._minibatch_size = 4
    worker._task_data_service = TaskDataService(train_reader, Spec.dataset_fn)
    worker._eval_data_service = TaskDataService(val_reader, Spec.dataset_fn)
    worker._predict_data_service = worker._task_data_service
    task = pb.Task(task_id=1, shard_name="d", start=0, end=8, type=pb.EVALUATION)
    from elasticdl_tpu.common.constants import Mode

    batches = list(worker._get_batches(task, Mode.EVALUATION))
    assert all(np.all(f == 1.0) for f, _l in batches)
    train_batches = list(worker._get_batches(task, Mode.TRAINING))
    assert all(np.all(f == 0.0) for f, _l in train_batches)
