"""Master servicer + client over localhost gRPC.

Parity surface: elasticdl/python/tests/servicer_test.py — the reference's
multi-process-in-one-process fixture pattern (SURVEY.md §4).
"""

import numpy as np
import pytest

from elasticdl_tpu.master.servicer import MasterServicer, start_master_server
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.master_client import MasterClient


@pytest.fixture
def cluster():
    manager = TaskManager(training_shards={"data": 30}, records_per_task=10)
    servicer = MasterServicer(task_manager=manager)
    server, port = start_master_server(servicer)
    clients = [MasterClient(f"localhost:{port}", worker_id=i) for i in range(2)]
    yield manager, servicer, clients
    for client in clients:
        client.close()
    server.stop(grace=None)


def test_get_and_report_over_grpc(cluster):
    manager, _servicer, (c0, c1) = cluster
    task = c0.get_task()
    assert task.task_id > 0
    assert task.type == pb.TRAINING
    c0.report_task_result(task.task_id, exec_counters={"batch_count": 3})
    assert manager.counts()["doing"] == 0


def test_error_report_requeues(cluster):
    manager, _servicer, (c0, c1) = cluster
    task = c0.get_task()
    c0.report_task_result(task.task_id, err_message="OOM")
    retry = c1.get_task()
    assert (retry.start, retry.end) == (task.start, task.end)


def test_full_drain_two_workers(cluster):
    manager, _servicer, clients = cluster
    done = 0
    active = True
    while active:
        active = False
        for client in clients:
            task = client.get_task()
            if task.task_id == -1 and task.type != pb.WAIT:
                continue
            if task.task_id != -1:
                client.report_task_result(task.task_id)
                done += 1
                active = True
    assert done == 3
    assert manager.finished()


def test_comm_rank_default_single_world(cluster):
    _manager, _servicer, (c0, _c1) = cluster
    response = c0.get_comm_rank()
    assert response.rank_id == 0
    assert response.world_size == 1


def test_shard_checkpoint_over_grpc(cluster):
    _manager, _servicer, (c0, _c1) = cluster
    content = c0.get_shard_checkpoint()
    resumed = TaskManager.from_checkpoint(content)
    assert resumed.counts()["todo"] == 3


def test_report_version_noop_without_services(cluster):
    _manager, _servicer, (c0, _c1) = cluster
    c0.report_version(5)  # should not raise
