"""Tests for the parallel package on the 8-virtual-device CPU mesh.

SURVEY.md §4: the fake-device layer — pjit/psum logic runs identically on
xla_force_host_platform_device_count=8 CPU devices and a real TPU slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel import (
    CollectiveCommunicator,
    CollectiveResult,
    DataParallelTrainer,
    MeshConfig,
    build_mesh,
)
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.worker.trainer import Trainer
from model_zoo.mnist import mnist_functional_api as zoo


def test_mesh_shapes():
    mesh = build_mesh(MeshConfig())
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh = build_mesh(MeshConfig(data=4, model=2))
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=3, model=3))


def test_pad_batch():
    feats = {"x": np.arange(10, dtype=np.float32).reshape(5, 2)}
    padded, mask = shd.pad_batch(feats, 4)
    assert padded["x"].shape == (8, 2)
    assert mask.tolist() == [1, 1, 1, 1, 1, 0, 0, 0]
    same, mask2 = shd.pad_batch(feats, 5)
    assert same["x"].shape == (5, 2) and mask2.sum() == 5


def _toy_batches(n_batches=6, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n_batches):
        yield (
            rng.rand(batch, 28, 28).astype(np.float32),
            rng.randint(0, 10, size=batch).astype(np.int32),
        )


def test_dp_trainer_matches_single_device():
    """The 8-way data-parallel step must produce the same params as the
    single-device step on identical data (psum-of-shard-grads == full-batch
    grad for a mean loss)."""
    mesh = build_mesh(MeshConfig())
    dp = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh, seed=0
    )
    single = Trainer(zoo.custom_model(), zoo.loss, zoo.optimizer(), seed=0)

    for feats, labels in _toy_batches():
        dp_loss = dp.train_step(feats, labels)
        s_loss = single.train_step(feats, labels)
        np.testing.assert_allclose(
            float(dp_loss), float(s_loss), rtol=1e-4, atol=1e-5
        )

    dp_vars = dp.get_variables_numpy()
    s_vars = single.get_variables_numpy()
    assert dp_vars.keys() == s_vars.keys()
    for k in dp_vars:
        np.testing.assert_allclose(dp_vars[k], s_vars[k], rtol=1e-3, atol=1e-4)


def test_dp_trainer_ragged_batch():
    """A final batch not divisible by the mesh (e.g. 13 rows on 8 devices)
    pads+masks, and matches the single-device result on the same 13 rows."""
    mesh = build_mesh(MeshConfig())
    dp = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh, seed=0
    )
    single = Trainer(zoo.custom_model(), zoo.loss, zoo.optimizer(), seed=0)
    rng = np.random.RandomState(1)
    feats = rng.rand(13, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=13).astype(np.int32)
    dp_loss = dp.train_step(feats, labels)
    s_loss = single.train_step(feats, labels)
    np.testing.assert_allclose(float(dp_loss), float(s_loss), rtol=1e-4, atol=1e-5)

    outputs = dp.eval_step(feats)
    assert outputs.shape[0] == 13
    np.testing.assert_allclose(
        outputs, single.eval_step(feats), rtol=1e-3, atol=1e-4
    )


def test_collective_allreduce_and_barrier():
    mesh = build_mesh(MeshConfig())
    comm = CollectiveCommunicator(mesh)
    status, out = comm.allreduce(np.array([2.0, 4.0]), op="MEAN")
    assert status == CollectiveResult.SUCCEEDED
    np.testing.assert_allclose(out, [2.0, 4.0])
    # SUM contributes once per PROCESS, not per device (reference
    # CollectiveCommunicator semantics): 1 process here, so sum == input.
    status, out = comm.allreduce(np.array([1.0]), op="SUM")
    assert status == CollectiveResult.SUCCEEDED
    np.testing.assert_allclose(out, [1.0])
    assert comm.barrier() == CollectiveResult.SUCCEEDED
    status, same = comm.broadcast(np.array([3.0]))
    assert status == CollectiveResult.SUCCEEDED
    np.testing.assert_allclose(same, [3.0])


def test_local_block_rounds_to_device_multiple():
    mesh = build_mesh(MeshConfig())  # 8 devices, 1 process
    dp = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh, seed=0
    )
    assert dp.local_block(10) == 16
    assert dp.local_block(8) == 8
    assert dp.local_block(1) == 8


def test_train_step_local_indivisible_minibatch():
    """minibatch 10 on an 8-device mesh: caller pads to local_block(10)=16
    with a mask; result must match single-device training on the 10 real
    rows."""
    from elasticdl_tpu.parallel import sharding as shd

    mesh = build_mesh(MeshConfig())
    dp = DataParallelTrainer(
        zoo.custom_model(), zoo.loss, zoo.optimizer(), mesh, seed=0
    )
    single = Trainer(zoo.custom_model(), zoo.loss, zoo.optimizer(), seed=0)
    rng = np.random.RandomState(3)
    feats = rng.rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=10).astype(np.int32)
    block = dp.local_block(10)
    pf, mask = shd.pad_batch(feats, block)
    pl, _ = shd.pad_batch(labels, block)
    dp_loss = dp.train_step_local(pf, pl, mask)
    s_loss = single.train_step(feats, labels)
    np.testing.assert_allclose(float(dp_loss), float(s_loss), rtol=1e-4, atol=1e-5)


class TestRestoreConsistency:
    """The re-formation path now uses CollectiveCommunicator (round-1
    weak #5: built but orphaned): after restore, all ranks must agree on
    the checkpoint step or the worker aborts so the world re-forms."""

    def _worker(self):
        from elasticdl_tpu.worker.collective_worker import CollectiveWorker
        from elasticdl_tpu.parallel.elastic import WorldInfo

        class FakeReader:
            metadata = None

            def create_shards(self):
                return {"s": 4}

            def shard_names(self):
                return ["s"]

        class FakeTrainer:
            mesh = build_mesh(MeshConfig())

            def local_block(self, mb):
                return mb

        class FakeSpec:
            dataset_fn = None

        return CollectiveWorker(
            master_client=None,
            model_spec=FakeSpec(),
            data_reader=FakeReader(),
            minibatch_size=4,
            world=WorldInfo(rank=1, world_size=2, rendezvous_id=1,
                            coordinator_addr="x"),
            trainer=FakeTrainer(),
        )

    def test_consistent_step_passes(self, monkeypatch):
        from elasticdl_tpu.parallel import collective as coll

        worker = self._worker()
        # Exact-int comparison: must hold even past float32's 2^24.
        worker._last_ckpt_step = 2**24 + 1
        monkeypatch.setattr(
            coll.CollectiveCommunicator,
            "broadcast",
            lambda self, data, root=0: (
                coll.CollectiveResult.SUCCEEDED, np.int64(2**24 + 1)
            ),
        )
        worker._verify_restore_consistency()  # no raise

    def test_divergent_step_aborts(self, monkeypatch):
        from elasticdl_tpu.parallel import collective as coll

        worker = self._worker()
        worker._last_ckpt_step = 40
        monkeypatch.setattr(
            coll.CollectiveCommunicator,
            "broadcast",
            lambda self, data, root=0: (
                coll.CollectiveResult.SUCCEEDED, np.int64(20)
            ),
        )
        with pytest.raises(RuntimeError, match="divergent restores"):
            worker._verify_restore_consistency()

    def test_failed_collective_aborts(self, monkeypatch):
        from elasticdl_tpu.parallel import collective as coll

        worker = self._worker()
        monkeypatch.setattr(
            coll.CollectiveCommunicator,
            "broadcast",
            lambda self, data, root=0: (coll.CollectiveResult.FAILED, None),
        )
        with pytest.raises(RuntimeError, match="re-forming"):
            worker._verify_restore_consistency()


class TestChunkedEvalReporting:
    """Eval memory bound (VERDICT round-2 weak #5): the leader flushes
    (outputs, labels) to the master every EVAL_REPORT_BATCHES batches, so
    worker memory is window-bounded regardless of task size — and the
    chunked reports concatenate to exactly the single-report content."""

    def _worker(self, client, n_records, mb):
        from elasticdl_tpu.parallel.elastic import WorldInfo
        from elasticdl_tpu.worker.collective_worker import CollectiveWorker

        class Reader:
            metadata = None

            def create_shards(self):
                return {"s": n_records}

            def shard_names(self):
                return ["s"]

            def read_records(self, task):
                for i in range(task.start, task.end):
                    yield (
                        {"x": np.full((2,), i, np.float32)},
                        np.int32(i),
                    )

        class FakeTrainer:
            mesh = build_mesh(MeshConfig())

            def local_block(self, mb_):
                return mb_

            def eval_step_local(self, features):
                # Deterministic per-row output: first feature column.
                return np.asarray(features["x"][:, 0])

        class Spec:
            dataset_fn = staticmethod(lambda ds, mode, md: ds)
            columnar_dataset_fn = None

        return CollectiveWorker(
            master_client=client,
            model_spec=Spec(),
            data_reader=Reader(),
            minibatch_size=mb,
            world=WorldInfo(rank=0, world_size=1, rendezvous_id=1,
                            coordinator_addr="x"),
            trainer=FakeTrainer(),
        )

    def test_chunked_reports_concatenate_to_full_task(self, monkeypatch):
        from elasticdl_tpu.proto import elasticdl_pb2 as pb
        from elasticdl_tpu.worker.collective_worker import CollectiveWorker

        reports = []

        class Client:
            def report_evaluation_metrics(self, model_version, model_outputs,
                                          labels, task_id=0):
                reports.append((model_outputs, labels, task_id))

        class Task:
            type = pb.EVALUATION
            start, end = 0, 80
            task_id = 7
            model_version = 3

        monkeypatch.setattr(CollectiveWorker, "EVAL_REPORT_BATCHES", 2)
        worker = self._worker(Client(), n_records=80, mb=8)
        worker._process_eval_task(Task())
        # 80 records / mb 8 = 10 batches -> 5 flushes of 2 batches each,
        # all scoped to the task id.
        assert len(reports) == 5
        assert all(r[2] == 7 for r in reports)
        outs = np.concatenate([r[0]["output"] for r in reports])
        labs = np.concatenate(
            [next(iter(r[1].values())) for r in reports]
        )
        np.testing.assert_array_equal(outs, np.arange(80, dtype=np.float32))
        np.testing.assert_array_equal(labs, np.arange(80))


class TestAutoWindowSizing:
    """--train_window_steps=0 sizes the dispatch window automatically:
    up to AUTO_WINDOW_STEPS, bounded by task batches and the staged-bytes
    cap, rounded down to a sparse_apply_every multiple (VERDICT round-2
    weak #7: the measured-good window is now the default, not a knob)."""

    def _worker(self, train_window_steps=0, apply_every=1):
        from elasticdl_tpu.parallel.elastic import WorldInfo
        from elasticdl_tpu.worker.collective_worker import CollectiveWorker

        class Reader:
            metadata = None

            def create_shards(self):
                return {"s": 8}

            def shard_names(self):
                return ["s"]

        class FakeTrainer:
            mesh = build_mesh(MeshConfig())
            _sparse_apply_every = apply_every

            def local_block(self, mb):
                return mb

        class Spec:
            dataset_fn = None
            columnar_dataset_fn = None

        return CollectiveWorker(
            master_client=None,
            model_spec=Spec(),
            data_reader=Reader(),
            minibatch_size=8,
            world=WorldInfo(rank=0, world_size=1, rendezvous_id=1,
                            coordinator_addr="x"),
            trainer=FakeTrainer(),
            train_window_steps=train_window_steps,
        )

    def test_auto_caps_at_task_and_steps(self):
        w = self._worker()
        assert w._window_candidate(10_000) == w.AUTO_WINDOW_STEPS
        assert w._window_candidate(37) == 37

    def test_auto_bytes_cap(self):
        w = self._worker()
        w._batch_nbytes = 256 << 20  # 256 MB/batch -> 4 batches in 1 GB
        assert w._window_candidate(10_000) == 4

    def test_explicit_window_ignores_bytes_cap(self):
        w = self._worker(train_window_steps=128)
        w._batch_nbytes = 64 << 20
        assert w._window_candidate(10_000) == 128

    def test_auto_rounds_down_to_apply_multiple(self):
        w = self._worker(apply_every=16)
        w._batch_nbytes = 1 << 20
        assert w._window_candidate(250) % 16 == 0
        # Tiny tasks never round below one apply interval.
        assert w._window_candidate(5) == 5

    def test_explicit_window_grows_to_apply_multiple(self):
        w = self._worker(train_window_steps=6, apply_every=4)
        assert w._window_steps == 8


def test_auto_apply_resync_grows_explicit_window():
    """--sparse_apply_every=auto resolves inside the trainer at init;
    the worker re-syncs its dispatch-window sizing right after
    (collective_worker._sync_apply_every) — an explicit window then
    grows to a chunk multiple exactly as a numeric flag would have
    grown it at construction."""
    from elasticdl_tpu.parallel.elastic import WorldInfo
    from elasticdl_tpu.worker.collective_worker import CollectiveWorker

    class FakeReader:
        metadata = None

        def create_shards(self):
            return {"s": 4}

        def shard_names(self):
            return ["s"]

    class FakeTrainer:
        mesh = build_mesh(MeshConfig())
        _sparse_apply_every = None  # auto, unresolved until init

        def local_block(self, mb):
            return mb

    class FakeSpec:
        dataset_fn = None

    trainer = FakeTrainer()
    worker = CollectiveWorker(
        master_client=None,
        model_spec=FakeSpec(),
        data_reader=FakeReader(),
        minibatch_size=4,
        world=WorldInfo(rank=0, world_size=1, rendezvous_id=1,
                        coordinator_addr="x"),
        trainer=trainer,
        train_window_steps=10,
    )
    # Unresolved auto reads as strict: no growth at construction.
    assert worker._apply_every == 1
    assert worker._window_steps == 10

    trainer._sparse_apply_every = 32  # what ensure_initialized resolves
    assert worker._sync_apply_every() is True
    assert worker._apply_every == 32
    assert worker._window_steps == 32  # grown to the chunk multiple
    assert worker._sync_apply_every() is False  # idempotent
