"""Request-level serving tracing: tail-based exemplars, shared batch
spans, and p99 phase attribution (docs/observability.md "Request
tracing & exemplars").

Covers:

- the `ExemplarSampler` decision: deterministic 1-in-N head samples,
  SLO-tied tail samples, every shed/dropped/error outcome captured, a
  hard-bounded ring, and O(sampled) journaling (unsampled requests
  write nothing; untraced requests are invisible);
- the shared `serve.batch` span: journaled ONCE per batch on the first
  sampled member, deduped by a bounded id ring;
- the frontend's span assembly through a fake gRPC context: the
  client-propagated trace id opens `rpc.predict` under the client span,
  phase spans nest per the settled parenting model, and a queue-full
  shed that never reaches the batcher still journals;
- `obs.trace.request_chain`: the full waterfall ordering including the
  trace-id-less shared batch span resolved via `batch_span_id`;
- `slo_alert` fire edges attaching exemplar trace ids from the
  registered provider (and surviving a broken provider);
- `obs.top --serving` phase columns + exemplar footer, degrading to the
  exact pre-tracing frame on old journals;
- `obs.report`'s tail-latency attribution section (and its absence on
  journals without `request_trace` rows);
- the loadgen client half: deterministic trace ids and journaled
  `client.predict` root spans;
- the `slow`-marked acceptance e2e: a 2-replica fleet under traced
  closed-loop load with an injected execute stall (queue backlog) must
  journal a schema-valid timeline from which the assembled trace yields
  a slow request's FULL waterfall with dominant phase queue, obs.report
  attributes p99 exemplars to the same phase, and the fired latency
  `slo_alert` carries exemplar trace ids resolvable in that trace —
  while the no-stall control run journals only head samples and fires
  nothing.
"""

import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.obs import report as report_mod
from elasticdl_tpu.obs import top
from elasticdl_tpu.obs import trace as trace_mod
from elasticdl_tpu.obs.metrics import MetricsRegistry
from elasticdl_tpu.obs.slo import SLOPlane, serving_latency_slo
from elasticdl_tpu.serving.batcher import BatcherConfig, MicroBatcher
from elasticdl_tpu.serving.frontend import PredictServicer, encode_features
from elasticdl_tpu.serving.ledger import ExemplarSampler

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
GOLDEN = os.path.join(TESTS_DIR, "golden_journal.jsonl")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, "scripts", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class _CapturingJournal:
    """Stand-in journal: records land in a list, nothing hits disk."""

    def __init__(self):
        self.records = []

    def record(self, event, **fields):
        self.records.append({"event": event, **fields})


# ---------------------------------------------------------------------------
# ExemplarSampler: the sampling decision
# ---------------------------------------------------------------------------


def _served(sampler, i, latency_ms=2.0):
    return sampler.observe(
        f"lg0-{i:08d}", {}, "served", latency_s=latency_ms / 1e3
    )


def test_head_sampling_is_deterministic():
    """The head sample is a counter, not a coin flip: two samplers fed
    the same traced stream journal the IDENTICAL request set."""
    journals = (_CapturingJournal(), _CapturingJournal())
    picks = []
    for journal in journals:
        sampler = ExemplarSampler(
            head_every=4, tail_threshold_ms=0.0, journal=journal
        )
        reasons = [_served(sampler, i) for i in range(13)]
        picks.append(reasons)
        assert sampler.counts() == {"observed": 13, "sampled": 4}
    assert picks[0] == picks[1]
    # 1-in-4 of the traced stream: requests 0, 4, 8, 12.
    ids = [[r["trace_id"] for r in j.records] for j in journals]
    assert ids[0] == ids[1] == [f"lg0-{i:08d}" for i in (0, 4, 8, 12)]
    assert all(r["sampled_by"] == "head" for r in journals[0].records)


def test_ring_is_bounded_and_journaling_is_o_sampled():
    journal = _CapturingJournal()
    sampler = ExemplarSampler(
        head_every=0, tail_threshold_ms=1.0, capacity=8, journal=journal
    )
    for i in range(100):
        assert _served(sampler, i, latency_ms=50.0) == "tail"
    assert sampler.counts() == {"observed": 100, "sampled": 100}
    assert len(sampler.exemplars()) == 8  # ring capacity, not 100
    assert len(journal.records) == 100  # every sample journaled once
    # Head off + sub-threshold latency: nothing journals at all.
    journal.records.clear()
    for i in range(100, 200):
        assert _served(sampler, i, latency_ms=0.5) == ""
    assert journal.records == []


def test_bad_outcomes_always_sampled():
    """Failures are always evidence — even with head sampling off and
    no tail threshold, every shed/dropped/error journals."""
    journal = _CapturingJournal()
    sampler = ExemplarSampler(
        head_every=0, tail_threshold_ms=0.0, journal=journal
    )
    for i, outcome in enumerate(("shed", "dropped", "error", "served")):
        sampler.observe(f"lg0-{i:08d}", {}, outcome, latency_s=0.001)
    sampled = [(r["outcome"], r["sampled_by"]) for r in journal.records]
    assert sampled == [
        ("shed", "outcome"), ("dropped", "outcome"), ("error", "outcome")
    ]


def test_untraced_requests_are_invisible():
    """No trace id -> no record AND no counter tick, so the head period
    stays pure in the traced stream."""
    journal = _CapturingJournal()
    sampler = ExemplarSampler(head_every=2, journal=journal)
    assert sampler.observe("", {}, "served", latency_s=0.001) == ""
    assert sampler.observe("", {}, "shed", latency_s=0.001) == ""
    assert sampler.counts() == {"observed": 0, "sampled": 0}
    assert journal.records == []


def test_dominant_phase_and_latency_from_phases():
    journal = _CapturingJournal()
    sampler = ExemplarSampler(head_every=1, journal=journal)
    phases = {"queue": 0.061, "batch": 0.002, "execute": 0.012,
              "respond": 0.003}
    assert sampler.observe("lg0-00000000", phases, "served") == "head"
    (rec,) = journal.records
    assert rec["dominant_phase"] == "queue"
    assert rec["latency_ms"] == pytest.approx(78.0, abs=0.01)
    assert rec["phases"]["queue"] == pytest.approx(61.0)
    assert sampler.slowest()["trace_id"] == "lg0-00000000"
    assert sampler.trace_ids() == ["lg0-00000000"]


def test_shared_batch_span_journaled_once(journal_file):
    """Two sampled members of the same batch journal ONE serve.batch
    span; the second member only links to it."""
    sampler = ExemplarSampler(head_every=1)
    batch = {"name": "serve.batch", "start_ts": 100.0, "duration_s": 0.01,
             "span_id": "b-shared", "batch_rows": 8, "bucket": 8,
             "requests": 2}
    for i in range(2):
        sampler.observe(
            f"lg0-{i:08d}", {"queue": 0.001}, "served",
            spans=[], batch=dict(batch),
        )
    batches = [e for e in _events(journal_file)
               if e["event"] == "span" and e["name"] == "serve.batch"]
    assert len(batches) == 1
    assert batches[0]["span_id"] == "b-shared"
    traces = [e for e in _events(journal_file)
              if e["event"] == "request_trace"]
    assert len(traces) == 2


# ---------------------------------------------------------------------------
# Frontend span assembly through a fake gRPC context
# ---------------------------------------------------------------------------


class _Ctx:
    """The slice of grpc.ServicerContext PredictServicer touches."""

    def __init__(self, metadata=None, remaining=5.0):
        self._metadata = metadata or ()
        self._remaining = remaining

    def invocation_metadata(self):
        return self._metadata

    def time_remaining(self):
        return self._remaining

    def abort(self, code, message):
        raise RuntimeError(f"abort {code}: {message}")


class _FakeReplica:
    class generation:
        gen_id = 3


def test_frontend_propagates_trace_to_phase_spans(journal_file):
    """A client-propagated trace id produces the settled span set:
    rpc.predict under the client span, serve.queue under rpc, the
    member serve.execute under the SHARED serve.batch span, and
    serve.respond back under rpc (the clamp-safety parent)."""
    from elasticdl_tpu.common import grpc_utils

    sampler = ExemplarSampler(head_every=1, replica_id=0)
    batcher = MicroBatcher(
        lambda features, n_valid: np.zeros(
            features["x"].shape[0], np.float32
        ),
        BatcherConfig(max_batch_size=4, max_wait_us=100, queue_limit=8),
    ).start()
    servicer = PredictServicer(_FakeReplica(), batcher, sampler=sampler)
    payload = encode_features({"x": np.zeros((2, 1), np.float32)})
    try:
        ctx = _Ctx(grpc_utils.trace_metadata("lg5-00000000",
                                             "lg5-00000000"))
        servicer.predict(payload, ctx)
        # An untraced request journals NOTHING (wire-compatible client).
        servicer.predict(payload, _Ctx())
    finally:
        batcher.stop()

    events = _events(journal_file)
    traces = [e for e in events if e["event"] == "request_trace"]
    assert len(traces) == 1
    (rec,) = traces
    assert rec["trace_id"] == "lg5-00000000"
    assert rec["outcome"] == "served" and rec["rows"] == 2
    assert rec["replica_id"] == 0 and rec["generation"] == 3
    assert set(rec["phases"]) == {"queue", "batch", "execute", "respond"}

    spans = {e["name"]: e for e in events if e["event"] == "span"}
    assert set(spans) == {"rpc.predict", "serve.queue", "serve.batch",
                          "serve.execute", "serve.respond"}
    batch_id = spans["serve.batch"]["span_id"]
    assert spans["rpc.predict"]["parent_span_id"] == "lg5-00000000"
    assert spans["rpc.predict"]["batch_span_id"] == batch_id
    rpc_id = spans["rpc.predict"]["span_id"]
    assert spans["serve.queue"]["parent_span_id"] == rpc_id
    assert spans["serve.execute"]["parent_span_id"] == batch_id
    assert spans["serve.respond"]["parent_span_id"] == rpc_id
    # The shared batch span belongs to every member equally: no trace id.
    assert spans["serve.batch"].get("trace_id", "") == ""
    assert spans["serve.batch"]["batch_rows"] == 2
    assert spans["serve.batch"]["generation"] == 3


def test_frontend_samples_queue_full_shed(journal_file):
    """A shed request never reaches the batcher, but it is still an
    outcome sample: request_trace + the rpc.predict span journal even
    though no phase stamps exist."""
    from elasticdl_tpu.common import grpc_utils

    gate = threading.Event()
    executing = threading.Event()

    def execute(features, n_valid):
        executing.set()
        gate.wait(timeout=30)
        return np.zeros(features["x"].shape[0], np.float32)

    sampler = ExemplarSampler(head_every=0, tail_threshold_ms=0.0)
    batcher = MicroBatcher(
        execute,
        BatcherConfig(max_batch_size=1, max_wait_us=100, queue_limit=1),
    ).start()
    servicer = PredictServicer(_FakeReplica(), batcher, sampler=sampler)
    payload = encode_features({"x": np.zeros((1, 1), np.float32)})
    try:
        first = batcher.submit({"x": np.zeros((1, 1), np.float32)})
        assert executing.wait(timeout=10)
        queued = batcher.submit({"x": np.zeros((1, 1), np.float32)})
        ctx = _Ctx(grpc_utils.trace_metadata("lg5-00000007",
                                             "lg5-00000007"))
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            servicer.predict(payload, ctx)
        gate.set()
        first.wait(timeout=30)
        queued.wait(timeout=30)
    finally:
        gate.set()
        batcher.stop()
    events = _events(journal_file)
    (rec,) = [e for e in events if e["event"] == "request_trace"]
    assert rec["trace_id"] == "lg5-00000007"
    assert rec["outcome"] == "shed" and rec["sampled_by"] == "outcome"
    names = [e["name"] for e in events if e["event"] == "span"]
    assert names == ["rpc.predict"]


# ---------------------------------------------------------------------------
# obs.trace: the waterfall chain
# ---------------------------------------------------------------------------


def test_request_chain_resolves_shared_batch_hop():
    def span(name, span_id, parent_id="", trace_id="t1", start=0.0,
             **args):
        return {"name": name, "span_id": span_id,
                "parent_span_id": parent_id, "trace_id": trace_id,
                "start": start, "end": start + 0.01, "args": args}

    spans = [
        span("serve.respond", "p1", "r1", start=0.040),
        span("client.predict", "t1", "", start=0.000),
        span("rpc.predict", "r1", "t1", start=0.001,
             batch_span_id="b1"),
        span("serve.batch", "b1", "", trace_id="", start=0.031),
        span("serve.execute", "x1", "b1", start=0.032,
             batch_span_id="b1"),
        span("serve.queue", "q1", "r1", start=0.001),
        # Noise from an unrelated trace must not leak in.
        span("rpc.predict", "r2", "t2", trace_id="t2", start=0.5),
    ]
    chain = trace_mod.request_chain(spans, "t1")
    assert [s["name"] for s in chain] == list(trace_mod.SERVING_SPAN_ORDER)
    assert trace_mod.request_chain(spans, "no-such-trace") == []


# ---------------------------------------------------------------------------
# slo_alert exemplars
# ---------------------------------------------------------------------------


def test_latency_alert_attaches_exemplar_trace_ids(journal_file):
    registry = MetricsRegistry()
    gauge = registry.gauge("elasticdl_serving_latency_p99_ms", "")
    plane = SLOPlane(
        registry=registry,
        specs=[serving_latency_slo(20.0, compliance_window_s=60.0)],
        origin="t",
    )
    plane.slos.set_exemplar_provider(
        lambda slo: ["lg0-00000102", "lg0-00000140"]
    )
    evidence_seen = []
    plane.slos.add_alert_callback(
        lambda slo, firing, ev: evidence_seen.append((firing, ev))
    )
    for tick in range(30):
        gauge.set(500.0)
        plane.tick(float(tick))
    # Recover so the clear edge journals too.
    for tick in range(30, 120):
        gauge.set(1.0)
        plane.tick(float(tick))
    alerts = [e for e in _events(journal_file) if e["event"] == "slo_alert"]
    fires = [a for a in alerts if a["state"] == "fire"]
    clears = [a for a in alerts if a["state"] == "clear"]
    assert fires and clears
    assert fires[0]["exemplars"] == ["lg0-00000102", "lg0-00000140"]
    # Clear edges carry no exemplars (nothing is offending anymore).
    assert all("exemplars" not in a for a in clears)
    fired = [ev for firing, ev in evidence_seen if firing]
    assert fired and fired[0]["exemplars"] == [
        "lg0-00000102", "lg0-00000140"
    ]


def test_broken_exemplar_provider_never_blocks_the_alert(journal_file):
    registry = MetricsRegistry()
    gauge = registry.gauge("elasticdl_serving_latency_p99_ms", "")
    plane = SLOPlane(
        registry=registry,
        specs=[serving_latency_slo(20.0, compliance_window_s=60.0)],
        origin="t",
    )

    def exploding(slo):
        raise RuntimeError("exemplar store unavailable")

    plane.slos.set_exemplar_provider(exploding)
    for tick in range(30):
        gauge.set(500.0)
        plane.tick(float(tick))
    fires = [e for e in _events(journal_file)
             if e["event"] == "slo_alert" and e["state"] == "fire"]
    assert fires, "alert must fire even when the provider is broken"
    assert all("exemplars" not in a for a in fires)


# ---------------------------------------------------------------------------
# obs.top: phase columns + exemplar footer, clean degradation
# ---------------------------------------------------------------------------


def _telemetry_row(**extra):
    row = {"event": "serving_telemetry", "replica_id": 1, "ts": 99.0,
           "generation": 2, "step": 7, "qps": 123.4, "p50_ms": 0.5,
           "p99_ms": 4.5, "queue_depth": 3, "inflight": 2,
           "availability_ratio": 0.98, "served": 700, "shed": 14,
           "errors": 0}
    row.update(extra)
    return row


def test_obs_top_phase_columns_and_exemplar_footer():
    events = [_telemetry_row(
        queue_p99_ms=61.0, batch_p99_ms=1.2, execute_p99_ms=9.4,
        respond_p99_ms=0.4,
        exemplar={"trace_id": "lg3-00000042", "latency_ms": 78.3,
                  "dominant_phase": "queue"},
    )]
    rows = top.serving_rows(events, now=101.0)
    assert rows[0]["queue_p99_ms"] == 61.0
    frame = top.render_serving(rows, {})
    for header in ("QU(ms)", "BA(ms)", "EX(ms)", "RE(ms)"):
        assert header in frame, frame
    assert "61.0" in frame
    assert "lg3-00000042" in frame and "dominant queue" in frame


def test_obs_top_degrades_without_phase_fields():
    """Pre-tracing journals must render the EXACT pre-tracing frame —
    no phantom columns, no exemplar footer."""
    events = [_telemetry_row()]
    frame = top.render_serving(top.serving_rows(events, now=101.0), {})
    assert "QU(ms)" not in frame and "dominant" not in frame
    assert "P99(ms)" in frame and "123.4" in frame


# ---------------------------------------------------------------------------
# obs.report: tail latency attribution
# ---------------------------------------------------------------------------


def _request_trace_rows():
    return [
        {"event": "request_trace", "ts": 1.0, "trace_id": "a",
         "outcome": "served", "sampled_by": "head", "latency_ms": 5.0,
         "phases": {"queue": 1.0, "batch": 0.5, "execute": 3.0,
                    "respond": 0.5},
         "dominant_phase": "execute", "rows": 8, "replica_id": 0},
        {"event": "request_trace", "ts": 2.0, "trace_id": "b",
         "outcome": "served", "sampled_by": "tail", "latency_ms": 80.0,
         "phases": {"queue": 70.0, "batch": 2.0, "execute": 6.0,
                    "respond": 2.0},
         "dominant_phase": "queue", "rows": 8, "replica_id": 1},
        {"event": "request_trace", "ts": 3.0, "trace_id": "c",
         "outcome": "shed", "sampled_by": "outcome", "latency_ms": 0.5,
         "phases": {}, "dominant_phase": "", "rows": 8, "replica_id": 1},
    ]


def test_report_tail_latency_attribution():
    tail = report_mod._tail_latency_summary(_request_trace_rows())
    assert tail["sampled"] == 3
    assert tail["by_reason"] == {"head": 1, "tail": 1, "outcome": 1}
    assert tail["exemplars"][0]["trace_id"] == "b"  # slowest first
    assert tail["dominant_phase"] == "queue"
    fractions = tail["phase_fractions"]
    assert max(fractions, key=fractions.get) == "queue"
    assert sum(fractions.values()) == pytest.approx(1.0)
    # Journals without request_trace rows render no section at all.
    assert report_mod._tail_latency_summary(
        [{"event": "job_start", "ts": 0.0}]
    ) is None


def test_report_renders_tail_section_from_golden_journal():
    summary = report_mod.summarize(report_mod.load_events(GOLDEN))
    assert "tail_latency" in summary
    text = report_mod.render_report(summary)
    assert "tail latency attribution" in text
    assert "lg7-00000102" in text and "dominant queue" in text


# ---------------------------------------------------------------------------
# loadgen: the client half
# ---------------------------------------------------------------------------


def test_loadgen_client_tracer_journals_root_spans(tmp_path):
    loadgen = _load_script("loadgen")
    assert loadgen.trace_id_for(7, 102) == "lg7-00000102"
    assert loadgen.trace_id_for(7, 102) == loadgen.trace_id_for(7, 102)
    tracer = loadgen.ClientTracer(seed=7, journal_dir=str(tmp_path))
    try:
        tracer.record(3, "served", 100.0, 0.0123)
        tracer.record(9, "shed", 101.0, 0.0007)
    finally:
        obs.journal().configure(None)
    events = _events(os.path.join(str(tmp_path), "events.jsonl"))
    spans = [e for e in events if e["event"] == "span"]
    assert [s["trace_id"] for s in spans] == [
        "lg7-00000003", "lg7-00000009"
    ]
    for span in spans:
        assert span["name"] == "client.predict"
        assert span["span_id"] == span["trace_id"]  # the trace ROOT
        assert span["proc"] == "loadgen"
    assert tracer.slowest(1)[0]["trace_id"] == "lg7-00000003"
    table = loadgen.render_slowest(
        tracer.slowest(2),
        events=[{"event": "request_trace", "trace_id": "lg7-00000003",
                 "latency_ms": 12.3, "dominant_phase": "queue",
                 "phases": {"queue": 10.0, "batch": 0.5, "execute": 1.5,
                            "respond": 0.3}}],
    )
    assert "lg7-00000003" in table and "queue" in table


# ---------------------------------------------------------------------------
# Acceptance e2e: stall -> tail exemplars -> alert evidence -> waterfall
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.e2e
def test_request_tracing_fleet_e2e(tmp_path, obs_registry_snapshot):
    """The ISSUE acceptance run.  A 2-replica fleet under traced load
    with an injected execute stall (ELASTICDL_FAULTS latency at the
    serving.execute site wedges the batcher thread, so requests pile up
    in the queue) must produce ONE shared journal from which:

    - a tail-sampled slow request resolves to the FULL waterfall
      client.predict -> rpc.predict -> serve.queue -> shared serve.batch
      -> serve.execute -> serve.respond with dominant phase queue;
    - obs.report's p99 exemplars name the same dominant phase;
    - the fired serving_latency slo_alert carries exemplar trace ids
      resolvable in the assembled trace.

    The control run (same fleet shape, no fault, SLO far above observed
    latency) journals ONLY head samples and fires nothing.
    """
    from test_serving import _exported_deepfm

    from elasticdl_tpu.serving.frontend import PredictClient
    from elasticdl_tpu.serving.supervisor import (
        start_serving_fleet,
        wait_for_replicas,
    )

    loadgen = _load_script("loadgen")
    validator = _load_script("validate_journal")
    _, _, gen1_dir, feats, _ = _exported_deepfm(tmp_path)
    warm = str(tmp_path / "warm.npz")
    with open(warm, "wb") as fh:
        fh.write(encode_features({k: v[:1] for k, v in feats.items()}))

    def run_fleet(serve_dir, env, num_requests, seed, slo_p99_ms):
        os.makedirs(serve_dir)
        # max_batch_size == the stream's batch_rows: ONE request per
        # dispatch, so a stalled dispatch leaves real queue depth behind
        # it (a 16-row budget would drain two waiters per stall and the
        # backlog — the queue phase under test — would never build).
        manager = start_serving_fleet(
            2, gen1_dir, serve_dir,
            worker_env=env,
            model_zoo="model_zoo",
            max_batch_size=8,
            max_wait_us=1000,
            telemetry_interval_s=0.5,
            warmup_features=warm,
            slo_p99_ms=slo_p99_ms,
            slo_compliance_window_s=60.0,
            trace_head_every=16,
        )
        clients = []
        journal_path = os.path.join(serve_dir, "events.jsonl")
        try:
            live = wait_for_replicas(serve_dir, 2, timeout_s=300)
            clients = [
                PredictClient(f"127.0.0.1:{r['port']}", deadline_s=60.0)
                for r in live
            ]
            tracer = loadgen.ClientTracer(seed=seed,
                                          journal_dir=serve_dir)
            stream = loadgen.RequestStream(loadgen.StreamConfig(seed=seed))
            result = loadgen.run_closed_loop(
                loadgen.round_robin_predict([c.predict for c in clients]),
                stream, num_requests=num_requests, concurrency=8,
                trace=tracer,
            )
            assert result.summary()["served"] == num_requests
            # Let telemetry/SLO ticks see the post-run ledger state; the
            # stall run needs the fire edge, which lands within a few
            # 0.5s ticks of the 5s-window burn going bad.
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                alerts = [
                    e for e in _events(journal_path)
                    if e["event"] == "slo_alert" and e["state"] == "fire"
                    and e.get("slo") == "serving_latency"
                ]
                if not env.get("ELASTICDL_FAULTS") or alerts:
                    break
                time.sleep(0.5)
        finally:
            for client in clients:
                client.close()
            manager.stop()
            obs.journal().configure(None)
        assert validator.validate_file(journal_path) == []
        return _events(journal_path)

    base_env = {"JAX_PLATFORMS": "cpu", "ELASTICDL_FORCE_PLATFORM": "cpu"}

    # -- stall run: 0.35s execute stalls starting at the 5th dispatch ---
    events = run_fleet(
        str(tmp_path / "serve_stall"),
        dict(base_env,
             ELASTICDL_FAULTS="serving.execute:latency=0.35@4x20"),
        num_requests=120, seed=11, slo_p99_ms=50.0,
    )
    traces = [e for e in events if e["event"] == "request_trace"]
    tails = [e for e in traces if e["sampled_by"] == "tail"]
    assert tails, "stalled requests above the 50ms SLO must tail-sample"
    assert any(e["dominant_phase"] == "queue" for e in tails)

    asm = trace_mod.assemble([str(tmp_path / "serve_stall")])
    assert asm["invariant_problems"] == []
    assert trace_mod.validate_chrome_trace(asm["chrome"]) == []
    spans = asm["spans"]
    # At least one slow queue-dominated request resolves to the FULL
    # six-span waterfall (served requests have every phase stamp).
    full_chains = []
    for event in tails:
        # The request INSIDE a stalled dispatch is execute-dominated;
        # the ones queued behind it carry the stall as queue time — the
        # waterfall the acceptance run is after.
        if event["outcome"] != "served" or event["dominant_phase"] != "queue":
            continue
        chain = trace_mod.request_chain(spans, event["trace_id"])
        if [s["name"] for s in chain] == list(
            trace_mod.SERVING_SPAN_ORDER
        ):
            full_chains.append((event, chain))
    assert full_chains, (
        "no queue-dominated tail exemplar produced a complete waterfall"
    )
    event, chain = full_chains[0]
    by_name = {s["name"]: s for s in chain}
    assert (by_name["serve.queue"]["end"]
            - by_name["serve.queue"]["start"]) > (
        by_name["serve.execute"]["end"]
        - by_name["serve.execute"]["start"]
    )

    # obs.report attributes the p99 exemplars to the same phase.
    summary = report_mod.summarize(events)
    assert summary["tail_latency"]["dominant_phase"] == "queue"

    # The fired latency alert carries resolvable exemplar evidence.
    fires = [e for e in events if e["event"] == "slo_alert"
             and e["state"] == "fire" and e["slo"] == "serving_latency"]
    assert fires, "the injected stall must page the latency SLO"
    with_exemplars = [a for a in fires if a.get("exemplars")]
    assert with_exemplars, fires
    for trace_id in with_exemplars[0]["exemplars"]:
        assert trace_mod.request_chain(spans, trace_id), trace_id

    # -- control run: no stall, SLO far above observed latency ----------
    control = run_fleet(
        str(tmp_path / "serve_ok"), dict(base_env),
        num_requests=60, seed=12, slo_p99_ms=2000.0,
    )
    ctl_traces = [e for e in control if e["event"] == "request_trace"]
    assert ctl_traces, "head sampling must still journal exemplars"
    assert {e["sampled_by"] for e in ctl_traces} == {"head"}
    assert not [e for e in control if e["event"] == "slo_alert"
                and e["state"] == "fire"]
