"""Model-quality observability plane tests (ISSUE 20).

Fast tier: the online-eval math (AUC/logloss/calibration), the
label-join ledger's bookkeeping (expiry, orphans, fault-injected drops
and duplicates), the canary gate's verdict lattice, the drift monitor's
edge discipline, and the graceful-degradation pins — `obs.top` and
`obs.report` must render journals from fleets predating the quality
plane without a single quality artifact.  An analyzer gate re-runs the
trace-purity and metric-cardinality rules over every file this plane
touched.

Slow tier (`make test-quality` / `make test-serving`): the ISSUE's
acceptance e2e — a 2-replica fleet under labeled load, a poisoned
(label-flipped) feed that both burns the quality SLO and produces a
regressed delta the canary gate HOLDS while the previous generation
serves on untouched, then a healthy recovery delta that passes — plus
the no-poison control that must fire nothing.  Everything runs on a
virtual clock, so the run replays bit-exactly.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.common import faults
from elasticdl_tpu.data.stream import click_label_rule, feedback_labels
from elasticdl_tpu.obs import report as report_mod
from elasticdl_tpu.obs import top as top_mod
from elasticdl_tpu.obs.quality import (
    CanaryGate,
    DriftMonitor,
    QualityLedger,
    ReplayBuffer,
    binary_auc,
    binary_logloss,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
GOLDEN = os.path.join(TESTS_DIR, "golden_journal.jsonl")


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_journal",
        os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules["validate_journal"] = module
    spec.loader.exec_module(module)
    return module


def _golden_events():
    return _events(GOLDEN)


def _pre_quality(events):
    """The same journal as seen by a fleet predating the quality plane."""
    return [
        e for e in events
        if not str(e.get("event", "")).startswith("quality")
    ]


# ---------------------------------------------------------------------------
# Online-eval math
# ---------------------------------------------------------------------------


def test_binary_auc_matches_bruteforce_pairwise():
    rng = np.random.RandomState(7)
    labels = (rng.rand(64) < 0.3).astype(np.float64)
    preds = rng.rand(64)
    wins = ties = 0
    for i in np.flatnonzero(labels == 1.0):
        for j in np.flatnonzero(labels == 0.0):
            if preds[i] > preds[j]:
                wins += 1
            elif preds[i] == preds[j]:
                ties += 1
    total = labels.sum() * (labels.size - labels.sum())
    expected = (wins + 0.5 * ties) / total
    assert binary_auc(labels, preds) == pytest.approx(expected, abs=1e-12)
    # Heavy ties resolve as half-wins, not as either extreme.
    tied = np.full(10, 0.5)
    tied_labels = np.array([1, 0] * 5, dtype=np.float64)
    assert binary_auc(tied_labels, tied) == pytest.approx(0.5)
    # A single-class window cannot define AUC: None, never a sentinel.
    assert binary_auc(np.ones(8), preds[:8]) is None
    assert binary_auc(np.zeros(8), preds[:8]) is None


# ---------------------------------------------------------------------------
# Label-join ledger bookkeeping
# ---------------------------------------------------------------------------


def test_ledger_expiry_orphans_and_window_eviction():
    ledger = QualityLedger(
        window_size=8, join_window_s=5.0, max_pending=64, origin="t"
    )
    preds = np.array([0.9, 0.1], dtype=np.float32)
    labels = np.array([1.0, 0.0], dtype=np.float32)
    ledger.note_prediction("a", preds, now=0.0)
    ledger.note_prediction("b", preds, now=1.0)
    # "a" expires at t=6 (outside the 5s join window); its label orphans.
    assert ledger.note_label("b", labels, now=4.0) is True
    assert ledger.note_label("a", labels, now=6.1) is False
    # A label with no sampled prediction orphans too.
    assert ledger.note_label("never-sampled", labels, now=6.2) is False
    snap = ledger.snapshot()
    assert snap["joined"] == 2
    assert snap["expired"] == 1
    assert snap["orphans"] == 2
    assert snap["pending"] == 0
    # The window is a ring: 5 more joined pairs of 2 evict the oldest.
    for i in range(5):
        tid = f"c{i}"
        ledger.note_prediction(tid, preds, now=7.0 + i)
        ledger.note_label(tid, labels, now=7.0 + i)
    snap = ledger.snapshot()
    assert snap["window"] == 8
    assert snap["joined"] == 12
    # Online metrics are recomputed from exactly the window pairs.
    window_labels, window_preds = ledger.pairs()
    assert snap["auc"] == pytest.approx(
        binary_auc(window_labels, window_preds), abs=1e-12
    )
    assert snap["logloss"] == pytest.approx(
        binary_logloss(window_labels, window_preds), abs=1e-12
    )


def test_ledger_label_join_fault_drop_and_duplicate():
    ledger = QualityLedger(window_size=64, join_window_s=60.0, origin="t")
    preds = np.array([0.8], dtype=np.float32)
    labels = np.array([1.0], dtype=np.float32)
    # Call 1 drops the label, call 2 delivers it twice (the second
    # delivery joins nothing — its prediction was consumed — and counts
    # as an orphan, the honest at-least-once bookkeeping).
    faults.install("quality.label_join:error@1, quality.label_join:truncate@2")
    ledger.note_prediction("x", preds, now=0.0)
    assert ledger.note_label("x", labels, now=1.0) is False  # dropped
    assert ledger.note_label("x", labels, now=2.0) is True  # + duplicate
    snap = ledger.snapshot()
    assert snap["dropped_injected"] == 1
    assert snap["duplicates_injected"] == 1
    assert snap["joined"] == 1
    assert snap["orphans"] == 1


def test_ledger_journal_silent_until_first_prediction(
    journal_file, obs_registry_snapshot
):
    ledger = QualityLedger(window_size=16, join_window_s=60.0, origin="r")
    # Pre-quality runs journal nothing new: no predictions sampled yet.
    assert ledger.journal_window(now=0.0) is None
    assert _events(journal_file) == []
    ledger.note_prediction("t0", np.array([0.7]), now=0.0)
    ledger.note_label("t0", np.array([1.0]), now=1.0)
    snap = ledger.journal_window(now=2.0)
    assert snap is not None
    events = _events(journal_file)
    assert [e["event"] for e in events] == ["quality_window"]
    event = events[0]
    assert event["joined"] == 1 and event["origin"] == "r"
    assert 0.0 <= event["auc"] <= 1.0 if "auc" in event else True
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []


# ---------------------------------------------------------------------------
# Canary gate verdict lattice
# ---------------------------------------------------------------------------


def _labeled_replay(n_batches=8, rows=16):
    from elasticdl_tpu.data.stream import synthetic_click_batch

    replay = ReplayBuffer(max_batches=n_batches)
    for b in range(n_batches):
        feats = synthetic_click_batch(b * rows, (b + 1) * rows, 1000)
        replay.add(feats, click_label_rule(feats))
    return replay


def _scorer(offset):
    def predict(features):
        labels = click_label_rule(features)
        return np.clip(0.5 + offset * (2.0 * labels - 1.0), 0.01, 0.99)

    return predict


def test_gate_holds_regression_and_passes_parity():
    gate = CanaryGate(_labeled_replay(), min_rows=64)
    good, bad = _scorer(0.35), _scorer(-0.35)
    verdict = gate.evaluate(good, good)
    assert verdict["outcome"] == "passed"
    assert verdict["quality"] == "known"
    assert verdict["reason"] == "within_thresholds"
    verdict = gate.evaluate(good, bad)
    assert verdict["outcome"] == "held"
    assert "logloss_regress" in verdict["reason"]
    assert verdict["candidate_logloss"] > verdict["baseline_logloss"]
    # The escape hatch records the same evidence but never blocks.
    forced = CanaryGate(_labeled_replay(), min_rows=64, force=True)
    verdict = forced.evaluate(good, bad)
    assert verdict["outcome"] == "forced"
    assert verdict["quality"] == "known"


def test_gate_unknown_policy_and_shadow_faults():
    cold = ReplayBuffer(max_batches=4)  # no labeled rows at all
    assert CanaryGate(cold, min_rows=64).evaluate(
        _scorer(0.3), _scorer(0.3)
    )["outcome"] == "passed"  # open: a broken label pipe can't freeze swaps
    held = CanaryGate(cold, min_rows=64, unknown_policy="closed").evaluate(
        _scorer(0.3), _scorer(0.3)
    )
    assert held["outcome"] == "held"
    assert held["reason"] == "insufficient_labeled_rows"
    # A candidate that blows up mid-shadow degrades to unknown, never raises.
    def broken(_features):
        raise RuntimeError("shape mismatch")

    verdict = CanaryGate(_labeled_replay(), min_rows=64).evaluate(
        _scorer(0.3), broken
    )
    assert verdict["quality"] == "unknown"
    assert verdict["reason"].startswith("shadow_eval_error:")
    # The quality.shadow_eval fault site is the same unknown path.
    faults.install("quality.shadow_eval:error=injected@1")
    verdict = CanaryGate(
        _labeled_replay(), min_rows=64, unknown_policy="closed"
    ).evaluate(_scorer(0.3), _scorer(0.3))
    assert verdict["outcome"] == "held"
    assert verdict["reason"] == "shadow_eval_fault:injected"


# ---------------------------------------------------------------------------
# Drift monitor edge discipline
# ---------------------------------------------------------------------------


def test_drift_monitor_edge_triggered_events(
    journal_file, obs_registry_snapshot
):
    from elasticdl_tpu.data.stream import synthetic_click_batch

    monitor = DriftMonitor(threshold=0.25, bins=32, origin="replica_0")
    assert monitor.evaluate(0.0) is None  # incomparable: no serve sketch
    for b in range(16):
        monitor.observe_train(
            synthetic_click_batch(b * 64, (b + 1) * 64, 5000)
        )
    # Matched traffic: same generator, same range — no edge.
    for b in range(16):
        monitor.observe_serve(
            synthetic_click_batch(b * 64, (b + 1) * 64, 5000)
        )
    low = monitor.evaluate(1.0)
    assert low is not None and low < 0.25
    # Skewed serving traffic (one hot id) breaches — ONE event, not one
    # per tick.
    hot = {"user": np.full(4096, 17, dtype=np.int64),
           "item": np.full(4096, 23, dtype=np.int64)}
    monitor.observe_serve(hot)
    high = monitor.evaluate(2.0)
    assert high is not None and high > 0.25
    monitor.evaluate(3.0)  # still breached: no second event
    # Flooding matched traffic clears the breach: the second edge.
    for b in range(256):
        monitor.observe_serve(
            synthetic_click_batch(b * 64, (b + 1) * 64, 5000)
        )
    assert monitor.evaluate(4.0) < 0.25
    events = _events(journal_file)
    assert [e["event"] for e in events] == ["quality_drift"] * 2
    assert [e["state"] for e in events] == ["breach", "clear"]
    assert all(e["origin"] == "replica_0" for e in events)
    assert all(e["threshold"] == 0.25 for e in events)
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []


# ---------------------------------------------------------------------------
# Graceful degradation: pre-quality journals render no quality artifact
# ---------------------------------------------------------------------------


def _synthetic_serving_events(with_quality):
    base = {
        "event": "serving_telemetry", "qps": 120.0, "p50_ms": 2.0,
        "p99_ms": 9.0, "queue_depth": 0, "inflight": 1,
        "availability_ratio": 1.0, "served": 1200, "shed": 0,
        "errors": 0, "generation": 2, "step": 640,
    }
    events = [dict(base, ts=100.0, replica_id=0),
              dict(base, ts=100.5, replica_id=1)]
    if with_quality:
        events += [
            {"event": "quality_window", "ts": 101.0, "origin": "replica_0",
             "joined": 512, "window": 256, "pending": 9, "expired": 3,
             "orphans": 1, "auc": 0.71, "logloss": 0.48,
             "calibration_error": 0.04},
            {"event": "quality_drift", "ts": 101.2, "origin": "replica_0",
             "state": "breach", "divergence": 0.41, "threshold": 0.25},
        ]
    return events


def test_top_serving_frame_is_byte_identical_without_quality_events():
    pre = _synthetic_serving_events(with_quality=False)
    rows = top_mod.serving_rows(pre, now=102.0)
    frame = top_mod.render_serving(rows, {}, addr="journal")
    # Pre-quality journal: no quality column, cell, or note — and the
    # frame is deterministic byte for byte.
    assert "AUC" not in frame and "CAL" not in frame
    assert "DRIFT" not in frame and "quality" not in frame
    assert frame == top_mod.render_serving(
        top_mod.serving_rows(pre, now=102.0), {}, addr="journal"
    )
    assert top_mod.quality_note(pre) == ""
    # The same telemetry WITH quality events grows the columns + note.
    full = _synthetic_serving_events(with_quality=True)
    frame = top_mod.render_serving(
        top_mod.serving_rows(full, now=102.0), {}, addr="journal"
    )
    assert "AUC" in frame and "CAL" in frame and "DRIFT" in frame
    assert "0.710" in frame and "0.040" in frame
    assert "0.41!" in frame  # breached drift cell carries the marker
    note = top_mod.quality_note(full)
    assert note.startswith("quality: joined=512 pending=9")
    # Replica 1 journaled no quality: its cells degrade to "-".
    replica_1 = [l for l in frame.splitlines() if l.startswith("1 ")]
    assert replica_1 and replica_1[0].split()[-3:] == ["-", "-", "-"]


def test_report_has_no_quality_section_on_pre_quality_journal():
    events = _golden_events()
    pre = _pre_quality(events)
    assert len(pre) < len(events), "golden journal must carry quality rows"
    summary = report_mod.summarize(pre)
    assert "quality" not in summary
    rendered = report_mod.render_report(summary)
    assert "model quality" not in rendered
    assert "quality_gate" not in rendered
    # The full golden journal reconstructs the plane: windows, the held
    # gate, the drift breach.
    summary = report_mod.summarize(events)
    quality = summary["quality"]
    assert quality["window_updates"] >= 1
    assert quality["holds"] >= 1
    assert quality["drift_breaches"] >= 1
    assert quality["gates"][-1]["outcome"] == "held"
    rendered = report_mod.render_report(summary)
    assert "model quality" in rendered and "HELD" in rendered


# ---------------------------------------------------------------------------
# Invariant-rule coverage of the quality plane's call sites
# ---------------------------------------------------------------------------


def test_quality_call_sites_pass_purity_and_cardinality_rules():
    """Satellite: every file the quality plane touched keeps (a) obs
    calls out of traced code and (b) unbounded names out of metric
    labels — and both rules still bite on seeded violations, so the
    clean pass is not vacuous."""
    from elasticdl_tpu.analysis.core import SourceFile, run_checks
    from elasticdl_tpu.analysis.jax_rules import check_trace_purity
    from elasticdl_tpu.analysis.rules import check_metric_label_cardinality

    call_sites = [
        os.path.join(REPO_ROOT, rel)
        for rel in (
            "elasticdl_tpu/obs/quality.py",
            "elasticdl_tpu/obs/slo.py",
            "elasticdl_tpu/obs/top.py",
            "elasticdl_tpu/obs/report.py",
            "elasticdl_tpu/serving/continuous.py",
            "elasticdl_tpu/serving/runtime.py",
            "elasticdl_tpu/serving/batcher.py",
            "elasticdl_tpu/serving/ledger.py",
            "elasticdl_tpu/serving/frontend.py",
            "elasticdl_tpu/serving/replica_main.py",
            "elasticdl_tpu/data/stream.py",
            "elasticdl_tpu/worker/worker.py",
            "elasticdl_tpu/worker/main.py",
            "scripts/loadgen.py",
        )
    ]
    violations = run_checks(
        call_sites, [check_trace_purity, check_metric_label_cardinality]
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    seeded_purity = SourceFile.parse(
        "seeded_purity.py",
        "import jax\n"
        "@jax.jit\n"
        "def step(x, ledger):\n"
        "    ledger.journal.record('quality_window', joined=1)\n"
        "    return x\n",
    )
    assert check_trace_purity(seeded_purity), (
        "trace-purity no longer catches journal calls under jit"
    )
    seeded_cardinality = SourceFile.parse(
        "seeded_card.py",
        "from elasticdl_tpu import obs\n"
        "obs.gauge('elasticdl_quality_auc', 'h',\n"
        "          labelnames=('worker_id',))\n",
    )
    assert check_metric_label_cardinality(seeded_cardinality), (
        "cardinality rule no longer catches worker_id labels"
    )


# ---------------------------------------------------------------------------
# Acceptance e2e: poisoned delta held, SLO burned, recovery passes
# ---------------------------------------------------------------------------


def _click_labels_like(feats, reference_labels):
    labels = feedback_labels(feats)
    if labels is None:
        return None
    return labels.astype(np.asarray(reference_labels).dtype).reshape(
        np.asarray(reference_labels).shape
    )


@pytest.mark.slow
@pytest.mark.e2e
def test_poisoned_delta_canary_gate_e2e(
    tmp_path, journal_file, obs_registry_snapshot
):
    """ISSUE 20 acceptance: a 2-replica fleet under labeled load.  A
    label-flipped feed (`stream.labels:error`) poisons BOTH the training
    shard (the retrained delta regresses) and the online joins (the
    windowed logloss burns the model_quality SLO).  The canary gate
    HOLDS the poisoned delta on every retry while the previous
    generation serves zero dropped requests; after the feed heals and a
    recovery retrain compacts past the quarantined link, the healthy
    artifact passes the same gate.  Virtual clock throughout."""
    from elasticdl_tpu.checkpoint.delta import DeltaExporter
    from elasticdl_tpu.obs.slo import SLOPlane, quality_slo
    from elasticdl_tpu.serving.continuous import DeltaWatcher
    from elasticdl_tpu.serving.runtime import ServingReplica
    from test_serving import _trained_deepfm

    zoo, trainer, batches = _trained_deepfm(steps=0)
    ref_labels = batches[0][1]

    def train_steps(count, start):
        for k in range(count):
            feats, _ = batches[(start + k) % len(batches)]
            labels = _click_labels_like(feats, ref_labels)
            assert labels is not None
            trainer.train_step(feats, labels)
            drift.observe_train(feats)

    drift = DriftMonitor(threshold=0.2, bins=64, origin="replica_0")

    # Ground truth everywhere is the stream's click_label_rule, so the
    # feed, the joins, and the offline audit agree element-wise.
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(
        pub_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    train_steps(24, start=0)
    full_dir = exporter.publish_full(trainer)

    replicas, ledgers, watchers = [], [], []
    for rid in range(2):
        replica = ServingReplica(full_dir, model_zoo="model_zoo")
        replay = ReplayBuffer(max_batches=16)
        ledger = QualityLedger(
            window_size=256, join_window_s=8.0,
            origin=f"replica_{rid}", replay=replay,
        )
        gate = CanaryGate(
            replay, max_logloss_regress=0.10, max_auc_drop=0.05,
            min_rows=64,
        )
        watcher = DeltaWatcher(
            replica, pub_dir, gate=gate, origin=f"replica_{rid}"
        )
        replicas.append(replica)
        ledgers.append(ledger)
        watchers.append(watcher)

    base_step = replicas[0].generation.step
    served = 0
    pending_feats = {}  # tick -> features awaiting their delayed label

    def serve_tick(tick, feats, attach_features):
        """One labeled-loadgen tick: both replicas predict, the label
        for tick-2 arrives 2 virtual seconds late, windows journal."""
        nonlocal served
        now = float(tick)
        for rid, (replica, ledger) in enumerate(zip(replicas, ledgers)):
            preds = np.asarray(replica.execute(feats, n_valid=16)).ravel()
            served += 1
            ledger.note_prediction(
                f"t{tick}-r{rid}", preds, now,
                features=feats if attach_features else None,
            )
        pending_feats[tick] = feats
        late = pending_feats.pop(tick - 2, None)
        if late is not None:
            labels = feedback_labels(late)  # the one shared label feed
            if labels is not None:
                for rid, ledger in enumerate(ledgers):
                    ledger.note_label(f"t{tick - 2}-r{rid}", labels, now)
        for ledger in ledgers:
            ledger.journal_window(now)
        drift.observe_serve(feats)
        drift.evaluate(now)

    # -- Phase A (t=0..29): clean labeled traffic fills the windows and
    # the gates' replay buffers with trusted evidence.
    for tick in range(30):
        serve_tick(tick, batches[(tick * 7) % len(batches)][0],
                   attach_features=True)
    baselines = []
    for ledger in ledgers:
        snap = ledger.snapshot()
        labels, preds = ledger.pairs()
        # Acceptance: the online AUC reproduces the offline audit of the
        # exact same joined set.
        assert snap["auc"] == pytest.approx(
            binary_auc(labels, preds), abs=1e-9
        )
        assert snap["logloss"] == pytest.approx(
            binary_logloss(labels, preds), abs=1e-9
        )
        assert snap["joined"] >= 256
        baselines.append(snap["logloss"])
    probe = batches[0][0]
    baseline_out = np.asarray(replicas[0].execute(probe, n_valid=16))

    plane = SLOPlane(
        specs=[quality_slo(
            max_logloss=max(baselines) + 0.15,
            compliance_window_s=7200.0, min_window_s=5.0,
        )],
        status_interval_s=1000.0, origin="replica_0",
    )

    # -- Poison: the upstream label shard flips.  The SAME fault feeds
    # the training loop (a poisoned retrain) and the online joins (the
    # quality windows).
    faults.install("stream.labels:errorx*")
    train_steps(30, start=30)
    poisoned_delta = exporter.publish_delta(trainer)
    assert poisoned_delta is not None

    held_polls = 0
    hot_feats = {
        "dense": batches[0][0]["dense"],
        "cat": np.full_like(np.asarray(batches[0][0]["cat"]), 17),
    }
    for tick in range(30, 50):
        # During the storm the sampler stops attaching features, so the
        # replay evidence stays the last known-good labeled set rather
        # than silently absorbing the poisoned feed.
        serve_tick(tick, batches[(tick * 7) % len(batches)][0],
                   attach_features=False)
        # A flash crowd on one hot key rides the same replicas — the
        # train-serve drift sketch must notice the traffic mix shifting
        # while the label feed burns.
        for replica in replicas:
            np.asarray(replica.execute(hot_feats, n_valid=16))
            served += 1
        drift.observe_serve(hot_feats)
        plane.tick(float(tick))
        if tick in (31, 45):  # the watcher retries a held link forever
            for watcher in watchers:
                summary = watcher.poll_once()
                assert summary["outcome"] == "held"
                assert summary["held"] == poisoned_delta
                assert "logloss_regress" in summary["reason"]
                held_polls += 1
    assert held_polls == 4
    assert "model_quality" in plane.slos.alerting(), (
        "poisoned joins must burn the quality SLO"
    )
    # The previous generation never stopped serving, bit-identically.
    for replica in replicas:
        assert replica.generation.step == base_step
    np.testing.assert_array_equal(
        baseline_out, np.asarray(replicas[0].execute(probe, n_valid=16))
    )

    # -- Recovery: the feed heals, and a clean retrain compacts past the
    # quarantined link.  Compaction folds into a fresh FULL artifact, so
    # catching up is the (ungated) quarantine-repair reload; the NEXT
    # clean delta then rides through the same canary gate and passes.
    faults.clear()
    train_steps(60, start=60)
    assert exporter.publish_delta(trainer) is not None
    assert exporter.compact() is not None
    for tick in range(50, 56):
        serve_tick(tick, batches[(tick * 7) % len(batches)][0],
                   attach_features=True)
    for watcher, replica in zip(watchers, replicas):
        summary = watcher.poll_once()
        assert summary["outcome"] == "applied", summary
        assert summary["reloaded_full"] is True
        assert replica.generation.step == exporter.head_step
    train_steps(12, start=120)
    healthy_delta = exporter.publish_delta(trainer)
    assert healthy_delta is not None
    for tick in range(56, 62):
        serve_tick(tick, batches[(tick * 7) % len(batches)][0],
                   attach_features=True)
    for watcher, replica in zip(watchers, replicas):
        summary = watcher.poll_once()
        assert summary["outcome"] == "applied", summary
        assert summary["applied_deltas"] == 1
        assert replica.generation.step == exporter.head_step
    assert served == 2 * 82  # zero dropped requests, every request served

    # -- Journal: the run's whole quality story, schema-valid.
    events = _events(journal_file)
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []

    gates = [e for e in events if e["event"] == "quality_gate"]
    outcomes = [(e["origin"], e["outcome"]) for e in gates]
    assert outcomes.count(("replica_0", "held")) == 2
    assert outcomes.count(("replica_1", "held")) == 2
    assert outcomes[-2:] == [
        ("replica_0", "passed"), ("replica_1", "passed")
    ]
    for gate_event in gates:
        if gate_event["outcome"] == "held":
            assert "logloss_regress" in gate_event["reason"]
            assert gate_event["candidate_logloss"] > \
                gate_event["baseline_logloss"] + 0.10
            assert gate_event["step"] > base_step

    alerts = [e for e in events if e["event"] == "slo_alert"]
    fired = [a for a in alerts if a["state"] == "fire"]
    assert fired and fired[0]["slo"] == "model_quality"
    assert fired[0]["offending"] == "elasticdl_quality_logloss"

    drifts = [e for e in events if e["event"] == "quality_drift"]
    assert any(e["state"] == "breach" for e in drifts), (
        "hot-batch storm never tripped the train-serve drift sketch"
    )

    # The quality windows tell the poisoning story.  Windows journal in
    # tick order (one per tick from the first join at tick 2): the first
    # 28 are phase A's clean joins; by ticks 42..47 (indices 40..45) the
    # 256-pair window has fully churned onto flipped labels.
    lls = [e["logloss"] for e in events
           if e["event"] == "quality_window"
           and e["origin"] == "replica_0" and "logloss" in e]
    assert len(lls) == 60  # ticks 2..61, every tick journals its window
    assert max(lls[:28]) < min(lls[40:46]), (
        "poisoned joins must visibly degrade the windowed logloss"
    )

    # obs.report reconstructs the held-swap timeline from the journal.
    summary = report_mod.summarize(events)
    quality = summary["quality"]
    assert quality["holds"] == 4
    assert quality["gate_decisions"] == 6
    assert quality["drift_breaches"] >= 1
    gate_timeline = [g["outcome"] for g in quality["gates"]]
    assert gate_timeline[:4] == ["held"] * 4
    assert gate_timeline[-2:] == ["passed"] * 2
    rendered = report_mod.render_report(summary)
    assert "model quality" in rendered and "HELD" in rendered


@pytest.mark.slow
@pytest.mark.e2e
def test_no_poison_control_fires_nothing(
    tmp_path, journal_file, obs_registry_snapshot
):
    """The control run: same fleet, same labeled load, no fault.  The
    healthy delta passes the gate, the quality SLO never alerts, and no
    drift or hold appears anywhere in the journal."""
    from elasticdl_tpu.checkpoint.delta import DeltaExporter
    from elasticdl_tpu.obs.slo import SLOPlane, quality_slo
    from elasticdl_tpu.serving.continuous import DeltaWatcher
    from elasticdl_tpu.serving.runtime import ServingReplica
    from test_serving import _trained_deepfm

    zoo, trainer, batches = _trained_deepfm(steps=0)
    ref_labels = batches[0][1]
    for k in range(24):
        feats, _ = batches[k % len(batches)]
        trainer.train_step(feats, _click_labels_like(feats, ref_labels))

    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(
        pub_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    full_dir = exporter.publish_full(trainer)
    replica = ServingReplica(full_dir, model_zoo="model_zoo")
    replay = ReplayBuffer(max_batches=16)
    ledger = QualityLedger(
        window_size=256, join_window_s=8.0, origin="replica_0",
        replay=replay,
    )
    gate = CanaryGate(replay, min_rows=64)
    watcher = DeltaWatcher(replica, pub_dir, gate=gate, origin="replica_0")

    pending = {}
    for tick in range(30):
        now = float(tick)
        feats = batches[(tick * 7) % len(batches)][0]
        preds = np.asarray(replica.execute(feats, n_valid=16)).ravel()
        ledger.note_prediction(f"t{tick}", preds, now, features=feats)
        pending[tick] = feats
        late = pending.pop(tick - 2, None)
        if late is not None:
            ledger.note_label(f"t{tick - 2}", feedback_labels(late), now)
        ledger.journal_window(now)

    plane = SLOPlane(
        specs=[quality_slo(
            max_logloss=ledger.snapshot()["logloss"] + 0.15,
            compliance_window_s=7200.0, min_window_s=5.0,
        )],
        status_interval_s=1000.0, origin="replica_0",
    )
    for k in range(24, 48):
        feats, _ = batches[k % len(batches)]
        trainer.train_step(feats, _click_labels_like(feats, ref_labels))
    assert exporter.publish_delta(trainer) is not None
    for tick in range(30, 50):
        now = float(tick)
        feats = batches[(tick * 7) % len(batches)][0]
        preds = np.asarray(replica.execute(feats, n_valid=16)).ravel()
        ledger.note_prediction(f"t{tick}", preds, now, features=feats)
        pending[tick] = feats
        late = pending.pop(tick - 2, None)
        if late is not None:
            ledger.note_label(f"t{tick - 2}", feedback_labels(late), now)
        ledger.journal_window(now)
        plane.tick(now)

    summary = watcher.poll_once()
    assert summary["outcome"] == "applied", summary
    assert replica.generation.step == exporter.head_step
    assert not plane.slos.alerting()

    events = _events(journal_file)
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []
    gates = [e for e in events if e["event"] == "quality_gate"]
    assert [e["outcome"] for e in gates] == ["passed"]
    assert not any(e["event"] == "slo_alert" for e in events)
    assert not any(e["event"] == "quality_drift" for e in events)
