"""FSDP dense-sharding tests (SURVEY.md §5: "dense: replicated or
FSDP-sharded").  The 8-virtual-device mesh verifies that sharded state
really spans devices, trains equivalently to replicated mode, and
round-trips checkpoints/export."""

import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer


def _model():
    from model_zoo.mnist import mnist_functional_api as zoo

    return zoo


def _batches(n=64, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.rand(n, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    return images, labels


def _trainer(dense_sharding):
    zoo = _model()
    mesh = build_mesh(MeshConfig())
    return DataParallelTrainer(
        zoo.custom_model(),
        zoo.loss,
        optax.sgd(0.1, momentum=0.9),
        mesh,
        seed=0,
        dense_sharding=dense_sharding,
    )


def test_fsdp_state_actually_shards():
    trainer = _trainer("fsdp")
    images, labels = _batches()
    trainer.ensure_initialized(images[:16])
    state = trainer.state
    # The big dense kernels span all 8 devices with 1/8 per device...
    big = [
        p for p in __import__("jax").tree.leaves(state.params)
        if p.size >= DataParallelTrainer.FSDP_MIN_LEAF
        and p.shape[0] % 8 == 0
    ]
    assert big, "test model has no shardable leaves"
    for leaf in big:
        assert len(leaf.sharding.device_set) == 8
        shard = leaf.addressable_shards[0]
        assert shard.data.size == leaf.size // 8
    # ...and scalars/small leaves stay replicated.
    step_shard = state.step.addressable_shards[0]
    assert step_shard.data.size == state.step.size


def test_fsdp_trains_equivalently_to_replicated():
    images, labels = _batches(n=16)
    losses = {}
    for mode in ("replicated", "fsdp"):
        trainer = _trainer(mode)
        # Same batch each step: the random data is memorizable, so the
        # loss must fall — and both layouts must fall IDENTICALLY.
        losses[mode] = [
            float(trainer.train_step(images, labels)) for _ in range(6)
        ]
    np.testing.assert_allclose(
        losses["replicated"], losses["fsdp"], rtol=2e-4
    )
    assert losses["fsdp"][-1] < losses["fsdp"][0]  # it actually learns


def test_fsdp_checkpoint_roundtrip_and_export(tmp_path):
    images, labels = _batches(n=32)
    t1 = _trainer("fsdp")
    for i in range(2):
        t1.train_step(images[i * 16 : (i + 1) * 16], labels[i * 16 :][:16])
    host = t1.state_to_host()
    # Host snapshot is complete (gathered), numpy, full-shape.
    first = np.asarray(__import__("jax").tree.leaves(host.params)[0])
    assert first.ndim >= 1

    t2 = _trainer("fsdp")
    t2.state = host  # restore re-shards under the fsdp layout
    l1 = float(t1.train_step(images[:16], labels[:16]))
    l2 = float(t2.train_step(images[:16], labels[:16]))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)

    # Export gathers sharded params into a servable artifact.
    from elasticdl_tpu.serving import export_model, load_for_serving

    out = str(tmp_path / "export")
    export_model(
        t1, out,
        model_zoo="model_zoo",
        model_def="mnist.mnist_functional_api",
    )
    served = load_for_serving(out)
    pred = np.asarray(served.predict(images[:4]))
    assert pred.shape == (4, 10) and np.isfinite(pred).all()


def test_fsdp_sharded_checkpoint_roundtrip(tmp_path):
    """FSDP jobs checkpoint shard-wise: sharded leaves write their row
    intervals, replicated leaves write once, no full-model gather — and
    restore rebuilds identical training state."""
    import json

    from elasticdl_tpu.checkpoint import ShardedCheckpointSaver

    images, labels = _batches(n=16)
    t1 = _trainer("fsdp")
    for _ in range(3):
        t1.train_step(images, labels)
    saver = ShardedCheckpointSaver(str(tmp_path))
    t1.save_checkpoint(saver, t1.step)

    manifest = json.loads(
        (tmp_path / "step_000000000003" / "manifest.json").read_text()
    )
    assert any(k.startswith("dense|") for k in manifest["arrays"])

    t2 = _trainer("fsdp")
    t2.set_sharded_restore(saver, 3)
    assert t2.step == 3
    l1 = float(t1.train_step(images, labels))
    l2 = float(t2.train_step(images, labels))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_rejects_unknown_mode():
    with pytest.raises(ValueError, match="dense_sharding"):
        _trainer("zero3")
