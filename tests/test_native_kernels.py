"""C++ kernel library parity tests.

Parity surface: the reference's Go kernel tests
(elasticdl/pkg/kernel/kernel_test.go — optimizer math vs golden values).
Here the golden reference is the JAX sparse path (parallel/sparse_optim)
and optax, so the native and compiled implementations are pinned to the
same math.
"""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel import sparse_optim

native = pytest.importorskip("elasticdl_tpu.native")
if native.load() is None:
    pytest.skip("no C++ toolchain available", allow_module_level=True)

VOCAB, DIM = 16, 4


@pytest.fixture
def kernels():
    return native.NativeKernels()


@pytest.fixture
def data():
    rng = np.random.RandomState(0)
    return {
        "table": rng.rand(VOCAB, DIM).astype(np.float32),
        "ids": np.array([3, 7, 3, 0, 7], np.int64),
        "grads": rng.rand(5, DIM).astype(np.float32),
    }


def test_dense_sgd_matches_optax(kernels):
    rng = np.random.RandomState(1)
    param = rng.rand(32).astype(np.float32)
    grad = rng.rand(32).astype(np.float32)
    expected = np.asarray(
        optax.apply_updates(
            jnp.asarray(param),
            optax.sgd(0.1).update(jnp.asarray(grad),
                                  optax.sgd(0.1).init(jnp.asarray(param)))[0],
        )
    )
    kernels.sgd(param, grad, 0.1)
    np.testing.assert_allclose(param, expected, rtol=1e-6)


def test_dense_adam_matches_optax(kernels):
    rng = np.random.RandomState(2)
    param = rng.rand(32).astype(np.float32)
    grads = [rng.rand(32).astype(np.float32) for _ in range(3)]
    tx = optax.adam(0.01, b1=0.9, b2=0.999, eps=1e-8)
    jp = jnp.asarray(param)
    opt_state = tx.init(jp)
    m = np.zeros_like(param)
    v = np.zeros_like(param)
    for step, g in enumerate(grads, start=1):
        updates, opt_state = tx.update(jnp.asarray(g), opt_state, jp)
        jp = optax.apply_updates(jp, updates)
        kernels.adam(param, m, v, g, 0.01, 0.9, 0.999, 1e-8, step)
    # float32 reassociation drift between optax and the sequential C++
    # loop: tiny absolute, looks large relatively on near-zero params.
    np.testing.assert_allclose(param, np.asarray(jp), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("name", ["sgd", "momentum", "adagrad", "adam"])
def test_sparse_kernels_match_jax_path(kernels, data, name):
    """The native sparse apply must produce the same tables/slots as the
    XLA-compiled sparse_optim over multiple steps with duplicate ids."""
    table_native = data["table"].copy()
    jax_opt = {
        "sgd": sparse_optim.sgd(0.1),
        "momentum": sparse_optim.momentum(0.1, mu=0.9),
        "adagrad": sparse_optim.adagrad(0.1, epsilon=1e-7),
        "adam": sparse_optim.adam(0.01),
    }[name]
    jt = jnp.asarray(data["table"])
    slots = jax_opt.init_slots_logical(jt)

    velocity = np.zeros_like(table_native)
    accum = np.zeros_like(table_native)
    m = np.zeros_like(table_native)
    v = np.zeros_like(table_native)
    t_rows = np.zeros((VOCAB,), np.int64)

    rng = np.random.RandomState(3)
    for _ in range(3):
        grads = rng.rand(5, DIM).astype(np.float32)
        ids32 = data["ids"].astype(np.int32)
        jt, slots = jax_opt.apply_logical(jt, slots, jnp.asarray(ids32),
                                          jnp.asarray(grads))
        if name == "sgd":
            kernels.sgd_sparse(table_native, data["ids"], grads, 0.1)
        elif name == "momentum":
            kernels.momentum_sparse(table_native, velocity, data["ids"],
                                    grads, 0.1, 0.9)
        elif name == "adagrad":
            kernels.adagrad_sparse(table_native, accum, data["ids"], grads,
                                   0.1, eps=1e-7)
        else:
            kernels.adam_sparse(table_native, m, v, t_rows, data["ids"],
                                grads, 0.01)
    np.testing.assert_allclose(table_native, np.asarray(jt), rtol=1e-4,
                               atol=1e-6)
    from elasticdl_tpu.parallel import packed as pk
    from elasticdl_tpu.parallel.packed import PackedSpec

    spec = PackedSpec(VOCAB, DIM)
    if name == "momentum":
        np.testing.assert_allclose(
            velocity, np.asarray(pk.unpack(spec, slots["momentum"])),
            rtol=1e-4, atol=1e-6,
        )
    if name == "adagrad":
        np.testing.assert_allclose(
            accum, np.asarray(pk.unpack(spec, slots["accumulator"])),
            rtol=1e-4, atol=1e-6,
        )
    if name == "adam":
        np.testing.assert_allclose(
            m, np.asarray(pk.unpack(spec, slots["m"])), rtol=1e-4, atol=1e-6
        )
        # t is stored lane-broadcast (packed table shape); column 0 of the
        # unpacked logical view is the per-row step count.
        np.testing.assert_allclose(
            t_rows,
            np.asarray(pk.unpack(spec, slots["t"]))[:, 0].astype(np.int64),
        )


def test_sparse_zero_grad_rows_untouched(kernels, data):
    table = data["table"].copy()
    m = np.zeros_like(table)
    v = np.zeros_like(table)
    t_rows = np.zeros((VOCAB,), np.int64)
    kernels.adam_sparse(
        table, m, v, t_rows, np.array([2, 5], np.int64),
        np.zeros((2, DIM), np.float32), 0.01,
    )
    np.testing.assert_array_equal(table, data["table"])
    assert t_rows.sum() == 0
