"""Flag system tests (parity: args_test.py in the reference)."""

import pytest

from elasticdl_tpu.common import args as args_mod


def test_master_parser_minimal():
    args = args_mod.parse_master_args(
        ["--model_zoo", "model_zoo", "--model_def", "mnist.mnist_functional_api"]
    )
    assert args.distribution_strategy == "Local"
    assert args.num_workers == 1
    assert args.records_per_task == 4096


def test_unknown_flags_tolerated():
    args = args_mod.parse_master_args(
        [
            "--model_zoo", "z", "--model_def", "m",
            "--totally_unknown_flag", "42",
        ]
    )
    assert args.model_def == "m"


def test_worker_parser_requires_identity():
    with pytest.raises(SystemExit):
        args_mod.parse_worker_args(["--model_zoo", "z", "--model_def", "m"])


def test_parse_dict_params():
    params = args_mod.parse_dict_params("lr=0.1,hidden=128,name=mlp,flag=true")
    assert params == {"lr": 0.1, "hidden": 128, "name": "mlp", "flag": True}
    assert args_mod.parse_dict_params("") == {}
    with pytest.raises(ValueError):
        args_mod.parse_dict_params("oops")


def test_args_roundtrip_to_argv():
    args = args_mod.parse_master_args(
        ["--model_zoo", "z", "--model_def", "m", "--num_workers", "4"]
    )
    argv = args_mod.args_to_argv(args)
    again = args_mod.parse_master_args(argv)
    assert again.num_workers == 4
    assert again.model_def == "m"
