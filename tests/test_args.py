"""Flag system tests (parity: args_test.py in the reference)."""

import pytest

from elasticdl_tpu.common import args as args_mod


def test_master_parser_minimal():
    args = args_mod.parse_master_args(
        ["--model_zoo", "model_zoo", "--model_def", "mnist.mnist_functional_api"]
    )
    assert args.distribution_strategy == "Local"
    assert args.num_workers == 1
    assert args.records_per_task == 4096


def test_unknown_flags_tolerated():
    args = args_mod.parse_master_args(
        [
            "--model_zoo", "z", "--model_def", "m",
            "--totally_unknown_flag", "42",
        ]
    )
    assert args.model_def == "m"


def test_worker_parser_requires_identity():
    with pytest.raises(SystemExit):
        args_mod.parse_worker_args(["--model_zoo", "z", "--model_def", "m"])


def test_parse_dict_params():
    params = args_mod.parse_dict_params("lr=0.1,hidden=128,name=mlp,flag=true")
    assert params == {"lr": 0.1, "hidden": 128, "name": "mlp", "flag": True}
    assert args_mod.parse_dict_params("") == {}
    with pytest.raises(ValueError):
        args_mod.parse_dict_params("oops")


def test_args_roundtrip_to_argv():
    args = args_mod.parse_master_args(
        ["--model_zoo", "z", "--model_def", "m", "--num_workers", "4"]
    )
    argv = args_mod.args_to_argv(args)
    again = args_mod.parse_master_args(argv)
    assert again.num_workers == 4
    assert again.model_def == "m"


def test_use_bf16_reaches_opted_in_models():
    """Round-1 weak #8: --use_bf16 was parsed and forwarded but nothing
    read it.  It now flows into model_params for zoo models whose
    custom_model accepts a use_bf16 parameter; explicit model_params win;
    models without the parameter are untouched."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.model_utils import load_model_spec

    base = ["--model_zoo", "model_zoo", "--training_data", "t"]
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "cifar10.cifar10_functional_api",
                "--use_bf16=false"]))
    assert spec.model_params["use_bf16"] is False
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "cifar10.cifar10_functional_api"]))
    assert spec.model_params["use_bf16"] is True  # flag default
    # Explicit model_params override the flag.
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "cifar10.cifar10_functional_api",
                "--use_bf16=false", "--model_params", "use_bf16=true"]))
    assert spec.model_params["use_bf16"] is True
    # Models that don't opt in see nothing.
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "mnist.mnist_functional_api"]))
    assert "use_bf16" not in spec.model_params


def test_sparse_apply_every_reaches_layout_aware_models():
    """--sparse_apply_every flows into model_params for zoo models whose
    custom_model declares the parameter (deepfm's per-mode table layout);
    explicit model_params win; other models are untouched."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.model_utils import load_model_spec

    base = ["--model_zoo", "model_zoo", "--training_data", "t"]
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "deepfm.deepfm_functional_api",
                "--sparse_apply_every", "16"]))
    assert spec.model_params["sparse_apply_every"] == 16
    # Flag default is 'auto' (round-5): forwarded as-is — the model and
    # the trainer each resolve it from the same row threshold.
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "deepfm.deepfm_functional_api"]))
    assert spec.model_params["sparse_apply_every"] == "auto"
    # Explicit model_params win for the LAYOUT, but the trainer still
    # applies with the job flag — the in-job inconsistency is warned
    # loudly (round-4 ADVICE).  The repo logger doesn't propagate, so
    # capture with a handler on the named logger.
    import io
    import logging

    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    logging.getLogger("elasticdl_tpu.common.model_utils").addHandler(handler)
    try:
        spec = load_model_spec(parse_master_args(
            base + ["--model_def", "deepfm.deepfm_functional_api",
                    "--sparse_apply_every", "16",
                    "--model_params", "sparse_apply_every=1"]))
    finally:
        logging.getLogger(
            "elasticdl_tpu.common.model_utils"
        ).removeHandler(handler)
    assert spec.model_params["sparse_apply_every"] == 1
    assert "TABLE LAYOUT only" in stream.getvalue()
    spec = load_model_spec(parse_master_args(
        base + ["--model_def", "mnist.mnist_functional_api"]))
    assert "sparse_apply_every" not in spec.model_params


def test_oov_diagnostics_flag_round_trip():
    from elasticdl_tpu.common.args import (
        args_to_argv,
        parse_master_args,
        parse_worker_args,
    )

    argv = ["--model_zoo", "z", "--model_def", "m", "--training_data", "t",
            "--oov_diagnostics"]
    args = parse_master_args(argv)
    assert args.oov_diagnostics is True
    worker_argv = args_to_argv(args, keys={"model_zoo", "model_def",
                                           "oov_diagnostics"})
    again = parse_worker_args(
        ["--worker_id", "0", "--master_addr", "x"] + worker_argv
    )
    assert again.oov_diagnostics is True
