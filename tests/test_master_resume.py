"""Master restart resume: a killed master's replacement continues the
epoch from the persisted shard-progress snapshot (reference: PS-mode
masters persist shard progress — SURVEY.md §5 checkpoint/resume).
"""

import os

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.master.main import start_master
from elasticdl_tpu.master.task_manager import (
    TaskManager,
    TaskProgressPersister,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.master_client import MasterClient


def _job_args(tmp_path, n_records=512, records_per_task=64):
    return parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=mnist.mnist_functional_api",
        f"--training_data=synthetic://mnist?n={n_records}",
        f"--records_per_task={records_per_task}",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--num_epochs=2",
        "--distribution_strategy=AllreduceStrategy",
    ])


def _drain(client, trained, stop_after=None):
    """Pull and complete tasks, recording trained ranges; optionally stop
    after N tasks (leaving the job unfinished)."""
    done = 0
    while True:
        task = client.get_task()
        if task.task_id == -1 and task.type != pb.WAIT:
            return done
        if task.type == pb.WAIT:
            continue
        if task.type == pb.TRAINING:
            trained.append((task.epoch, task.start, task.end))
        client.report_task_result(task.task_id, "")
        done += 1
        if stop_after is not None and done >= stop_after:
            return done


def test_master_killed_midepoch_resumes(tmp_path):
    n_records, rpt = 512, 64
    args = _job_args(tmp_path, n_records, rpt)
    trained = []

    # First master: train ~half of epoch 0, snapshot, then die without a
    # clean shutdown (server only; the final persist never runs).
    master = start_master(args)
    client = MasterClient(master.addr, worker_id=0)
    _drain(client, trained, stop_after=5)
    master.progress_persister.persist_now()
    client.close()
    master.server.stop(grace=None)  # hard kill: no final persist
    # ...but reap the persister thread: leaked, its 2s loop would keep
    # bumping the task_progress save histogram under later tests'
    # exact-delta asserts (a real cross-suite flake).
    master.progress_persister.cancel()

    progress_path = TaskProgressPersister.progress_path(args.checkpoint_dir)
    assert os.path.exists(progress_path)

    # Replacement master resumes from the snapshot mid-epoch.
    master2 = start_master(_job_args(tmp_path, n_records, rpt))
    assert master2.task_manager.finished_record_count == 5 * rpt
    assert master2.task_manager.counts()["epoch"] == 0
    client2 = MasterClient(master2.addr, worker_id=1)
    _drain(client2, trained)
    assert master2.task_manager.finished()

    # Every record of both epochs trained at least once.
    for epoch in (0, 1):
        covered = set()
        for ep, start, end in trained:
            if ep == epoch:
                covered.update(range(start, end))
        assert covered == set(range(n_records)), f"gap in epoch {epoch}"
    client2.close()
    master2.stop()


def test_finished_job_snapshot_resumes_as_finished(tmp_path):
    manager = TaskManager(training_shards={"s": 128}, records_per_task=64)
    task_ids = []
    while True:
        task = manager.get(0)
        if task.task_id == -1:
            break
        task_ids.append(task.task_id)
    for tid in task_ids:
        manager.report(tid, True)
    restored = TaskManager.from_checkpoint(manager.to_checkpoint())
    assert restored.finished_record_count == 128
    assert restored.counts()["todo"] == 0


def test_corrupt_progress_snapshot_starts_fresh(tmp_path):
    args = _job_args(tmp_path)
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    with open(TaskProgressPersister.progress_path(args.checkpoint_dir), "w") as f:
        f.write("{not json")
    master = start_master(args)
    try:
        assert master.task_manager.counts()["todo"] == 512 // 64
        assert master.task_manager.finished_record_count == 0
    finally:
        master.stop()
