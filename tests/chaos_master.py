"""Minimal master driver for the chaos e2e (tests/test_chaos.py).

Runs ONLY the master control plane — TaskManager (+ resume from the
persisted shard-progress snapshot), progress persister, and the gRPC
servicer — with none of the model/jax imports, so a SIGKILL + restart
cycle completes in a couple of seconds and the chaos test measures the
workers' RPC-retry ride-through, not interpreter start-up.

The real resume path through `build_master` is proved by
tests/test_master_resume.py; this driver reuses the same persistence
primitives (TaskProgressPersister.progress_path / from_checkpoint).

Usage:
    python tests/chaos_master.py CKPT_DIR PORT SHARD_NAME N_RECORDS \
        RECORDS_PER_TASK NUM_EPOCHS

Writes CKPT_DIR/MASTER_DONE when the job finishes:
    {"resumed": bool, "resumed_finished_records": int,
     "finished_records": int}
"""

import json
import os
import sys
import time

from elasticdl_tpu.common import faults
from elasticdl_tpu.master.servicer import MasterServicer, start_master_server
from elasticdl_tpu.master.task_manager import TaskManager, TaskProgressPersister

DONE_FILE = "MASTER_DONE"


def main(argv):
    ckpt_dir, port, shard_name = argv[0], int(argv[1]), argv[2]
    n_records, records_per_task, num_epochs = (int(v) for v in argv[3:6])
    faults.install_from_env()
    # Journal before anything else: both master generations append to the
    # same timeline, so the SIGKILL + resume cycle is reconstructable
    # post-hoc (the chaos test asserts on these records).  The goodput
    # ledger seeds from the predecessor's phase accounting the same way
    # a real replacement master does (master/main.build_master).
    from elasticdl_tpu import obs
    from elasticdl_tpu.obs import goodput, tracing
    from elasticdl_tpu.obs.journal import DEFAULT_FILENAME

    predecessor_journal = os.path.exists(
        os.path.join(ckpt_dir, DEFAULT_FILENAME)
    )
    journal_path = obs.init_journal(ckpt_dir)
    if predecessor_journal:
        goodput.ledger().seed_from_journal(journal_path)
    # Tracing identity + flight recorder (same wiring as the real
    # master entrypoint): spans label `master`, and even this driver's
    # exit flushes any open span tail.
    tracing.set_process("master")
    tracing.install_flight_recorder()

    resumed = False
    resumed_finished = 0
    task_manager = None
    progress_path = TaskProgressPersister.progress_path(ckpt_dir)
    if os.path.exists(progress_path):
        with open(progress_path) as f:
            task_manager = TaskManager.from_checkpoint(f.read())
        resumed = True
        resumed_finished = task_manager.finished_record_count
    if task_manager is None:
        task_manager = TaskManager(
            training_shards={shard_name: n_records},
            records_per_task=records_per_task,
            num_epochs=num_epochs,
        )

    obs.journal().record(
        "master_start", job_name="chaos", resumed=resumed,
        finished_records=resumed_finished,
    )
    goodput.ledger().transition("idle", cause="master_start")
    servicer = MasterServicer(task_manager=task_manager)
    # The replacement master binds the SAME port its predecessor was
    # SIGKILLed on; brief bind failures (straggling kernel state) retry.
    bound = 0
    for _ in range(40):
        server, bound = start_master_server(servicer, port=port)
        if bound == port:
            break
        server.stop(grace=None)
        time.sleep(0.25)
    if bound != port:
        print(f"could not bind port {port}", file=sys.stderr)
        return 3

    # Observability surface on an EPHEMERAL port, discovered via the
    # port file next to the journal — the chaos test must never race
    # another suite for a hardcoded metrics port.
    from elasticdl_tpu.obs.exporter import MetricsExporter

    exporter = MetricsExporter(port=0).start()
    exporter.write_port_file(ckpt_dir)

    persister = TaskProgressPersister(
        task_manager, ckpt_dir, interval_s=0.1
    ).start()
    while not task_manager.finished():
        time.sleep(0.02)
    persister.stop()
    # Terminal goodput accounting: the summary record the postmortem
    # report (and the chaos test's report assertions) key off.
    goodput.ledger().finish("job_complete")
    with open(os.path.join(ckpt_dir, DONE_FILE), "w") as f:
        json.dump(
            {
                "resumed": resumed,
                "resumed_finished_records": resumed_finished,
                "finished_records": task_manager.finished_record_count,
            },
            f,
        )
    # Linger so workers' final get_task (job-complete answer) and version
    # reports land instead of hitting a stopping server.
    time.sleep(3.0)
    server.stop(grace=1)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
