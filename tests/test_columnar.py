"""Columnar task materialization (data/columnar.py).

The no-per-record-Python data path: reader.read_columns chunks ->
columnar_dataset_fn whole-column transform -> row-view batches.  Pinned
against the per-record dataset path it replaces (same records, same
lockstep determinism), plus a real 2-worker PS cluster job over an ETRF
file proving the worker engages it end to end.
"""

import os
import time

import numpy as np
import pytest

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data.columnar import (
    ColumnarTask,
    materialize_columnar_task,
    training_permutation,
)
from elasticdl_tpu.data.dataset import Dataset
from model_zoo.deepfm import deepfm_functional_api as zoo


class _Task:
    type = 1  # pb.TRAINING

    def __init__(self, start, end, task_id=0):
        self.start, self.end, self.task_id = start, end, task_id


def _write_criteo(tmp_path, n=200, seed=0):
    layout = zoo.criteo_record_layout()
    rng = np.random.RandomState(seed)
    recs = []
    for _ in range(n):
        recs.append(
            layout.pack(
                dense=rng.rand(zoo.NUM_DENSE).astype(np.float32),
                cat=rng.randint(0, 100, size=zoo.NUM_CAT).astype(np.int32),
                label=[int(rng.rand() > 0.5)],
            )
        )
    path = str(tmp_path / "criteo.etrf")
    recordfile.write_records(path, recs)
    return path


def test_columnar_matches_per_record_eval(tmp_path):
    """Evaluation mode (no shuffle): columnar rows == the per-record
    dataset path rows, in order."""
    path = _write_criteo(tmp_path)
    reader = zoo.CriteoRecordReader(path)
    task = _Task(30, 170)

    columnar = materialize_columnar_task(
        reader, task, zoo.columnar_dataset_fn, "evaluation", None
    )
    assert columnar is not None and columnar.n == 140

    dataset = zoo.dataset_fn(
        Dataset.from_generator(lambda: reader.read_records(task)),
        "evaluation",
        None,
    )
    records = list(dataset)
    assert len(records) == columnar.n
    feats, labels = columnar.slice(0, columnar.n)
    for i, (rf, rl) in enumerate(records):
        np.testing.assert_array_equal(feats["dense"][i], rf["dense"])
        np.testing.assert_array_equal(feats["cat"][i], rf["cat"])
        assert labels[i] == rl


def test_columnar_training_is_deterministic_permutation(tmp_path):
    """Training mode shuffles with a deterministic permutation — identical
    on every call (the lockstep requirement), rows a permutation of the
    eval-order rows."""
    path = _write_criteo(tmp_path)
    reader = zoo.CriteoRecordReader(path)
    task = _Task(0, 200)

    a = materialize_columnar_task(
        reader, task, zoo.columnar_dataset_fn, "training", None
    )
    b = materialize_columnar_task(
        reader, task, zoo.columnar_dataset_fn, "training", None
    )
    np.testing.assert_array_equal(a.features["cat"], b.features["cat"])
    np.testing.assert_array_equal(a.labels, b.labels)

    ordered = materialize_columnar_task(
        reader, task, zoo.columnar_dataset_fn, "evaluation", None
    )
    # The shuffle seed is TASK-DERIVED (identical on every rank, but
    # varying across tasks/epochs — round-5 review fix: a fixed seed
    # replayed the same order every epoch).
    seed = (31 * task.start + task.end) % (2**31)
    perm = training_permutation(200, seed=seed)
    np.testing.assert_array_equal(
        a.features["cat"], ordered.features["cat"][perm]
    )
    np.testing.assert_array_equal(a.labels, ordered.labels[perm])

    # A later epoch of the same range shuffles DIFFERENTLY.
    class _EpochTask:
        start, end, epoch = 0, 200, 1

    later = materialize_columnar_task(
        reader, _EpochTask, zoo.columnar_dataset_fn, "training", None
    )
    assert not np.array_equal(a.features["cat"], later.features["cat"])


def test_columnar_falls_back_without_surface(tmp_path):
    path = _write_criteo(tmp_path, n=10)
    reader = zoo.CriteoRecordReader(path)
    task = _Task(0, 10)
    # No columnar_dataset_fn -> per-record path.
    assert materialize_columnar_task(reader, task, None, "training", None) is None

    class NoColumns:
        pass

    assert (
        materialize_columnar_task(
            NoColumns(), task, zoo.columnar_dataset_fn, "training", None
        )
        is None
    )


def test_columnar_task_slices_are_views():
    feats = {"x": np.arange(20).reshape(10, 2)}
    labels = np.arange(10)
    ct = ColumnarTask(feats, labels)
    f, l = ct.slice(3, 7)
    assert f["x"].base is not None  # view, not copy
    np.testing.assert_array_equal(f["x"], feats["x"][3:7])
    np.testing.assert_array_equal(l, [3, 4, 5, 6])
    with pytest.raises(ValueError):
        ColumnarTask({"x": np.zeros((5, 2))}, np.zeros((4,)))


def test_ps_cluster_job_uses_columnar_path(tmp_path):
    """Real 2-worker PS job over an ETRF file: completes, and both the
    flag-forwarding and the columnar engagement log prove the production
    worker ran the vectorized path."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.main import start_master
    from elasticdl_tpu.master.pod_manager import (
        LocalProcessManager,
        worker_argv_from_args,
    )
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous

    path = _write_criteo(tmp_path, n=256)
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        f"--training_data={path}",
        "--model_params=vocab_size=100",
        "--records_per_task=64",
        "--minibatch_size=8",
        "--num_workers=2",
        "--distribution_strategy=ParameterServerStrategy",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "ELASTICDL_FORCE_PLATFORM": "cpu",
            "JAX_PLATFORMS": "cpu",
        },
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        deadline = time.time() + 420
        while time.time() < deadline and not master.task_manager.finished():
            time.sleep(0.5)
        assert master.task_manager.finished(), "ETRF PS job did not finish"
    finally:
        manager.stop()
        master.stop()

    logs = ""
    logdir = tmp_path / "logs"
    for f in os.listdir(logdir):
        logs += (logdir / f).read_text()
    assert "Columnar task path engaged" in logs
