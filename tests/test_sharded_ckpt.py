"""Sharded (per-process) checkpointing tests.

Parity surface: the reference's per-PS-pod partition snapshots
(pkg/ps/checkpoint.go).  Here each process writes only its local table
rows; restore reassembles arbitrary row intervals under the NEW world's
sharding — including worlds of a different size than the one that saved
(the shrink/grow restore path of elastic re-formation).
"""

import json
import os

import numpy as np
import optax
import pytest

from elasticdl_tpu.checkpoint import RowReader, ShardedCheckpointSaver
from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer

from test_embedding import SparseModel, _loss, VOCAB


def _write_parts(step_dir, name, parts):
    """Simulate a multi-process save: one npz per (fake) process."""
    os.makedirs(step_dir, exist_ok=True)
    for i, (lo, hi, data) in enumerate(parts):
        np.savez(
            os.path.join(step_dir, f"shards_p{i}of{len(parts)}.npz"),
            **{f"{name}|{lo}|{hi}": data},
        )


class TestRowReader:
    def test_reassembles_across_files(self, tmp_path):
        data = np.arange(160, dtype=np.float32).reshape(16, 10)
        step_dir = str(tmp_path / "step_000000000001")
        _write_parts(
            step_dir, "table|emb", [(0, 8, data[0:8]), (8, 16, data[8:16])]
        )
        reader = RowReader(step_dir, "table|emb")
        np.testing.assert_array_equal(reader.read(0, 16), data)
        np.testing.assert_array_equal(reader.read(3, 12), data[3:12])
        np.testing.assert_array_equal(reader.read(8, 9), data[8:9])

    def test_missing_rows_raise(self, tmp_path):
        data = np.zeros((4, 2), np.float32)
        step_dir = str(tmp_path / "step_000000000001")
        _write_parts(step_dir, "t", [(0, 4, data), (8, 12, data)])
        reader = RowReader(step_dir, "t")
        with pytest.raises(ValueError, match="missing"):
            reader.read(2, 10)

    def test_name_isolation(self, tmp_path):
        """Entries of other arrays (names that themselves contain '|')
        are never mixed in."""
        step_dir = str(tmp_path / "step_000000000001")
        os.makedirs(step_dir)
        np.savez(
            os.path.join(step_dir, "shards_p0of1.npz"),
            **{
                "slot|emb|m|0|4": np.ones((4, 2), np.float32),
                "slot|emb|v|0|4": np.full((4, 2), 7, np.float32),
            },
        )
        np.testing.assert_array_equal(
            RowReader(step_dir, "slot|emb|v").read(0, 4),
            np.full((4, 2), 7, np.float32),
        )


def _make_trainer(mesh):
    return ShardedEmbeddingTrainer(
        SparseModel(), _loss, optax.sgd(0.1), mesh,
        embedding_optimizer=sparse_optim.adam(0.05), seed=0,
    )


def _train_batches():
    rng = np.random.RandomState(7)
    ids = rng.randint(0, VOCAB, size=(8, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=8).astype(np.int32)
    return ids, labels


def test_sharded_save_restore_roundtrip(tmp_path):
    mesh = build_mesh(MeshConfig())
    saver = ShardedCheckpointSaver(str(tmp_path))
    t1 = _make_trainer(mesh)
    ids, labels = _train_batches()
    for _ in range(3):
        t1.train_step(ids, labels)
    t1.save_checkpoint(saver, t1.step)

    # Layout: manifest + dense pickle + this process's shard file; no
    # host-complete state pickle anywhere.
    assert saver.latest_step() == 3
    step_dir = tmp_path / "step_000000000003"
    files = sorted(os.listdir(step_dir))
    assert "manifest.json" in files and "dense.pkl" in files
    assert any(f.startswith("shards_p0of") for f in files)
    assert "state.pkl" not in files
    manifest = json.loads((step_dir / "manifest.json").read_text())
    assert any(k.startswith("table|") for k in manifest["arrays"])
    assert any(k.startswith("slot|") for k in manifest["arrays"])

    # Restore at worker boot (structure unknown yet -> deferred).
    t2 = _make_trainer(mesh)
    t2.set_sharded_restore(saver, 3)
    assert t2.step == 3
    l1 = float(t1.train_step(ids, labels))
    l2 = float(t2.train_step(ids, labels))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_sharded_restore_from_differently_split_files(tmp_path):
    """A world of a different size saved this checkpoint: the shard rows
    arrive split across several files with arbitrary intervals.  Restore
    must reassemble them bit-identically."""
    mesh = build_mesh(MeshConfig())
    saver = ShardedCheckpointSaver(str(tmp_path))
    t1 = _make_trainer(mesh)
    ids, labels = _train_batches()
    for _ in range(2):
        t1.train_step(ids, labels)
    t1.save_checkpoint(saver, t1.step)

    # Rewrite the single-process shard file as if 2 processes had saved:
    # every entry split at an uneven row boundary.
    step_dir = str(tmp_path / "step_000000000002")
    src = next(
        f for f in os.listdir(step_dir) if f.startswith("shards_p0of1")
    )
    npz = np.load(os.path.join(step_dir, src))
    part0, part1 = {}, {}
    for key in npz.files:
        name, lo, hi = key.rsplit("|", 2)
        lo, hi = int(lo), int(hi)
        cut = lo + max(1, (hi - lo) // 3)
        part0[f"{name}|{lo}|{cut}"] = npz[key][: cut - lo]
        part1[f"{name}|{cut}|{hi}"] = npz[key][cut - lo :]
    os.unlink(os.path.join(step_dir, src))
    np.savez(os.path.join(step_dir, "shards_p0of2.npz"), **part0)
    np.savez(os.path.join(step_dir, "shards_p1of2.npz"), **part1)
    manifest_path = os.path.join(step_dir, "manifest.json")
    manifest = json.loads(open(manifest_path).read())
    manifest["n_processes"] = 2
    manifest["shard_files"] = ["shards_p0of2.npz", "shards_p1of2.npz"]
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    saver = ShardedCheckpointSaver(str(tmp_path))  # fresh index cache

    t2 = _make_trainer(mesh)
    t2.set_sharded_restore(saver, 2)
    l1 = float(t1.train_step(ids, labels))
    l2 = float(t2.train_step(ids, labels))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_stale_shard_files_are_ignored(tmp_path):
    """A file left behind by a world that died mid-save (different process
    count, stale weights) must never leak rows into a restore: only the
    manifest-inventoried files are read."""
    mesh = build_mesh(MeshConfig())
    saver = ShardedCheckpointSaver(str(tmp_path))
    t1 = _make_trainer(mesh)
    ids, labels = _train_batches()
    t1.train_step(ids, labels)
    t1.save_checkpoint(saver, 1)
    step_dir = str(tmp_path / "step_000000000001")
    # Forge a stale shard covering the same rows with garbage.
    src = next(f for f in os.listdir(step_dir) if f.startswith("shards_"))
    npz = np.load(os.path.join(step_dir, src))
    garbage = {k: np.full_like(npz[k], 1e9) for k in npz.files}
    np.savez(os.path.join(step_dir, "shards_p1of3.npz"), **garbage)

    t2 = _make_trainer(mesh)
    t2.set_sharded_restore(ShardedCheckpointSaver(str(tmp_path)), 1)
    l1 = float(t1.train_step(ids, labels))
    l2 = float(t2.train_step(ids, labels))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_garbage_collection_keeps_newest(tmp_path):
    mesh = build_mesh(MeshConfig())
    saver = ShardedCheckpointSaver(str(tmp_path), keep_max=2)
    trainer = _make_trainer(mesh)
    ids, labels = _train_batches()
    for step in (1, 2, 3, 4):
        trainer.train_step(ids, labels)
        trainer.save_checkpoint(saver, step)
    assert saver.steps() == [3, 4]


def test_table_layout_mismatch_raises_with_cause(tmp_path):
    """A checkpoint written under one table layout must refuse restore
    into a build with a different table set — naming the per-mode
    layout cause, not a bare KeyError.  The real-world trigger: DeepFM
    merges linear+fm tables under windowed sparse apply but splits them
    under strict mode at >10M rows, so flipping --sparse_apply_every
    across a restart silently changes the model's table structure."""
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    saver = ShardedCheckpointSaver(str(tmp_path))
    merged = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100, split_tables=False),
        zoo.loss, zoo.optimizer(), mesh,
        embedding_optimizer=zoo.embedding_optimizer(), seed=0,
    )
    rng = np.random.RandomState(0)
    feats = {
        "dense": rng.rand(8, zoo.NUM_DENSE).astype(np.float32),
        "cat": rng.randint(0, 100, size=(8, zoo.NUM_CAT)).astype(np.int32),
    }
    labels = rng.randint(0, 2, size=8).astype(np.int32)
    merged.train_step(feats, labels)
    merged.save_checkpoint(saver, merged.step)

    split = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100, split_tables=True),
        zoo.loss, zoo.optimizer(), mesh,
        embedding_optimizer=zoo.embedding_optimizer(), seed=0,
    )
    split.set_sharded_restore(saver, 1)
    with pytest.raises(ValueError, match="table layout changed"):
        split.ensure_initialized(feats)
