"""Dataset pipeline and record-file codec tests."""

import numpy as np
import pytest

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data.dataset import Dataset


class TestDataset:
    def test_map_batch(self):
        ds = Dataset.from_iterable(range(10)).map(lambda x: x * 2).batch(4)
        batches = list(ds)
        assert len(batches) == 3
        np.testing.assert_array_equal(batches[0], [0, 2, 4, 6])
        np.testing.assert_array_equal(batches[2], [16, 18])

    def test_batch_drop_remainder(self):
        ds = Dataset.from_iterable(range(10)).batch(4, drop_remainder=True)
        assert len(list(ds)) == 2

    def test_tuple_records_stack(self):
        records = [(np.ones(3) * i, i) for i in range(4)]
        ds = Dataset.from_iterable(records).batch(2)
        features, labels = next(iter(ds))
        assert features.shape == (2, 3)
        assert labels.shape == (2,)

    def test_dict_records_stack(self):
        records = [{"a": np.float32(i), "b": np.arange(2)} for i in range(4)]
        batch = next(iter(Dataset.from_iterable(records).batch(4)))
        assert batch["a"].shape == (4,)
        assert batch["b"].shape == (4, 2)

    def test_shuffle_is_permutation(self):
        ds = Dataset.from_iterable(range(100)).shuffle(16, seed=1)
        out = list(ds)
        assert sorted(out) == list(range(100))
        assert out != list(range(100))

    def test_reiterable(self):
        ds = Dataset.from_iterable(range(5)).map(lambda x: x + 1)
        assert list(ds) == list(ds) == [1, 2, 3, 4, 5]

    def test_filter_and_repeat(self):
        ds = Dataset.from_iterable(range(6)).filter(lambda x: x % 2 == 0).repeat(2)
        assert list(ds) == [0, 2, 4, 0, 2, 4]


class TestRecordFile:
    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "data.rio")
        records = [f"record-{i}".encode() for i in range(100)]
        assert recordfile.write_records(path, records) == 100
        assert recordfile.count_records(path) == 100
        assert list(recordfile.read_all(path)) == records

    def test_read_range_seeks(self, tmp_path):
        path = str(tmp_path / "data.rio")
        recordfile.write_records(path, [bytes([i]) * (i + 1) for i in range(50)])
        got = list(recordfile.read_range(path, 10, 13))
        assert got == [bytes([10]) * 11, bytes([11]) * 12, bytes([12]) * 13]
        assert list(recordfile.read_range(path, 48, 999)) == [
            bytes([48]) * 49,
            bytes([49]) * 50,
        ]
        assert list(recordfile.read_range(path, 30, 30)) == []

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "data.rio")
        recordfile.write_records(path, [b"hello world" * 10])
        raw = bytearray(open(path, "rb").read())
        raw[20] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(recordfile.RecordFileError):
            list(recordfile.read_all(path))

    def test_not_a_recordfile(self, tmp_path):
        path = str(tmp_path / "bogus.rio")
        open(path, "wb").write(b"not a record file at all, definitely")
        with pytest.raises(recordfile.RecordFileError):
            recordfile.count_records(path)


class TestSequentialRecords:
    """The eval-memory bound: records stream one-pass, never a full-task
    list (VERDICT round-2 weak #5)."""

    def _counting_dataset(self, n):
        from elasticdl_tpu.data.dataset import Dataset

        consumed = []

        def gen():
            for i in range(n):
                consumed.append(i)
                yield ({"x": np.full((2,), i, np.float32)}, np.int32(i))

        return Dataset.from_generator(gen), consumed

    def test_slices_match_list_semantics(self):
        from elasticdl_tpu.data.dataset import SequentialRecords

        ds, _ = self._counting_dataset(10)
        labels = [int(r[1]) for r in list(ds)]
        cur = SequentialRecords(ds)

        def got(lo, hi):
            return [int(r[1]) for r in cur.slice(lo, hi)]

        assert got(0, 3) == labels[0:3]
        assert got(5, 8) == labels[5:8]  # skip [3,5)
        assert got(8, 20) == labels[8:10]  # past end truncates
        assert got(20, 25) == []

    def test_streaming_consumes_only_what_is_needed(self):
        from elasticdl_tpu.data.dataset import SequentialRecords

        ds, consumed = self._counting_dataset(1000)
        cur = SequentialRecords(ds)
        cur.slice(0, 4)
        assert len(consumed) == 4, "cursor must not materialize the task"

    def test_one_pass_rewind_rejected(self):
        from elasticdl_tpu.data.dataset import SequentialRecords

        ds, _ = self._counting_dataset(10)
        cur = SequentialRecords(ds)
        cur.slice(0, 5)
        with pytest.raises(ValueError, match="one-pass"):
            cur.slice(2, 4)

    def test_template_peek_then_slice_includes_record_zero(self):
        from elasticdl_tpu.data.dataset import SequentialRecords

        ds, _ = self._counting_dataset(5)
        labels = [int(r[1]) for r in list(ds)]
        cur = SequentialRecords(ds)
        assert int(cur.template()[1]) == labels[0]  # peek does not consume
        assert [int(r[1]) for r in cur.slice(0, 2)] == labels[0:2]
        # Template stays available after exhaustion (ragged-tail shaping).
        assert [int(r[1]) for r in cur.slice(2, 99)] == labels[2:5]
        assert int(cur.template()[1]) == labels[0]
