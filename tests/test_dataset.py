"""Dataset pipeline and record-file codec tests."""

import numpy as np
import pytest

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data.dataset import Dataset


class TestDataset:
    def test_map_batch(self):
        ds = Dataset.from_iterable(range(10)).map(lambda x: x * 2).batch(4)
        batches = list(ds)
        assert len(batches) == 3
        np.testing.assert_array_equal(batches[0], [0, 2, 4, 6])
        np.testing.assert_array_equal(batches[2], [16, 18])

    def test_batch_drop_remainder(self):
        ds = Dataset.from_iterable(range(10)).batch(4, drop_remainder=True)
        assert len(list(ds)) == 2

    def test_tuple_records_stack(self):
        records = [(np.ones(3) * i, i) for i in range(4)]
        ds = Dataset.from_iterable(records).batch(2)
        features, labels = next(iter(ds))
        assert features.shape == (2, 3)
        assert labels.shape == (2,)

    def test_dict_records_stack(self):
        records = [{"a": np.float32(i), "b": np.arange(2)} for i in range(4)]
        batch = next(iter(Dataset.from_iterable(records).batch(4)))
        assert batch["a"].shape == (4,)
        assert batch["b"].shape == (4, 2)

    def test_shuffle_is_permutation(self):
        ds = Dataset.from_iterable(range(100)).shuffle(16, seed=1)
        out = list(ds)
        assert sorted(out) == list(range(100))
        assert out != list(range(100))

    def test_reiterable(self):
        ds = Dataset.from_iterable(range(5)).map(lambda x: x + 1)
        assert list(ds) == list(ds) == [1, 2, 3, 4, 5]

    def test_filter_and_repeat(self):
        ds = Dataset.from_iterable(range(6)).filter(lambda x: x % 2 == 0).repeat(2)
        assert list(ds) == [0, 2, 4, 0, 2, 4]


class TestRecordFile:
    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "data.rio")
        records = [f"record-{i}".encode() for i in range(100)]
        assert recordfile.write_records(path, records) == 100
        assert recordfile.count_records(path) == 100
        assert list(recordfile.read_all(path)) == records

    def test_read_range_seeks(self, tmp_path):
        path = str(tmp_path / "data.rio")
        recordfile.write_records(path, [bytes([i]) * (i + 1) for i in range(50)])
        got = list(recordfile.read_range(path, 10, 13))
        assert got == [bytes([10]) * 11, bytes([11]) * 12, bytes([12]) * 13]
        assert list(recordfile.read_range(path, 48, 999)) == [
            bytes([48]) * 49,
            bytes([49]) * 50,
        ]
        assert list(recordfile.read_range(path, 30, 30)) == []

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "data.rio")
        recordfile.write_records(path, [b"hello world" * 10])
        raw = bytearray(open(path, "rb").read())
        raw[20] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(recordfile.RecordFileError):
            list(recordfile.read_all(path))

    def test_not_a_recordfile(self, tmp_path):
        path = str(tmp_path / "bogus.rio")
        open(path, "wb").write(b"not a record file at all, definitely")
        with pytest.raises(recordfile.RecordFileError):
            recordfile.count_records(path)
