"""Policy-engine internals (master/policy.py): eviction hysteresis,
kill-budget exhaustion/refill, amortization math against synthetic
ledger costs, the min-workers floor, thrash scale-down + target restore,
and the pod manager's scale-down regression (the old `max()` clamp made
lowering the target a silent no-op).

The two-baseline preemption-storm e2e lives in tests/test_chaos.py."""

import importlib.util
import json
import os

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.master.policy import (
    ElasticPolicyEngine,
    PolicyConfig,
)
from elasticdl_tpu.obs import goodput

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_journal",
        os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeLedger:
    """Synthetic goodput-ledger surface the engine consumes."""

    def __init__(self):
        self.seconds = {p: 0.0 for p in goodput.PHASES}
        self.rescales = 0
        self.last = None
        self.since = None
        self.in_flight = False

    def phase_seconds(self):
        return dict(self.seconds)

    def counts(self):
        return {
            "records_done": 0, "records_redone": 0,
            "redo_pending": 0, "rescales": self.rescales,
        }

    def last_rescale(self):
        return dict(self.last) if self.last else None

    def seconds_since_last_rescale(self):
        return self.since

    def rescale_in_flight(self):
        return self.in_flight


class FakeManager:
    """Manager surface: world membership + the two enforcement calls."""

    def __init__(self, ids):
        self.ids = list(ids)
        self.kills = []
        self.scales = []
        self.target = len(self.ids)

    def current_worker_ids(self):
        return list(self.ids)

    def kill_worker(self, worker_id, sig=9):
        if worker_id not in self.ids:
            raise ValueError(f"No live worker {worker_id}")
        self.kills.append((worker_id, sig))
        self.ids.remove(worker_id)

    def scale(self, n):
        self.scales.append(n)
        self.ids = list(range(100, 100 + n))
        self.target = n

    def set_target_num_workers(self, n):
        self.target = n

    def target_num_workers(self):
        return self.target


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _engine(config, manager=None, ledger=None, clock=None):
    return ElasticPolicyEngine(
        config,
        manager=manager,
        ledger=ledger or FakeLedger(),
        clock=clock or FakeClock(),
    )


# ---------------------------------------------------------------------------
# (c) Eviction: hysteresis, budget, floor
# ---------------------------------------------------------------------------


def test_eviction_needs_consecutive_flag_ticks(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    manager = FakeManager([0, 1, 2, 3])
    engine = _engine(
        PolicyConfig(evict_after_ticks=3, kill_budget=5, min_workers=1),
        manager=manager, clock=clock,
    )
    engine.note_straggler(2, True, {"metric": "step_time", "value": 0.9})
    for _ in range(2):
        engine.tick(clock.advance(1.0))
        assert manager.kills == []  # hysteresis: not yet
    decisions = engine.tick(clock.advance(1.0))
    assert manager.kills == [(2, 9)]
    (evict,) = [d for d in decisions if d["action"] == "evict"]
    assert evict["reason"] == "persistent_straggler"
    assert evict["worker_id"] == 2
    assert evict["flag_streak_ticks"] == 3
    assert evict["straggler_evidence"]["metric"] == "step_time"
    journaled = [
        e for e in _events(journal_file)
        if e["event"] == "policy_decision" and e["action"] == "evict"
    ]
    assert len(journaled) == 1 and journaled[0]["worker_id"] == 2


def test_single_noisy_flag_never_kills(journal_file, obs_registry_snapshot):
    """A flag that clears before the streak completes (one noisy
    snapshot, detector-cleared) resets the streak — no kill, ever."""
    clock = FakeClock()
    manager = FakeManager([0, 1, 2])
    engine = _engine(
        PolicyConfig(evict_after_ticks=2, kill_budget=5),
        manager=manager, clock=clock,
    )
    engine.note_straggler(1, True)
    engine.tick(clock.advance(1.0))
    engine.note_straggler(1, False)  # cleared: streak must reset
    for _ in range(5):
        engine.tick(clock.advance(1.0))
    engine.note_straggler(1, True)  # re-flagged: needs a FRESH streak
    engine.tick(clock.advance(1.0))
    assert manager.kills == []
    engine.tick(clock.advance(1.0))
    assert manager.kills == [(1, 9)]


def test_kill_budget_exhaustion_and_refill(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    manager = FakeManager([0, 1, 2, 3, 4])
    engine = _engine(
        PolicyConfig(
            evict_after_ticks=1, kill_budget=1, kill_budget_window_s=100.0,
        ),
        manager=manager, clock=clock,
    )
    engine.note_straggler(1, True)
    engine.note_straggler(3, True)
    decisions = engine.tick(clock.advance(1.0))
    # Budget 1: exactly one kill; the second falls back to advisory-only.
    assert manager.kills == [(1, 9)]
    assert engine.kill_budget_remaining() == 0
    holds = [d for d in decisions if d["action"] == "hold"]
    assert [h["reason"] for h in holds] == ["kill_budget_exhausted"]
    assert holds[0]["worker_id"] == 3
    # Still flagged through the window: no more kills...
    for _ in range(3):
        engine.tick(clock.advance(1.0))
    assert len(manager.kills) == 1
    # ...until the window elapses and the budget refills.
    clock.advance(100.0)
    assert engine.kill_budget_remaining() == 1
    engine.tick(clock.t)
    assert manager.kills == [(1, 9), (3, 9)]


def test_zero_budget_is_advisory_only(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    manager = FakeManager([0, 1, 2])
    engine = _engine(
        PolicyConfig(evict_after_ticks=1, kill_budget=0),
        manager=manager, clock=clock,
    )
    engine.note_straggler(1, True)
    decisions = engine.tick(clock.advance(1.0))
    assert manager.kills == []
    assert [d["reason"] for d in decisions] == ["kill_budget_exhausted"]


def test_min_workers_floor_blocks_eviction(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    manager = FakeManager([0, 1])
    engine = _engine(
        PolicyConfig(evict_after_ticks=1, kill_budget=5, min_workers=2),
        manager=manager, clock=clock,
    )
    engine.note_straggler(1, True)
    decisions = engine.tick(clock.advance(1.0))
    assert manager.kills == []
    (hold,) = decisions
    assert hold["action"] == "hold"
    assert hold["reason"] == "min_workers_floor"
    assert hold["worker_id"] == 1


# ---------------------------------------------------------------------------
# (a) Scale-up gate: amortization math, cooldown, in-flight
# ---------------------------------------------------------------------------


def test_amortization_math_against_synthetic_costs(
    journal_file, obs_registry_snapshot
):
    """n=2 workers, k=2 granted, measured cost C=100s: required horizon
    is C*(n+k)/k = 200s.  H=150 denies, H=250 approves."""
    ledger = FakeLedger()
    ledger.last = {"total_s": 100.0, "t_end": 0.0, "cause": "worker_churn"}
    ledger.since = 1000.0  # far past any cooldown
    manager = FakeManager([0, 1])
    denied = _engine(
        PolicyConfig(amortize_horizon_s=150.0),
        manager=manager, ledger=ledger,
    )
    assert denied.gate_scale_up(2, 2) == 0
    approved = _engine(
        PolicyConfig(amortize_horizon_s=250.0),
        manager=manager, ledger=ledger,
    )
    assert approved.gate_scale_up(2, 2) == 2
    events = [
        e for e in _events(journal_file) if e["event"] == "policy_decision"
    ]
    assert [e["action"] for e in events] == ["hold", "scale_up"]
    assert events[0]["reason"] == "unamortized_rescale_cost"
    assert events[0]["required_horizon_s"] == pytest.approx(200.0)
    assert events[1]["reason"] == "amortized"
    assert events[1]["last_rescale_cost_s"] == pytest.approx(100.0)


def test_unpriced_fleet_scales_up_optimistically(
    journal_file, obs_registry_snapshot
):
    """No completed rescale yet -> no measured cost -> approve (the
    first rescale is how the price gets measured)."""
    engine = _engine(PolicyConfig(), manager=FakeManager([0]))
    assert engine.gate_scale_up(3, 3) == 3


def test_cooldown_keyed_off_last_rescale_cost(
    journal_file, obs_registry_snapshot
):
    ledger = FakeLedger()
    ledger.last = {"total_s": 20.0, "t_end": 0.0, "cause": "scale_up"}
    engine = _engine(
        PolicyConfig(
            cooldown_factor=4.0, min_cooldown_s=30.0,
            amortize_horizon_s=3600.0,
        ),
        manager=FakeManager([0, 1]), ledger=ledger,
    )
    # cooldown = max(30, 4*20) = 80s
    ledger.since = 79.0
    assert engine.gate_scale_up(1, 1) == 0
    events = _events(journal_file)
    assert events[-1]["action"] == "hold"
    assert events[-1]["reason"] == "cooldown"
    assert events[-1]["cooldown_s"] == pytest.approx(80.0)
    ledger.since = 81.0
    assert engine.gate_scale_up(1, 1) == 1


def test_gate_denies_while_rescale_in_flight(
    journal_file, obs_registry_snapshot
):
    ledger = FakeLedger()
    ledger.in_flight = True
    engine = _engine(PolicyConfig(), manager=FakeManager([0]), ledger=ledger)
    assert engine.gate_scale_up(1, 1) == 0
    assert engine.gate_scale_up(1, 0) == 0  # no grant: no decision at all
    events = [
        e for e in _events(journal_file) if e["event"] == "policy_decision"
    ]
    assert [e["reason"] for e in events] == ["rescale_in_flight"]


# ---------------------------------------------------------------------------
# (b) Thrash: hold, park at the floor, restore after quiet
# ---------------------------------------------------------------------------


def _thrash_engine(manager, ledger, clock, **overrides):
    config = dict(
        thrash_window_s=60.0, thrash_rescales=2, thrash_overhead_frac=0.2,
        scale_down_after=2, min_cooldown_s=5.0, cooldown_factor=1.0,
        min_workers=1, amortize_horizon_s=3600.0,
    )
    config.update(overrides)
    return _engine(
        PolicyConfig(**config), manager=manager, ledger=ledger, clock=clock
    )


def _storm_ledger_step(ledger, training=5.0, overhead=0.0, rescales=0):
    ledger.seconds["training"] += training
    ledger.seconds["rendezvous"] += overhead
    ledger.rescales += rescales


def test_thrash_scale_down_parks_at_floor_then_restores(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    ledger = FakeLedger()
    manager = FakeManager([0, 1, 2])
    engine = _thrash_engine(manager, ledger, clock)

    # Quiet baseline tick.
    _storm_ledger_step(ledger, training=5.0)
    engine.tick(clock.advance(1.0))
    assert engine.gate_scale_up(1, 1) == 1  # healthy: grants flow

    # Storm: two rescales land, overhead dominates the window.
    _storm_ledger_step(ledger, training=1.0, overhead=4.0, rescales=2)
    ledger.last = {"total_s": 2.0, "t_end": 0.0, "cause": "worker_churn"}
    ledger.since = 0.5
    decisions = engine.tick(clock.advance(1.0))  # thrash strike 1
    assert manager.scales == []
    assert any(
        d["action"] == "hold" and d["reason"] == "rescale_thrash"
        for d in decisions
    )
    assert engine.gate_scale_up(1, 1) == 0  # thrash suppresses scale-up

    _storm_ledger_step(ledger, training=1.0, overhead=3.0, rescales=1)
    # Past the policy's own post-scale-action cooldown (the healthy
    # grant above counts as a scale action too).
    decisions = engine.tick(clock.advance(6.0))  # strike 2 -> enforce
    (down,) = [d for d in decisions if d["action"] == "scale_down"]
    assert down["reason"] == "rescale_thrash"
    assert down["old_size"] == 3 and down["new_size"] == 1
    assert manager.scales == [1]
    assert len(manager.current_worker_ids()) == 1

    # Storm over: window drains, cooldown passes -> target restored.
    ledger.since = 100.0
    clock.advance(120.0)
    _storm_ledger_step(ledger, training=120.0)
    decisions = engine.tick(clock.t)  # window slid clean; thrash clears
    decisions += engine.tick(clock.advance(1.0))
    restored = [d for d in decisions if d["reason"] == "target_restored"]
    assert restored and restored[0]["restored_target"] == 3
    assert manager.target == 3
    # The actual growth then flows back through the gate.
    assert engine.gate_scale_up(2, 2) == 2


def test_scale_down_waits_out_inflight_rescale(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    ledger = FakeLedger()
    manager = FakeManager([0, 1, 2])
    engine = _thrash_engine(manager, ledger, clock)
    _storm_ledger_step(ledger, training=5.0)
    engine.tick(clock.advance(1.0))
    _storm_ledger_step(ledger, training=1.0, overhead=4.0, rescales=2)
    ledger.in_flight = True
    for _ in range(4):  # strikes accumulate but enforcement waits
        _storm_ledger_step(ledger, training=0.5, overhead=1.0, rescales=1)
        engine.tick(clock.advance(1.0))
    assert manager.scales == []
    ledger.in_flight = False
    _storm_ledger_step(ledger, training=0.5, overhead=1.0, rescales=1)
    engine.tick(clock.advance(1.0))
    assert manager.scales == [1]


def test_hold_journal_dedup(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    engine = _engine(
        PolicyConfig(hold_journal_interval_s=30.0),
        manager=FakeManager([0, 1]), clock=clock,
    )
    for _ in range(10):
        engine.tick(clock.advance(1.0))
    holds = [
        e for e in _events(journal_file)
        if e["event"] == "policy_decision" and e["action"] == "hold"
    ]
    assert len(holds) == 1  # identical consecutive holds dedup...
    clock.advance(31.0)
    engine.tick(clock.t)
    holds = [
        e for e in _events(journal_file)
        if e["event"] == "policy_decision" and e["action"] == "hold"
    ]
    assert len(holds) == 2  # ...to one per interval


def test_policy_decisions_pass_schema_validation(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    ledger = FakeLedger()
    ledger.last = {"total_s": 50.0, "t_end": 0.0, "cause": "scale"}
    ledger.since = 1000.0
    manager = FakeManager([0, 1, 2])
    engine = _engine(
        PolicyConfig(evict_after_ticks=1, kill_budget=1,
                     amortize_horizon_s=10.0),
        manager=manager, ledger=ledger, clock=clock,
    )
    engine.note_straggler(1, True)
    engine.tick(clock.advance(1.0))          # evict
    engine.gate_scale_up(1, 1)               # unamortized hold
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []
    events = [
        e for e in _events(journal_file) if e["event"] == "policy_decision"
    ]
    assert {e["action"] for e in events} == {"evict", "hold"}


def test_gated_scale_up_wrapper_chains_and_forwards(
    journal_file, obs_registry_snapshot
):
    """job_runner's oracle wrapper: grant flows oracle -> policy gate,
    and the k8s probe's backoff feedback passes through."""
    from elasticdl_tpu.master.job_runner import _gated_scale_up

    engine = _engine(PolicyConfig(), manager=FakeManager([0]))
    assert _gated_scale_up(None, engine) is None
    plain = lambda needed: needed  # noqa: E731
    assert _gated_scale_up(plain, None) is plain

    class Probe:
        def __init__(self):
            self.calls = []

        def __call__(self, needed):
            return min(needed, 1)

        def failed(self):
            self.calls.append("failed")

        def succeeded(self):
            self.calls.append("succeeded")

    probe = Probe()
    gated = _gated_scale_up(probe, engine)
    assert gated(3) == 1  # oracle capped the grant; unpriced gate approves
    gated.failed()
    gated.succeeded()
    assert probe.calls == ["failed", "succeeded"]


def test_config_from_args_maps_flags():
    from elasticdl_tpu.common.args import parse_master_args

    args = parse_master_args([
        "--model_zoo=model_zoo", "--model_def=m.m",
        "--policy_amortize_horizon_s=123.5", "--policy_min_workers=2",
        "--policy_evict_after=7", "--policy_kill_budget=4",
        "--policy_kill_budget_window_s=55", "--policy_enabled=false",
    ])
    config = PolicyConfig.from_args(args)
    assert config.amortize_horizon_s == 123.5
    assert config.min_workers == 2
    assert config.evict_after_ticks == 7
    assert config.kill_budget == 4
    assert config.kill_budget_window_s == 55.0
    # On/off lives with the caller (job_runner reads args.policy_enabled
    # and simply doesn't build an engine), not inside PolicyConfig.
    assert args.policy_enabled is False


# ---------------------------------------------------------------------------
# obs.top header: last policy decision, degrading against old masters
# ---------------------------------------------------------------------------


def test_top_header_shows_last_policy_decision():
    from elasticdl_tpu.obs import top

    events = [
        {"event": "policy_decision", "action": "hold", "reason": "steady"},
        {"event": "worker_telemetry", "worker_id": 0},
        {"event": "policy_decision", "action": "evict",
         "reason": "persistent_straggler", "worker_id": 3},
    ]
    assert top.policy_header(events) == (
        "policy=evict(persistent_straggler) worker=3"
    )
    # Old masters journal no policy_decision events: degrade to nothing.
    assert top.policy_header([]) == ""
    assert top.policy_header([{"event": "worker_telemetry"}]) == ""
    # Malformed tails (journal corruption) degrade too, never raise.
    assert top.policy_header([{"event": "policy_decision"}]) == ""
    frame = top.render(
        [], {"elasticdl_world_size": 3}, addr="x:1",
        job_header="goodput=97.2%  " + top.policy_header(events),
    )
    assert "policy=evict(persistent_straggler)" in frame


# ---------------------------------------------------------------------------
# Pod manager: scale-down is real (the max() clamp regression)
# ---------------------------------------------------------------------------


class _Handle:
    def __init__(self, worker_id):
        self.worker_id = worker_id


class FakeSubstrateManager:
    """In-process ElasticWorkerManager with a no-op substrate — real
    supervision/scaling logic, no child processes."""

    def __new__(cls, *args, **kwargs):
        from elasticdl_tpu.master.pod_manager import ElasticWorkerManager

        class _Fake(ElasticWorkerManager):
            def _substrate_launch(self, worker_ids):
                return [_Handle(wid) for wid in worker_ids]

            def _substrate_poll(self, handle):
                return None  # everyone stays alive

            def _substrate_terminate(self, handles):
                pass

            def _substrate_kill(self, handle, sig=9):
                pass

        return _Fake(*args, **kwargs)


def test_scale_down_lowers_target_and_sticks(
    journal_file, obs_registry_snapshot
):
    """Regression: scale() used to clamp the target with max(), so a
    scale-down was immediately undone by _maybe_scale_up regrowth."""
    goodput.reset_ledger()
    manager = FakeSubstrateManager(
        num_workers=3,
        worker_argv_fn=lambda wid: ["true"],
        poll_interval_s=0.02,
        scale_up_check_fn=lambda needed: needed,  # capacity always there
    )
    try:
        manager.start()
        assert len(manager.current_worker_ids()) == 3
        manager.scale(2)
        assert manager.target_num_workers() == 2
        import time as _time

        _time.sleep(0.2)  # several monitor polls: regrow must NOT happen
        assert len(manager.current_worker_ids()) == 2
        assert manager.target_num_workers() == 2
        # Raising the target through the restore path regrows.
        manager.set_target_num_workers(3)
        deadline = _time.time() + 5
        while len(manager.current_worker_ids()) != 3:
            assert _time.time() < deadline, "regrow to restored target"
            _time.sleep(0.02)
    finally:
        manager.stop()
        goodput.reset_ledger()
    scale_events = [
        e for e in _events(journal_file) if e["event"] == "scale"
    ]
    assert [e["direction"] for e in scale_events] == ["down"]
    assert scale_events[0]["old_size"] == 3
    assert scale_events[0]["new_size"] == 2
    assert any(e["event"] == "scale_up" for e in _events(journal_file))


def test_scale_rejects_zero(obs_registry_snapshot):
    manager = FakeSubstrateManager(
        num_workers=1, worker_argv_fn=lambda wid: ["true"]
    )
    with pytest.raises(ValueError):
        manager.scale(0)
