"""Delta-checkpoint chain tests (docs/design.md "Continuous training"):
diff-based publish, CRC-manifested commit, torn-write quarantine with
full-chain fallback, compaction repair, and the serving-side row-patch
apply with atomic rollback."""

import json
import os

import numpy as np
import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.checkpoint.delta import (
    DeltaExporter,
    load_delta,
    resolve_chain,
    scan_pub_dir,
)
from elasticdl_tpu.common import faults
from test_serving import _trained_deepfm

_ZOO_ARGS = dict(
    model_zoo="model_zoo",
    model_def="deepfm.deepfm_functional_api",
    model_params="vocab_size=100",
)


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_scan_pub_dir_skips_tmp_and_quarantine(tmp_path):
    for name in (
        "full_000000000004",
        "delta_000000000004_000000000006",
        "delta.tmpabc123",
        "publish.tmpdef",
        "full_000000000002.quarantined",
        "delta_000000000002_000000000004.quarantined.2",
        "unrelated",
    ):
        os.makedirs(tmp_path / name)
    fulls, deltas = scan_pub_dir(str(tmp_path))
    assert fulls == [4]
    assert deltas == [(4, 6)]


def test_resolve_chain_empty_dir(tmp_path):
    assert resolve_chain(str(tmp_path)) == (None, [])


def test_delta_chain_publish_apply_compact(
    tmp_path, journal_file, obs_registry_snapshot
):
    """The whole loop on one trainer: full -> delta (row diff, applied
    in place without reload or recompile) -> compaction full."""
    from elasticdl_tpu.serving.runtime import ServingReplica

    zoo, trainer, batches = _trained_deepfm(steps=2)
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(pub_dir, **_ZOO_ARGS)
    full_dir = exporter.publish_full(trainer, event_time=1.0)
    base_step = exporter.head_step
    assert os.path.basename(full_dir) == f"full_{base_step:012d}"

    # No training since the full: a delta publish is a no-op.
    assert exporter.publish_delta(trainer, event_time=1.0) is None

    for feats_l, labels in batches[2:4]:
        trainer.train_step(feats_l, labels)
    delta_dir = exporter.publish_delta(trainer, event_time=2.0)
    assert delta_dir is not None

    # The diff is sparse: a 2-step minibatch touches well under the full
    # vocabulary, and the stored rows reproduce the new table exactly.
    loaded = load_delta(delta_dir)
    manifest = loaded["manifest"]
    assert manifest["base_step"] == base_step
    assert manifest["step"] == exporter.head_step > base_step
    sig = json.loads(
        open(os.path.join(full_dir, "signature.json")).read()
    )
    assert sig["event_time"] == 1.0
    for meta in sig["tables"]:
        key = meta["key"]
        base_table = np.load(os.path.join(full_dir, meta["file"]))
        rows, vals, dmeta = loaded["tables"][key]
        assert 0 < dmeta["rows"] < base_table.shape[0]
        patched = np.array(base_table)
        patched[rows] = vals
        np.testing.assert_array_equal(patched, exporter._head[key])

    # Chain resolution links the delta to its base.
    assert resolve_chain(pub_dir) == (full_dir, [delta_dir])

    # Serving side: load the full, apply the delta IN PLACE — same
    # compiled step (no retrace), new generation, exact trainer parity.
    replica = ServingReplica(full_dir, model_zoo="model_zoo")
    old_gen = replica.generation
    feats = {k: np.asarray(v) for k, v in batches[0][0].items()}
    replica.apply_delta(delta_dir)
    new_gen = replica.generation
    assert new_gen.gen_id == old_gen.gen_id + 1
    assert new_gen.step == manifest["step"]
    assert new_gen.serve_fn is old_gen.serve_fn  # no recompile
    assert new_gen.event_time == 2.0
    np.testing.assert_allclose(
        replica.execute(feats, n_valid=16),
        np.asarray(trainer.eval_step(feats)),
        rtol=1e-5,
    )

    # Compaction folds the head into a fresh full that re-anchors the
    # chain (no deltas dangle past it).
    compacted = exporter.compact()
    assert os.path.basename(compacted) == f"full_{manifest['step']:012d}"
    assert exporter.deltas_since_full == 0
    base_dir, chain = resolve_chain(pub_dir)
    assert base_dir == compacted and chain == []

    events = _events(journal_file)
    deltas = [e for e in events if e["event"] == "delta_checkpoint"]
    assert len(deltas) == 1 and deltas[0]["base_step"] == base_step
    assert deltas[0]["rows"] > 0 and deltas[0]["event_time"] == 2.0
    compactions = [e for e in events if e["event"] == "delta_compaction"]
    assert len(compactions) == 1 and compactions[0]["deltas_folded"] == 1
    swaps = [e for e in events if e["event"] == "model_swap"]
    assert [s["kind"] for s in swaps] == ["delta"]
    assert swaps[0]["outcome"] == "applied" and swaps[0]["undrained"] == 0


def test_torn_delta_quarantined_and_compaction_repairs(
    tmp_path, journal_file, obs_registry_snapshot
):
    """The `ckpt.delta` fault tears the largest delta file after its
    checksum is manifested: resolve_chain proves the corruption, moves
    the link aside, and the chain falls back to the last full — until a
    compaction republishes past the gap."""
    zoo, trainer, batches = _trained_deepfm(steps=2)
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(pub_dir, **_ZOO_ARGS)
    full_dir = exporter.publish_full(trainer, event_time=1.0)

    faults.install("ckpt.delta:truncate@1")
    for feats, labels in batches[2:4]:
        trainer.train_step(feats, labels)
    torn_dir = exporter.publish_delta(trainer, event_time=2.0)
    head_after_torn = exporter.head_step
    faults.clear()

    # The consumer proves the tear and quarantines; the chain degrades
    # to the last good full (stale-serving, never down).
    base_dir, chain = resolve_chain(pub_dir)
    assert base_dir == full_dir and chain == []
    assert not os.path.exists(torn_dir)
    assert os.path.exists(torn_dir + ".quarantined")
    quarantined = [
        e for e in _events(journal_file)
        if e["event"] == "checkpoint_quarantined"
    ]
    assert len(quarantined) == 1
    assert quarantined[0]["path"] == torn_dir
    assert "torn write" in quarantined[0]["reason"]

    # The exporter's head mirrors the TRAINER (it advanced through the
    # torn publish), so compaction repairs the gap at the head step —
    # and the repaired full is built from the pristine in-memory head,
    # not the torn bytes on disk: it must load and match the trainer.
    compacted = exporter.compact()
    base_dir, chain = resolve_chain(pub_dir)
    assert base_dir == compacted and chain == []
    assert exporter.head_step == head_after_torn
    from elasticdl_tpu.serving.runtime import ServingReplica

    replica = ServingReplica(compacted, model_zoo="model_zoo")
    probe = {k: np.asarray(v) for k, v in batches[0][0].items()}
    np.testing.assert_allclose(
        replica.execute(probe, n_valid=16),
        np.asarray(trainer.eval_step(probe)),
        rtol=1e-5,
    )
    # A later delta chains from the compacted full, not the torn link.
    for feats, labels in batches[0:2]:
        trainer.train_step(feats, labels)
    next_delta = exporter.publish_delta(trainer, event_time=3.0)
    base_dir, chain = resolve_chain(pub_dir)
    assert base_dir == compacted and chain == [next_delta]


def test_corrupt_full_falls_back_to_previous(
    tmp_path, journal_file, obs_registry_snapshot
):
    zoo, trainer, batches = _trained_deepfm(steps=2)
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(pub_dir, **_ZOO_ARGS)
    old_full = exporter.publish_full(trainer, event_time=1.0)
    for feats, labels in batches[2:4]:
        trainer.train_step(feats, labels)
    new_full = exporter.publish_full(trainer, event_time=2.0)

    # Same-size bit flip in the newest full: crc catches it, the walk
    # falls back one full instead of failing the resolve.
    victim = os.path.join(new_full, "variables.pkl")
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))

    base_dir, chain = resolve_chain(pub_dir)
    assert base_dir == old_full and chain == []
    assert os.path.exists(new_full + ".quarantined")
    reasons = [
        e["reason"] for e in _events(journal_file)
        if e["event"] == "checkpoint_quarantined"
    ]
    assert len(reasons) == 1 and "crc32" in reasons[0]


def test_delta_apply_fault_rolls_back_then_retries(
    tmp_path, journal_file, obs_registry_snapshot
):
    """The `serving.delta_apply` fault: the FIRST apply fails and rolls
    back atomically (old generation keeps answering, journaled
    rolled_back); the watcher's next poll retries the same link and
    succeeds — the stale-serving rung is temporary by construction."""
    from elasticdl_tpu.serving.continuous import DeltaWatcher
    from elasticdl_tpu.serving.runtime import ServingReplica

    zoo, trainer, batches = _trained_deepfm(steps=2)
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(pub_dir, **_ZOO_ARGS)
    full_dir = exporter.publish_full(trainer, event_time=1.0)
    for feats, labels in batches[2:4]:
        trainer.train_step(feats, labels)
    delta_dir = exporter.publish_delta(trainer, event_time=2.0)

    replica = ServingReplica(full_dir, model_zoo="model_zoo")
    old_gen = replica.generation
    feats = {k: np.asarray(v) for k, v in batches[0][0].items()}
    baseline = replica.execute(feats, n_valid=16)

    faults.install("serving.delta_apply:error=injected@1")
    watcher = DeltaWatcher(replica, pub_dir)
    summary = watcher.poll_once()
    assert summary["failed"] == delta_dir
    assert summary["applied_deltas"] == 0
    # Rolled back: same generation object, still answering, same bits.
    assert replica.generation is old_gen
    np.testing.assert_array_equal(
        replica.execute(feats, n_valid=16), baseline
    )

    summary = watcher.poll_once()  # fault exhausted: the retry lands
    assert summary["failed"] is None and summary["applied_deltas"] == 1
    assert replica.generation.step == exporter.head_step

    swaps = [e for e in _events(journal_file) if e["event"] == "model_swap"]
    assert [s["outcome"] for s in swaps] == ["rolled_back", "applied"]
    assert swaps[0]["kind"] == "delta"
    assert "injected" in swaps[0]["reason"]
    assert swaps[0]["generation"] == old_gen.gen_id  # pointer never moved


def test_delta_apply_rejects_chain_gap(tmp_path, obs_registry_snapshot):
    """A delta whose base_step is not the serving step is a gap: apply
    refuses (rolled back) rather than patching rows into the wrong
    base — the watcher waits for compaction instead."""
    from elasticdl_tpu.serving.runtime import ServingReplica

    zoo, trainer, batches = _trained_deepfm(steps=2)
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(pub_dir, **_ZOO_ARGS)
    full_dir = exporter.publish_full(trainer, event_time=1.0)
    for feats, labels in batches[2:4]:
        trainer.train_step(feats, labels)
    exporter.publish_delta(trainer, event_time=2.0)
    for feats, labels in batches[0:2]:
        trainer.train_step(feats, labels)
    second_delta = exporter.publish_delta(trainer, event_time=3.0)

    replica = ServingReplica(full_dir, model_zoo="model_zoo")
    with pytest.raises(ValueError, match="chains from step"):
        replica.apply_delta(second_delta)
    assert replica.generation.gen_id == 1
