"""Async staging engine units (data/pipeline.py — ROADMAP item 4).

The contracts the step loops and the serving batcher lean on:

- ParsePool.imap is indistinguishable from serial `map` under thread
  jitter: submission-order yields, submission-order error positions,
  bounded read-ahead from the source iterator.
- Prefetcher is a bounded readahead: the queue bound is a backpressure
  contract (a slow consumer stalls the producer, host memory stays
  flat), close() is a synchronous drain (the churn/rescale/checkpoint
  boundary guarantee: no stale in-flight batch crosses a rendezvous
  generation), and the wait/overlap clocks feed step anatomy.
- StagingPipeline books staging time as the exclusive `stage` phase
  only when nothing is outstanding on the device queue, overlap credit
  otherwise.
- The async Local-mode train loop produces a BIT-IDENTICAL loss curve
  to the sync loop on CPU — the pipeline reorders work in time, never
  in effect.
"""

import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.data.pipeline import (
    ParsePool,
    PipelineConfig,
    Prefetcher,
    StagingPipeline,
    bucket_for,
    bucket_sizes,
    pad_and_stage,
    pad_features,
)

# ---------------------------------------------------------------------------
# ParsePool
# ---------------------------------------------------------------------------


def _jittered_square(x):
    # Deterministic per-item jitter: later items often finish FIRST on a
    # multi-worker pool, so ordered reassembly is actually exercised.
    time.sleep(((x * 7919) % 5) / 1000.0)
    return x * x


def test_parse_pool_jittered_ordering_matches_serial_map():
    items = list(range(48))
    expect = [x * x for x in items]
    with ParsePool(workers=4) as pool:
        assert list(pool.imap(_jittered_square, items)) == expect
        # Determinism: a second pass over the same (still-jittered) pool
        # reproduces the same sequence.
        assert list(pool.imap(_jittered_square, items)) == expect


def test_parse_pool_workers_zero_is_serial_map():
    pool = ParsePool(workers=0)
    assert list(pool.imap(_jittered_square, range(8))) == [
        x * x for x in range(8)
    ]
    pool.close()  # no threads to join; must still be a no-op


def test_parse_pool_error_raises_at_failing_item_position():
    def boom_at_7(x):
        time.sleep(((x * 31) % 3) / 1000.0)
        if x == 7:
            raise ValueError("chunk 7 corrupt")
        return x

    with ParsePool(workers=3) as pool:
        out = []
        with pytest.raises(ValueError, match="chunk 7 corrupt"):
            for value in pool.imap(boom_at_7, range(16)):
                out.append(value)
        # Everything BEFORE the failing item was yielded, in order —
        # exactly where serial map would have stopped.
        assert out == list(range(7))


def test_parse_pool_lookahead_bounds_source_readahead():
    pulled = [0]

    def counting_source():
        for i in range(32):
            pulled[0] += 1
            yield i

    with ParsePool(workers=2) as pool:
        it = pool.imap(lambda x: x, counting_source(), lookahead=3)
        consumed = 0
        for value in it:
            assert value == consumed
            consumed += 1
            # The submitter never runs more than `lookahead` items past
            # the consumer (+1 for the iterator's own refill turn) —
            # this bound is what keeps host memory flat on a slow
            # device.
            assert pulled[0] <= consumed + 3 + 1
        assert consumed == 32


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------


def test_prefetcher_yields_in_order_and_counts():
    with Prefetcher(iter(range(20)), max_inflight=4) as prefetcher:
        assert list(prefetcher) == list(range(20))
        assert prefetcher.produced == 20
        assert prefetcher.consumed == 20
        assert prefetcher.overlap_s >= 0.0
    # Exhausted + closed: further next() is a clean StopIteration.
    assert next(iter(prefetcher), None) is None


def test_prefetcher_backpressure_bounds_producer_runahead():
    produced_log = []

    def slow_to_consume_source():
        for i in range(24):
            produced_log.append(i)
            yield i

    prefetcher = Prefetcher(slow_to_consume_source(), max_inflight=2)
    try:
        for consumed, value in enumerate(prefetcher, start=1):
            assert value == consumed - 1
            time.sleep(0.002)  # consumer is the slow side
            # Queue bound 2 + one item in the producer's hand: the
            # producer may never run further ahead than that.
            assert len(produced_log) <= consumed + 2 + 1
    finally:
        prefetcher.close()
    assert prefetcher.consumed == 24


def test_prefetcher_close_mid_iteration_is_synchronous_drain():
    """Simulated churn: the worker loop dies mid-task; the finally-close
    must leave no producer thread and no observable stale batch."""

    def endless():
        i = 0
        while True:
            yield i
            i += 1

    prefetcher = Prefetcher(endless(), max_inflight=2)
    seen = []
    with pytest.raises(RuntimeError, match="simulated churn"):
        try:
            for value in prefetcher:
                seen.append(value)
                if len(seen) == 3:
                    raise RuntimeError("simulated churn")
        finally:
            prefetcher.close()
    assert seen == [0, 1, 2]
    assert not prefetcher._thread.is_alive()
    # After the drain the iterator is terminally finished — a stale
    # buffered batch can never surface in the next generation.
    assert next(iter(prefetcher), None) is None


def test_prefetcher_drain_then_fresh_generation_sees_fresh_data():
    """Checkpoint/rescale boundary: drain the old pipeline, build a new
    one for the re-formed world — the new generation must see exactly
    its own source from the start, nothing carried over."""
    first = Prefetcher(iter(range(100)), max_inflight=4)
    for _ in range(5):
        next(first)
    first.close()
    second = Prefetcher(iter(range(100, 108)), max_inflight=4)
    try:
        assert list(second) == list(range(100, 108))
    finally:
        second.close()


def test_prefetcher_propagates_source_exception_at_consume_point():
    def poisoned():
        yield 1
        yield 2
        raise OSError("read failed")

    prefetcher = Prefetcher(poisoned(), max_inflight=2)
    try:
        assert next(prefetcher) == 1
        assert next(prefetcher) == 2
        with pytest.raises(OSError, match="read failed"):
            next(prefetcher)
    finally:
        prefetcher.close()


def test_prefetcher_close_unblocks_stuck_producer():
    """close() while the producer is blocked on a full queue must not
    deadlock (the 0.05 s put poll re-checks the stop flag)."""
    prefetcher = Prefetcher(iter(range(1000)), max_inflight=1)
    time.sleep(0.02)  # let the producer fill the queue and block
    done = threading.Event()

    def closer():
        prefetcher.close()
        done.set()

    t = threading.Thread(target=closer)
    t.start()
    t.join(timeout=5.0)
    assert done.is_set(), "close() deadlocked against a blocked producer"


# ---------------------------------------------------------------------------
# StagingPipeline (overlap booking)
# ---------------------------------------------------------------------------


def test_staging_pipeline_books_stage_then_overlap():
    from elasticdl_tpu.obs.stepstats import StepAnatomy

    anatomy = StepAnatomy(worker_id=0)
    staging = StagingPipeline(anatomy, dispatch_depth=2)

    def fake_stage():
        time.sleep(0.002)
        return "staged"

    # Nothing outstanding: staging really serializes -> `stage` phase.
    assert staging.stage(fake_stage) == "staged"
    staging.note_dispatched()
    assert staging.outstanding == 1
    # A dispatch is in flight: the same staging call is hidden work.
    staging.stage(fake_stage)
    with anatomy.dispatch(1, 8):
        pass
    window = anatomy.close_window()
    assert window is not None
    assert window.get("stage", 0.0) > 0.0
    assert window.get("overlap_s", 0.0) > 0.0
    # Exclusive fractions still sum to 1 — overlap rides BESIDE them.
    from elasticdl_tpu.obs.stepstats import phase_fractions

    fractions = phase_fractions(anatomy.totals())
    assert "overlap_s" not in fractions
    assert abs(sum(fractions.values()) - 1.0) < 1e-6


def test_staging_pipeline_depth_cap_and_sync_reset():
    staging = StagingPipeline(anatomy=None, dispatch_depth=2)
    for _ in range(5):
        staging.note_dispatched()
    assert staging.outstanding == 2  # capped at dispatch_depth
    staging.note_synced()
    assert staging.outstanding == 0
    staging.note_dispatched()
    staging.drain()  # task/rendezvous boundary forgets in-flight state
    assert staging.outstanding == 0


# ---------------------------------------------------------------------------
# Shared pad-and-stage (serving reuse)
# ---------------------------------------------------------------------------


def test_bucket_helpers_are_shared_with_serving_batcher():
    from elasticdl_tpu.serving import batcher

    assert batcher.bucket_sizes is bucket_sizes
    assert batcher.bucket_for is bucket_for
    assert batcher.pad_features is pad_features
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_for(3, (1, 2, 4, 8)) == 4


def test_pad_and_stage_pads_to_bucket_and_stages():
    features = {"x": np.arange(6, dtype=np.float32).reshape(3, 2)}
    staged_calls = []

    def stage_fn(padded):
        staged_calls.append(padded)
        return ("on-device", padded)

    out, bucket = pad_and_stage(features, 3, bucket_sizes(8), stage_fn)
    assert bucket == 4
    assert out[0] == "on-device"
    padded = staged_calls[0]["x"]
    assert padded.shape == (4, 2)
    np.testing.assert_array_equal(padded[:3], features["x"])
    np.testing.assert_array_equal(padded[3:], 0.0)
    # Without a stage_fn the padded host batch comes back directly.
    out, bucket = pad_and_stage(features, 3, bucket_sizes(8))
    assert bucket == 4 and out["x"].shape == (4, 2)


# ---------------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------------


def test_pipeline_config_from_parsed_args():
    from elasticdl_tpu.common.args import parse_worker_args

    args = parse_worker_args(
        [
            "--master_addr", "localhost:0",
            "--worker_id", "0",
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--pipeline", "async",
            "--parse_pool_workers", "3",
            "--pipeline_inflight", "5",
            "--dispatch_depth", "4",
        ]
    )
    config = PipelineConfig.from_args(args)
    assert config.is_async
    assert config.parse_workers == 3
    assert config.max_inflight == 5
    assert config.dispatch_depth == 4
    # Defaults: sync, no pool — the reference-parity serial loop.
    default = PipelineConfig()
    assert not default.is_async and default.parse_workers == 0
    with pytest.raises(ValueError):
        PipelineConfig(mode="turbo")


# ---------------------------------------------------------------------------
# Sync-vs-async equivalence (the acceptance gate)
# ---------------------------------------------------------------------------


def _local_losses(tmp_path, pipeline_mode):
    from elasticdl_tpu.client import api
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.worker import trainer as trainer_mod

    args = parse_master_args(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--distribution_strategy", "Local",
            "--training_data", "synthetic://mnist?n=320",
            "--records_per_task", "160",
            "--minibatch_size", "32",
            "--num_epochs", "1",
            "--pipeline", pipeline_mode,
            "--pipeline_inflight", "3",
        ]
    )
    losses = []
    original = trainer_mod.Trainer.train_step

    def spy(self, features, labels):
        loss = original(self, features, labels)
        losses.append(float(loss))
        return loss

    trainer_mod.Trainer.train_step = spy
    try:
        assert api._run_local(args, mode="training") == 0
    finally:
        trainer_mod.Trainer.train_step = original
    return losses


def test_async_pipeline_loss_curve_bit_identical_to_sync(tmp_path):
    """The pipeline moves host work in TIME, never in EFFECT: the same
    job through the async prefetch path must reproduce the sync loss
    sequence bit for bit on CPU."""
    sync_losses = _local_losses(tmp_path, "sync")
    async_losses = _local_losses(tmp_path, "async")
    assert len(sync_losses) == 10  # 320 records / 32 batch
    assert async_losses == sync_losses  # exact float equality, per step
