"""Embedding engine tests: layer semantics, sparse optimizers vs dense
golden math, and sparse-path training equivalence with dense autodiff.

Parity surface: elasticdl/python/tests/embedding_layer_test.py and the Go
kernel tests in elasticdl/pkg/kernel (golden-value sparse-apply parity).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from elasticdl_tpu.worker.trainer import Trainer, TrainState

VOCAB, DIM = 32, 8


# ---------------------------------------------------------------------------
# Sparse optimizers vs dense golden math.
# ---------------------------------------------------------------------------

def _golden_rows(ids, grads):
    """Per-unique-row summed grads (numpy reference)."""
    out = {}
    for i, g in zip(ids, grads):
        out.setdefault(int(i), np.zeros(grads.shape[1], np.float32))
        out[int(i)] += g
    return out


class TestSparseOptimizers:
    def setup_method(self, method):
        rng = np.random.RandomState(0)
        self.table = rng.rand(VOCAB, DIM).astype(np.float32)
        self.ids = np.array([3, 7, 3, 0], np.int32)  # duplicate id 3
        self.grads = rng.rand(4, DIM).astype(np.float32)

    def test_sgd_matches_segment_summed_update(self):
        opt = sparse_optim.sgd(0.1)
        new_table, _ = opt.apply_logical(
            jnp.asarray(self.table), opt.init_slots_logical(jnp.asarray(self.table)),
            jnp.asarray(self.ids), jnp.asarray(self.grads),
        )
        expected = self.table.copy()
        for row, g in _golden_rows(self.ids, self.grads).items():
            expected[row] -= 0.1 * g
        np.testing.assert_allclose(np.asarray(new_table), expected, rtol=1e-6)

    def test_adagrad_matches_golden(self):
        opt = sparse_optim.adagrad(0.1, epsilon=1e-7)
        slots = opt.init_slots_logical(jnp.asarray(self.table))
        new_table, new_slots = opt.apply_logical(
            jnp.asarray(self.table), slots,
            jnp.asarray(self.ids), jnp.asarray(self.grads),
        )
        expected = self.table.copy()
        acc = np.zeros_like(self.table)
        for row, g in _golden_rows(self.ids, self.grads).items():
            acc[row] += g * g
            expected[row] -= 0.1 * g / (np.sqrt(acc[row]) + 1e-7)
        np.testing.assert_allclose(np.asarray(new_table), expected, rtol=1e-5)
        from elasticdl_tpu.parallel.packed import PackedSpec
        from elasticdl_tpu.parallel import packed as pk

        spec = PackedSpec(VOCAB, DIM)
        np.testing.assert_allclose(
            np.asarray(pk.unpack(spec, new_slots["accumulator"])), acc, rtol=1e-6
        )

    def test_momentum_matches_golden(self):
        opt = sparse_optim.momentum(0.1, mu=0.9)
        slots = opt.init_slots_logical(jnp.asarray(self.table))
        table, slots = opt.apply_logical(
            jnp.asarray(self.table), slots,
            jnp.asarray(self.ids), jnp.asarray(self.grads),
        )
        # Second apply exercises existing momentum.
        table, slots = opt.apply_logical(
            table, slots, jnp.asarray(self.ids), jnp.asarray(self.grads)
        )
        expected = self.table.copy()
        v = np.zeros_like(self.table)
        for _ in range(2):
            for row, g in _golden_rows(self.ids, self.grads).items():
                v[row] = 0.9 * v[row] + g
                expected[row] -= 0.1 * v[row]
        np.testing.assert_allclose(np.asarray(table), expected, rtol=1e-5)

    def test_adam_matches_golden(self):
        opt = sparse_optim.adam(0.01, 0.9, 0.999, 1e-8)
        slots = opt.init_slots_logical(jnp.asarray(self.table))
        table, slots = opt.apply_logical(
            jnp.asarray(self.table), slots,
            jnp.asarray(self.ids), jnp.asarray(self.grads),
        )
        expected = self.table.copy()
        m = np.zeros_like(self.table)
        v = np.zeros_like(self.table)
        for row, g in _golden_rows(self.ids, self.grads).items():
            m[row] = 0.9 * m[row] + 0.1 * g
            v[row] = 0.999 * v[row] + 0.001 * g * g
            m_hat = m[row] / (1 - 0.9)
            v_hat = v[row] / (1 - 0.999)
            expected[row] -= 0.01 * m_hat / (np.sqrt(v_hat) + 1e-8)
        np.testing.assert_allclose(np.asarray(table), expected, rtol=1e-5)


# ---------------------------------------------------------------------------
# Layer semantics.
# ---------------------------------------------------------------------------

class TestEmbeddingLayer:
    def _apply(self, layer, ids):
        from elasticdl_tpu.parallel import packed as pk

        variables = layer.init(jax.random.PRNGKey(0), ids)
        packed_table = variables["params"]["embedding"].unbox()
        table = pk.unpack(layer.spec, packed_table)  # logical [vocab, dim]
        out = layer.apply(variables, ids)
        return np.asarray(table), np.asarray(out)

    def test_plain_lookup(self):
        ids = jnp.asarray([[1, 2], [3, 1]], jnp.int32)
        table, out = self._apply(Embedding(VOCAB, DIM), ids)
        np.testing.assert_allclose(out, table[np.asarray(ids)], rtol=1e-6)

    def test_combiner_mean_with_padding(self):
        ids = jnp.asarray([[1, 2, -1], [3, -1, -1]], jnp.int32)
        table, out = self._apply(Embedding(VOCAB, DIM, combiner="mean"), ids)
        np.testing.assert_allclose(
            out[0], (table[1] + table[2]) / 2.0, rtol=1e-5
        )
        np.testing.assert_allclose(out[1], table[3], rtol=1e-5)

    def test_combiner_sum(self):
        ids = jnp.asarray([[1, 2, -1]], jnp.int32)
        table, out = self._apply(Embedding(VOCAB, DIM, combiner="sum"), ids)
        np.testing.assert_allclose(out[0], table[1] + table[2], rtol=1e-5)

    def test_high_oov_ids_read_zeros(self):
        """The fixed-vocab contract (docs/design.md): ids >= vocab_size
        contribute zeros, exactly like negative padding — NOT a clamped
        read of the last row (what the raw gather would do)."""
        ids = jnp.asarray([[1, VOCAB, VOCAB + 7], [2 * VOCAB, 3, -1]],
                          jnp.int32)
        table, out = self._apply(Embedding(VOCAB, DIM), ids)
        np.testing.assert_allclose(out[0, 0], table[1], rtol=1e-6)
        np.testing.assert_allclose(out[0, 1], np.zeros(DIM), atol=0)
        np.testing.assert_allclose(out[0, 2], np.zeros(DIM), atol=0)
        np.testing.assert_allclose(out[1, 0], np.zeros(DIM), atol=0)
        np.testing.assert_allclose(out[1, 1], table[3], rtol=1e-6)
        np.testing.assert_allclose(out[1, 2], np.zeros(DIM), atol=0)

    def test_oov_diagnostics_prints_count(self, capfd):
        from elasticdl_tpu.parallel import packed as pk

        pk.set_oov_debug(True)
        try:
            ids = jnp.asarray([[1, VOCAB + 5, VOCAB]], jnp.int32)
            self._apply(Embedding(VOCAB, DIM, name="probe"), ids)
            jax.effects_barrier()
        finally:
            pk.set_oov_debug(False)
        captured = capfd.readouterr()
        assert "OOV diagnostics [probe]" in captured.out, captured
        assert "2 ids >= vocab_size" in captured.out, captured

    def test_oov_diagnostics_silent_when_in_range(self, capfd):
        from elasticdl_tpu.parallel import packed as pk

        pk.set_oov_debug(True)
        try:
            ids = jnp.asarray([[1, 2, -1]], jnp.int32)
            self._apply(Embedding(VOCAB, DIM), ids)
            jax.effects_barrier()
        finally:
            pk.set_oov_debug(False)
        assert "OOV diagnostics" not in capfd.readouterr().out


# ---------------------------------------------------------------------------
# Training equivalence: the sparse path (stop_gradient + perturbation +
# scatter apply) must produce EXACTLY the dense-autodiff updates under SGD.
# ---------------------------------------------------------------------------

class SparseModel(nn.Module):
    @nn.compact
    def __call__(self, ids):
        x = Embedding(VOCAB, DIM, combiner="sum", name="emb")(ids)
        return nn.Dense(4, name="head")(x)


class DenseModel(nn.Module):
    @nn.compact
    def __call__(self, ids):
        table = self.param(
            "table", nn.initializers.uniform(0.05), (VOCAB, DIM)
        )
        ids = jnp.asarray(ids, jnp.int32)
        valid = ids >= 0
        acts = jnp.take(table, jnp.where(valid, ids, 0), axis=0)
        acts = acts * valid[..., None].astype(acts.dtype)
        return nn.Dense(4, name="head")(jnp.sum(acts, axis=-2))


def _loss(labels, outputs):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, labels.astype(jnp.int32)
    ).mean()


def test_sparse_path_matches_dense_autodiff_sgd():
    mesh = build_mesh(MeshConfig())
    sparse_trainer = ShardedEmbeddingTrainer(
        SparseModel(), _loss, optax.sgd(0.2), mesh,
        embedding_optimizer=sparse_optim.sgd(0.2), seed=0,
    )
    dense_trainer = Trainer(DenseModel(), _loss, optax.sgd(0.2), seed=0)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
    ids[rng.rand(16, 3) < 0.2] = -1  # padding positions
    labels = rng.randint(0, 4, size=16).astype(np.int32)

    # Sync initial params: copy the sparse trainer's init into the dense one.
    sparse_trainer.ensure_initialized(ids)
    dense_trainer.ensure_initialized(ids)
    sv = sparse_trainer.get_variables_numpy()
    dense_params = {
        "table": jnp.asarray(sv["params/emb/embedding"]),
        "head": {
            "kernel": jnp.asarray(sv["params/head/kernel"]),
            "bias": jnp.asarray(sv["params/head/bias"]),
        },
    }
    dense_trainer.state = TrainState(
        jnp.zeros((), jnp.int32), dense_params,
        optax.sgd(0.2).init(dense_params), {},
    )

    for step in range(5):
        s_loss = sparse_trainer.train_step(ids, labels)
        d_loss = dense_trainer.train_step(ids, labels)
        np.testing.assert_allclose(
            float(s_loss), float(d_loss), rtol=1e-5, atol=1e-6,
            err_msg=f"loss diverged at step {step}",
        )
    sv = sparse_trainer.get_variables_numpy()
    dv = dense_trainer.get_variables_numpy()
    np.testing.assert_allclose(
        sv["params/emb/embedding"], dv["params/table"], rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        sv["params/head/kernel"], dv["params/head/kernel"], rtol=1e-4, atol=1e-6
    )


def test_sharded_trainer_eval_step():
    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        SparseModel(), _loss, optax.sgd(0.1), mesh,
        embedding_optimizer=sparse_optim.sgd(0.1),
    )
    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=16).astype(np.int32)
    trainer.train_step(ids, labels)
    out = trainer.eval_step(ids)
    assert out.shape == (16, 4) and np.isfinite(out).all()


def test_checkpoint_restore_roundtrip():
    import jax as _jax

    mesh = build_mesh(MeshConfig())

    def make():
        return ShardedEmbeddingTrainer(
            SparseModel(), _loss, optax.sgd(0.1), mesh,
            embedding_optimizer=sparse_optim.adagrad(0.1), seed=0,
        )

    rng = np.random.RandomState(2)
    ids = rng.randint(0, VOCAB, size=(8, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=8).astype(np.int32)
    t1 = make()
    for _ in range(3):
        t1.train_step(ids, labels)
    snapshot = _jax.device_get(t1.state)

    t2 = make()
    t2.state = snapshot  # restore BEFORE first batch (worker boot path)
    assert t2.step == 3
    l1 = float(t1.train_step(ids, labels))
    l2 = float(t2.train_step(ids, labels))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_embedding_trains_densely_under_local_trainer():
    """Outside PS mode the table is a normal param: dense autodiff must
    train it (no silent freeze)."""
    trainer = Trainer(SparseModel(), _loss, optax.sgd(0.2), seed=0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=16).astype(np.int32)
    trainer.ensure_initialized(ids)
    before = trainer.get_variables_numpy()["params/emb/embedding"].copy()
    for _ in range(3):
        trainer.train_step(ids, labels)
    after = trainer.get_variables_numpy()["params/emb/embedding"]
    assert np.abs(after - before).max() > 0, "embedding table never trained"


def test_dense_trainer_handles_ragged_batches():
    """The capture collections (perturbations/ids) must NOT live in
    model_state: they'd freeze the init batch's shape (crash on a ragged
    final batch) and grow the sow tuple every step (recompile per step)."""
    trainer = Trainer(SparseModel(), _loss, optax.sgd(0.2), seed=0)
    rng = np.random.RandomState(0)
    for batch in (16, 16, 7, 16, 3):  # ragged sizes interleaved
        ids = rng.randint(0, VOCAB, size=(batch, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=batch).astype(np.int32)
        trainer.train_step(ids, labels)
    state = trainer.state
    assert "perturbations" not in state.model_state
    assert "embedding_ids" not in state.model_state


def test_dp_trainer_handles_ragged_batches():
    """Same invariant for the AllReduce trainer (padded final batch)."""
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer

    mesh = build_mesh(MeshConfig())
    trainer = DataParallelTrainer(SparseModel(), _loss, optax.sgd(0.2), mesh)
    rng = np.random.RandomState(0)
    for batch in (16, 5):
        ids = rng.randint(0, VOCAB, size=(batch, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=batch).astype(np.int32)
        trainer.train_step(ids, labels)
    assert "perturbations" not in trainer.state.model_state
    assert "embedding_ids" not in trainer.state.model_state


def test_masked_batch_does_not_touch_adam_slots():
    """A fully-masked (all-zero-grad) step must leave tables and moments
    untouched (padding rows must not drift)."""
    opt = sparse_optim.adam(0.01)
    table = jnp.asarray(np.random.RandomState(0).rand(8, 4).astype(np.float32))
    slots = opt.init_slots_logical(table)
    # Prime row 2 with a real update.
    ids = jnp.asarray([2], jnp.int32)
    g = jnp.ones((1, 4), jnp.float32)
    table1, slots1 = opt.apply_logical(table, slots, ids, g)
    # Zero-grad (masked) step touching rows 2 and 0.
    table2, slots2 = opt.apply_logical(
        table1, slots1, jnp.asarray([2, 0], jnp.int32),
        jnp.zeros((2, 4), jnp.float32),
    )
    np.testing.assert_array_equal(np.asarray(table2), np.asarray(table1))
    np.testing.assert_array_equal(np.asarray(slots2["m"]), np.asarray(slots1["m"]))
    np.testing.assert_array_equal(np.asarray(slots2["t"]), np.asarray(slots1["t"]))


def test_dense_trainer_exports_logical_table_shape():
    """Export from the Local/AllReduce path must show [vocab, dim], not the
    packed storage shape (same contract as the PS trainer)."""
    trainer = Trainer(SparseModel(), _loss, optax.sgd(0.1), seed=0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, size=(8, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=8).astype(np.int32)
    trainer.train_step(ids, labels)
    assert trainer.get_variables_numpy()["params/emb/embedding"].shape == (VOCAB, DIM)

    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer

    mesh = build_mesh(MeshConfig())
    dp = DataParallelTrainer(SparseModel(), _loss, optax.sgd(0.1), mesh)
    dp.train_step(ids, labels)
    assert dp.get_variables_numpy()["params/emb/embedding"].shape == (VOCAB, DIM)
