"""Windowed sparse apply (ps_trainer sparse_apply_every > 1).

The relaxation: within a W-step chunk, embedding grads accumulate and the
sparse optimizer applies ONCE from the sum (forwards read chunk-start
tables; dense params still update per step) — the async-PS staleness of
the reference traded for amortizing the streaming moment update (see
_train_chunk_impl).  These tests pin the plumbing and the exactness cases.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from tests.test_embedding import DIM, VOCAB, SparseModel, _loss


def _batches(k, rng, batch=16):
    out = []
    for _ in range(k):
        ids = rng.randint(0, VOCAB, size=(batch, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=batch).astype(np.int32)
        out.append((ids, labels, np.ones((batch,), np.float32)))
    return out


def _make(sparse_apply_every=1, emb_opt=None, dense_lr=0.1):
    return ShardedEmbeddingTrainer(
        SparseModel(), _loss, optax.sgd(dense_lr), build_mesh(MeshConfig()),
        embedding_optimizer=emb_opt or sparse_optim.adam(0.01),
        seed=0,
        sparse_apply_every=sparse_apply_every,
    )


def test_windowed_runs_with_remainder_chunk():
    """K=7, W=3 -> chunks of 3,3,1; losses come back per step and the step
    counter advances by K."""
    rng = np.random.RandomState(0)
    batches = _batches(7, rng)
    t = _make(sparse_apply_every=3)
    t.ensure_initialized(batches[0][0])
    losses = np.asarray(t.train_window(t.stage_window(batches)))
    assert losses.shape == (7,)
    assert np.isfinite(losses).all()
    assert t.step == 7


def test_windowed_first_chunk_first_loss_matches_strict():
    """Chunk 1 step 1 sees identical state in both modes -> identical loss."""
    rng = np.random.RandomState(1)
    batches = _batches(4, rng)

    t_strict = _make(1)
    t_strict.ensure_initialized(batches[0][0])
    strict_losses = np.asarray(t_strict.train_window(t_strict.stage_window(batches)))

    t_win = _make(4)
    t_win.ensure_initialized(batches[0][0])
    win_losses = np.asarray(t_win.train_window(t_win.stage_window(batches)))

    np.testing.assert_allclose(win_losses[0], strict_losses[0], rtol=1e-6)
    # Later losses DIFFER (stale tables within the chunk) — that's the
    # documented trade, not a bug; assert they still train sanely.
    assert np.isfinite(win_losses).all()


class LinearSparseModel(nn.Module):
    """Output linear in the embedding rows with a CONSTANT readout, so
    d loss/d row is independent of the table values: strict and windowed
    training produce bit-equal gradients, making windowed == strict
    exactly when the sparse optimizer is linear too (SGD)."""

    @nn.compact
    def __call__(self, ids):
        x = Embedding(VOCAB, DIM, combiner="sum", name="emb")(ids)
        return jnp.sum(x, axis=-1, keepdims=True) * jnp.ones((1, 4))


def _linear_loss(labels, outputs):
    # Linear in outputs -> constant gradient.
    return outputs.mean(axis=-1) * (labels.astype(jnp.float32) * 0 + 1.0)


def test_windowed_sgd_linear_model_exact():
    rng = np.random.RandomState(2)
    batches = _batches(6, rng)

    def make(w):
        return ShardedEmbeddingTrainer(
            LinearSparseModel(), _linear_loss, optax.sgd(0.0),
            build_mesh(MeshConfig()),
            embedding_optimizer=sparse_optim.sgd(0.05),
            seed=0,
            sparse_apply_every=w,
        )

    t1 = make(1)
    t1.ensure_initialized(batches[0][0])
    np.asarray(t1.train_window(t1.stage_window(batches)))

    t3 = make(3)
    t3.ensure_initialized(batches[0][0])
    np.asarray(t3.train_window(t3.stage_window(batches)))

    v1, v3 = t1.get_variables_numpy(), t3.get_variables_numpy()
    for key in v1:
        np.testing.assert_allclose(
            v3[key], v1[key], rtol=1e-6, atol=1e-7, err_msg=key
        )


def test_windowed_checkpoint_state_roundtrips():
    rng = np.random.RandomState(3)
    batches = _batches(4, rng)
    t = _make(2)
    t.ensure_initialized(batches[0][0])
    np.asarray(t.train_window(t.stage_window(batches)))
    state = t.state

    t2 = _make(2)
    t2.ensure_initialized(batches[0][0])
    t2.state = state
    more = _batches(2, rng)
    losses = np.asarray(t2.train_window(t2.stage_window(more)))
    assert np.isfinite(losses).all()
    assert t2.step == 6


def test_windowed_single_apply_per_chunk():
    """The chunk's sparse apply consumes the CONCATENATED (ids, grads) of
    all W steps through the normal optimizer apply — one moment update
    per chunk with summed duplicates (== apply_acc of the summed acc, by
    the dedup contract pinned in test_sparse_optim_modes)."""
    calls = []
    base = sparse_optim.adam(0.01)

    def counting_apply(spec, table, slots, ids, grads):
        calls.append(int(ids.shape[0]))
        return base.apply(spec, table, slots, ids, grads)

    spy = sparse_optim.SparseOptimizer(
        base.name, base.init_slots, counting_apply, base.hyperparams,
        base.apply_acc,
    )
    rng = np.random.RandomState(4)
    batches = _batches(6, rng)
    t = _make(3, emb_opt=spy)
    t.ensure_initialized(batches[0][0])
    np.asarray(t.train_window(t.stage_window(batches)))
    # 6 steps at W=3 -> 2 chunk applies, each over 3 stacked batches
    # (16 examples x 3 ids x 3 steps = 144 ids per apply).  Tracing may
    # record extra entries; the executed structure is what the loss shape
    # and step counter already pin — here we check each traced apply saw
    # the 3-step concatenation.
    assert all(n == 16 * 3 * 3 for n in calls)


def test_windowed_apply_convergence_parity():
    """Convergence tripwire for the windowed-apply semantics trade (the
    r04 A/B, scripts/convergence_ab.py + BASELINE.md "Windowed-apply
    convergence"): on the same learnable Zipf CTR stream, W=8 windowed
    apply must reach the same best held-out AUC as strict W=1 within a
    generous tolerance (measured diff at this scale: ~0.0006; on the
    chip-scale A/B, peak AUC at W=16/32 matched strict within 0.003).
    A real staleness bug — dropped window grads, mis-concatenated chunk
    ids, double-applied chunks — moves AUC far beyond 0.03."""
    from model_zoo import datasets
    from model_zoo.deepfm import deepfm_functional_api as zoo
    from model_zoo.wide_and_deep.wide_and_deep import _auc

    vocab, batch, spe, epochs = 200, 256, 16, 3
    dense, cats, labels = datasets.synthetic_ctr_columns(
        batch * spe, vocab_size=vocab, weights_seed=0, draw_seed=1,
        zipf_s=1.1,
    )
    e_dense, e_cats, e_labels = datasets.synthetic_ctr_columns(
        2048, vocab_size=vocab, weights_seed=0, draw_seed=2, zipf_s=1.1
    )

    def run(w: int) -> float:
        mesh = build_mesh(MeshConfig())
        trainer = ShardedEmbeddingTrainer(
            zoo.custom_model(vocab_size=vocab),
            zoo.loss,
            zoo.optimizer(),
            mesh,
            embedding_optimizer=sparse_optim.adam(
                0.001, bias_correction="global"
            ),
            sparse_apply_every=w,
            seed=0,
        )
        mask = np.ones((batch,), np.float32)

        def make_batch(i):
            lo, hi = i * batch, (i + 1) * batch
            return (
                {"dense": dense[lo:hi], "cat": cats[lo:hi]},
                labels[lo:hi],
                mask,
            )

        trainer.ensure_initialized(make_batch(0)[0])
        window = trainer.stage_window([make_batch(i) for i in range(spe)])
        best = 0.0
        for _ in range(epochs):
            losses = trainer.train_window(window)
            assert np.isfinite(np.asarray(losses)).all()
            outs = [
                np.asarray(
                    trainer.eval_step(
                        {
                            "dense": e_dense[lo : lo + batch],
                            "cat": e_cats[lo : lo + batch],
                        }
                    )
                )
                for lo in range(0, 2048, batch)
            ]
            best = max(best, _auc(np.concatenate(outs), e_labels))
        return best

    strict, windowed = run(1), run(8)
    assert strict > 0.58, f"strict run failed to learn (AUC {strict})"
    assert windowed > 0.58, f"windowed run failed to learn (AUC {windowed})"
    assert abs(strict - windowed) < 0.03, (strict, windowed)


def test_oov_counts_aggregate_across_windows():
    """OOV ids (>= vocab) are counted device-side per dispatch and
    drained by consume_oov_count(); negative ids are padding, NOT OOV
    (round-5 VERDICT weak #5).  Covers both the strict scan and the
    windowed chunk path."""
    for w in (1, 3):
        rng = np.random.RandomState(2)
        batches = _batches(6, rng)
        # Plant a known OOV pattern: 2 OOV ids in batch 0, 3 in batch 4,
        # plus a padding id that must NOT count.
        batches[0][0][0, 0] = VOCAB
        batches[0][0][1, 2] = VOCAB + 7
        batches[4][0][:3, 1] = VOCAB + 1
        batches[2][0][0, 0] = -1  # padding
        t = _make(sparse_apply_every=w)
        t.ensure_initialized(batches[0][0])
        t.train_window(t.stage_window(batches))
        assert t.consume_oov_count() == 5, f"W={w}"
        assert t.consume_oov_count() == 0  # drained
        # Per-step path counts too.
        t.train_step(batches[0][0], batches[0][1])
        assert t.consume_oov_count() == 2


def test_auto_apply_resolves_from_table_rows(monkeypatch):
    """--sparse_apply_every=auto: strict at <= AUTO_APPLY_TABLE_ROWS
    resident rows, AUTO_APPLY_W above — resolved at init, when the
    trainer first knows its table sizes (round-5 VERDICT #5)."""
    from elasticdl_tpu.parallel import ps_trainer as ps

    rng = np.random.RandomState(0)
    batches = _batches(4, rng)

    t = _make(sparse_apply_every="auto")
    assert t._sparse_apply_every is None  # unresolved until init
    t.ensure_initialized(batches[0][0])
    assert t._sparse_apply_every == 1  # tiny table -> strict

    # Same tiny model over a lowered threshold -> the windowed branch,
    # without building a real >10M-row table in the CPU suite.
    monkeypatch.setattr(ps, "AUTO_APPLY_TABLE_ROWS", 8)
    t2 = _make(sparse_apply_every="auto")
    t2.ensure_initialized(batches[0][0])
    assert t2._sparse_apply_every == ps.AUTO_APPLY_W
    # The windowed path actually runs: W=32 over a 4-step window is one
    # short chunk, applied once.
    losses = np.asarray(t2.train_window(t2.stage_window(batches)))
    assert losses.shape == (4,) and np.isfinite(losses).all()


def test_strict_mode_large_table_logs_perf_advice():
    """Strict per-step apply past 10M resident rows logs the windowed-
    apply recommendation (the measured ~3x + convergence-validated
    config); windowed runs stay quiet."""
    import contextlib
    import io
    import logging

    class BigModel(nn.Module):
        @nn.compact
        def __call__(self, ids, train: bool = False):
            return Embedding(10_000_064, 1)(ids)[..., 0]

    def loss(labels, out):
        return jnp.mean((out - labels) ** 2)

    @contextlib.contextmanager
    def capture():
        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        lg = logging.getLogger("elasticdl_tpu.parallel.ps_trainer")
        lg.addHandler(handler)
        try:
            yield buf
        finally:
            lg.removeHandler(handler)

    ids = np.zeros((8,), np.int32)
    labels = np.zeros((8,), np.float32)
    for apply_every, expect in ((1, True), (16, False)):
        mesh = build_mesh(MeshConfig())
        trainer = ShardedEmbeddingTrainer(
            BigModel(), loss, optax.sgd(0.1), mesh,
            embedding_optimizer=sparse_optim.sgd(0.1),
            sparse_apply_every=apply_every,
        )
        with capture() as buf:
            trainer.ensure_initialized(ids)
        trainer.train_step(ids, labels)
        advised = "sparse_apply_every=16" in buf.getvalue()
        assert advised is expect, (apply_every, buf.getvalue())
