"""Transient-failure RPC plane: retry wrapper + deadline + fault injection.

Covers ISSUE satellite "test coverage for the retry wrapper": a flaky fake
servicer that fails N times then succeeds, the exact (deterministic)
backoff schedule, deadline propagation to the server, and that
non-idempotent RPCs are never retried.
"""

import logging
import subprocess
import sys
import time

import grpc
import pytest

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.grpc_utils import (
    RetryPolicy,
    build_server,
    expected_backoff_schedule,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.service import (
    MasterServicer as BaseServicer,
    add_MasterServicer_to_server,
)
from elasticdl_tpu.worker.master_client import MasterClient

#: Fast-but-shaped policy for tests: real exponential backoff, tiny bases.
FAST_POLICY = RetryPolicy(
    timeout_s=5.0,
    max_attempts=6,
    base_backoff_s=0.01,
    max_backoff_s=0.04,
    jitter=0.25,
    total_budget_s=30.0,
)


class FlakyServicer(BaseServicer):
    """Fails the first `fail_get_task` get_task calls with UNAVAILABLE,
    then succeeds; report_task_result ALWAYS fails (the non-idempotent
    never-retried probe).  Records per-call deadlines as seen server-side."""

    def __init__(self, fail_get_task: int = 0):
        self.fail_get_task = fail_get_task
        self.get_task_calls = 0
        self.report_calls = 0
        self.deadlines = []

    def get_task(self, request, context):
        self.get_task_calls += 1
        self.deadlines.append(context.time_remaining())
        if self.get_task_calls <= self.fail_get_task:
            context.abort(grpc.StatusCode.UNAVAILABLE, "flaky (injected)")
        return pb.GetTaskResponse(
            task=pb.Task(task_id=7, type=pb.TRAINING, start=0, end=4)
        )

    def report_task_result(self, request, context):
        self.report_calls += 1
        self.deadlines.append(context.time_remaining())
        context.abort(grpc.StatusCode.UNAVAILABLE, "always down")


@pytest.fixture
def flaky_stack():
    """(servicer, make_client, sleeps) over a real localhost gRPC server.
    Backoff sleeps are recorded, not slept — the schedule is the assert."""
    created = []

    def build(fail_get_task=0, policy=FAST_POLICY):
        servicer = FlakyServicer(fail_get_task=fail_get_task)
        server = build_server(max_workers=4)
        add_MasterServicer_to_server(servicer, server)
        port = server.add_insecure_port("[::]:0")
        server.start()
        sleeps = []
        client = MasterClient(
            f"localhost:{port}", worker_id=0,
            retry_policy=policy, sleep=sleeps.append,
        )
        created.append((server, client))
        return servicer, client, sleeps

    yield build
    for server, client in created:
        client.close()
        server.stop(grace=None)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def test_flaky_rpc_retries_then_succeeds_with_exact_backoff(flaky_stack):
    servicer, client, sleeps = flaky_stack(fail_get_task=3)
    task = client.get_task()
    assert task.task_id == 7
    # 3 failures + 1 success, one backoff sleep per failure, and the
    # schedule is the policy's deterministic (seeded-jitter) exponential.
    assert servicer.get_task_calls == 4
    schedule = expected_backoff_schedule("get_task", FAST_POLICY, 3, seed="0")
    assert tuple(sleeps) == schedule
    # Exponential shape: each raw backoff at least ~doubles until the cap
    # (jitter <= 25% can't mask a 2x growth).
    assert sleeps[0] < sleeps[1] < sleeps[2]
    assert client.retry_stats.retries == 3
    assert client.retry_stats.calls == 1
    assert client.retry_stats.per_method_retries == {"get_task": 3}


def test_every_rpc_carries_an_explicit_deadline(flaky_stack):
    servicer, client, _sleeps = flaky_stack()
    client.get_task()
    with pytest.raises(grpc.RpcError):
        client.report_task_result(1, "")
    from elasticdl_tpu.common.constants import RPC

    assert len(servicer.deadlines) == 2
    # time_remaining() is None when the client set no deadline.
    get_task_remaining, report_remaining = servicer.deadlines
    assert get_task_remaining is not None
    assert 0 < get_task_remaining <= FAST_POLICY.timeout_s + 1.0
    assert report_remaining is not None
    assert 0 < report_remaining <= RPC.DEADLINE_S + 1.0


def test_non_idempotent_rpc_never_retried(flaky_stack):
    servicer, client, sleeps = flaky_stack()
    with pytest.raises(grpc.RpcError) as err:
        client.report_task_result(1, "")
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE
    assert servicer.report_calls == 1  # exactly one attempt
    assert sleeps == []  # and no backoff
    assert client.retry_stats.retries == 0


def test_injected_rpc_fault_is_deterministic(flaky_stack):
    """Two identical runs against a HEALTHY server with a 2-failure
    injection produce byte-identical retry behavior."""
    runs = []
    for _ in range(2):
        servicer, client, sleeps = flaky_stack(fail_get_task=0)
        faults.install("rpc.get_task:error=UNAVAILABLE@1x2")
        task = client.get_task()
        assert task.task_id == 7
        runs.append(tuple(sleeps))
        # The injected failures never reached the wire.
        assert servicer.get_task_calls == 1
        assert client.retry_stats.retries == 2
        faults.clear()
    assert runs[0] == runs[1] == expected_backoff_schedule(
        "get_task", FAST_POLICY, 2, seed="0"
    )


def test_injected_latency_fault(flaky_stack):
    servicer, client, sleeps = flaky_stack()
    faults.install("rpc.get_task:latency=0.123@1")
    assert client.get_task().task_id == 7
    assert sleeps == [0.123]  # delayed, not failed: same attempt proceeds
    assert servicer.get_task_calls == 1
    assert client.retry_stats.retries == 0


def test_non_transient_code_propagates_immediately(flaky_stack):
    servicer, client, sleeps = flaky_stack()
    faults.install("rpc.get_task:error=INVALID_ARGUMENT@1")
    with pytest.raises(grpc.RpcError) as err:
        client.get_task()
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert servicer.get_task_calls == 0
    assert sleeps == []


def test_retry_budget_bounds_total_time(flaky_stack):
    budgetless = RetryPolicy(
        timeout_s=5.0, max_attempts=6, base_backoff_s=0.01,
        max_backoff_s=0.04, jitter=0.25, total_budget_s=0.0,
    )
    servicer, client, sleeps = flaky_stack(policy=budgetless)
    faults.install("rpc.get_task:error=UNAVAILABLE@1x*")
    with pytest.raises(grpc.RpcError):
        client.get_task()
    # Zero budget: the first backoff would overshoot, so exactly one
    # attempt and no sleep.
    assert sleeps == []
    assert client.retry_stats.attempts == 1
    assert client.retry_stats.give_ups == 1


def test_faults_disabled_is_default_and_counts_nothing():
    assert not faults.enabled()
    assert faults.fire("rpc.get_task") is None
    assert faults.call_count("rpc.get_task") == 0


def test_fault_crash_kills_the_process_like_sigkill():
    """`worker.*:crash` exits without cleanup, with the spec's code."""
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from elasticdl_tpu.common import faults\n"
            "faults.install('worker.task:crash=7@2')\n"
            "for _ in range(5):\n"
            "    spec = faults.fire('worker.task')\n"
            "    if spec is not None and spec.kind == 'crash':\n"
            "        faults.crash_now(spec)\n"
            "raise SystemExit(99)  # unreachable when the fault fires\n",
        ],
        timeout=60,
    )
    assert proc.returncode == 7


def test_worker_task_loop_is_a_crash_injection_site(monkeypatch):
    """The simple worker fires the `worker.task` site before each task —
    crash_now intercepted so the test process survives."""
    from types import SimpleNamespace

    from elasticdl_tpu.worker.worker import Worker

    class _Boom(Exception):
        pass

    fired = []
    monkeypatch.setattr(
        faults, "crash_now", lambda spec: (_ for _ in ()).throw(_Boom())
    )
    faults.install("worker.task:crash@1")

    class _OneTaskClient:
        worker_id = 0

        def get_task(self, task_type=pb.TRAINING):
            fired.append("get_task")
            return pb.Task(task_id=1, type=pb.TRAINING, start=0, end=4)

        def report_task_result(self, *a, **k):
            pass

        def report_version(self, *a, **k):
            pass

    worker = Worker(
        master_client=_OneTaskClient(),
        model_spec=SimpleNamespace(dataset_fn=None, callbacks=None),
        data_reader=SimpleNamespace(metadata=None),
        minibatch_size=2,
        trainer=SimpleNamespace(step=0),
    )
    with pytest.raises(_Boom):
        worker.run()
    assert fired == ["get_task"]  # crashed before processing anything


def test_heartbeat_reporter_counts_failures_and_ratelimits_warnings():
    """Satellite: HeartbeatReporter._loop must not swallow errors silently
    — it counts them and warns with the error class, rate-limited."""
    from elasticdl_tpu.parallel.elastic import HeartbeatReporter, WorldInfo

    class _DownMaster:
        worker_id = 3

        def report_worker_liveness(self, host, rendezvous_id):
            raise ConnectionError("master is down")

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    elastic_logger = logging.getLogger("elasticdl_tpu.parallel.elastic")
    elastic_logger.addHandler(handler)
    world = WorldInfo(
        rank=0, world_size=1, rendezvous_id=1, coordinator_addr=""
    )
    reporter = HeartbeatReporter(
        _DownMaster(), world, host="h", interval_s=0.01
    )
    try:
        reporter.start()
        deadline = time.time() + 10
        while reporter.error_count < 3 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        reporter.stop()
        elastic_logger.removeHandler(handler)
    assert reporter.error_count >= 3
    warnings = [r for r in records if r.levelno == logging.WARNING]
    assert len(warnings) == 1  # rate-limited: one warning per interval
    assert "ConnectionError" in warnings[0].getMessage()
