"""In-process fake Kubernetes API server (pods-only) for tests.

Speaks just enough of the real wire protocol for
`elasticdl_tpu.master.k8s_client.K8sClient` to run unmodified against it:
create/get/list/delete pods plus the JSON-lines watch stream (ADDED /
MODIFIED / DELETED events, labelSelector filtering).  Tests drive pod
lifecycle explicitly (`set_running`, `fail_pod`, `succeed_pod`) or enable
`auto_run` to schedule every created pod immediately, and can toggle
`schedulable=False` to simulate a capacity-starved cluster.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlsplit


def _match_selector(labels: Dict[str, str], selector: str) -> bool:
    for clause in filter(None, selector.split(",")):
        if "=" not in clause:
            return False
        k, v = clause.split("=", 1)
        if labels.get(k.strip()) != v.strip():
            return False
    return True


class FakeK8sApiServer:
    def __init__(self, auto_run: bool = True, watch_max_events: int = 0):
        self.auto_run = auto_run
        self.schedulable = True
        # Chaos knob: close every watch stream after this many events
        # (0 = never), forcing clients through their reconnect path.
        self.watch_max_events = watch_max_events
        self._lock = threading.Lock()
        self._pods: Dict[str, dict] = {}
        self._rv = 0
        self._watchers: List[queue.Queue] = []
        self._uid = 0
        self.create_log: List[str] = []
        # Ordered (rv, event) history so watches with a resourceVersion
        # resume from where they left off (real apiserver semantics — a
        # reconnecting client must not miss events); bounded, with 410
        # Gone for clients whose rv fell off the end.
        self._event_log: List[tuple] = []
        self.event_log_cap = 1000

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def _send_json(self, obj, status=200):
                payload = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                parts = urlsplit(self.path)
                q = {k: v[0] for k, v in parse_qs(parts.query).items()}
                segs = parts.path.strip("/").split("/")
                # /api/v1/namespaces/{ns}/pods[/{name}]
                if len(segs) == 6:
                    pod = server.get_pod(segs[5])
                    if pod is None:
                        self._send_json(
                            {"kind": "Status", "code": 404,
                             "reason": "NotFound"}, 404)
                    else:
                        self._send_json(pod)
                    return
                selector = q.get("labelSelector", "")
                if q.get("watch") == "true":
                    self._watch(
                        selector,
                        float(q.get("timeoutSeconds", 30)),
                        q.get("resourceVersion", ""),
                    )
                    return
                self._send_json(
                    {
                        "kind": "PodList",
                        "metadata": {"resourceVersion": str(server._rv)},
                        "items": server.list_pods(selector),
                    }
                )

            def _watch(self, selector: str, timeout_s: float, rv: str):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                events = queue.Queue()
                if rv:
                    # Resume: replay history AFTER rv + register for live
                    # events in ONE atomic step (no gap), or 410 if the
                    # log no longer reaches back to rv.
                    if server._resume_watcher(int(rv), events) is None:
                        self.wfile.write(
                            (json.dumps({
                                "type": "ERROR",
                                "object": {"kind": "Status", "code": 410},
                            }) + "\n").encode()
                        )
                        self.wfile.flush()
                        return
                else:
                    # Like list-then-watch collapsed: current state first.
                    for pod in server.list_pods(selector):
                        events.put({"type": "ADDED", "object": pod})
                    server._add_watcher(events)
                deadline = time.time() + timeout_s
                sent = 0
                try:
                    while time.time() < deadline:
                        try:
                            event = events.get(timeout=0.1)
                        except queue.Empty:
                            continue
                        obj = event["object"]
                        labels = obj.get("metadata", {}).get("labels", {})
                        if selector and not _match_selector(labels, selector):
                            continue
                        self.wfile.write(
                            (json.dumps(event) + "\n").encode()
                        )
                        self.wfile.flush()
                        sent += 1
                        if (
                            server.watch_max_events
                            and sent >= server.watch_max_events
                        ):
                            return  # chaos: drop the stream mid-watch
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    server._remove_watcher(events)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                manifest = json.loads(self.rfile.read(length))
                created = server.create_pod(manifest)
                self._send_json(created, 201)

            def do_DELETE(self):
                segs = urlsplit(self.path).path.strip("/").split("/")
                name = segs[5]
                if server.delete_pod(name):
                    self._send_json({"kind": "Status", "status": "Success"})
                else:
                    self._send_json(
                        {"kind": "Status", "code": 404, "reason": "NotFound"},
                        404,
                    )

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-k8s-api", daemon=True
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FakeK8sApiServer":
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def host(self) -> str:
        return "http://127.0.0.1:%d" % self._httpd.server_address[1]

    # -- pod store ------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _add_watcher(self, q: queue.Queue):
        with self._lock:
            self._watchers.append(q)

    def _remove_watcher(self, q: queue.Queue):
        with self._lock:
            if q in self._watchers:
                self._watchers.remove(q)

    def _broadcast_locked(self, etype: str, pod: dict):
        event = {"type": etype, "object": json.loads(json.dumps(pod))}
        self._event_log.append((self._rv, event))
        del self._event_log[: -self.event_log_cap]
        for q in self._watchers:
            q.put(event)

    def _resume_watcher(self, rv: int, q: queue.Queue):
        """Atomically replay history after `rv` into `q` and register it
        for live events; None when the log no longer reaches back to `rv`
        (real 410 Gone semantics)."""
        with self._lock:
            if self._event_log and rv < self._event_log[0][0] - 1:
                return None
            for r, event in self._event_log:
                if r > rv:
                    q.put(event)
            self._watchers.append(q)
            return True

    def create_pod(self, manifest: dict) -> dict:
        with self._lock:
            pod = json.loads(json.dumps(manifest))
            name = pod["metadata"]["name"]
            self._uid += 1
            pod["metadata"].setdefault("uid", f"uid-{self._uid}")
            pod["metadata"]["resourceVersion"] = self._next_rv()
            pod["status"] = {"phase": "Pending"}
            self._pods[name] = pod
            self.create_log.append(name)
            self._broadcast_locked("ADDED", pod)
            if self.auto_run and self.schedulable:
                self._set_phase_locked(name, "Running")
            return json.loads(json.dumps(pod))

    def get_pod(self, name: str) -> Optional[dict]:
        with self._lock:
            pod = self._pods.get(name)
            return json.loads(json.dumps(pod)) if pod else None

    def list_pods(self, selector: str = "") -> List[dict]:
        with self._lock:
            return [
                json.loads(json.dumps(p))
                for p in self._pods.values()
                if not selector
                or _match_selector(p["metadata"].get("labels", {}), selector)
            ]

    def delete_pod(self, name: str) -> bool:
        with self._lock:
            pod = self._pods.pop(name, None)
            if pod is None:
                return False
            pod["metadata"]["resourceVersion"] = self._next_rv()
            self._broadcast_locked("DELETED", pod)
            return True

    # -- test controls --------------------------------------------------

    def _set_phase_locked(
        self, name: str, phase: str, exit_code: Optional[int] = None
    ):
        pod = self._pods[name]
        pod["status"]["phase"] = phase
        if phase == "Running":
            pod["status"]["podIP"] = "10.0.0.%d" % (self._uid % 250 + 1)
        if exit_code is not None:
            pod["status"]["containerStatuses"] = [
                {"state": {"terminated": {"exitCode": exit_code}}}
            ]
        pod["metadata"]["resourceVersion"] = self._next_rv()
        self._broadcast_locked("MODIFIED", pod)

    def set_running(self, name: str):
        with self._lock:
            self._set_phase_locked(name, "Running")

    def fail_pod(self, name: str, exit_code: int = 1):
        with self._lock:
            self._set_phase_locked(name, "Failed", exit_code)

    def succeed_pod(self, name: str):
        with self._lock:
            self._set_phase_locked(name, "Succeeded", 0)

    def succeed_all(self):
        with self._lock:
            for name, pod in list(self._pods.items()):
                if pod["status"]["phase"] in ("Pending", "Running"):
                    self._set_phase_locked(name, "Succeeded", 0)

    def pod_names(self) -> List[str]:
        with self._lock:
            return sorted(self._pods)
