"""Preprocessing-layer tests.

Mirrors the reference's elasticdl_preprocessing/tests layer-by-layer
golden tests, plus the properties the TPU split adds: device transforms
must be bit-identical between host numpy and jitted jnp execution, and
the census model must train end-to-end from RAW strings/floats through
the full transform stack.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.preprocessing import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
    to_padded_ids,
)


class TestHashing:
    def test_string_hash_is_stable_and_in_range(self):
        layer = Hashing(num_bins=16)
        x = np.asarray(["cat", "dog", "cat", ""], object)
        out = layer(x)
        assert out.dtype == np.int32
        assert out[0] == out[2]  # deterministic
        assert ((out >= 0) & (out < 16)).all()
        # Stable across instances AND processes (md5-based, not builtin
        # hash() which is salted per interpreter).
        np.testing.assert_array_equal(out, Hashing(num_bins=16)(x))

    def test_salt_changes_mapping(self):
        x = np.asarray([f"tok{i}" for i in range(64)], object)
        a, b = Hashing(num_bins=64)(x), Hashing(num_bins=64, salt=1)(x)
        assert (a != b).any()

    def test_int_hash_host_equals_device(self):
        layer = Hashing(num_bins=101)
        ids = np.arange(0, 5000, 7, dtype=np.int32)
        host = layer(ids)
        device = np.asarray(jax.jit(layer)(jnp.asarray(ids)))
        np.testing.assert_array_equal(host, device)
        assert ((host >= 0) & (host < 101)).all()

    def test_rejects_bad_bins(self):
        with pytest.raises(ValueError):
            Hashing(0)


class TestIndexLookup:
    def test_vocab_and_oov(self):
        layer = IndexLookup(["a", "b", "c"], num_oov_indices=1)
        out = layer(np.asarray([["a", "zzz"], ["c", "b"]], object))
        np.testing.assert_array_equal(out, [[1, 0], [3, 2]])
        assert layer.vocab_size == 4

    def test_multi_oov_stable_and_in_range(self):
        layer = IndexLookup(["a"], num_oov_indices=4)
        unknowns = np.asarray([f"u{i}" for i in range(32)], object)
        out = layer(unknowns)
        assert ((out >= 0) & (out < 4)).all()
        np.testing.assert_array_equal(out, layer(unknowns))
        assert layer(np.asarray(["a"]))[0] == 4

    def test_no_oov_raises(self):
        layer = IndexLookup(["a"], num_oov_indices=0)
        with pytest.raises(KeyError):
            layer(np.asarray(["b"]))


class TestDiscretization:
    def test_golden(self):
        layer = Discretization([0.0, 1.0, 10.0])
        out = layer(np.asarray([-5.0, 0.0, 0.5, 1.0, 3.0, 99.0]))
        np.testing.assert_array_equal(out, [0, 1, 1, 2, 2, 3])
        assert layer.num_bins == 4

    def test_host_equals_device(self):
        layer = Discretization([-1.0, 0.0, 2.5])
        x = np.linspace(-3, 3, 31).astype(np.float32)
        np.testing.assert_array_equal(
            layer(x), np.asarray(jax.jit(layer)(jnp.asarray(x)))
        )

    def test_unsorted_raises(self):
        with pytest.raises(ValueError):
            Discretization([1.0, 0.0])


class TestNormalizer:
    def test_golden(self):
        layer = Normalizer(subtract=10.0, divide=2.0)
        np.testing.assert_allclose(
            layer(np.asarray([10.0, 14.0])), [0.0, 2.0]
        )

    def test_from_stats_and_zero_div(self):
        layer = Normalizer.from_stats(mean=5.0, std=0.0)
        np.testing.assert_allclose(layer(np.asarray([6.0])), [1.0])
        with pytest.raises(ValueError):
            Normalizer(divide=0.0)

    def test_host_equals_device(self):
        layer = Normalizer(3.0, 7.0)
        x = np.linspace(-5, 5, 17).astype(np.float32)
        # allclose, not bit-equal: XLA strength-reduces the division to a
        # reciprocal multiply (1-ulp difference); the integer-producing
        # transforms (Hashing/Discretization/RoundIdentity) stay exact.
        np.testing.assert_allclose(
            layer(x),
            np.asarray(jax.jit(layer)(jnp.asarray(x))),
            rtol=1e-6,
        )


class TestRoundIdentity:
    def test_golden_and_clip(self):
        layer = RoundIdentity(max_value=10)
        out = layer(np.asarray([0.4, 0.6, 9.7, 50.0, -3.0]))
        np.testing.assert_array_equal(out, [0, 1, 10 - 1, 9, 0])

    def test_host_equals_device(self):
        layer = RoundIdentity(100)
        x = np.linspace(-10, 150, 41).astype(np.float32)
        np.testing.assert_array_equal(
            layer(x), np.asarray(jax.jit(layer)(jnp.asarray(x)))
        )


class TestConcatenateWithOffset:
    def test_offsets_disjoint_id_spaces(self):
        layer = ConcatenateWithOffset([4, 8, 2])
        out = layer(
            [
                np.asarray([0, 3], np.int32),
                np.asarray([0, 7], np.int32),
                np.asarray([1, 0], np.int32),
            ]
        )
        np.testing.assert_array_equal(out, [[0, 4, 13], [3, 11, 12]])
        assert layer.total_id_space == 14

    def test_padding_ids_stay_negative(self):
        layer = ConcatenateWithOffset([4, 4])
        out = layer(
            [np.asarray([[-1, 2]], np.int32), np.asarray([[1, -1]], np.int32)]
        )
        np.testing.assert_array_equal(out, [[-1, 2, 5, -1]])

    def test_column_count_mismatch(self):
        with pytest.raises(ValueError):
            ConcatenateWithOffset([4])([np.zeros(1), np.zeros(1)])

    def test_host_equals_device(self):
        layer = ConcatenateWithOffset([16, 16])
        cols = [
            np.arange(8, dtype=np.int32),
            np.arange(8, dtype=np.int32)[::-1].copy(),
        ]
        host = layer(cols)
        device = np.asarray(
            jax.jit(lambda a, b: layer([a, b]))(*map(jnp.asarray, cols))
        )
        np.testing.assert_array_equal(host, device)


def test_to_padded_ids():
    out = to_padded_ids([[1, 2, 3], [], [7, 8, 9, 10]], max_len=3)
    np.testing.assert_array_equal(
        out, [[1, 2, 3], [-1, -1, -1], [7, 8, 9]]
    )
    assert out.dtype == np.int32


# ---------------------------------------------------------------------------
# Census model: raw strings/floats through the whole stack.
# ---------------------------------------------------------------------------


def _census_batches(n=64, mb=16, seed=0):
    from elasticdl_tpu.data.dataset import Dataset, _stack
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from model_zoo import datasets
    from model_zoo.census import census_wide_deep as zoo

    reader = datasets.synthetic_census_reader(n=n, seed=seed)
    task = pb.Task(task_id=1, shard_name="s", start=0, end=n)
    records = list(
        zoo.dataset_fn(
            Dataset.from_generator(lambda: reader.read_records(task)),
            "training",
            None,
        )
    )
    for i in range(0, n, mb):
        yield _stack(records[i : i + mb])


def test_census_model_trains_from_raw_features():
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.census import census_wide_deep as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    losses = []
    for epoch in range(8):
        for feats, labels in _census_batches(n=64, mb=16, seed=epoch % 2):
            losses.append(float(trainer.train_step(feats, labels)))
    assert losses[-1] < losses[0] * 0.9, f"no learning: {losses[:2]} -> {losses[-2:]}"
    feats, labels = next(_census_batches(n=16, mb=16, seed=9))
    out = trainer.eval_step(feats)
    metrics = {
        name: fn(np.asarray(out), labels)
        for name, fn in zoo.eval_metrics_fn().items()
    }
    assert 0.0 <= metrics["auc"] <= 1.0


def test_census_train_serve_consistency():
    """The host transforms used by dataset_fn are the same objects a
    serving caller uses: one raw record preprocessed both ways yields
    identical features."""
    from model_zoo import datasets
    from model_zoo.census import census_wide_deep as zoo

    reader = datasets.synthetic_census_reader(n=4, seed=3)
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task = pb.Task(task_id=1, shard_name="s", start=0, end=4)
    for raw, _label in reader.read_records(task):
        once = zoo.preprocess_record(raw)
        twice = zoo.preprocess_record(dict(raw))
        for key in once:
            np.testing.assert_array_equal(once[key], twice[key])
        assert once["edu_id"] >= 0 and once["occ_id"] < 64
