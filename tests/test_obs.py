"""Observability plane tests (elasticdl_tpu/obs).

Covers the tentpole's acceptance surface:

- registry semantics (counter/gauge/histogram, labels, get-or-create)
  and Prometheus text exposition;
- registry concurrency under ``ELASTICDL_LOCKCHECK=1`` (hammered from
  threads, exact totals, clean lock-order report);
- exporter endpoint round-trip (/metrics + /healthz + /debug/vars over
  real HTTP, parsed, instrumented values asserted);
- journal rotation at the size cap;
- the master-side end-to-end: an in-process master (task manager +
  rendezvous + gRPC servicer + retrying client + checkpoint savers +
  crashing local worker fleet) scraped over /metrics contains the task
  latency histograms, rendezvous epoch/world-size, pod relaunch
  counters, RPC retry counters, and checkpoint duration metrics the
  ISSUE acceptance criteria name;
- the RetryStats periodic-summary satellite and the StepProfiler
  shutdown-flush satellite.
"""

import json
import logging
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.obs.exporter import MetricsExporter
from elasticdl_tpu.obs.journal import EventJournal
from elasticdl_tpu.obs.metrics import MetricsRegistry, RateTracker


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


def test_counter_labels_values_and_monotonicity():
    registry = MetricsRegistry()
    counter = registry.counter("c_total", "help", labelnames=("kind",))
    counter.inc(kind="a")
    counter.inc(2.5, kind="a")
    counter.inc(kind="b")
    assert counter.value(kind="a") == 3.5
    assert counter.value(kind="b") == 1
    with pytest.raises(ValueError):
        counter.inc(-1, kind="a")
    with pytest.raises(ValueError):
        counter.inc(kind="a", extra="nope")
    with pytest.raises(ValueError):
        counter.inc()  # missing the declared label


def test_gauge_set_inc_and_function_callbacks():
    registry = MetricsRegistry()
    gauge = registry.gauge("g", "help")
    gauge.set(5)
    gauge.inc(2)
    gauge.dec(3)
    assert gauge.value() == 4
    fn_gauge = registry.gauge("g_fn", "help")
    box = {"v": 7}
    fn_gauge.set_function(lambda: box["v"])
    assert fn_gauge.value() == 7
    box["v"] = 9
    assert fn_gauge.value() == 9
    # A dying callback never breaks the scrape; its sample is dropped.
    fn_gauge.set_function(lambda: 1 / 0)
    lines = registry.render_prometheus().splitlines()
    assert not any(line.startswith("g_fn ") for line in lines)
    assert any(line.startswith("g ") for line in lines)


def test_histogram_buckets_sum_count_and_exposition():
    registry = MetricsRegistry()
    hist = registry.histogram(
        "h_seconds", "help", labelnames=("op",), buckets=(0.1, 1.0, 10.0)
    )
    for value in (0.05, 0.5, 5.0, 50.0):
        hist.observe(value, op="x")
    assert hist.count(op="x") == 4
    assert hist.sum(op="x") == pytest.approx(55.55)
    text = registry.render_prometheus()
    assert '# TYPE h_seconds histogram' in text
    assert 'h_seconds_bucket{le="0.1",op="x"} 1' in text
    assert 'h_seconds_bucket{le="1",op="x"} 2' in text
    assert 'h_seconds_bucket{le="10",op="x"} 3' in text
    assert 'h_seconds_bucket{le="+Inf",op="x"} 4' in text
    assert 'h_seconds_count{op="x"} 4' in text


def test_registry_get_or_create_and_type_conflicts():
    registry = MetricsRegistry()
    first = registry.counter("same_total", "h", labelnames=("a",))
    assert registry.counter("same_total", "h", labelnames=("a",)) is first
    with pytest.raises(ValueError):
        registry.gauge("same_total", "h")  # wrong type
    with pytest.raises(ValueError):
        registry.counter("same_total", "h", labelnames=("b",))  # wrong labels


def test_exposition_escapes_label_values():
    registry = MetricsRegistry()
    counter = registry.counter("esc_total", "h", labelnames=("v",))
    counter.inc(v='say "hi"\nback\\slash')
    line = [
        ln for ln in registry.render_prometheus().splitlines()
        if ln.startswith("esc_total{")
    ][0]
    assert '\\"hi\\"' in line and "\\n" in line and "\\\\slash" in line


def test_unlabeled_counter_exports_at_zero():
    registry = MetricsRegistry()
    registry.counter("zero_total", "present before the first event")
    assert "\nzero_total 0" in registry.render_prometheus()


def test_rate_tracker_window():
    tracker = RateTracker(window_s=10.0)
    assert tracker.rate(now=0.0) == 0.0
    tracker.add(50, now=1.0)
    tracker.add(50, now=5.0)
    assert tracker.rate(now=5.0) == pytest.approx(10.0)
    # Events age out of the window.
    assert tracker.rate(now=100.0) == 0.0


# ---------------------------------------------------------------------------
# Concurrency under the runtime lock checker
# ---------------------------------------------------------------------------


def test_registry_concurrency_under_lockcheck(monkeypatch):
    """Hammer counters/gauges/histograms (and concurrent scrapes) from
    threads with ELASTICDL_LOCKCHECK=1: exact totals, no lost updates, and
    a clean lock-order report."""
    monkeypatch.setenv("ELASTICDL_LOCKCHECK", "1")
    from elasticdl_tpu.analysis import runtime

    runtime.reset()
    try:
        registry = MetricsRegistry()  # locks created under lockcheck
        counter = registry.counter("hammer_total", "h", labelnames=("t",))
        hist = registry.histogram("hammer_seconds", "h")
        gauge = registry.gauge("hammer_gauge", "h")
        gauge.set_function(lambda: counter.value(t="0"))
        iterations, n_threads = 400, 8

        def hammer(thread_index):
            for k in range(iterations):
                counter.inc(t=str(thread_index % 2))
                hist.observe(0.001 * (k % 7))
                if k % 100 == 0:
                    registry.render_prometheus()  # concurrent scrapes

        threads = [
            threading.Thread(target=hammer, args=(i,),
                             name=f"obs-hammer-{i}", daemon=True)
            for i in range(n_threads)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=60)
            assert not thread.is_alive()
        assert (
            counter.value(t="0") + counter.value(t="1")
            == iterations * n_threads
        )
        assert hist.count() == iterations * n_threads
        report = runtime.report()
        assert report["acquisitions"] > 0, "lockcheck never engaged"
        runtime.assert_clean()
    finally:
        runtime.reset()


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------


def test_journal_records_and_tail(tmp_path):
    journal = EventJournal(str(tmp_path / "j.jsonl"))
    journal.record("alpha", x=1)
    journal.record("beta", pod="w-3")
    with open(tmp_path / "j.jsonl") as f:
        events = [json.loads(line) for line in f]
    assert [e["event"] for e in events] == ["alpha", "beta"]
    assert all("ts" in e for e in events)
    assert [e["event"] for e in journal.tail(1)] == ["beta"]
    journal.close()


def test_journal_rotation_at_size_cap(tmp_path):
    path = tmp_path / "events.jsonl"
    journal = EventJournal(str(path), max_bytes=2000)
    for i in range(100):
        journal.record("evt", i=i, pad="x" * 40)
    journal.close()
    rotated = tmp_path / "events.jsonl.1"
    assert rotated.exists(), "size cap never rotated"
    assert os.path.getsize(path) <= 2000
    assert os.path.getsize(rotated) <= 2000
    # Both files hold valid JSONL and the newest events are in the
    # primary file.
    primary = [json.loads(line) for line in open(path)]
    old = [json.loads(line) for line in open(rotated)]
    assert primary and old
    assert primary[-1]["i"] == 99
    assert old[-1]["i"] < primary[0]["i"]
    # The in-memory tail survives rotation untruncated.
    assert journal.tail(5)[-1]["i"] == 99


def test_journal_tail_spans_rotation(tmp_path):
    """Satellite: a tail larger than the in-memory ring reads the files,
    and when the active file holds fewer than `n` lines (right after a
    rotation) the rotated file's tail fills the rest — no gap."""
    journal = EventJournal(
        str(tmp_path / "events.jsonl"), max_bytes=3000, tail_events=8
    )
    for i in range(200):
        journal.record("evt", i=i, pad="x" * 40)
    assert (tmp_path / "events.jsonl.1").exists(), "cap never rotated"
    # Contiguous across the rotation boundary: the active file alone
    # holds far fewer than 100 lines at max_bytes=3000, so a correct
    # tail must continue into the rotated file without a gap.
    with open(tmp_path / "events.jsonl") as f:
        active_lines = sum(1 for _ in f)
    assert active_lines < 100
    seq = [e["i"] for e in journal.tail(100)]
    assert seq[-1] == 199
    assert seq == list(range(seq[0], 200)), "gap across rotation boundary"
    assert len(seq) > active_lines, "tail never read the rotated file"
    # Small n still serves from the ring.
    assert [e["i"] for e in journal.tail(3)] == [197, 198, 199]
    journal.close()


def test_journal_tail_consistent_during_forced_rotation(tmp_path):
    """Rotation forced mid-tail: a writer hammers records (rotating
    every ~40 lines) while a reader tails across the boundary — every
    tail observes a contiguous, gap-free suffix."""
    journal = EventJournal(
        str(tmp_path / "events.jsonl"), max_bytes=2500, tail_events=4
    )
    stop = threading.Event()
    failures = []

    def writer():
        for i in range(1500):
            journal.record("evt", i=i, pad="y" * 40)
        stop.set()

    thread = threading.Thread(target=writer, name="journal-hammer", daemon=True)
    thread.start()
    while not stop.is_set():
        tail = [e["i"] for e in journal.tail(30)]
        if tail != list(range(tail[0], tail[0] + len(tail))):
            failures.append(tail)
            break
    thread.join(timeout=30)
    assert not thread.is_alive()
    assert not failures, f"non-contiguous tail during rotation: {failures[0]}"
    journal.close()


def test_journal_memory_only_without_configuration():
    journal = EventJournal()
    journal.record("only_in_memory")
    assert journal.path is None
    assert journal.tail(1)[0]["event"] == "only_in_memory"


def test_span_emits_histogram_and_journal_record():
    hist_before = obs.histogram(
        "elasticdl_span_obs_test_span_seconds", "Duration of obs.test.span spans"
    ).count()
    with obs.span("obs.test.span", task_id=42):
        pass
    hist = obs.registry().get("elasticdl_span_obs_test_span_seconds")
    assert hist.count() == hist_before + 1
    spans = [e for e in obs.journal().tail(20) if e["event"] == "span"]
    assert spans and spans[-1]["name"] == "obs.test.span"
    assert spans[-1]["task_id"] == 42
    assert spans[-1]["duration_s"] >= 0


def test_span_records_error_type():
    with pytest.raises(RuntimeError):
        with obs.span("obs.test.failing"):
            raise RuntimeError("boom")
    spans = [e for e in obs.journal().tail(20) if e["event"] == "span"]
    assert spans[-1]["error"] == "RuntimeError"


# ---------------------------------------------------------------------------
# Exporter round-trip
# ---------------------------------------------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as response:
        return response.status, response.read().decode()


def _head(url, timeout=10):
    request = urllib.request.Request(url, method="HEAD")
    with urllib.request.urlopen(request, timeout=timeout) as response:
        return response.status, response.headers, response.read()


def test_exporter_journal_endpoint_and_head(tmp_path):
    """Satellite: /journal serves the bounded event tail as JSON with no
    file-path leakage, and every endpoint answers HEAD without a body."""
    registry = MetricsRegistry()
    registry.counter("head_demo_total", "help").inc()
    journal = EventJournal(str(tmp_path / "events.jsonl"))
    for i in range(10):
        journal.record("evt", i=i)
    exporter = MetricsExporter(
        registry=registry, journal=journal, port=0
    ).start()
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        status, body = _get(base + "/journal")
        assert status == 200
        payload = json.loads(body)
        assert payload["count"] == 10
        assert [e["i"] for e in payload["events"]] == list(range(10))
        # No journal file path anywhere in the response (the endpoint may
        # be exposed beyond the master host).
        assert "events.jsonl" not in body
        # ?n= bounds the tail; nonsense values fall back to the default.
        status, body = _get(base + "/journal?n=3")
        assert [e["i"] for e in json.loads(body)["events"]] == [7, 8, 9]
        status, body = _get(base + "/journal?n=bogus")
        assert json.loads(body)["count"] == 10
        # HEAD: headers (incl. a real Content-Length) but no body.
        for path in ("/metrics", "/healthz", "/journal", "/debug/vars"):
            status, headers, head_body = _head(base + path)
            assert status == 200, path
            assert head_body == b"", path
            assert int(headers["Content-Length"]) > 0, path
        with pytest.raises(urllib.error.HTTPError) as err:
            _head(base + "/nope")
        assert err.value.code == 404
    finally:
        exporter.stop()
        journal.close()


def test_exporter_roundtrip_metrics_healthz_debug_vars(tmp_path):
    registry = MetricsRegistry()
    journal = EventJournal(str(tmp_path / "events.jsonl"))
    registry.counter("demo_total", "help").inc(3)
    registry.histogram(
        "demo_seconds", "help", labelnames=("op",)
    ).observe(0.12, op="save")
    journal.record("hello", worker_id=1)
    exporter = MetricsExporter(
        registry=registry, journal=journal, port=0
    ).start()
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        status, text = _get(base + "/metrics")
        assert status == 200
        assert "\ndemo_total 3" in text
        assert 'demo_seconds_bucket{le="+Inf",op="save"} 1' in text
        status, body = _get(base + "/healthz")
        health = json.loads(body)
        assert health["status"] == "ok" and health["uptime_s"] >= 0
        status, body = _get(base + "/debug/vars")
        debug = json.loads(body)
        assert debug["metrics"]["demo_total"]["values"][""] == 3
        assert debug["metrics"]["demo_seconds"]["type"] == "histogram"
        assert debug["journal"]["path"].endswith("events.jsonl")
        assert debug["journal"]["tail"][-1]["event"] == "hello"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/nope")
        assert err.value.code == 404
    finally:
        exporter.stop()
        journal.close()


# ---------------------------------------------------------------------------
# RetryStats satellites: registry fold-in + rate-limited summary
# ---------------------------------------------------------------------------


def _capture_logs(logger_name, records):
    handler = logging.Handler()
    handler.emit = records.append
    logging.getLogger(logger_name).addHandler(handler)
    return handler


def test_retry_stats_feed_the_registry():
    from elasticdl_tpu.common.grpc_utils import RetryStats

    retries = obs.registry().get("elasticdl_rpc_retries_total")
    give_ups = obs.registry().get("elasticdl_rpc_give_ups_total")
    before_r = retries.value(method="get_task")
    before_g = give_ups.value(method="get_task")
    stats = RetryStats()
    stats.record_call()
    for _ in range(3):
        stats.record_retry("get_task")
    stats.record_give_up("get_task", "UNAVAILABLE")
    assert retries.value(method="get_task") == before_r + 3
    assert give_ups.value(method="get_task") == before_g + 1
    assert stats.retries == 3 and stats.give_ups == 1  # per-client view


def test_retry_summary_is_rate_limited():
    from elasticdl_tpu.common.grpc_utils import RetryStats

    stats = RetryStats()
    records = []
    handler = _capture_logs("elasticdl_tpu.common.grpc_utils", records)
    try:
        stats.record_retry("get_task")
        stats.maybe_log_summary(now=0.0)  # opens the window, no line
        stats.record_retry("get_task")
        stats.record_retry("report_version")
        stats.maybe_log_summary(now=100.0)  # inside the window: silent
        assert records == []
        stats.maybe_log_summary(now=301.0)  # window elapsed: one line
        summaries = [
            r.getMessage() for r in records
            if "RPC retry summary" in r.getMessage()
        ]
        assert len(summaries) == 1
        assert "2 retries" in summaries[0]
        assert "get_task=1" in summaries[0]
        assert "report_version=1" in summaries[0]
        # Quiet window: no traffic, no line.
        stats.maybe_log_summary(now=1000.0)
        summaries = [
            r.getMessage() for r in records
            if "RPC retry summary" in r.getMessage()
        ]
        assert len(summaries) == 1
    finally:
        logging.getLogger("elasticdl_tpu.common.grpc_utils").removeHandler(
            handler
        )


# ---------------------------------------------------------------------------
# StepProfiler satellite: shutdown flush is registered
# ---------------------------------------------------------------------------


def test_step_profiler_registers_atexit_flush(monkeypatch):
    import atexit

    from elasticdl_tpu.common import profiler

    registered = []
    monkeypatch.setattr(
        atexit, "register", lambda fn, *a, **k: registered.append(fn) or fn
    )
    inactive = profiler.StepProfiler("", "", worker_id=0)
    assert registered == []  # unconfigured profiler: no hook
    active = profiler.StepProfiler("/tmp/logs", "5,10", worker_id=0)
    assert registered == [active.stop]
    assert inactive is not active


def test_worker_main_converts_sigterm_to_systemexit():
    from elasticdl_tpu.worker.main import _sigterm_to_systemexit

    with pytest.raises(SystemExit) as excinfo:
        _sigterm_to_systemexit(15, None)
    assert excinfo.value.code == 143


# ---------------------------------------------------------------------------
# Master end-to-end: one scrape shows the whole elastic control plane
# ---------------------------------------------------------------------------


def test_master_metrics_exporter_end_to_end(tmp_path):
    """The ISSUE acceptance scrape: a master serving real traffic exports
    task-latency histograms, rendezvous epoch/world-size, pod relaunch
    counters, RPC retry counters, and checkpoint duration metrics."""
    from elasticdl_tpu.checkpoint.saver import CheckpointSaver
    from elasticdl_tpu.common import faults
    from elasticdl_tpu.common.constants import TaskExecCounterKey
    from elasticdl_tpu.common.grpc_utils import RetryPolicy
    from elasticdl_tpu.master.pod_manager import LocalProcessManager
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.servicer import (
        MasterServicer,
        start_master_server,
    )
    from elasticdl_tpu.master.task_manager import (
        TaskManager,
        TaskProgressPersister,
    )
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.worker.master_client import MasterClient

    # The default registry accumulates across the whole pytest session
    # (instrumented services run in many tests), so correctness asserts
    # are DELTAS against these baselines; the scrape asserts presence.
    task_manager = TaskManager(
        training_shards={"shard": 128}, records_per_task=64
    )
    m_task_duration = obs.histogram(
        "elasticdl_task_duration_seconds", labelnames=("type",)
    )
    m_formation = obs.histogram(
        "elasticdl_rendezvous_formation_duration_seconds"
    )
    m_retries = obs.counter(
        "elasticdl_rpc_retries_total", labelnames=("method",)
    )
    m_relaunches = obs.counter(
        "elasticdl_worker_relaunches_total", labelnames=("reason",)
    )
    m_saves = obs.histogram(
        "elasticdl_checkpoint_save_duration_seconds", labelnames=("kind",)
    )
    m_restores = obs.histogram(
        "elasticdl_checkpoint_restore_duration_seconds", labelnames=("kind",)
    )
    base_train_done = m_task_duration.count(type="TRAINING")
    base_formations = m_formation.count()
    base_retries = m_retries.value(method="get_task")
    base_crashes = m_relaunches.value(reason="crash")
    base_full_saves = m_saves.count(kind="full")
    base_progress_saves = m_saves.count(kind="task_progress")
    base_restores = m_restores.count(kind="full")
    rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 23456)
    rendezvous.set_worker_hosts([(0, "127.0.0.1")])
    servicer = MasterServicer(
        task_manager=task_manager, rendezvous_server=rendezvous
    )
    server, port = start_master_server(servicer, port=0)
    client = MasterClient(
        f"localhost:{port}",
        worker_id=0,
        retry_policy=RetryPolicy(
            timeout_s=5.0, max_attempts=5, base_backoff_s=0.01,
            max_backoff_s=0.05, jitter=0.0, total_budget_s=30.0,
            wait_for_ready=True,
        ),
    )
    exporter = MetricsExporter(port=0).start()  # the default registry
    try:
        # RPC retry plane: the first get_task attempt fails transiently.
        faults.install("rpc.get_task:error=UNAVAILABLE@1")
        assert client.get_comm_rank().rank_id == 0  # rendezvous formation
        while True:
            task = client.get_task()
            if task.task_id == -1 and task.type != pb.WAIT:
                break
            if task.type == pb.WAIT:
                time.sleep(0.05)
                continue
            client.report_task_result(
                task.task_id,
                "",
                exec_counters={
                    TaskExecCounterKey.BATCH_COUNT: 4,
                    TaskExecCounterKey.RECORD_COUNT: task.end - task.start,
                },
            )
        assert client.retry_stats.retries >= 1
        faults.clear()

        # Checkpoint plane: a real save/restore plus the master's
        # shard-progress persister.
        saver = CheckpointSaver(str(tmp_path / "ckpt"), keep_max=2)
        saver.save({"w": [1.0, 2.0]}, step=1)
        state, step = saver.load_latest()
        assert (step, state) == (1, {"w": [1.0, 2.0]})
        persister = TaskProgressPersister(task_manager, str(tmp_path / "ckpt"))
        persister.persist_now()

        # Pod plane: a worker that crashes once and is relaunched.
        flaky = tmp_path / "flaky_worker.py"
        flaky.write_text(
            "import os, sys\n"
            "sentinel = sys.argv[1]\n"
            "if os.path.exists(sentinel):\n"
            "    sys.exit(0)\n"
            "open(sentinel, 'w').close()\n"
            "sys.exit(1)\n"
        )
        manager = LocalProcessManager(
            num_workers=1,
            worker_argv_fn=lambda wid: [
                sys.executable, str(flaky), str(tmp_path / "sentinel"),
            ],
            max_restarts=2,
            poll_interval_s=0.05,
        )
        manager.start()
        assert manager.wait(timeout=120) is True
        manager.stop()

        # --- correctness: exact deltas on the registry ------------------
        assert m_task_duration.count(type="TRAINING") == base_train_done + 2
        assert m_formation.count() == base_formations + 1
        assert m_retries.value(method="get_task") >= base_retries + 1
        assert m_relaunches.value(reason="crash") >= base_crashes + 1
        assert m_saves.count(kind="full") == base_full_saves + 1
        assert m_saves.count(kind="task_progress") == base_progress_saves + 1
        assert m_restores.count(kind="full") == base_restores + 1

        # --- the acceptance scrape: every family exposed over HTTP ------
        status, text = _get(f"http://127.0.0.1:{exporter.port}/metrics")
        assert status == 200
        # Task-latency histogram with real observations.
        assert '# TYPE elasticdl_task_duration_seconds histogram' in text
        assert 'elasticdl_task_duration_seconds_count{type="TRAINING"} ' in text
        # Rendezvous epoch counter + world-size gauge.
        assert "\nelasticdl_rendezvous_epochs_total " in text
        assert "\nelasticdl_world_size 1" in text
        assert (
            "\nelasticdl_rendezvous_formation_duration_seconds_count " in text
        )
        # Pod relaunch counter (the crash was counted by cause).
        assert 'elasticdl_worker_relaunches_total{reason="crash"} ' in text
        # RPC retry counters (folded RetryStats).
        assert 'elasticdl_rpc_retries_total{method="get_task"} ' in text
        # Checkpoint duration metrics, both kinds.
        assert (
            'elasticdl_checkpoint_save_duration_seconds_count{kind="full"} '
            in text
        )
        assert (
            'elasticdl_checkpoint_save_duration_seconds_count'
            '{kind="task_progress"} ' in text
        )
        assert (
            'elasticdl_checkpoint_restore_duration_seconds_count'
            '{kind="full"} ' in text
        )
        # Job throughput gauges derived from worker exec counters.
        assert "\nelasticdl_job_examples_per_second " in text
        assert "\nelasticdl_job_steps_per_second " in text
        # Dispatch/completion counters moved through the whole job.
        assert "\nelasticdl_tasks_dispatched_total " in text

        # /debug/vars carries the same metrics as JSON.
        status, body = _get(f"http://127.0.0.1:{exporter.port}/debug/vars")
        debug = json.loads(body)
        assert "elasticdl_task_duration_seconds" in debug["metrics"]
    finally:
        faults.clear()
        exporter.stop()
        client.close()
        server.stop(grace=None)
