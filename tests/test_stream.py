"""Continuous-training stream tests (docs/design.md "Continuous
training"): the deterministic stream source's schedule math, the
streaming task dispatcher's watermark-based eviction, and the two
crash-safe resume paths (progress snapshot, journal replay)."""

import json

import numpy as np
import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.common import faults
from elasticdl_tpu.data.stream import (
    SyntheticClickStream,
    iter_stream_batches,
    synthetic_click_batch,
)
from elasticdl_tpu.master.stream import StreamingTaskManager
from elasticdl_tpu.proto import elasticdl_pb2 as pb


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# SyntheticClickStream: schedule math on a driver-owned virtual clock
# ---------------------------------------------------------------------------


def test_stream_schedule_integration_and_spike():
    stream = SyntheticClickStream([(4.0, 100), (2.0, 400)], name="clicks")
    assert stream.available() == 0
    stream.advance(2.0)
    assert stream.available() == 200
    stream.advance(2.0)  # end of phase 1
    assert stream.available() == 400
    # Rate spike: the second phase produces 4x per second, and the LAST
    # phase's rate continues forever (a stream has no end).
    stream.advance(2.0)
    assert stream.available() == 400 + 800
    stream.advance(3.0)
    assert stream.available() == 400 + 800 + 1200


def test_stream_event_time_inverts_schedule():
    stream = SyntheticClickStream([(4.0, 100), (2.0, 400)])
    assert stream.event_time(0) == 0.0
    assert stream.event_time(200) == pytest.approx(2.0)
    assert stream.event_time(400) == pytest.approx(4.0)
    # Into the spike phase: 800 records past the boundary at 400/s.
    assert stream.event_time(400 + 800) == pytest.approx(6.0)
    # records_until / event_time are inverses on phase-interior points,
    # up to the floor at the integer record count (float division may
    # land an ulp under the exact boundary).
    for offset in (1, 57, 399, 401, 999):
        assert stream.records_until(stream.event_time(offset)) in (
            offset - 1, offset,
        )


def test_stream_stall_shifts_availability_not_event_time():
    stream = SyntheticClickStream([(10.0, 100)])
    stream.advance(4.0)
    before = stream.available()
    stream.stall(2.0)
    # A wedged pipe delays ARRIVAL: availability rewinds by the stall...
    assert stream.available() == before - 200
    # ...but event times are intrinsic to the records (minted upstream).
    assert stream.event_time(100) == pytest.approx(1.0)
    # Production catches back up once the stall has been ridden out.
    stream.advance(2.0)
    assert stream.available() == before


def test_stream_source_fault_site_stalls_on_call_count():
    faults.install("stream.source:latency=3.0@2")
    stream = SyntheticClickStream([(10.0, 100)])
    stream.advance(1.0)  # call 1: no fault
    assert stream.available() == 100
    stream.advance(1.0)  # call 2: wedged for 3.0 virtual seconds
    assert stream.available() == 0
    stream.advance(4.0)
    assert stream.available() == 300


def test_stream_source_schedule_spec_via_due():
    # The @t form never fires through advance(); a driver polling its own
    # elapsed time applies it (the chaos-e2e discipline).
    faults.install("stream.source:latency=2.0@t1.5")
    stream = SyntheticClickStream([(10.0, 100)])
    stream.advance(1.0)
    assert faults.due("stream.source", stream.elapsed_s) == []
    stream.advance(1.0)
    (spec,) = faults.due("stream.source", stream.elapsed_s)
    stream.stall(float(spec.arg))
    assert stream.available() == 0
    assert faults.remaining_due("stream.source") == 0


def test_stream_json_round_trip():
    stream = SyntheticClickStream([(4.0, 100), (2.0, 400)], name="clicks")
    stream.advance(3.0)
    stream.stall(0.5)
    stream.close()
    clone = SyntheticClickStream.from_json(stream.to_json())
    assert clone.name == "clicks"
    assert clone.closed
    assert clone.available() == stream.available()
    assert clone.event_time(123) == stream.event_time(123)


def test_stream_rejects_bad_schedules():
    with pytest.raises(ValueError):
        SyntheticClickStream([])
    with pytest.raises(ValueError):
        SyntheticClickStream([(4.0, -1)])
    with pytest.raises(ValueError):
        SyntheticClickStream([(4.0, 100), (2.0, 0)])  # endless zero rate
    stream = SyntheticClickStream([(1.0, 10)])
    with pytest.raises(ValueError):
        stream.advance(-0.1)


# ---------------------------------------------------------------------------
# Deterministic record batches: the at-least-once data contract
# ---------------------------------------------------------------------------


def test_synthetic_click_batch_is_offset_pure():
    whole = synthetic_click_batch(0, 100, vocab_size=50)
    part = synthetic_click_batch(40, 60, vocab_size=50)
    for name in whole:
        # A replayed sub-range is bit-identical to its slice of the
        # original: requeued tasks retrain on the SAME records.
        np.testing.assert_array_equal(part[name], whole[name][40:60])
        assert whole[name].dtype == np.int64
        assert whole[name].min() >= 0 and whole[name].max() < 50
    # Distinct fields decorrelate (different stride per field).
    assert not np.array_equal(whole["user"], whole["item"])


def test_iter_stream_batches_windows_and_tail():
    seen = list(
        iter_stream_batches(
            lambda lo, hi: (lo, hi), lo=10, hi=45, batch_size=16
        )
    )
    assert seen == [(10, 26), (26, 42), (42, 45)]


# ---------------------------------------------------------------------------
# StreamingTaskManager: dispatch, watermark eviction, backpressure
# ---------------------------------------------------------------------------


def _manager(stream, rpt=10, lookahead=3, **kw):
    return StreamingTaskManager(
        stream, records_per_task=rpt, lookahead_tasks=lookahead, **kw
    )


def test_streaming_dispatch_and_watermark_eviction(journal_file):
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(10.0)  # 100 records available
    manager = _manager(stream, rpt=10, lookahead=3)

    # Bounded lookahead: at most 3 tasks in existence (todo + doing).
    tasks = [manager.get(worker_id=1) for _ in range(3)]
    assert [(t.start, t.end) for t in tasks] == [(0, 10), (10, 20), (20, 30)]
    assert all(t.shard_name == "clicks" for t in tasks)
    wait = manager.get(worker_id=1)
    assert wait.type == pb.WAIT  # backpressure, never job-complete

    # Out-of-order completion: a hole above the watermark does not
    # advance it; closing the prefix evicts the whole contiguous run.
    assert manager.report(tasks[2].task_id, success=True, worker_id=1)
    assert manager.watermark == 0
    assert manager.report(tasks[0].task_id, success=True, worker_id=1)
    assert manager.watermark == 10
    assert manager.report(tasks[1].task_id, success=True, worker_id=1)
    assert manager.watermark == 30
    assert manager.stream_counts()["pending_ranges"] == 0

    marks = [e for e in _events(journal_file) if e["event"] == "stream_watermark"]
    assert [m["offset"] for m in marks] == [10, 30]
    assert all(m["stream"] == "clicks" for m in marks)
    # Watermark event time rides the schedule inverse.
    assert marks[-1]["event_time"] == pytest.approx(3.0)
    assert manager.watermark_event_time() == pytest.approx(3.0)


def test_streaming_partial_tail_waits_for_close():
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(2.5)  # 25 records: two full tasks + a 5-record tail
    manager = _manager(stream, rpt=10, lookahead=8)
    t1 = manager.get(1)
    t2 = manager.get(1)
    assert (t1.start, t1.end, t2.start, t2.end) == (0, 10, 10, 20)
    # Open stream: the partial tail waits to fill (uniform cuts).
    assert manager.get(1).type == pb.WAIT
    manager.report(t1.task_id, True, worker_id=1)
    manager.report(t2.task_id, True, worker_id=1)
    assert not manager.finished()

    stream.close()
    t3 = manager.get(1)
    assert (t3.start, t3.end) == (20, 25)
    manager.report(t3.task_id, True, worker_id=1)
    assert manager.watermark == 25
    # Drained and closed: the done protocol ran at the final report,
    # so the next poll is job-complete (never before close()).
    done = manager.get(1)
    assert done.task_id == -1 and done.type != pb.WAIT
    assert manager.finished()


def test_streaming_churn_requeue_rides_existing_path(journal_file):
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(4.0)
    manager = _manager(stream, rpt=10, lookahead=4)
    victim = manager.get(worker_id=7)
    survivor = manager.get(worker_id=1)
    assert manager.recover_tasks(worker_id=7) == 1
    assert manager.recovered_record_count == 10

    # The requeued range re-dispatches first (appendleft) and completes;
    # watermark accounting is unaffected by the churn.
    retry = manager.get(worker_id=1)
    assert (retry.start, retry.end) == (victim.start, victim.end)
    manager.report(retry.task_id, True, worker_id=1)
    manager.report(survivor.task_id, True, worker_id=1)
    assert manager.watermark == 20
    requeues = [e for e in _events(journal_file) if e["event"] == "task_requeue"]
    assert requeues and requeues[0]["reason"] == "worker_churn"


def test_streaming_failure_retry_and_watermark(journal_file):
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(2.0)
    manager = _manager(stream, rpt=10, lookahead=2, max_task_retries=2)
    task = manager.get(1)
    assert not manager.watermark
    manager.report(task.task_id, success=False, worker_id=1)
    retry = manager.get(1)
    assert (retry.start, retry.end) == (task.start, task.end)
    manager.report(retry.task_id, success=True, worker_id=1)
    assert manager.watermark == 10


# ---------------------------------------------------------------------------
# Crash-safe resume: progress snapshot and journal replay
# ---------------------------------------------------------------------------


def test_streaming_checkpoint_resume_mid_stream(journal_file):
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(6.0)
    manager = _manager(stream, rpt=10, lookahead=4)
    tasks = [manager.get(worker_id=1) for _ in range(4)]
    # Complete 0 and 2: watermark 10, hole [20, 30) above it; 1 and 3
    # in flight at the "crash".
    manager.report(tasks[0].task_id, True, worker_id=1)
    manager.report(tasks[2].task_id, True, worker_id=1)
    snapshot = manager.to_checkpoint()

    state = json.loads(snapshot)
    assert state["stream"]["watermark"] == 10
    assert state["stream"]["completed"] == [[20, 30]]
    assert state["stream"]["source"]["name"] == "clicks"

    resumed = StreamingTaskManager.from_checkpoint(snapshot)
    assert resumed.watermark == 10
    counts = resumed.stream_counts()
    assert counts["pending_ranges"] == 1
    # In-flight ranges were folded into todo (at-least-once); the
    # completed hole never re-emits.
    redo = []
    while True:
        task = resumed.get(worker_id=2)
        if task.type == pb.WAIT or task.task_id == -1:
            break
        redo.append((task.start, task.end))
        resumed.report(task.task_id, True, worker_id=2)
    assert (10, 20) in redo and (30, 40) in redo
    assert all(not (lo >= 20 and hi <= 30) for lo, hi in redo)
    assert resumed.watermark == 60  # drained the 60 available records


def test_streaming_resume_from_journal_redo_exact(journal_file):
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(6.0)
    manager = _manager(stream, rpt=10, lookahead=4)
    tasks = [manager.get(worker_id=1) for _ in range(4)]
    manager.report(tasks[0].task_id, True, worker_id=1)
    manager.report(tasks[2].task_id, True, worker_id=1)
    # Master SIGKILL: no snapshot, only the journal survives.
    del manager

    events = _events(journal_file)
    resumed = StreamingTaskManager.resume_from_journal(
        events,
        SyntheticClickStream.from_json(stream.to_json()),
        records_per_task=10,
        lookahead_tasks=4,
    )
    assert resumed.watermark == 10
    assert resumed.stream_counts()["pending_ranges"] == 1
    assert resumed.finished_record_count == 20  # watermark + the hole

    # Redo debt is EXACT: precisely the two ranges in flight at the kill
    # re-cut; the completed hole [20, 30) never re-emits.
    redo = []
    while True:
        task = resumed.get(worker_id=2)
        if task.type == pb.WAIT or task.task_id == -1:
            break
        redo.append((task.start, task.end))
        resumed.report(task.task_id, True, worker_id=2)
    assert redo[:2] == [(10, 20), (30, 40)]
    assert all(not (lo >= 20 and hi <= 30) for lo, hi in redo)
    assert resumed.watermark == 60

    # The resume itself is journaled with the stream cursor.
    resumes = [
        e for e in _events(journal_file)
        if e["event"] == "task_progress_resume"
    ]
    assert resumes and resumes[-1]["watermark"] == 10
    assert resumes[-1]["completed_above_watermark"] == 1


def test_streaming_resume_from_journal_contiguous_prefix_advances():
    # Every dispatched range completed before the kill, but the LAST
    # watermark journal write raced the crash: the done chain above the
    # journaled watermark must fold in at resume, not re-emit.
    stream = SyntheticClickStream([(10.0, 10)], name="clicks")
    stream.advance(5.0)  # 50 available: records exist past the done chain
    events = [
        {"event": "stream_watermark", "stream": "clicks", "offset": 10,
         "event_time": 1.0, "next_offset": 30, "pending_ranges": 0},
        {"event": "task_dispatch", "task_id": 2, "shard": "clicks",
         "start": 10, "end": 20, "worker_id": 1},
        {"event": "task_dispatch", "task_id": 3, "shard": "clicks",
         "start": 20, "end": 30, "worker_id": 1},
        {"event": "task_done", "task_id": 2},
        {"event": "task_done", "task_id": 3},
    ]
    resumed = StreamingTaskManager.resume_from_journal(
        events, stream, records_per_task=10
    )
    assert resumed.watermark == 30
    assert resumed.stream_counts()["pending_ranges"] == 0
    assert resumed.finished_record_count == 30
    task = resumed.get(worker_id=1)
    assert task.start == 30  # the frontier resumes past everything done
