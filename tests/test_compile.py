"""Declarative sharding compile layer (parallel/compile.py, ISSUE 10).

Four gates:

1. Rule-table semantics over REAL model pytrees (DeepFM / ResNet-50 /
   transformer-LM param trees from jax.eval_shape): first-match wins,
   unmatched non-scalar leaves are errors, scalars replicate without
   consulting the table, regex order is precedence.
2. Strategy selection (pjit-with-shardings vs shard_map for map-style
   bodies) + the donation round-trip through `CompilePlan.compile`.
3. Per-trainer HLO-structure parity on the 8-device dryrun mesh: the
   compile-layer-built step compiles to the SAME collective structure
   as the pre-port hand-rolled jax.jit/shard_map construction — the
   refactor moved the plumbing, not the program.
4. The grep gate: no direct jax.jit/pjit/shard_map construction left in
   dp_trainer.py / ps_trainer.py / ring_attention.py — every compiled
   entry point goes through parallel/compile.py.
"""

import re

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel import compile as pc
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer

# ---------------------------------------------------------------------------
# 1. Rule-table matching over the zoo pytrees
# ---------------------------------------------------------------------------


def _deepfm_params():
    from model_zoo.deepfm import deepfm_functional_api as zoo

    model = zoo.custom_model(vocab_size=50)
    features = {
        "dense": jax.ShapeDtypeStruct((4, zoo.NUM_DENSE), jnp.float32),
        "cat": jax.ShapeDtypeStruct((4, zoo.NUM_CAT), jnp.int32),
    }
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0), features)
    return variables["params"]


def _resnet_params():
    from model_zoo.resnet50 import resnet50_subclass as zoo

    model = zoo.custom_model(use_bf16=False)
    images = jax.ShapeDtypeStruct((2, 32, 32, 3), jnp.float32)
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0), images)
    return variables["params"]


def _transformer_params():
    from model_zoo.transformer import transformer_lm as lm

    model = lm.custom_model(
        vocab=64, d_model=16, num_heads=2, num_layers=1, max_len=32
    )
    tokens = jax.ShapeDtypeStruct((2, 16), jnp.int32)
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0), tokens)
    return variables["params"]


def test_rule_table_matches_deepfm_embedding_by_regex():
    params = _deepfm_params()
    table = pc.RuleTable(
        [
            pc.Rule(r"embedding", P(MODEL_AXIS)),
            pc.Rule(r".*", P()),
        ],
        name="test-deepfm",
    )
    specs, stats = table.match(params)
    flat = dict(pc.tree_paths(specs))
    emb = [k for k in flat if "embedding/embedding" in k]
    assert emb, f"no embedding leaf found in {sorted(flat)[:5]}..."
    for key in emb:
        assert flat[key] == P(MODEL_AXIS), (key, flat[key])
    # Dense leaves fell through to the catch-all.
    dense = [k for k in flat if k.startswith("Dense")]
    assert dense and all(flat[k] == P() for k in dense)
    assert stats["rule_hits"] > 0 and stats["rule_misses"] == 0


def test_rule_table_first_match_wins_and_order_is_precedence():
    params = _transformer_params()
    # A specific rule listed FIRST beats the later broad rule...
    specific_first = pc.RuleTable([
        pc.Rule(r"embed", P(MODEL_AXIS)),
        pc.Rule(r".*", P()),
    ]).match(params)[0]
    # ...and the same specific rule listed AFTER a catch-all never fires.
    broad_first = pc.RuleTable([
        pc.Rule(r".*", P()),
        pc.Rule(r"embed", P(MODEL_AXIS)),
    ]).match(params)[0]
    flat_sf = dict(pc.tree_paths(specific_first))
    flat_bf = dict(pc.tree_paths(broad_first))
    embed_keys = [k for k in flat_sf if "embed" in k.lower()]
    assert embed_keys
    assert any(flat_sf[k] == P(MODEL_AXIS) for k in embed_keys)
    assert all(flat_bf[k] == P() for k in embed_keys)


def test_rule_table_unmatched_leaf_is_an_error():
    params = _resnet_params()
    table = pc.RuleTable(
        [pc.Rule(r"^this_matches_nothing$", P())], name="resnet-hole"
    )
    with pytest.raises(ValueError, match="no rule for leaf"):
        table.match(params)


def test_rule_table_scalars_replicate_without_consulting_rules():
    tree = {"count": jnp.zeros((), jnp.int32), "w": jnp.zeros((8, 4))}
    specs, stats = pc.RuleTable([pc.Rule(r"^w$", P(DATA_AXIS))]).match(tree)
    assert specs["count"] == P()      # scalar: no rule needed
    assert specs["w"] == P(DATA_AXIS)
    assert stats["scalars"] == 1


def test_rule_table_shape_aware_callable_rule():
    def big_only(path, shape):
        return P(DATA_AXIS) if int(np.prod(shape)) >= 64 else P()

    tree = {"big": jnp.zeros((64, 4)), "small": jnp.zeros((2, 2))}
    specs, _ = pc.RuleTable([pc.Rule(r".*", big_only)]).match(tree)
    assert specs["big"] == P(DATA_AXIS) and specs["small"] == P()


def test_match_partition_rules_functional_form():
    specs = pc.match_partition_rules(
        [pc.Rule(r".*", P())], {"a": jnp.zeros((4, 4))}
    )
    assert specs["a"] == P()


# ---------------------------------------------------------------------------
# 2. Strategy selection + donation round-trip
# ---------------------------------------------------------------------------


def test_select_strategy():
    assert pc.select_strategy(in_shardings=(P(),), out_shardings=P()) == "pjit"
    assert pc.select_strategy() == "pjit"
    assert pc.select_strategy(in_specs=(P(DATA_AXIS),),
                              out_specs=P(DATA_AXIS)) == "shard_map"
    with pytest.raises(ValueError, match="BOTH in_specs and out_specs"):
        pc.select_strategy(in_specs=(P(DATA_AXIS),))


def _journal_events(event):
    from elasticdl_tpu import obs

    return [e for e in obs.journal().tail(100) if e.get("event") == event]


def test_compile_pjit_strategy_donation_round_trip_and_journal():
    mesh = build_mesh(MeshConfig())
    plan = pc.CompilePlan(
        mesh,
        pc.RuleTable([pc.Rule(r".*", P())], name="test-table"),
        trainer="test_trainer",
    )
    repl = plan.replicated()
    shardings = plan.state_shardings({"w": jnp.zeros((8, 8))})
    step = plan.compile(
        lambda state, x: (state + x, jnp.sum(x)),
        name="test_step",
        in_shardings=(shardings["w"], repl),
        out_shardings=(shardings["w"], repl),
        donate_argnums=(0,),
    )
    state = jax.device_put(jnp.ones((8, 8)), shardings["w"])
    x = jax.device_put(jnp.ones((8, 8)), repl)
    new_state, total = step(state, x)
    np.testing.assert_allclose(np.asarray(new_state), 2.0)
    assert float(total) == 64.0
    assert state.is_deleted(), "donated input buffer survived the call"
    events = _journal_events("compile_plan")
    assert events, "compile() did not journal a compile_plan event"
    last = events[-1]
    assert last["trainer"] == "test_trainer"
    assert last["strategy"] == "pjit"
    assert last["name"] == "test_step"
    assert last["rule_table"] == "test-table"
    assert last["rule_hits"] == 1
    assert last["donated_argnums"] == [0]


def test_compile_shard_map_strategy_runs_map_style_body():
    mesh = build_mesh(MeshConfig(data=8, model=1))
    plan = pc.CompilePlan(mesh, trainer="test_trainer")

    def body(x):
        return x * jax.lax.psum(jnp.ones((), x.dtype), DATA_AXIS)

    fn = plan.compile(
        body,
        name="test_map",
        in_specs=(P(DATA_AXIS),),
        out_specs=P(DATA_AXIS),
    )
    out = fn(jnp.ones((16, 4)))
    np.testing.assert_allclose(np.asarray(out), 8.0)
    last = _journal_events("compile_plan")[-1]
    assert last["strategy"] == "shard_map"


# ---------------------------------------------------------------------------
# 3. Per-trainer HLO-structure parity (compile layer vs hand-rolled)
# ---------------------------------------------------------------------------

COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


def _collective_signature(hlo_text):
    """Sorted (opcode, result shapes) multiset — the structure that must
    survive the port (instruction NAMES are arbitrary)."""
    sigs = []
    for op in COLLECTIVES:
        pat = re.compile(rf"=\s*[^=]*\b{re.escape(op)}(-start)?\(")
        for line in hlo_text.splitlines():
            if pat.search(line):
                shapes = tuple(
                    re.findall(r"[a-z0-9]+\[[0-9,]*\]", line.split("=")[0])
                )
                sigs.append((op, shapes))
    return sorted(sigs)


class _DenseModel(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))


def _dense_loss(labels, outputs):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, labels.astype(jnp.int32)
    ).mean()


@pytest.mark.parametrize("dense_sharding", ["replicated", "fsdp"])
def test_dp_trainer_hlo_parity_with_hand_rolled_step(dense_sharding):
    mesh = build_mesh(MeshConfig(data=4, model=2))
    trainer = DataParallelTrainer(
        _DenseModel(), _dense_loss, optax.sgd(0.1), mesh,
        dense_sharding=dense_sharding,
    )
    rng = np.random.RandomState(0)
    features = rng.rand(16, 64).astype(np.float32)
    labels = rng.randint(0, 4, size=16).astype(np.int32)
    trainer.ensure_initialized(features)
    staged = trainer.stage_batch(features, labels, np.ones((16,), np.float32))
    ported = trainer._train_step.lower(
        trainer.state, *staged
    ).compile().as_text()

    # The pre-port construction: a hand-rolled jax.jit with the same
    # impl, shardings, and donation (what _compile_steps used to build).
    state_sh = trainer._state_shardings(trainer.state)
    batch = shd.batch_sharded(mesh)
    repl = shd.replicated(mesh)
    hand = jax.jit(
        trainer._train_step_impl,
        in_shardings=(state_sh, batch, batch, batch),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )
    hand_rolled = hand.lower(trainer.state, *staged).compile().as_text()
    assert _collective_signature(ported) == _collective_signature(
        hand_rolled
    )


def test_ps_trainer_hlo_parity_with_hand_rolled_step():
    from elasticdl_tpu.layers import Embedding

    class _SparseModel(nn.Module):
        @nn.compact
        def __call__(self, ids):
            x = Embedding(2048, 8, combiner="sum", name="emb")(ids)
            return nn.Dense(4, name="head")(x)

    mesh = build_mesh(MeshConfig(data=4, model=2))
    trainer = ShardedEmbeddingTrainer(
        _SparseModel(), _dense_loss, optax.sgd(0.1), mesh,
        embedding_optimizer=sparse_optim.adam(0.01),
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 2048, size=(16, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=16).astype(np.int32)
    trainer.ensure_initialized(ids)
    staged = trainer.stage_batch(ids, labels, np.ones((16,), np.float32))
    ported = trainer._train_step.lower(
        trainer.state, *staged
    ).compile().as_text()

    state_sh = trainer._state_shardings(trainer.state)
    batch = shd.batch_sharded(mesh)
    repl = shd.replicated(mesh)
    hand = jax.jit(
        trainer._train_step_impl,
        in_shardings=(state_sh, batch, batch, batch),
        out_shardings=(state_sh, (repl, repl)),
        donate_argnums=(0,),
    )
    hand_rolled = hand.lower(trainer.state, *staged).compile().as_text()
    assert _collective_signature(ported) == _collective_signature(
        hand_rolled
    )
    # The rule table reproduced the hand-rolled placement exactly: the
    # table is sharded across the WHOLE mesh, like the old
    # _table_sharding computed.
    sh = state_sh.tables["emb/embedding"]
    assert sh.spec == P((DATA_AXIS, MODEL_AXIS), None)


def test_ring_attention_hlo_parity_with_hand_rolled_shard_map():
    from functools import partial

    from elasticdl_tpu.parallel import ring_attention as ra

    mesh = build_mesh(MeshConfig(data=4, model=2))
    rng = np.random.RandomState(2)
    shape = (4, 16, 2, 8)  # [B, T, H, D]
    q = jnp.asarray(rng.randn(*shape).astype(np.float32))
    spec = P(DATA_AXIS, MODEL_AXIS, None, None)
    sharding = NamedSharding(mesh, spec)
    q = jax.device_put(q, sharding)

    ported_fn = ra.make_ring_attention(mesh, causal=True, impl="xla")
    ported = jax.jit(ported_fn).lower(q, q, q).compile().as_text()

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    hand_fn = sm(
        partial(
            ra._ring_dispatch, axis_name=MODEL_AXIS, causal=True,
            scale=None, layout="contiguous", impl="xla",
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    hand_rolled = jax.jit(hand_fn).lower(q, q, q).compile().as_text()
    assert _collective_signature(ported) == _collective_signature(
        hand_rolled
    )
    # And the ring really is a ppermute chain either way.
    assert any(op == "collective-permute"
               for op, _ in _collective_signature(ported))


# ---------------------------------------------------------------------------
# 4. Grep gate: the trainers compile ONLY through parallel/compile.py
# ---------------------------------------------------------------------------

_TRAINER_FILES = (
    "elasticdl_tpu/parallel/dp_trainer.py",
    "elasticdl_tpu/parallel/ps_trainer.py",
    "elasticdl_tpu/parallel/ring_attention.py",
)

#: Direct compile-construction idioms the port removed.  `pc.` entry
#: points (compile/ shard_map_call / jit_utility) are the sanctioned
#: spellings.
_DIRECT_COMPILE_RE = re.compile(
    r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.shard_map\b|"
    r"from\s+jax\.experimental\.shard_map\s+import"
)


@pytest.mark.parametrize("rel_path", _TRAINER_FILES)
def test_no_direct_jit_or_shard_map_left_in_trainers(rel_path):
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, rel_path), "r", encoding="utf-8") as f:
        text = f.read()
    hits = [
        (i + 1, line.strip())
        for i, line in enumerate(text.splitlines())
        if _DIRECT_COMPILE_RE.search(line.split("#", 1)[0])
    ]
    assert not hits, (
        f"{rel_path} still hand-rolls compilation (use "
        f"parallel/compile.py entry points): {hits}"
    )
