"""Compiled-HLO structure assertions at 8 virtual devices (VERDICT
round-2 #5).

The multichip dryrun proves sharded programs compile and produce finite
numbers; these tests pin the compiled COLLECTIVE structure, because a
regression that, say, turns the sharded-table lookup into a full-table
all-gather would pass every numeric test and only surface as a mystery
slowdown on real hardware this environment cannot provide.

Matching note: HLO instruction NAMES are arbitrary (`%ppermute.13 = ...
collective-permute(...)`) — match the opcode after `=`, never the name.
Assertions are deliberately coarse (opcode presence/absence + shape
bounds) so jax/XLA version bumps don't flake them.
"""

import re

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer

COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


def collective_lines(hlo_text: str, opcode: str):
    """Instruction lines whose OPCODE is `opcode` (async variants too)."""
    pat = re.compile(rf"=\s*[^=]*\b{re.escape(opcode)}(-start)?\(")
    return [l.strip() for l in hlo_text.splitlines() if pat.search(l)]


def result_dims(line: str):
    """All array shapes on the line, as tuples of ints."""
    return [
        tuple(int(d) for d in m.split(",") if d)
        for m in re.findall(r"[a-z0-9]+\[([0-9,]*)\]", line)
    ]


VOCAB, DIM = 2048, 8  # 128 storage blocks -> shards 8 ways exactly


class _SparseModel(nn.Module):
    @nn.compact
    def __call__(self, ids):
        x = Embedding(VOCAB, DIM, combiner="sum", name="emb")(ids)
        return nn.Dense(4, name="head")(x)


def _loss(labels, outputs):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, labels.astype(jnp.int32)
    ).mean()


def _ps_train_step_hlo():
    mesh = build_mesh(MeshConfig(data=4, model=2))
    trainer = ShardedEmbeddingTrainer(
        _SparseModel(), _loss, optax.sgd(0.1), mesh,
        embedding_optimizer=sparse_optim.adam(0.01),
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, size=(16, 3)).astype(np.int32)
    labels = rng.randint(0, 4, size=16).astype(np.int32)
    trainer.ensure_initialized(ids)
    # Precondition of everything below: the table really is sharded.
    table = trainer.state.tables["emb/embedding"]
    assert table.shape[0] % 8 == 0
    assert not table.sharding.is_fully_replicated
    staged = trainer.stage_batch(ids, labels, np.ones((16,), np.float32))
    lowered = trainer._train_step.lower(trainer.state, *staged)
    return lowered.compile().as_text(), table.shape


def test_ps_step_never_allgathers_the_table():
    """The sharded-table PS step's collectives move only index/row-batch
    sized data; NO collective carries a full-table-shaped array (that
    would be the gather-the-world regression the sharded design exists
    to avoid)."""
    hlo, table_shape = _ps_train_step_hlo()
    num_blocks = table_shape[0]
    offenders = []
    seen_any = 0
    for op in COLLECTIVES:
        for line in collective_lines(hlo, op):
            seen_any += 1
            for dims in result_dims(line):
                if dims and dims[0] >= num_blocks:
                    offenders.append((op, line[:160]))
    # The program IS distributed (loss all-reduce at minimum)...
    assert seen_any >= 1, "no collectives at all — program not partitioned?"
    # ...but nothing table-shaped crosses the interconnect.
    assert not offenders, offenders


def test_ps_step_gathers_indices_not_rows_for_lookup():
    """The lookup's cross-shard traffic is the batch's ids (s32, tiny) and
    the combined gathered rows — visible as at least one small all-gather
    or all-reduce well below table size."""
    hlo, table_shape = _ps_train_step_hlo()
    small = []
    for op in ("all-gather", "all-reduce"):
        for line in collective_lines(hlo, op):
            for dims in result_dims(line):
                if dims and dims[0] < table_shape[0]:
                    small.append(dims)
    assert small, "expected batch-sized lookup collectives"


def _transformer_step_hlo(model_axis_mode: str, dense_sharding: str):
    from model_zoo.transformer import transformer_lm as lm

    mesh = build_mesh(MeshConfig(data=4, model=2))
    kwargs = (
        {"model_axis_mode": "tp"} if model_axis_mode == "tp" else {}
    )
    trainer = DataParallelTrainer(
        lm.custom_model(
            vocab=64, d_model=16, num_heads=2, num_layers=1, max_len=64,
            mesh=mesh, **kwargs,
        ),
        lm.loss,
        lm.optimizer(),
        mesh,
        dense_sharding=dense_sharding,
    )
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    targets = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    trainer.ensure_initialized(tokens)
    staged = trainer.stage_batch(tokens, targets, np.ones((8,), np.float32))
    return trainer._train_step.lower(trainer.state, *staged).compile().as_text()


def test_ring_attention_compiles_to_collective_permute_chain():
    """Context parallelism IS the ppermute ring: the compiled cp train
    step must rotate KV blocks via collective-permute (forward AND the
    reverse ring in the backward pass).  Losing these means ring
    attention silently degraded to a local/replicated computation."""
    hlo = _transformer_step_hlo("cp", "replicated")
    permutes = collective_lines(hlo, "collective-permute")
    assert len(permutes) >= 2, f"expected a ppermute chain, got {permutes}"
    # The rotating payload is a KV block (4-D [b, t_local, h, d]), not a
    # degenerate scalar.
    assert any(
        any(len(dims) == 4 for dims in result_dims(l)) for l in permutes
    ), permutes


def test_fsdp_step_shards_param_traffic():
    """FSDP must gather weights (all-gather) and reduce gradients
    (reduce-scatter, or the all-reduce+slice form XLA's partitioner picks
    on some backends) — and the optimizer update itself must touch only
    SHARDED param-state shapes.  A silent fall-back to fully replicated
    params would show up as zero all-gathers."""
    hlo = _transformer_step_hlo("cp", "fsdp")
    gathers = collective_lines(hlo, "all-gather")
    assert gathers, "FSDP step has no weight all-gathers"
    reduces = collective_lines(hlo, "reduce-scatter") + collective_lines(
        hlo, "all-reduce"
    )
    assert reduces, "FSDP step has no gradient reduction collectives"


def test_tensor_parallel_step_reduces_partial_activations():
    """Megatron-style TP: row-parallel matmul outputs are partial sums —
    the compiled step must all-reduce (or reduce-scatter) activations,
    and the qkv/MLP weight tensors must not be all-gathered whole."""
    hlo = _transformer_step_hlo("tp", "replicated")
    reduces = collective_lines(hlo, "all-reduce") + collective_lines(
        hlo, "reduce-scatter"
    )
    assert reduces, "TP step has no activation reductions"
