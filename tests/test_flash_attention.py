"""Pallas flash-attention kernel tests (interpret mode on CPU).

Golden parity vs dense attention — forward and backward (the custom-VJP
dq / dk/dv kernels) — full and causal, f32 and bf16.  The real-TPU
lowering of the same kernels is exercised by the transformer bench
(BASELINE.md) on hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops import flash_attention
from elasticdl_tpu.ops.flash_attention import supports
from tests.test_ring_attention import _qkv, dense_attention

BLOCK = dict(block_q=16, block_k=16)  # tiny blocks: interpret mode is slow


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv(b=1, t=64, h=2, d=16, seed=0)
    out = flash_attention(q, k, v, causal=causal, **BLOCK)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(b=1, t=32, h=2, d=8, seed=4)

    def flash_loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, **BLOCK) ** 2
        )

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_bf16_forward_close_to_f32_dense():
    q, k, v = _qkv(b=1, t=32, h=2, d=16, seed=2, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, **BLOCK)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
    )


def test_supports_and_shape_validation():
    assert supports(256, 64)
    assert not supports(100, 64)  # not a block multiple
    q, k, v = _qkv(b=1, t=24, h=2, d=8)
    with pytest.raises(ValueError, match="multiple of block sizes"):
        flash_attention(q, k, v, **BLOCK)


def test_under_jit_and_vmapless_batch():
    """The kernel composes with jit (the trainers always jit the step)."""
    q, k, v = _qkv(b=2, t=32, h=2, d=8, seed=9)
    f = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True, **BLOCK)
    )
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(dense_attention(q, k, v, causal=True)),
        atol=2e-5,
    )
