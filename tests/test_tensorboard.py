"""TensorBoard service + profiler-hook tests (reference:
master/tensorboard_service.py; SURVEY.md §5 names jax.profiler the cheap
observability win)."""

import glob
import os

import numpy as np
import pytest

from elasticdl_tpu.common.profiler import StepProfiler, parse_profile_steps
from elasticdl_tpu.master.tensorboard_service import TensorBoardService


def _read_scalars(log_dir):
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    acc = EventAccumulator(log_dir)
    acc.Reload()
    return {
        tag: [(e.step, e.value) for e in acc.Scalars(tag)]
        for tag in acc.Tags()["scalars"]
    }


class FakeTaskManager:
    finished_record_count = 128

    def counts(self):
        return {"todo": 3, "doing": 1, "epoch": 2}


def test_scalar_service_writes_event_files(tmp_path):
    log_dir = str(tmp_path / "tb")
    service = TensorBoardService(
        log_dir,
        task_manager=FakeTaskManager(),
        model_version_fn=lambda: 40,
        restarts_fn=lambda: 1,
        sample_interval_s=3600,  # sampling driven manually below
    )
    service.write_dict_to_summary({"auc": 0.75, "accuracy": 0.9}, version=40)
    service._sample_progress()
    service.close()

    assert glob.glob(os.path.join(log_dir, "events.out.tfevents.*"))
    scalars = _read_scalars(log_dir)
    assert scalars["eval/auc"][0] == (40, pytest.approx(0.75))
    assert scalars["eval/accuracy"][0] == (40, pytest.approx(0.9))
    assert scalars["train/records_finished"][0][1] == 128
    assert scalars["train/epoch"][0][1] == 2
    assert scalars["train/worker_restarts"][0][1] == 1


def test_local_job_honors_tensorboard_flag(tmp_path):
    """`--tensorboard_log_dir` end-to-end: a Local training job with
    evaluation writes eval-metric scalars the TB event reader can load."""
    from elasticdl_tpu.client import api

    log_dir = str(tmp_path / "tb")
    rc = api.train(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--training_data", "synthetic://mnist?n=256",
            "--validation_data", "synthetic://mnist?n=64&seed=1",
            "--minibatch_size", "32",
            "--num_epochs", "1",
            "--records_per_task", "128",
            "--distribution_strategy", "Local",
            "--tensorboard_log_dir", log_dir,
        ]
    )
    assert rc == 0
    scalars = _read_scalars(log_dir)
    assert any(tag.startswith("eval/") for tag in scalars), scalars.keys()
    assert "train/records_finished" in scalars
    # The final sample (flushed at close) saw the whole dataset trained.
    assert scalars["train/records_finished"][-1][1] == 256


class TestProfiler:
    def test_parse(self):
        assert parse_profile_steps("") is None
        assert parse_profile_steps("5,8") == (5, 8)
        with pytest.raises(ValueError):
            parse_profile_steps("8,5")
        with pytest.raises(ValueError):
            parse_profile_steps("abc")

    def test_inactive_without_steps(self, tmp_path):
        profiler = StepProfiler(str(tmp_path), "")
        profiler.before_steps(1)
        profiler.after_steps(1)

    def test_profile_steps_without_log_dir_rejected(self):
        # The silently-dangling-flag failure mode: must be loud.
        with pytest.raises(ValueError, match="tensorboard_log_dir"):
            StepProfiler("", "1,2")
        from elasticdl_tpu.common.args import parse_master_args

        with pytest.raises(ValueError, match="tensorboard_log_dir"):
            parse_master_args(
                ["--model_zoo", "z", "--model_def", "m.f",
                 "--training_data", "t", "--profile_steps", "1,2"]
            )

    def test_malformed_spec_fails_at_parse_time(self):
        """A bad spec must fail the submission, not crash-loop workers."""
        from elasticdl_tpu.common.args import parse_master_args

        with pytest.raises(SystemExit):
            parse_master_args(
                ["--model_zoo", "z", "--model_def", "m.f",
                 "--training_data", "t", "--tensorboard_log_dir", "/tb",
                 "--profile_steps", "20,10"]
            )

    def test_traces_window(self, tmp_path):
        import jax
        import jax.numpy as jnp

        profiler = StepProfiler(str(tmp_path), "2,4", worker_id=0)
        f = jax.jit(lambda x: x * 2 + 1)
        step = 0
        for _ in range(6):
            profiler.before_steps(step)
            f(jnp.ones((8,))).block_until_ready()
            step += 1
            profiler.after_steps(step)
        profiler.stop()  # idempotent (already stopped after step 3)
        trace_dir = os.path.join(str(tmp_path), "profile", "worker_0")
        files = [
            p
            for p in glob.glob(os.path.join(trace_dir, "**"), recursive=True)
            if os.path.isfile(p)
        ]
        assert files, "no trace files written"

    def test_fused_window_rounds_outward(self, tmp_path):
        """A trainer running 8 steps per device call with a 2-step profile
        window traces the whole enclosing window instead of skipping."""
        profiler = StepProfiler(str(tmp_path), "11,13", worker_id=0)
        profiler.before_steps(0, n=8)   # steps 1..8: before window
        assert not profiler._tracing
        profiler.after_steps(8)
        profiler.before_steps(8, n=8)   # steps 9..16: overlaps [11, 13)
        assert profiler._tracing
        profiler.after_steps(16)
        assert not profiler._tracing and profiler._done

    def test_missed_window_warns_not_silent(self, tmp_path, monkeypatch):
        from elasticdl_tpu.common import profiler as profiler_mod

        warnings = []
        monkeypatch.setattr(
            profiler_mod.logger,
            "warning",
            lambda msg, *a: warnings.append(msg % a),
        )
        profiler = StepProfiler(str(tmp_path), "2,3", worker_id=0)
        profiler.before_steps(10, n=8)  # window long gone
        assert profiler._done and not profiler._tracing
        assert any("already passed" in w for w in warnings)


def test_observability_flags_forward_to_workers():
    """The flags must round-trip to worker pods or cluster jobs silently
    lose profiling (the round-1 dangling-flag failure mode)."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.pod_manager import worker_argv_from_args

    args = parse_master_args(
        [
            "--model_zoo", "z", "--model_def", "m.f",
            "--training_data", "t",
            "--tensorboard_log_dir", "/tb",
            "--profile_steps", "10,20",
        ]
    )
    argv = worker_argv_from_args(args, "localhost:1")(0)
    joined = " ".join(argv)
    assert "--tensorboard_log_dir /tb" in joined
    assert "--profile_steps 10,20" in joined
