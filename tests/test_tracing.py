"""Distributed tracing plane tests (obs/tracing.py + obs/trace.py).

Covers:

- Tracer semantics: contextvar parent/child nesting, trace-id
  inheritance, the root convention (root span_id == trace_id), error
  stamping, thread isolation, after-the-fact ``record_span``, and the
  aggregate phase-window child spans;
- ``obs.span`` integration: every existing span call site now journals
  span/trace ids while keeping its histogram half;
- the crash flight recorder: open spans flush with duration-so-far and
  a final ``registry_snapshot`` lands in the journal;
- clock-offset estimation: midpoint recovery from heartbeat
  round-trips, median robustness, the master-authoritative one-way
  fallback below 2 round-trips, and zero-signal behavior;
- monotonic clamping: no negative durations or child-escaping-parent
  spans survive assembly, including through clamped ancestors;
- golden journals -> Chrome trace-event JSON that schema-validates
  (stdlib validator), with per-process rows and lane-packed tids;
- ``--metrics_port 0`` discovery: the exporter writes the bound port
  next to the journal and readers find it without hardcoding;
- obs.report's "slowest task chains" table from task.lifetime spans;
- the ISSUE acceptance e2e: a real master + 3 gRPC workers produce,
  via the assembler, a schema-valid Chrome trace reconstructing a full
  dispatch -> RPC -> execute -> report chain with zero negative or
  child-escaping spans.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

from elasticdl_tpu import obs
from elasticdl_tpu.obs import tracing
from elasticdl_tpu.obs.journal import EventJournal
from elasticdl_tpu.obs import trace as trace_mod

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


def _spans(journal):
    return [e for e in journal.tail(500) if e.get("event") == "span"]


# ---------------------------------------------------------------------------
# Tracer semantics
# ---------------------------------------------------------------------------


def test_tracer_nesting_inherits_trace_and_parent():
    journal = EventJournal()
    tracer = tracing.Tracer(journal=journal, proc="testproc")
    with tracer.span("outer", trace_id="t-1") as outer:
        assert tracer.current() is outer
        with tracer.span("inner") as inner:
            assert inner.trace_id == "t-1"
            assert inner.parent_span_id == outer.span_id
    assert tracer.current() is None
    records = _spans(journal)
    assert [r["name"] for r in records] == ["inner", "outer"]
    inner_rec, outer_rec = records
    assert inner_rec["parent_span_id"] == outer_rec["span_id"]
    assert inner_rec["trace_id"] == outer_rec["trace_id"] == "t-1"
    assert outer_rec["proc"] == "testproc"
    for rec in records:
        assert rec["duration_s"] >= 0
        assert rec["start_ts"] > 0
        assert rec["span_id"]


def test_root_convention_span_id_is_trace_id():
    journal = EventJournal()
    tracer = tracing.Tracer(journal=journal)
    with tracer.span("task.lifetime", trace_id="t-9", root=True) as root:
        assert root.span_id == "t-9"
    rec = tracer.record_span(
        "task.lifetime", start_ts=100.0, duration_s=2.5,
        trace_id="t-10", root=True, task_id=7,
    )
    assert rec["span_id"] == "t-10"
    assert rec["start_ts"] == 100.0
    assert rec["duration_s"] == 2.5
    assert rec["task_id"] == 7


def test_span_error_stamped_on_exception():
    journal = EventJournal()
    tracer = tracing.Tracer(journal=journal)
    try:
        with tracer.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    (rec,) = _spans(journal)
    assert rec["error"] == "ValueError"
    assert tracer.open_spans() == {}


def test_thread_contexts_do_not_cross_parent():
    journal = EventJournal()
    tracer = tracing.Tracer(journal=journal)
    seen = {}
    barrier = threading.Barrier(2)

    def run(tag):
        with tracer.span(f"outer_{tag}") as outer:
            barrier.wait(timeout=10)
            with tracer.span(f"inner_{tag}") as inner:
                seen[tag] = (outer.span_id, inner.parent_span_id)
            barrier.wait(timeout=10)

    threads = [
        threading.Thread(target=run, args=(tag,), daemon=True)
        for tag in ("a", "b")
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=30)
    assert seen["a"][1] == seen["a"][0]
    assert seen["b"][1] == seen["b"][0]
    assert seen["a"][0] != seen["b"][0]


def test_record_window_spans_sequential_under_current():
    journal = EventJournal()
    tracer = tracing.Tracer(journal=journal)
    window = {"steps": 8, "data_wait": 1.0, "execute": 3.0, "bookkeep": 0.5}
    # No-op outside a span: phase detail has no tree to hang from.
    assert tracer.record_window_spans(window, end_ts=100.0) == 0
    with tracer.span("worker.task", trace_id="t-1") as task_span:
        emitted = tracer.record_window_spans(window, end_ts=100.0)
    assert emitted == 3
    phases = [r for r in _spans(journal) if r["name"].startswith("step.")]
    assert [r["name"] for r in phases] == [
        "step.data_wait", "step.execute", "step.bookkeep",
    ]
    # Sequential, exclusive, ending at end_ts; all children of the task.
    assert phases[0]["start_ts"] == 95.5
    assert phases[1]["start_ts"] == 96.5
    assert phases[2]["start_ts"] == 99.5
    for rec in phases:
        assert rec["parent_span_id"] == task_span.span_id
        assert rec["trace_id"] == "t-1"


def test_obs_span_integration_journals_ids_and_observes_histogram(
    obs_registry_snapshot,
):
    test_start = time.time() - 1
    with obs.span(
        "worker.task", labels={"type": "TRAINING"},
        task_id=3, trace_id="t-int-1",
    ) as span:
        assert span.trace_id == "t-int-1"
    rec = next(
        e for e in reversed(obs.journal().tail(200))
        if e.get("event") == "span" and e.get("trace_id") == "t-int-1"
    )
    assert rec["name"] == "worker.task"
    assert rec["span_id"] and rec["start_ts"] >= test_start
    assert rec["task_id"] == 3 and rec["type"] == "TRAINING"
    hist = obs.registry().get("elasticdl_span_worker_task_seconds")
    assert hist is not None


def test_flight_recorder_flushes_open_spans_and_registry(
    obs_registry_snapshot,
):
    test_start = time.time() - 1
    tracer = tracing.tracer()
    entered = threading.Event()
    release = threading.Event()

    def hold():
        with tracer.span("worker.task", trace_id="t-fr-1"):
            entered.set()
            release.wait(timeout=30)

    thread = threading.Thread(target=hold, daemon=True)
    thread.start()
    assert entered.wait(timeout=10)
    try:
        flushed = tracing.flush_flight_record("test_shutdown")
        assert flushed >= 1
        tail = obs.journal().tail(200)
        span_rec = next(
            e for e in reversed(tail)
            if e.get("event") == "span" and e.get("trace_id") == "t-fr-1"
        )
        assert span_rec["flushed"] == "test_shutdown"
        assert span_rec["duration_s"] >= 0
        snap = next(
            e for e in reversed(tail)
            if e.get("event") == "registry_snapshot"
            and e["ts"] >= test_start
        )
        assert snap["reason"] == "test_shutdown"
        assert "metrics" in snap or "families" in snap
    finally:
        release.set()
        thread.join(timeout=10)


# ---------------------------------------------------------------------------
# Clock-offset estimation
# ---------------------------------------------------------------------------


def _probe(wid, stamp, skew, rtt=0.04):
    """A worker clock_probe + the matching master worker_telemetry pair
    for a worker whose clock runs `skew` seconds ahead of the master's
    (symmetric legs: the master stamp lands mid-window)."""
    stamp = round(stamp, 3)
    probe = {
        "ts": stamp + rtt, "event": "clock_probe", "worker_id": wid,
        "probe_ts": stamp, "t_send": stamp, "t_recv": stamp + rtt,
    }
    telemetry = {
        "ts": stamp - skew + rtt / 2, "event": "worker_telemetry",
        "worker_id": wid, "worker_ts": stamp,
    }
    return probe, telemetry


def test_offset_midpoint_recovers_symmetric_skew():
    probes, telemetry = [], []
    for k in range(4):
        p, t = _probe(0, 1000.0 + k, skew=25.0)
        probes.append(p)
        telemetry.append(t)
    offset, method, pairs = trace_mod.estimate_offset(probes, telemetry)
    assert method == "midpoint" and pairs == 4
    assert abs(offset - (-25.0)) < 1e-6


def test_offset_median_shrugs_off_outlier_probe():
    probes, telemetry = [], []
    for k in range(5):
        p, t = _probe(0, 1000.0 + k, skew=-10.0)
        probes.append(p)
        telemetry.append(t)
    # One probe with a wildly delayed return leg (asymmetric rtt).
    probes[2]["t_recv"] = probes[2]["t_send"] + 30.0
    offset, method, _pairs = trace_mod.estimate_offset(probes, telemetry)
    assert method == "midpoint"
    assert abs(offset - 10.0) < 0.05


def test_offset_master_authoritative_fallback_below_two_roundtrips():
    probe, telemetry = _probe(0, 1000.0, skew=5.0, rtt=0.02)
    offset, method, pairs = trace_mod.estimate_offset([probe], [telemetry])
    # One matched pair: fall back to the one-way ingest delta — the
    # master-authoritative estimate (offset plus the one-way delay).
    assert method == "one_way" and pairs == 1
    assert abs(offset - (-5.0 + 0.01)) < 1e-6
    offset, method, pairs = trace_mod.estimate_offset([], [])
    assert (offset, method, pairs) == (0.0, "none", 0)


def test_estimate_offsets_per_worker_sources():
    master = []
    workers = {}
    for wid, skew in ((0, 12.0), (1, -3.0)):
        events = []
        for k in range(3):
            p, t = _probe(wid, 2000.0 + k, skew=skew)
            events.append(p)
            master.append(t)
        workers[f"worker_{wid}"] = events
    offsets = trace_mod.estimate_offsets(
        {"master": master, **workers}
    )
    assert offsets["master"]["method"] == "authoritative"
    assert abs(offsets["worker_0"]["offset_s"] + 12.0) < 1e-6
    assert abs(offsets["worker_1"]["offset_s"] - 3.0) < 1e-6
    assert offsets["worker_0"]["method"] == "midpoint"


# ---------------------------------------------------------------------------
# Clamping
# ---------------------------------------------------------------------------


def _span(span_id, start, end, parent="", name="s", proc="p"):
    return {
        "name": name, "trace_id": "t", "span_id": span_id,
        "parent_span_id": parent, "start": start, "end": end,
        "proc": proc, "args": {},
    }


def test_clamp_fixes_negative_and_escaping_spans():
    spans = [
        _span("root", 0.0, 10.0),
        _span("early", -1.0, 4.0, parent="root"),       # starts early
        _span("late", 8.0, 12.0, parent="root"),        # ends late
        _span("negative", 5.0, 3.0, parent="root"),     # negative length
        _span("fine", 2.0, 6.0, parent="root"),
    ]
    assert trace_mod.check_invariants(spans) != []
    adjusted = trace_mod.clamp_spans(spans)
    assert adjusted == 3
    assert trace_mod.check_invariants(spans) == []
    by_id = {s["span_id"]: s for s in spans}
    assert by_id["early"]["start"] == 0.0
    assert by_id["late"]["end"] == 10.0
    assert by_id["negative"]["end"] == by_id["negative"]["start"]
    assert "clamped" not in by_id["fine"]


def test_clamp_cascades_through_clamped_ancestors():
    spans = [
        _span("root", 0.0, 10.0),
        _span("mid", 7.0, 14.0, parent="root"),   # clamps to [7, 10]
        _span("leaf", 11.0, 13.0, parent="mid"),  # must land inside [7, 10]
    ]
    trace_mod.clamp_spans(spans)
    assert trace_mod.check_invariants(spans) == []
    leaf = next(s for s in spans if s["span_id"] == "leaf")
    assert 7.0 <= leaf["start"] <= leaf["end"] <= 10.0


# ---------------------------------------------------------------------------
# Golden journals -> Chrome trace
# ---------------------------------------------------------------------------


def _write_jsonl(path, events):
    with open(path, "w", encoding="utf-8") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")


def _golden_journals(tmp_path, skew=40.0):
    """A master + one skewed worker journal with a full task chain."""
    t0 = 1_754_000_000.0
    trace_id = "t-g.0-1"
    master = [
        {"ts": t0, "event": "master_start", "job_name": "golden"},
        {"ts": t0 + 0.02, "event": "task_dispatch", "task_id": 1,
         "worker_id": 0, "trace_id": trace_id},
        {"ts": t0 + 0.02, "event": "span", "name": "rpc.get_task",
         "start_ts": t0 + 0.01, "duration_s": 0.008, "span_id": "m1",
         "parent_span_id": "w1", "trace_id": trace_id, "proc": "master"},
        {"ts": t0 + 5.02, "event": "span",
         "name": "rpc.report_task_result", "start_ts": t0 + 5.0,
         "duration_s": 0.01, "span_id": "m2", "parent_span_id": "w9",
         "trace_id": trace_id, "proc": "master"},
        {"ts": t0 + 5.03, "event": "span", "name": "task.lifetime",
         "start_ts": t0 + 0.01, "duration_s": 5.01, "span_id": trace_id,
         "trace_id": trace_id, "proc": "master", "task_id": 1},
        {"ts": t0 + 6.0, "event": "phase_transition", "from": "training",
         "to": "idle", "seconds": 5.5, "cause": "wait"},
    ]
    worker = []
    for k in range(3):
        stamp = round(t0 + skew + 0.5 + k, 3)
        worker.append(
            {"ts": stamp + 0.04, "event": "clock_probe", "worker_id": 0,
             "probe_ts": stamp, "t_send": stamp, "t_recv": stamp + 0.04}
        )
        master.append(
            {"ts": stamp - skew + 0.02, "event": "worker_telemetry",
             "worker_id": 0, "worker_ts": stamp}
        )
    base = t0 + skew
    worker.extend([
        {"ts": base + 0.02, "event": "span", "name": "worker.get_task",
         "start_ts": base + 0.008, "duration_s": 0.011, "span_id": "w1",
         "parent_span_id": trace_id, "trace_id": trace_id,
         "proc": "worker_0"},
        {"ts": base + 4.9, "event": "span", "name": "worker.task",
         "start_ts": base + 0.02, "duration_s": 4.88, "span_id": "w2",
         "parent_span_id": trace_id, "trace_id": trace_id,
         "proc": "worker_0"},
        {"ts": base + 4.9, "event": "span", "name": "step.data_wait",
         "start_ts": base + 0.03, "duration_s": 1.2, "span_id": "w3",
         "parent_span_id": "w2", "trace_id": trace_id, "proc": "worker_0"},
        {"ts": base + 4.9, "event": "span", "name": "step.execute",
         "start_ts": base + 1.23, "duration_s": 3.6, "span_id": "w4",
         "parent_span_id": "w2", "trace_id": trace_id, "proc": "worker_0"},
        {"ts": base + 5.02, "event": "span", "name": "worker.report_task",
         "start_ts": base + 4.99, "duration_s": 0.02, "span_id": "w9",
         "parent_span_id": trace_id, "trace_id": trace_id,
         "proc": "worker_0"},
    ])
    master.sort(key=lambda e: e["ts"])
    _write_jsonl(os.path.join(str(tmp_path), "events.jsonl"), master)
    _write_jsonl(
        os.path.join(str(tmp_path), "events_worker_0.jsonl"), worker
    )
    return trace_id


def test_golden_journals_assemble_to_schema_valid_chrome_trace(tmp_path):
    trace_id = _golden_journals(tmp_path, skew=40.0)
    result = trace_mod.assemble([str(tmp_path)])
    # Offset recovered (midpoint over 3 probes), worker events aligned.
    info = result["offsets"]["worker_0"]
    assert info["method"] == "midpoint" and info["pairs"] == 3
    assert abs(info["offset_s"] + 40.0) < 0.021
    assert result["invariant_problems"] == []
    # Chain: every hop nests (after alignment) inside the root.
    by_id = {s["span_id"]: s for s in result["spans"]}
    root = by_id[trace_id]
    for span_id in ("w1", "m1", "w2", "w3", "w4", "w9", "m2"):
        span = by_id[span_id]
        assert root["start"] - 1e-9 <= span["start"], span_id
        assert span["end"] <= root["end"] + 1e-9, span_id
    # The worker.task interior aligned into the master's 5s window, not
    # 40 seconds away.
    assert abs(by_id["w2"]["start"] - (root["start"] + 0.01)) < 0.1
    # Chrome export schema-validates; both processes named.
    chrome = result["chrome"]
    assert trace_mod.validate_chrome_trace(chrome) == []
    names = {
        e["args"]["name"]
        for e in chrome["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"master", "worker_0"} <= names
    cats = {e.get("cat") for e in chrome["traceEvents"] if e["ph"] == "X"}
    assert "span" in cats and "goodput_phase" in cats
    # Text waterfall renders the chain.
    text = trace_mod.render_waterfall(result["spans"])
    assert "task.lifetime" in text and "step.execute" in text


def test_trace_cli_writes_json_and_waterfall(tmp_path):
    _golden_journals(tmp_path, skew=-7.0)
    out = os.path.join(str(tmp_path), "trace.json")
    rc = trace_mod.main([str(tmp_path), "-o", out])
    assert rc == 0
    with open(out) as f:
        chrome = json.load(f)
    assert trace_mod.validate_chrome_trace(chrome) == []
    assert chrome["otherData"]["clock_offsets"]["worker_0"]["method"] == (
        "midpoint"
    )
    rc = trace_mod.main([str(tmp_path)])  # text fallback path
    assert rc == 0


def test_trace_selftest_subprocess():
    completed = subprocess.run(
        [sys.executable, "-m", "elasticdl_tpu.obs.trace", "--selftest"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert completed.returncode == 0, completed.stderr
    assert "trace selftest OK" in completed.stdout


# ---------------------------------------------------------------------------
# Satellites: metrics-port discovery + report task-chain table
# ---------------------------------------------------------------------------


def test_metrics_port_discovery_file(tmp_path, obs_registry_snapshot):
    from elasticdl_tpu.obs.exporter import MetricsExporter

    exporter = MetricsExporter(port=0).start()
    try:
        path = exporter.write_port_file(str(tmp_path))
        assert path and os.path.exists(path)
        port = MetricsExporter.read_port_file(str(tmp_path))
        assert port == exporter.port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as response:
            assert response.status == 200
    finally:
        exporter.stop()
    assert MetricsExporter.read_port_file(str(tmp_path / "nope")) is None


def test_report_slowest_task_chains_table():
    from elasticdl_tpu.obs import report

    t0 = 1_754_000_000.0
    events = [
        {"ts": t0, "event": "master_start", "job_name": "j"},
        {"ts": t0 + 9, "event": "span", "name": "task.lifetime",
         "start_ts": t0, "duration_s": 9.0, "span_id": "t-1",
         "trace_id": "t-1", "task_id": 1, "worker_id": 0,
         "type": "TRAINING"},
        {"ts": t0 + 8.5, "event": "span", "name": "worker.task",
         "start_ts": t0 + 0.1, "duration_s": 8.2, "span_id": "w",
         "trace_id": "t-1"},
        {"ts": t0 + 3, "event": "span", "name": "task.lifetime",
         "start_ts": t0, "duration_s": 3.0, "span_id": "t-2",
         "trace_id": "t-2", "task_id": 2, "worker_id": 1,
         "type": "TRAINING", "error": "timeout"},
        {"ts": t0 + 10, "event": "phase_transition", "from": "training",
         "to": "idle", "seconds": 10.0},
    ]
    summary = report.summarize(events)
    chains = summary["task_chains"]
    assert [c["trace_id"] for c in chains] == ["t-1", "t-2"]
    assert chains[0]["duration_s"] == 9.0
    assert chains[0]["worker_s"] == 8.2
    assert abs(chains[0]["overhead_s"] - 0.8) < 1e-9
    assert chains[1]["error"] == "timeout"
    text = report.render_report(summary)
    assert "slowest task chains" in text
    assert "trace t-1" in text


# ---------------------------------------------------------------------------
# Acceptance e2e: real master + 3 gRPC workers -> assembled trace
# ---------------------------------------------------------------------------


def test_trace_end_to_end_master_and_three_workers(
    tmp_path, obs_registry_snapshot
):
    """ISSUE acceptance: a real master + 3 gRPC workers run produces,
    via the assembler, a schema-valid Chrome trace that reconstructs a
    completed task's dispatch -> RPC -> execute -> report chain across
    the gRPC boundary with zero negative-duration or
    child-escaping-parent spans."""
    from elasticdl_tpu.common.constants import TaskExecCounterKey
    from elasticdl_tpu.common.grpc_utils import RetryPolicy
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.servicer import (
        MasterServicer,
        start_master_server,
    )
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.obs.exporter import MetricsExporter
    from elasticdl_tpu.obs.telemetry import (
        TelemetryAggregator,
        WorkerTelemetry,
    )
    from elasticdl_tpu.parallel.elastic import HeartbeatReporter, WorldInfo
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.worker.master_client import MasterClient

    journal = obs.journal()
    previous_path = journal.path
    journal_path = obs.init_journal(str(tmp_path))
    task_manager = TaskManager(
        training_shards={"shard": 96}, records_per_task=32
    )
    rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 23457)
    rendezvous.set_worker_hosts(
        [(0, "127.0.0.1"), (1, "127.0.0.1"), (2, "127.0.0.1")]
    )
    aggregator = TelemetryAggregator(
        current_workers_fn=lambda: [w for w, _h in rendezvous.world()],
        journal_interval_s=0.0,  # every ingest journals: probe pairs
    )
    servicer = MasterServicer(
        task_manager=task_manager,
        rendezvous_server=rendezvous,
        telemetry=aggregator,
    )
    server, port = start_master_server(servicer, port=0)
    exporter = MetricsExporter(port=0).start()
    assert exporter.write_port_file(str(tmp_path))
    policy = RetryPolicy(
        timeout_s=5.0, max_attempts=3, base_backoff_s=0.01,
        max_backoff_s=0.05, jitter=0.0, total_budget_s=30.0,
        wait_for_ready=True,
    )
    clients = [
        MasterClient(f"localhost:{port}", worker_id=wid, retry_policy=policy)
        for wid in range(3)
    ]
    telemetries = {
        wid: WorkerTelemetry(wid, step_window=4) for wid in range(3)
    }
    reporters = [
        HeartbeatReporter(
            clients[wid],
            WorldInfo(rank=wid, world_size=3, rendezvous_id=1,
                      coordinator_addr=""),
            host="127.0.0.1",
            interval_s=0.05,
            telemetry=telemetries[wid],
        )
        for wid in range(3)
    ]
    completed_traces = []
    errors = []

    def worker_loop(wid):
        client = clients[wid]
        try:
            while True:
                task = client.get_task()
                if task.task_id == -1 and task.type != pb.WAIT:
                    return
                if task.type == pb.WAIT:
                    time.sleep(0.02)
                    continue
                with obs.span(
                    "worker.task",
                    labels={"type": pb.TaskType.Name(task.type)},
                    task_id=task.task_id,
                    trace_id=task.trace_id,
                    worker_id=wid,
                ):
                    telemetries[wid].record_steps(
                        2, duration_s=0.02, records=task.end - task.start
                    )
                    # The step-anatomy window this "training" produced,
                    # as aggregate phase child spans.
                    tracing.tracer().record_window_spans(
                        {"steps": 2, "data_wait": 0.004, "execute": 0.016}
                    )
                client.report_task_result(
                    task.task_id, "",
                    exec_counters={
                        TaskExecCounterKey.BATCH_COUNT: 2,
                        TaskExecCounterKey.RECORD_COUNT: (
                            task.end - task.start
                        ),
                    },
                    trace_id=task.trace_id,
                )
                completed_traces.append(task.trace_id)
        except Exception as exc:  # surfaces in the main thread's assert
            errors.append((wid, exc))

    threads = [
        threading.Thread(target=worker_loop, args=(wid,), daemon=True)
        for wid in range(3)
    ]
    try:
        for reporter in reporters:
            reporter.start()
        # Let a few heartbeats land first so clock probes exist.
        deadline = time.time() + 30
        while time.time() < deadline:
            probes = [
                e for e in journal.tail(500)
                if e.get("event") == "clock_probe"
            ]
            if len(probes) >= 6:
                break
            time.sleep(0.02)
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=60)
        assert errors == [], errors
        assert completed_traces
    finally:
        for reporter in reporters:
            reporter.stop()
        exporter.stop()
        for client in clients:
            client.close()
        server.stop(grace=None)
        journal.configure(previous_path)

    result = trace_mod.assemble([journal_path])
    assert result["invariant_problems"] == []
    chrome = result["chrome"]
    assert trace_mod.validate_chrome_trace(chrome) == []
    by_id = {s["span_id"]: s for s in result["spans"]}
    children = trace_mod.span_children(result["spans"])
    # Every completed trace has its full chain; check one end to end.
    trace_id = completed_traces[0]
    assert trace_id in by_id, "task.lifetime root span missing"
    root = by_id[trace_id]
    kids = {span["name"] for span in children.get(trace_id, ())}
    assert {
        "worker.get_task", "worker.task", "worker.report_task",
    } <= kids, kids
    task_span = next(
        span for span in children[trace_id] if span["name"] == "worker.task"
    )
    phase_names = {
        span["name"] for span in children.get(task_span["span_id"], ())
    }
    assert {"step.data_wait", "step.execute"} <= phase_names
    rpc_names = {
        span["name"]
        for span in result["spans"]
        if span["trace_id"] == trace_id
    }
    assert {"rpc.get_task", "rpc.report_task_result"} <= rpc_names
    # Nesting survived assembly: every span of the trace sits inside
    # the root's aligned extent, and none has negative duration.
    for span in result["spans"]:
        if span["trace_id"] != trace_id:
            continue
        assert span["end"] >= span["start"]
        assert root["start"] - 1e-9 <= span["start"]
        assert span["end"] <= root["end"] + 1e-9
    # The journal on disk is schema-valid, including the new events.
    completed = subprocess.run(
        [sys.executable, os.path.join("scripts", "validate_journal.py"),
         journal_path],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert completed.returncode == 0, (
        completed.stdout + completed.stderr
    )
