"""SLO plane: metrics-history ring + error-budget burn-rate alerting.

Covers (docs/observability.md "SLO plane"):

- the history ring's hard bounds: label-churn eviction, clock-regression
  clamping, counter-reset-aware deltas, quantile_over_time vs the exact
  quantile, tick-jitter independence of rate();
- SLOSpec validation + window scaling, ratio and threshold burn math,
  fire/clear edge discipline, status rate-limiting, and "no data is not
  a breach";
- the policy engine's `note_slo_alert` advisory input (journaled holds
  with `slo_advisory` evidence, phantom-clear drop) and the
  supervisor's `SLOAlertFollower` journal-tail dedup;
- the exporter's bounded `/slo` endpoint (with and without a plane,
  HEAD, no file paths) and obs.top's SLO header/sparkline degrade;
- obs.report's error-budget section over the golden journal and its
  absence over pre-SLO journals;
- the journal schema rows for `slo_status` / `slo_alert`;
- the `slow`-marked acceptance e2e: a 2-replica (in-process) serving
  fleet under deterministic load, an injected latency regression on one
  replica that must page within bounded ticks, clear after the fault
  window, ride the shared journal into a policy advisory, and replay
  into a correctly-attributed error-budget timeline — while the
  no-fault control run fires nothing.
"""

import importlib.util
import json
import os
import random
import urllib.request

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.master.policy import ElasticPolicyEngine, PolicyConfig
from elasticdl_tpu.obs import report as report_mod
from elasticdl_tpu.obs import top
from elasticdl_tpu.obs.exporter import MetricsExporter
from elasticdl_tpu.obs.history import MetricsHistory, _quantile
from elasticdl_tpu.obs.metrics import MetricsRegistry
from elasticdl_tpu.obs.slo import (
    SLOPlane,
    SLOSpec,
    WINDOWS,
    serving_availability_slo,
    serving_latency_slo,
)
from elasticdl_tpu.serving.ledger import AvailabilityLedger
from elasticdl_tpu.serving.supervisor import SLOAlertFollower

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
GOLDEN = os.path.join(TESTS_DIR, "golden_journal.jsonl")


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_journal",
        os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


# ---------------------------------------------------------------------------
# MetricsHistory: the ring's hard bounds and window math
# ---------------------------------------------------------------------------


def test_history_delta_and_rate_counter_reset_aware():
    registry = MetricsRegistry()
    reqs = registry.counter("t_reqs_total", "", labelnames=("outcome",))
    history = MetricsHistory(registry)
    for tick in range(10):
        reqs.inc(5, outcome="served")
        history.sample(float(tick))
    # Window [4, 9] plus the t=3 baseline anchor: 6 increments of 5.
    assert history.delta("t_reqs_total", 5.0, now=9.0) == pytest.approx(30.0)
    assert history.rate("t_reqs_total", 5.0, now=9.0) == pytest.approx(6.0)
    # A counter reset (sample below its predecessor) restarts
    # accumulation from zero instead of going negative.
    gauge = registry.gauge("t_resetting", "")
    for t, value in enumerate([10.0, 20.0, 5.0, 8.0]):
        gauge.set(value)
        history.sample(100.0 + t)
    assert history.delta("t_resetting", 10.0, now=103.0) == pytest.approx(
        (20.0 - 10.0) + 5.0 + (8.0 - 5.0)
    )
    # rate() guards the degenerate window.
    assert history.rate("t_resetting", 0.0) == 0.0


def test_history_label_churn_eviction_is_bounded_and_lru():
    registry = MetricsRegistry()
    gauge = registry.gauge("t_churn", "", labelnames=("key",))
    history = MetricsHistory(registry, max_series=8)
    for i in range(40):
        gauge.set(float(i), key=f"k{i}")
        history.sample(float(i))
    assert history.series_count() <= 8
    assert history.evicted_total() >= 32
    # Every label set stays registry-live and is refreshed each tick, so
    # the survivors are the most-recently CREATED (insertion refreshes
    # position); the ring never exceeds its bound regardless.
    for i in range(40, 50):
        gauge.set(float(i), key=f"k{i}")
        history.sample(float(i))
    assert history.series_count() <= 8


def test_history_clock_regression_clamps_never_rewinds():
    registry = MetricsRegistry()
    gauge = registry.gauge("t_clock", "")
    history = MetricsHistory(registry)
    gauge.set(1.0)
    assert history.sample(10.0) == 10.0
    gauge.set(2.0)
    # A rewound clock (restarted ticker, NTP step) pins to the last
    # accepted time — windowed queries never see negative spans.
    assert history.sample(4.0) == 10.0
    assert history.last_sample_time() == 10.0
    assert history.latest("t_clock") == 2.0
    assert history.sample(11.0) == 11.0


def test_history_quantile_over_time_matches_exact_quantile():
    registry = MetricsRegistry()
    gauge = registry.gauge("t_quant", "")
    history = MetricsHistory(registry, max_samples=256)
    values = [float(v) for v in range(100)]
    for t, value in enumerate(values):
        gauge.set(value)
        history.sample(float(t))
    for q in (0.0, 0.5, 0.9, 0.99, 1.0):
        assert history.quantile_over_time(
            "t_quant", q, window_s=1000.0, now=99.0
        ) == pytest.approx(_quantile(values, q))
    # Narrow window only pools in-window samples.
    assert history.quantile_over_time(
        "t_quant", 0.0, window_s=9.0, now=99.0
    ) == pytest.approx(90.0)
    # No samples in the window -> None, not 0.0.
    assert history.quantile_over_time(
        "t_quant", 0.5, window_s=5.0, now=5000.0
    ) is None
    assert history.threshold_fraction(
        "t_quant", 5.0, 50.0, now=5000.0
    ) is None


def test_history_rate_is_tick_jitter_independent():
    """Two samplers over identical counter traffic — one regular, one
    with jittered tick times — must agree on rate(): the delta math is
    anchored on values, not sample counts."""
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    c_a = reg_a.counter("t_jit_total", "")
    c_b = reg_b.counter("t_jit_total", "")
    hist_a = MetricsHistory(reg_a)
    hist_b = MetricsHistory(reg_b)
    rng = random.Random(7)
    # 10 units/s of virtual time for 30 s.
    t_b = 0.0
    for t in range(30):
        c_a.inc(10)
        hist_a.sample(float(t))
    while t_b < 29.0:
        step = rng.uniform(0.2, 1.8)
        t_b = min(29.0, t_b + step)
        c_b.inc(10 * step)
        hist_b.sample(t_b)
    rate_a = hist_a.rate("t_jit_total", 20.0, now=29.0)
    rate_b = hist_b.rate("t_jit_total", 20.0, now=29.0)
    assert rate_a == pytest.approx(10.0, rel=0.1)
    assert rate_b == pytest.approx(10.0, rel=0.1)


def test_history_snapshot_is_bounded():
    registry = MetricsRegistry()
    gauge = registry.gauge("t_snap", "", labelnames=("key",))
    history = MetricsHistory(registry)
    for t in range(64):
        for k in range(10):
            gauge.set(float(t), key=f"k{k}")
        history.sample(float(t))
    snap = history.snapshot(max_series=4, samples_per_series=5)
    assert len(snap) == 4
    for row in snap:
        assert len(row["points"]) <= 5
        assert set(row) == {"metric", "kind", "labels", "points"}
    named = history.snapshot(names=["no_such_metric"])
    assert named == []


# ---------------------------------------------------------------------------
# SLOSpec validation + burn math
# ---------------------------------------------------------------------------


def test_slospec_validation_and_window_scaling():
    with pytest.raises(ValueError):
        SLOSpec(name="Bad Name", kind="ratio", objective=0.9)
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="nope", objective=0.9)
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="ratio", objective=1.5)
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="threshold", objective=0.9,
                bad_when="sideways")
    spec = serving_latency_slo(20.0, compliance_window_s=7200.0)
    windows = spec.windows()
    assert set(windows) == set(WINDOWS)
    # 7200/8640 < min_window_s -> clamped to 5; the rest scale.
    assert windows["fast_short"] == pytest.approx(5.0)
    assert windows["fast_long"] == pytest.approx(10.0)
    assert windows["slow_long"] == pytest.approx(60.0)
    # Windows never exceed the compliance window itself.
    tiny = serving_latency_slo(20.0, compliance_window_s=3.0)
    assert all(w <= 3.0 for w in tiny.windows().values())
    assert spec.budget() == pytest.approx(0.01)


def test_ratio_slo_burn_rate_math(journal_file):
    registry = MetricsRegistry()
    reqs = registry.counter(
        "elasticdl_serving_requests_total", "", labelnames=("outcome",)
    )
    plane = SLOPlane(
        registry=registry,
        specs=[serving_availability_slo(0.9, compliance_window_s=7200.0)],
        origin="t",
    )
    # Steady 10% drop rate = burning the budget at exactly 1.0x.
    for tick in range(80):
        reqs.inc(9, outcome="served")
        reqs.inc(1, outcome="dropped")
        plane.tick(float(tick))
    (status,) = plane.slos.statuses()
    for window in WINDOWS:
        assert status["burn_rates"][window] == pytest.approx(1.0, abs=0.05)
    assert not status["alerting"]
    assert not plane.slos.alerting()
    # bad_fraction is the fraction over observed samples: a steady
    # burn of exactly 1.0 reads as the budget fully committed.
    assert status["bad_fraction"] == pytest.approx(0.1, abs=0.01)
    assert status["budget_remaining_ratio"] == pytest.approx(0.0, abs=0.05)


def test_ratio_slo_pages_and_attributes_offender(journal_file, tmp_path):
    registry = MetricsRegistry()
    reqs = registry.counter(
        "elasticdl_serving_requests_total", "", labelnames=("outcome",)
    )
    plane = SLOPlane(
        registry=registry,
        specs=[serving_availability_slo(0.99, compliance_window_s=7200.0)],
        origin="t",
    )
    fired_at = None
    for tick in range(30):
        reqs.inc(5, outcome="served")
        if tick >= 10:
            reqs.inc(5, outcome="shed")  # 50% bad -> burn 50x the budget
        edges = plane.tick(float(tick))
        if edges and fired_at is None:
            fired_at = tick
            (edge,) = edges
            assert edge["state"] == "fire"
            # The slow pair (lower threshold) can trip a tick before the
            # fast pair; the edge is binary — no re-fire on escalation.
            assert edge["grade"] in ("warn", "page")
            # Attribution points at the worst non-good series.
            assert edge["offending"] == (
                "elasticdl_serving_requests_total{outcome=shed}"
            )
    assert fired_at is not None and fired_at <= 25
    # The live grade escalates to page once both fast windows are over.
    assert plane.slos.alerting() == {"serving_availability": "page"}


def test_threshold_slo_no_data_is_not_a_breach(journal_file):
    registry = MetricsRegistry()  # the latency gauge never registers
    plane = SLOPlane(
        registry=registry,
        specs=[serving_latency_slo(20.0, compliance_window_s=7200.0)],
        origin="t",
    )
    for tick in range(30):
        plane.tick(float(tick))
    (status,) = plane.slos.statuses()
    assert not status["alerting"]
    assert status["budget_remaining_ratio"] == 1.0
    assert all(b == 0.0 for b in status["burn_rates"].values())


def test_status_journaling_is_rate_limited(journal_file):
    registry = MetricsRegistry()
    gauge = registry.gauge("elasticdl_serving_latency_p99_ms", "")
    plane = SLOPlane(
        registry=registry,
        specs=[serving_latency_slo(20.0, compliance_window_s=7200.0)],
        status_interval_s=10.0,
        origin="t",
    )
    for tick in range(100):
        gauge.set(2.0)
        plane.tick(float(tick))
    statuses = [
        e for e in _events(journal_file) if e["event"] == "slo_status"
    ]
    # 100 one-second ticks at a 10s status interval: ~10 rows, not 100.
    assert 9 <= len(statuses) <= 11
    for status in statuses:
        assert status["slo"] == "serving_latency"
        assert "budget_remaining_ratio" in status
        assert status["origin"] == "t"


def test_duplicate_spec_name_rejected():
    registry = MetricsRegistry()
    plane = SLOPlane(registry=registry, specs=[serving_latency_slo(20.0)])
    with pytest.raises(ValueError):
        plane.slos.add(serving_latency_slo(10.0))


# ---------------------------------------------------------------------------
# Policy advisory input + journal-tail follower
# ---------------------------------------------------------------------------

FIRE_EVIDENCE = {
    "grade": "page",
    "burn_rates": {"fast_short": 20.0, "fast_long": 16.0,
                   "slow_short": 16.0, "slow_long": 3.0},
    "budget_remaining_ratio": 0.41,
    "offending": "elasticdl_serving_latency_p99_ms",
    "origin": "replica_0",
}


def test_policy_note_slo_alert_advisory(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    engine = ElasticPolicyEngine(PolicyConfig(), clock=clock)
    engine.note_slo_alert("serving_latency", True, FIRE_EVIDENCE)
    assert "serving_latency" in engine.slo_alerts()
    clock.advance(60.0)
    engine.note_slo_alert("serving_latency", False, {"origin": "replica_0"})
    assert engine.slo_alerts() == {}
    decisions = [
        e for e in _events(journal_file) if e["event"] == "policy_decision"
    ]
    assert [d["reason"] for d in decisions] == [
        "slo_alert", "slo_alert_cleared",
    ]
    fire = decisions[0]
    assert fire["slo"] == "serving_latency"
    assert fire["grade"] == "page"
    assert fire["offending"] == "elasticdl_serving_latency_p99_ms"
    # The advisory set rides the decision evidence while fired.
    assert fire["slo_advisory"] == ["serving_latency"]
    assert "slo_advisory" not in decisions[1]


def test_policy_drops_phantom_clear(journal_file, obs_registry_snapshot):
    engine = ElasticPolicyEngine(PolicyConfig(), clock=FakeClock())
    # A follower replaying an old journal tail sends a clear for an SLO
    # this engine never saw fire: no state change, no journal event.
    engine.note_slo_alert("never_fired", False, {})
    assert engine.slo_alerts() == {}
    assert [
        e for e in _events(journal_file) if e["event"] == "policy_decision"
    ] == []


class _RecordingPolicy:
    def __init__(self):
        self.calls = []

    def note_slo_alert(self, slo, alerting, evidence=None):
        self.calls.append((slo, alerting, dict(evidence or {})))


def test_slo_alert_follower_forwards_each_edge_once(journal_file):
    journal = obs.journal()
    journal.record("serving_replica_start", replica_id=0, port=1)
    journal.record("slo_alert", slo="serving_latency", state="fire",
                   **FIRE_EVIDENCE)
    journal.record("slo_alert", slo="serving_latency", state="clear",
                   grade="page", origin="replica_0")
    policy = _RecordingPolicy()
    follower = SLOAlertFollower(policy, journal=journal)
    assert follower.poll_once() == 2
    # Re-polling the same tail forwards nothing new.
    assert follower.poll_once() == 0
    journal.record("slo_alert", slo="serving_availability", state="fire",
                   grade="warn", origin="replica_1")
    assert follower.poll_once() == 1
    assert [(c[0], c[1]) for c in policy.calls] == [
        ("serving_latency", True),
        ("serving_latency", False),
        ("serving_availability", True),
    ]
    assert policy.calls[0][2]["grade"] == "page"
    assert policy.calls[0][2]["origin"] == "replica_0"


def test_slo_alert_follower_survives_policy_exception(journal_file):
    journal = obs.journal()
    journal.record("slo_alert", slo="a_slo", state="fire", origin="r")
    journal.record("slo_alert", slo="b_slo", state="fire", origin="r")

    class ExplodingPolicy:
        def __init__(self):
            self.seen = []

        def note_slo_alert(self, slo, alerting, evidence=None):
            self.seen.append(slo)
            if slo == "a_slo":
                raise RuntimeError("boom")

    policy = ExplodingPolicy()
    follower = SLOAlertFollower(policy, journal=journal)
    # The a_slo failure must not starve b_slo's forward.
    follower.poll_once()
    assert policy.seen == ["a_slo", "b_slo"]


# ---------------------------------------------------------------------------
# /slo endpoint + obs.top rendering
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as response:
        return response.status, response.read()


def test_exporter_slo_endpoint_without_plane(
    journal_file, obs_registry_snapshot
):
    exporter = MetricsExporter(port=0).start()
    try:
        status, body = _get(f"http://127.0.0.1:{exporter.port}/slo")
        assert status == 200
        payload = json.loads(body)
        # Old masters / workers: empty statuses, never an error — and
        # obs.top renders no SLO row from this.
        assert payload["statuses"] == []
        assert top.slo_header(payload) == ""
        assert top.slo_sparkline_notes(payload) == []
    finally:
        exporter.stop()


def test_exporter_slo_endpoint_with_plane(tmp_path, journal_file,
                                          obs_registry_snapshot):
    registry = MetricsRegistry()
    gauge = registry.gauge("elasticdl_serving_latency_p99_ms", "")
    plane = SLOPlane(
        registry=registry,
        specs=[serving_latency_slo(20.0, compliance_window_s=7200.0)],
        origin="replica_0",
    )
    for tick in range(40):
        gauge.set(2.0)
        plane.tick(float(tick))
    exporter = MetricsExporter(port=0, slo_plane=plane).start()
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        status, body = _get(f"{base}/slo?n=5")
        assert status == 200
        payload = json.loads(body)
        assert payload["origin"] == "replica_0"
        assert payload["ticks"] == 40
        (row,) = payload["statuses"]
        assert row["slo"] == "serving_latency"
        assert len(row["sparkline"]) <= 5
        assert payload["series"]
        for series in payload["series"]:
            assert len(series["points"]) <= 5
        # Bounded and path-free: the payload never leaks the journal dir.
        assert str(tmp_path) not in body.decode()
        # ?n= is clamped to SLO_SAMPLES_MAX, not trusted.
        _, big = _get(f"{base}/slo?n=99999")
        for series in json.loads(big)["series"]:
            assert len(series["points"]) <= MetricsExporter.SLO_SAMPLES_MAX
        # HEAD answers headers-only (probes HEAD before they GET).
        request = urllib.request.Request(f"{base}/slo", method="HEAD")
        with urllib.request.urlopen(request, timeout=5) as response:
            assert response.status == 200
            assert response.read() == b""
        # 404 advertises the endpoint.
        try:
            _get(f"{base}/nope")
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
            assert b"/slo" in exc.read()
        # obs.top renders the header + sparkline from the live payload.
        fetched = top.fetch_slo(base)
        header = top.slo_header(fetched)
        assert header.startswith("slo: budget=100.0%")
        assert top.slo_sparkline_notes(fetched)[0].startswith(
            "slo serving_latency: "
        )
    finally:
        exporter.stop()


def test_top_slo_helpers_degrade():
    # Dead port: fetch_slo returns None, helpers return empty.
    assert top.fetch_slo("http://127.0.0.1:1", timeout_s=0.2) is None
    assert top.slo_header(None) == ""
    assert top.slo_sparkline_notes(None) == []
    assert top.slo_header({"statuses": "garbage"}) == ""
    assert top._spark([]) == ""
    assert top._spark([1.0, 1.0, 1.0]) == "▁▁▁"
    ramp = top._spark([0.0, 1.0, 2.0, 3.0])
    assert len(ramp) == 4 and ramp[0] == "▁" and ramp[-1] == "█"
    assert len(top._spark(list(range(100)), width=24)) == 24
    header = top.slo_header({
        "statuses": [
            {"slo": "serving_latency", "budget_remaining_ratio": 0.41,
             "burn_rates": {"fast_short": 20.0}, "alerting": True,
             "grade": "page"},
        ]
    })
    assert "budget=41.0%" in header
    assert "worst_burn=20.0x(serving_latency@fast_short)" in header
    assert "ALERT[serving_latency:page]" in header


def test_top_frame_renders_against_master_without_slo_plane(
    journal_file, obs_registry_snapshot
):
    """An old master (no /slo wired) must still render a full frame."""
    obs.journal().record("master_start", job_name="t", port=1)
    exporter = MetricsExporter(port=0).start()
    try:
        frame = top.snapshot_frame(f"127.0.0.1:{exporter.port}")
        assert frame.startswith("elasticdl top")
        assert "slo:" not in frame and "slo " not in frame
    finally:
        exporter.stop()


# ---------------------------------------------------------------------------
# obs.report error-budget section
# ---------------------------------------------------------------------------


def test_report_error_budget_section_over_golden():
    summary = report_mod.summarize(report_mod.load_events(GOLDEN))
    slo = summary["slo"]
    assert slo["status_updates"] == 2
    (breach,) = slo["breaches"]
    assert breach["slo"] == "serving_latency"
    assert breach["origin"] == "replica_0"
    assert breach["grade"] == "page"
    assert breach["seconds"] == pytest.approx(5.0)
    assert breach["cleared_ts"] is not None
    assert breach["offending"] == "elasticdl_serving_latency_p99_ms"
    # Attribution: the shed inside the breach window and the phase the
    # job was in while the budget burned.
    assert breach["shed_reasons"] == {"queue_full": 1}
    assert breach["dominant_goodput_phase"] == "training"
    (entry,) = slo["slos"]
    assert entry["min_budget_remaining_ratio"] == pytest.approx(0.39)
    text = report_mod.render_report(summary)
    assert "error budget (SLO plane): 2 status update(s), 1 breach(es)" \
        in text
    assert "page  serving_latency@replica_0 for 5.0s" in text
    assert "shed: queue_full x1" in text
    assert "during training" in text


def test_report_no_slo_events_no_section(tmp_path):
    events = [
        e for e in report_mod.load_events(GOLDEN)
        if e["event"] not in ("slo_status", "slo_alert")
    ]
    summary = report_mod.summarize(events)
    assert "slo" not in summary
    assert "error budget" not in report_mod.render_report(summary)


def test_report_open_breach_and_orphan_clear(tmp_path):
    path = tmp_path / "events.jsonl"
    rows = [
        {"ts": 10.0, "event": "master_start", "job_name": "t"},
        # Orphan clear (head-truncated journal): skipped, not a breach.
        {"ts": 11.0, "event": "slo_alert", "slo": "goodput",
         "state": "clear", "origin": "master"},
        {"ts": 12.0, "event": "slo_alert", "slo": "serving_latency",
         "state": "fire", "grade": "warn", "origin": "replica_1"},
        {"ts": 20.0, "event": "job_failed", "reason": "x"},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    summary = report_mod.summarize(report_mod.load_events(str(path)))
    slo = summary["slo"]
    (breach,) = slo["breaches"]
    assert breach["cleared_ts"] is None
    assert slo["open_breaches"] == 1
    # Open breaches extend to the journal's end.
    assert breach["seconds"] == pytest.approx(8.0)
    assert "OPEN at journal end" in report_mod.render_report(summary)


# ---------------------------------------------------------------------------
# Journal schema rows
# ---------------------------------------------------------------------------


def test_validator_accepts_and_rejects_slo_rows(tmp_path):
    validator = _load_validator()
    good = tmp_path / "good.jsonl"
    good.write_text(
        json.dumps({
            "ts": 1.0, "event": "slo_status", "slo": "serving_latency",
            "budget_remaining_ratio": 0.5,
            "burn_rates": {"fast_short": 1.0}, "origin": "replica_0",
        }) + "\n" + json.dumps({
            "ts": 2.0, "event": "slo_alert", "slo": "serving_latency",
            "state": "fire", "grade": "page", "origin": "replica_0",
        }) + "\n"
    )
    assert validator.validate_file(str(good)) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"ts": 1.0, "event": "slo_status", "slo": "x"}) + "\n"
        + json.dumps({"ts": 2.0, "event": "slo_alert", "slo": "x"}) + "\n"
        + json.dumps({"ts": 3.0, "event": "slo_alert", "state": "fire"})
        + "\n"
    )
    problems = validator.validate_file(str(bad))
    assert len(problems) == 3


# ---------------------------------------------------------------------------
# Acceptance e2e: 2-replica fleet, latency regression, policy advisory,
# error-budget replay — and the no-fault control
# ---------------------------------------------------------------------------

FAULT_START, FAULT_END, TOTAL_TICKS = 60, 120, 280
REQUESTS_PER_TICK = 100


def _run_fleet(tmp_path, fault: bool):
    """Two in-process 'replicas' (private registry + real
    AvailabilityLedger + SLOPlane each) sharing one journal, a
    deterministic loadgen, and the supervisor-side follower wired to a
    real policy engine — the whole sensor->policy loop on a virtual
    clock."""
    journal_path = obs.init_journal(str(tmp_path))
    clock = FakeClock(t=0.0)
    engine = ElasticPolicyEngine(PolicyConfig(), clock=clock)
    follower = SLOAlertFollower(engine, journal=obs.journal())
    rng = random.Random(4242)

    replicas = []
    for rid in range(2):
        registry = MetricsRegistry()
        ledger = AvailabilityLedger(clock=clock, registry=registry)
        plane = SLOPlane(
            registry=registry,
            specs=[
                serving_latency_slo(
                    20.0, objective=0.99, compliance_window_s=7200.0
                ),
                serving_availability_slo(
                    0.999, compliance_window_s=7200.0
                ),
            ],
            origin=f"replica_{rid}",
        )
        replicas.append((rid, ledger, plane))

    fired_tick = cleared_tick = None
    for tick in range(TOTAL_TICKS):
        clock.advance(1.0)
        in_fault = fault and FAULT_START <= tick < FAULT_END
        for rid, ledger, plane in replicas:
            for _ in range(REQUESTS_PER_TICK):
                latency = 0.002 + rng.random() * 0.0005
                if in_fault and rid == 0:
                    latency = 0.05 + rng.random() * 0.01
                ledger.record_request({"execute": latency}, "served")
            if in_fault and rid == 0 and tick % 10 == 0:
                # The regression also backs the queue up: a shed lands
                # in the shared journal for breach attribution.
                ledger.record_shed(rows=8)
                obs.journal().record(
                    "request_shed", reason="queue_full",
                    queue_depth=256, queue_limit=256, rows=8,
                )
            plane.tick(float(tick))
        follower.poll_once()
        alerts = engine.slo_alerts()
        if fired_tick is None and alerts:
            fired_tick = tick
        if fired_tick is not None and cleared_tick is None \
                and tick >= FAULT_END and not alerts:
            cleared_tick = tick
    return journal_path, engine, fired_tick, cleared_tick


@pytest.mark.slow
def test_slo_e2e_fleet_latency_regression_pages_and_clears(
    tmp_path, obs_registry_snapshot
):
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()
    try:
        journal_path, engine, fired_tick, cleared_tick = _run_fleet(
            fleet_dir, fault=True
        )
        # Fast-window reaction: paged within 20 ticks of fault onset.
        assert fired_tick is not None
        assert FAULT_START < fired_tick <= FAULT_START + 20, fired_tick
        # ... and cleared after the fault window drained through the
        # ledger's sliding percentile + the slow burn windows.
        assert cleared_tick is not None, "alert never cleared"
        assert engine.slo_alerts() == {}

        events = _events(journal_path)
        alerts = [e for e in events if e["event"] == "slo_alert"]
        assert [a["state"] for a in alerts] == ["fire", "clear"]
        assert all(a["origin"] == "replica_0" for a in alerts)
        assert alerts[0]["grade"] == "page"
        assert alerts[0]["offending"] == "elasticdl_serving_latency_p99_ms"
        # Only the faulted replica's latency SLO fired — availability
        # stayed green on both replicas, latency stayed green on 1.
        assert {a["slo"] for a in alerts} == {"serving_latency"}

        # The sensor->policy edge: the follower's forward journaled
        # advisory policy decisions carrying the SLO evidence.
        decisions = [
            e for e in events if e["event"] == "policy_decision"
        ]
        fires = [d for d in decisions if d.get("reason") == "slo_alert"]
        assert fires and fires[0]["slo"] == "serving_latency"
        assert fires[0]["slo_advisory"] == ["serving_latency"]
        assert fires[0]["origin"] == "replica_0"
        assert any(
            d.get("reason") == "slo_alert_cleared" for d in decisions
        )

        # The journal schema-validates end to end.
        validator = _load_validator()
        assert validator.validate_file(journal_path) == []

        # obs.report reconstructs the error-budget timeline with
        # attribution from the same journal.
        summary = report_mod.summarize(report_mod.load_events(journal_path))
        slo = summary["slo"]
        (breach,) = slo["breaches"]
        assert breach["slo"] == "serving_latency"
        assert breach["origin"] == "replica_0"
        assert breach["grade"] == "page"
        assert breach["cleared_ts"] is not None
        assert breach["cleared_ts"] >= breach["fired_ts"]
        assert breach["shed_reasons"]["queue_full"] >= 1
        assert slo["open_breaches"] == 0
        text = report_mod.render_report(summary)
        assert "error budget (SLO plane)" in text
        assert "serving_latency@replica_0" in text
    finally:
        obs.journal().configure(None)

    # Control: identical fleet and loadgen, no fault — zero alerts.
    control_dir = tmp_path / "control"
    control_dir.mkdir()
    try:
        journal_path, engine, fired_tick, _cleared = _run_fleet(
            control_dir, fault=False
        )
        assert fired_tick is None
        assert engine.slo_alerts() == {}
        events = _events(journal_path)
        assert [e for e in events if e["event"] == "slo_alert"] == []
        assert [
            e for e in events if e["event"] == "policy_decision"
        ] == []
        # Statuses still flowed (the sensors ran; they just saw green).
        assert [e for e in events if e["event"] == "slo_status"]
    finally:
        obs.journal().configure(None)
