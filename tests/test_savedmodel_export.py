"""TF SavedModel converter (scripts/export_savedmodel.py): the native
serving artifact re-exported for a TF-Serving fleet must predict
identically to the native path — the test_serving parity case re-run
through TF (docs/design.md "Serving artifact" converter recipe;
reference deployment path †common/model_handler.py -> SavedModel)."""

import os
import sys

import numpy as np
import pytest

from elasticdl_tpu.serving import export_model

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
)

tf = pytest.importorskip("tensorflow")


def test_savedmodel_matches_native_serving(tmp_path):
    from export_savedmodel import convert
    from tests.test_serving import _trained_deepfm

    zoo, trainer, batches = _trained_deepfm()
    artifact = str(tmp_path / "artifact")
    export_model(
        trainer,
        artifact,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    out_dir = str(tmp_path / "savedmodel")
    # convert() itself asserts SavedModel-vs-native parity on its traced
    # example batch before returning.
    convert(artifact, out_dir, model_zoo="model_zoo", batch=4)

    # Independent check on REAL trained-data features, against the
    # trainer's own eval outputs, through the reloaded SavedModel.
    reloaded = tf.saved_model.load(out_dir)
    feats, _ = batches[0]
    got = reloaded.signatures["serving_default"](
        dense=tf.constant(np.asarray(feats["dense"])),
        cat=tf.constant(np.asarray(feats["cat"])),
    )["outputs"].numpy()
    expected = np.asarray(trainer.eval_step(feats))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    # Batch-polymorphic signature: a different batch size serves too.
    half = {k: np.asarray(v)[:8] for k, v in feats.items()}
    got_half = reloaded.signatures["serving_default"](
        dense=tf.constant(half["dense"]), cat=tf.constant(half["cat"])
    )["outputs"].numpy()
    np.testing.assert_allclose(got_half, got[:8], rtol=1e-5, atol=1e-5)
