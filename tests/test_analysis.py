"""Unit tests for the invariant analyzer (elasticdl_tpu.analysis).

One must-pass + must-fail fixture pair per rule (control-plane rules in
rules.py AND the flow-aware hot-path family in jax_rules.py), the
inline-suppression contract, the JSON/baseline CLI surface, and the
repo-level acceptance gates:

- the production tree (elasticdl_tpu/ + model_zoo/) is invariant-clean
  (`python -m elasticdl_tpu.analysis` exits 0) — this test IS the
  tier-1 wiring of `make check-invariants`;
- a seeded violation of every registered rule makes the CLI exit
  non-zero;
- tracedness is transitive: a helper called only from a jitted fn is
  flagged for a planted host sync.
"""

import textwrap

from elasticdl_tpu.analysis.__main__ import main as analysis_main
from elasticdl_tpu.analysis.core import SourceFile, run_checks
from elasticdl_tpu.analysis.rules import ALL_RULES, RULE_NAMES


def violations(text, rule, path="fixture.py"):
    source = SourceFile.parse(path, textwrap.dedent(text))
    found = [
        v
        for v in ALL_RULES[rule](source)
        if not source.suppressed(v.rule, v.line)
    ]
    assert all(v.rule == rule for v in found)
    return found


# ---------------------------------------------------------------------------
# rpc-deadline
# ---------------------------------------------------------------------------


def test_rpc_deadline_flags_raw_stub_call():
    found = violations(
        """
        def f(self, req):
            return self._stub.get_task(req)
        """,
        "rpc-deadline",
    )
    assert len(found) == 1 and "timeout" in found[0].message


def test_rpc_deadline_flags_getattr_dispatch():
    found = violations(
        """
        def f(stub, method, req):
            return getattr(stub, method)(req)
        """,
        "rpc-deadline",
    )
    assert len(found) == 1


def test_rpc_deadline_accepts_explicit_timeout_and_wrappers():
    found = violations(
        """
        def f(self, req):
            self._stub.get_task(req, timeout=10.0)
            return call_with_retry(
                getattr(self._stub, "get_task"), req,
                method="get_task", policy=IDEMPOTENT_POLICY,
            )
        """,
        "rpc-deadline",
    )
    assert found == []


# ---------------------------------------------------------------------------
# idempotency
# ---------------------------------------------------------------------------


def test_idempotency_flags_retried_result_report():
    found = violations(
        """
        def f(self, req):
            self._call_idempotent("report_task_result", req)
        """,
        "idempotency",
    )
    assert len(found) == 1 and "report_task_result" in found[0].message


def test_idempotency_flags_call_with_retry_on_eval_report():
    found = violations(
        """
        def f(fn, req):
            call_with_retry(fn, req, "report_evaluation_metrics",
                            IDEMPOTENT_POLICY)
        """,
        "idempotency",
    )
    assert len(found) == 1


def test_idempotency_accepts_no_retry_policies():
    found = violations(
        """
        def f(self, fn, req):
            call_with_retry(fn, req, "report_task_result",
                            NON_IDEMPOTENT_POLICY)
            call_with_retry(fn, req, "report_task_result",
                            self._no_retry_policy)
            call_with_retry(fn, req, "report_task_result",
                            RetryPolicy(max_attempts=1))
            self._call_idempotent("get_task", req)
        """,
        "idempotency",
    )
    assert found == []


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_determinism_flags_wall_clock_and_unseeded_rng():
    found = violations(
        """
        # deterministic-replay-path
        import random, time, datetime

        def f():
            a = time.time()
            b = random.random()
            c = datetime.now()
            d = random.Random()
            return a, b, c, d
        """,
        "determinism",
    )
    assert len(found) == 4


def test_determinism_accepts_monotonic_and_seeded_rng():
    found = violations(
        """
        # deterministic-replay-path
        import random, time

        def f(seed):
            a = time.monotonic()
            b = random.Random(seed).random()
            time.sleep(0.1)
            return a, b
        """,
        "determinism",
    )
    assert found == []


def test_determinism_applies_by_path_suffix():
    text = "import time\nx = time.time()\n"
    assert violations(text, "determinism",
                      path="elasticdl_tpu/common/faults.py")
    assert not violations(text, "determinism", path="somewhere_else.py")


def test_determinism_allows_seeded_rng_reads_inside_backoff():
    # The real backoff jitter pattern from grpc_utils must stay legal.
    found = violations(
        """
        # deterministic-replay-path
        import random

        def backoff(salt, method, attempt):
            return random.Random(f"{salt}:{method}:{attempt}").random()
        """,
        "determinism",
    )
    assert found == []


# ---------------------------------------------------------------------------
# thread-hygiene
# ---------------------------------------------------------------------------


def test_thread_hygiene_flags_missing_name_and_daemon():
    found = violations(
        """
        import threading

        def f(target):
            threading.Thread(target=target)
            threading.Thread(target=target, daemon=True)
            threading.Thread(target=target, name="ok")
        """,
        "thread-hygiene",
    )
    assert len(found) == 3
    assert "name, daemon" in found[0].message


def test_thread_hygiene_accepts_named_daemon_threads():
    found = violations(
        """
        import threading
        from threading import Thread

        def f(target):
            threading.Thread(target=target, name="w", daemon=True)
            Thread(target=target, name="w2", daemon=False)
        """,
        "thread-hygiene",
    )
    assert found == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = """
import threading


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._free = 0  # no annotation: unguarded

    def good(self):
        with self._lock:
            self._items.append(1)
            self._count += 1
        self._free = 9

    def good_via_locked_helper(self):
        with self._lock:
            self._refill_locked()

    def _refill_locked(self):
        self._items.extend([1, 2])
        self._items[0] = 3

    def bad_assign(self):
        self._count = 5

    def bad_mutator(self):
        self._items.append(1)

    def bad_subscript(self):
        self._items[0] = 1

    def bad_nested_thread_target(self):
        with self._lock:
            def target():
                self._items.pop()  # lock NOT held when target() runs
            return target
"""


def test_lock_discipline_flags_off_lock_mutations_only():
    found = violations(_LOCKED_CLASS, "lock-discipline")
    lines = {v.line for v in found}
    bad_methods = {"bad_assign", "bad_mutator", "bad_subscript"}
    assert len(found) == 4  # three bad_* methods + the nested closure
    assert all(
        any(m in v.message for m in bad_methods | {"bad_nested_thread_target"})
        for v in found
    )
    assert lines  # every violation is anchored to a line


def test_lock_discipline_dataclass_fields_and_named_locks():
    found = violations(
        """
        import threading
        from dataclasses import dataclass, field


        @dataclass
        class Stats:
            calls: int = 0  # guarded-by: _meta_lock
            _meta_lock: threading.Lock = field(default_factory=threading.Lock)

            def good(self):
                with self._meta_lock:
                    self.calls += 1

            def bad(self):
                self.calls += 1

            def wrong_lock(self):
                with self._other:
                    self.calls += 1
        """,
        "lock-discipline",
    )
    assert len(found) == 2
    assert all("_meta_lock" in v.message for v in found)


def test_lock_discipline_standalone_block_for_inherited_fields():
    found = violations(
        """
        class Sub(Base):
            def __init__(self):
                super().__init__()
                # guarded-by: _lock: _handles, _size

            def bad(self):
                self._size = 3

            def good(self):
                with self._lock:
                    self._handles = []
        """,
        "lock-discipline",
    )
    assert len(found) == 1 and "_size" in found[0].message


# ---------------------------------------------------------------------------
# metric-label-cardinality
# ---------------------------------------------------------------------------


def test_metric_cardinality_flags_unbounded_labelnames():
    found = violations(
        """
        def f(obs):
            obs.counter("t_total", "h", labelnames=("task_id", "type"))
            obs.histogram("d_seconds", "h", labelnames=["pod_name"])
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 2
    assert "task_id" in found[0].message and "journal" in found[0].message


def test_metric_cardinality_flags_unbounded_label_kwargs():
    found = violations(
        """
        def f(metric, task, pod):
            metric.inc(task_id=task.id)
            metric.labels(worker_id=3).observe(0.1)
            metric.set(1.0, host=pod.ip)
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 3


def test_metric_cardinality_flags_dynamic_metric_names():
    found = violations(
        """
        def f(obs, task):
            obs.counter(f"task_{task.id}_total", "h")
            obs.gauge("prefix_" + task.name, "h")
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 2
    assert "dynamic metric name" in found[0].message


def test_metric_cardinality_ignores_non_metric_lookalikes():
    """collections.Counter arithmetic and unrelated .counter()/.histogram()
    methods must not trip the rule — only registry-shaped receivers do."""
    found = violations(
        """
        import collections

        def f(a, b, dataframe, name):
            total = collections.Counter(a + b)
            dataframe.histogram(f"col_{name}")
            stats = a.counter("x" + name)
            return total, stats
        """,
        "metric-label-cardinality",
    )
    assert found == []


def test_metric_cardinality_accepts_bounded_labels_and_journal_fields():
    found = violations(
        """
        def f(obs, journal, task):
            c = obs.counter(
                "elasticdl_task_requeues_total", "h",
                labelnames=("reason", "type"),
            )
            c.inc(reason="timeout", type="TRAINING")
            obs.histogram("d_seconds", "h", labelnames=("kind",))
            # Unbounded identifiers ride the JOURNAL, which is fine.
            journal.record("task_requeue", task_id=task.id, pod="w-3")
        """,
        "metric-label-cardinality",
    )
    assert found == []


# ---------------------------------------------------------------------------
# Hot-path rule family (jax_rules.py, on the traced.py dataflow core)
# ---------------------------------------------------------------------------


def test_host_sync_flags_syncs_under_trace():
    found = violations(
        """
        import jax
        import numpy as np

        @jax.jit
        def step(state, x):
            loss = float(x)
            np.asarray(state)
            print(loss)
            jax.device_get(x)
            return x.item()
        """,
        "jit-host-sync",
    )
    assert len(found) == 5
    assert any("jax.debug.print" in v.message for v in found)


def test_host_sync_is_transitive_through_helpers():
    """Acceptance: a helper called ONLY from a jitted fn is flagged for a
    planted host sync (tracedness is transitive, not per-line)."""
    found = violations(
        """
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(x):
            return helper(x)
        """,
        "jit-host-sync",
    )
    assert len(found) == 1 and "helper" in found[0].message


def test_host_sync_ignores_host_code_and_static_shape_math():
    """The same constructs are legal on the host side of the jit
    boundary, and shape arithmetic is legal UNDER it."""
    found = violations(
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x):
            b, d = x.shape
            n = int(np.prod(x.shape))
            scale = 1.0 / (d ** 0.5)
            jax.debug.print("n={n}", n=n)
            return jnp.sum(x) * scale

        def host_loop(step_fn, batches):
            for batch in batches:
                loss = step_fn(batch)
                print(float(np.asarray(loss).item()))
        """,
        "jit-host-sync",
    )
    assert found == []


def test_host_sync_sees_scan_body_and_lambda_roots():
    found = violations(
        """
        import jax

        def run(state, xs):
            def body(carry, x):
                carry.item()
                return carry, x
            return jax.lax.scan(body, state, xs)
        """,
        "jit-host-sync",
    )
    assert len(found) == 1


def test_pallas_kernel_bodies_are_traced():
    """Satellite of the fused-sparse-kernel PR: a pl.pallas_call kernel
    body IS traced code — host syncs and obs/lock calls inside it are
    flagged, including when the kernel arrives through
    functools.partial (the flash_attention / sparse_embedding idiom)."""
    found = violations(
        """
        import functools
        import numpy as np
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref, *, scale):
            v = x_ref[...]
            np.asarray(v)
            o_ref[...] = v * scale

        def launch(x):
            return pl.pallas_call(
                functools.partial(kernel, scale=2.0),
                out_shape=None,
            )(x)
        """,
        "jit-host-sync",
    )
    assert len(found) == 1 and "kernel" in found[0].message
    found = violations(
        """
        from jax.experimental import pallas as pl
        from elasticdl_tpu import obs

        def kernel(x_ref, o_ref):
            obs.journal().record("step", n=1)
            o_ref[...] = x_ref[...]

        def launch(x):
            return pl.pallas_call(kernel, out_shape=None)(x)
        """,
        "trace-purity",
    )
    assert len(found) == 1 and "obs-plane" in found[0].message


def test_pallas_index_map_lambdas_stay_host_scope():
    """Index-map lambdas inside BlockSpec/GridSpec run at trace SETUP
    on the host — shape math, numpy, and mutable captures there are
    legal and must not false-positive, even when the grid spec rides a
    POSITIONAL pallas_call argument (the PrefetchScalarGridSpec
    idiom)."""
    fixture = """
        import numpy as np
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(ids_ref, x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x, offsets):
            starts = [int(np.asarray(o)) for o in offsets]
            return pl.pallas_call(
                kernel,
                pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(4,),
                    in_specs=[
                        pl.BlockSpec(
                            (8, 128),
                            lambda i, p: (starts[0] + np.int32(0), 0),
                        ),
                    ],
                    out_specs=pl.BlockSpec((8, 128), lambda i, p: (i, 0)),
                ),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """
    assert violations(fixture, "retrace-hazard") == []
    assert violations(fixture, "jit-host-sync") == []


def test_retrace_hazard_flags_jit_in_loop_and_per_step_method():
    found = violations(
        """
        import jax

        def run(fn, xs):
            for x in xs:
                jax.jit(fn)(x)

        class T:
            def train_step(self, state, x):
                return jax.jit(self._impl)(state, x)

            def _impl(self, state, x):
                return state
        """,
        "retrace-hazard",
    )
    assert len(found) == 2
    assert any("loop" in v.message for v in found)
    assert any("train_step" in v.message for v in found)


def test_retrace_hazard_flags_unhashable_static_and_mutable_closure():
    found = violations(
        """
        import jax

        def f(x, opts=[]):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def make(xs):
            stats = []

            @jax.jit
            def step(x):
                stats.append(1)
                return x

            return step
        """,
        "retrace-hazard",
    )
    assert len(found) == 2
    assert any("opts" in v.message for v in found)
    assert any("stats" in v.message for v in found)


def test_retrace_hazard_accepts_compile_time_construction():
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._compile_steps()

            def _compile_steps(self):
                self._train_step = jax.jit(
                    self._impl, donate_argnums=(0,)
                )

            def _impl(self, state, x):
                return state
        """,
        "retrace-hazard",
    )
    assert found == []


def test_donation_flags_train_step_without_donation():
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(self._train_step_impl)

            def _train_step_impl(self, state, batch):
                return state
        """,
        "donation-discipline",
    )
    assert len(found) == 1 and "donate" in found[0].message


def test_donation_flags_use_after_donating_call():
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(
                    self._train_step_impl, donate_argnums=(0,)
                )

            def _train_step_impl(self, state, batch):
                return state, 0.0

            def run(self, state, batch):
                new_state, loss = self._train_step(state, batch)
                return state
        """,
        "donation-discipline",
    )
    assert len(found) == 1 and "donated" in found[0].message


def test_donation_accepts_donating_steps_and_undonated_eval():
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(
                    self._train_step_impl, donate_argnums=(0,)
                )
                self._eval_step = jax.jit(self._eval_step_impl)

            def _train_step_impl(self, state, batch):
                return state, 0.0

            def _eval_step_impl(self, state, batch):
                return batch

            def run(self, state, batch):
                state, loss = self._train_step(state, batch)
                return self._eval_step(state, batch)
        """,
        "donation-discipline",
    )
    assert found == []


def test_async_staging_flags_buffer_read_before_donating_dispatch():
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(
                    self._train_step_impl, donate_argnums=(0,)
                )

            def _train_step_impl(self, staged, rows):
                return staged

            def run(self, staging, batch):
                staged = staging.stage_batch(batch)
                rows = len(batch)
                return self._train_step(staged, rows)
        """,
        "async-staging-discipline",
    )
    assert len(found) == 1
    assert "batch" in found[0].message and "reclamation" in found[0].message


def test_async_staging_accepts_undonated_result_and_rebind():
    # Staged result feeds a NON-donated position (the repo's own
    # `len(pending)` after `stage_window(pending)` shape) — the buffer
    # stays live, bookkeeping reads are fine.
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(
                    self._train_step_impl, donate_argnums=(0,)
                )

            def _train_step_impl(self, state, window):
                return state, 0.0

            def run(self, staging, state, pending):
                window = staging.stage_window(pending)
                count = len(pending)
                state, loss = self._train_step(state, window)
                return count
        """,
        "async-staging-discipline",
    )
    assert found == []
    # A re-bind of the buffer name between stage and dispatch kills the
    # hazard (the read would see the new binding, not the donated one).
    found = violations(
        """
        import jax

        class T:
            def __init__(self):
                self._train_step = jax.jit(
                    self._train_step_impl, donate_argnums=(0,)
                )

            def _train_step_impl(self, staged, rows):
                return staged

            def run(self, staging, batch):
                staged = staging.stage_batch(batch)
                batch = self._next()
                return self._train_step(staged, len(batch))
        """,
        "async-staging-discipline",
    )
    assert found == []


def test_trace_purity_flags_obs_io_and_locks_under_trace():
    found = violations(
        """
        import jax

        @jax.jit
        def step(x, journal, registry):
            journal.record("step", loss=x)
            registry.counter("steps_total", "h").inc()
            with STEP_LOCK:
                y = x + 1
            open("/tmp/trace.log")
            return y
        """,
        "trace-purity",
    )
    assert len(found) == 4
    assert any("journal" in v.message for v in found)
    assert any("STEP_LOCK" in v.message for v in found)


def test_trace_purity_accepts_host_side_obs():
    found = violations(
        """
        import jax

        @jax.jit
        def step(state, x):
            return state, x

        def host_loop(journal, lock, state, batches):
            for batch in batches:
                state, loss = step(state, batch)
                with lock:
                    journal.record("step", loss=float(loss))
        """,
        "trace-purity",
    )
    assert found == []


def test_sharding_coverage_gates_marked_multi_device_files():
    text = """
    # multi-device-path
    import jax

    def compile_steps(impl, shardings):
        bare = jax.jit(impl)
        good = jax.jit(
            impl, in_shardings=shardings, out_shardings=shardings
        )
        with mesh:
            contextual = jax.jit(impl)
        return bare, good, contextual
    """
    found = violations(text, "sharding-coverage")
    assert len(found) == 1 and "in_shardings" in found[0].message
    # Same file without the marker (and off parallel/): out of scope.
    clean = violations(text.replace("# multi-device-path", ""),
                       "sharding-coverage")
    assert clean == []


def test_sharding_coverage_applies_to_parallel_tree_by_path():
    text = "import jax\nstep = jax.jit(lambda x: x + 1)\n"
    assert violations(text, "sharding-coverage",
                      path="elasticdl_tpu/parallel/new_trainer.py")
    assert not violations(text, "sharding-coverage",
                          path="elasticdl_tpu/worker/new_trainer.py")


# ---------------------------------------------------------------------------
# Suppression
# ---------------------------------------------------------------------------


def test_noqa_invariant_suppresses_by_rule_and_star():
    found = violations(
        """
        import threading

        def f(target):
            threading.Thread(target=target)  # noqa-invariant: thread-hygiene
            threading.Thread(target=target)  # noqa-invariant: *
            threading.Thread(target=target)  # noqa-invariant: rpc-deadline
        """,
        "thread-hygiene",
    )
    assert len(found) == 1  # only the wrong-rule suppression still flags


def test_noqa_on_def_line_covers_decorator_line_violations():
    """A suppression on the `def` line also covers violations reported
    on its decorator lines (decorator-form jit sites anchor there)."""
    flagged = violations(
        """
        from functools import partial
        import jax

        @partial(jax.jit)
        def train_step(state, x):
            return state
        """,
        "donation-discipline",
    )
    assert len(flagged) == 1  # sanity: the fixture does violate
    suppressed = violations(
        """
        from functools import partial
        import jax

        @partial(jax.jit)
        def train_step(state, x):  # noqa-invariant: donation-discipline
            return state
        """,
        "donation-discipline",
    )
    assert suppressed == []


# ---------------------------------------------------------------------------
# Repo-level gates (this is the tier-1 wiring of `make check-invariants`)
# ---------------------------------------------------------------------------


def test_production_tree_is_invariant_clean(capsys):
    assert analysis_main([]) == 0
    assert "OK" in capsys.readouterr().out


def test_production_annotations_actually_engage():
    """Guard against the analyzer rotting into a no-op: the TaskManager
    must expose guarded fields the lock-discipline rule sees."""
    import ast

    from elasticdl_tpu.analysis.rules import _collect_guarded_fields
    from elasticdl_tpu.master import task_manager

    source = SourceFile.parse(task_manager.__file__)
    guarded = {}
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef) and node.name == "TaskManager":
            guarded = _collect_guarded_fields(source, node)
    assert "_todo" in guarded and guarded["_todo"] == "_lock"
    assert "_doing" in guarded


_SEEDED_VIOLATIONS = {
    "rpc-deadline": "def f(s, r):\n    return s._stub.get(r)\n",
    "idempotency": (
        "def f(s, r):\n"
        "    s._call_idempotent('report_task_result', r)\n"
    ),
    "determinism": (
        "# deterministic-replay-path\n"
        "import time\n"
        "def f():\n"
        "    return time.time()\n"
    ),
    "thread-hygiene": (
        "import threading\n"
        "def f(t):\n"
        "    threading.Thread(target=t)\n"
    ),
    "lock-discipline": (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0  # guarded-by: _lock\n"
        "    def bad(self):\n"
        "        self._x = 1\n"
    ),
    "metric-label-cardinality": (
        "def f(obs, task):\n"
        "    c = obs.counter('t_total', 'h', labelnames=('task_id',))\n"
        "    c.inc(task_id=task.id)\n"
    ),
    "jit-host-sync": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    print(x)\n"
        "    return x\n"
    ),
    "retrace-hazard": (
        "import jax\n"
        "def run(fn, xs):\n"
        "    for x in xs:\n"
        "        jax.jit(fn)(x)\n"
    ),
    "donation-discipline": (
        "import jax\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._train_step = jax.jit(self._train_step_impl)\n"
        "    def _train_step_impl(self, state, batch):\n"
        "        return state\n"
    ),
    "async-staging-discipline": (
        "import jax\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._train_step = jax.jit(\n"
        "            self._impl, donate_argnums=(0,)\n"
        "        )\n"
        "    def _impl(self, staged, rows):\n"
        "        return staged\n"
        "    def run(self, staging, batch):\n"
        "        staged = staging.stage_batch(batch)\n"
        "        rows = len(batch)\n"
        "        return self._train_step(staged, rows)\n"
    ),
    "trace-purity": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x, journal):\n"
        "    journal.record('step', loss=x)\n"
        "    return x\n"
    ),
    "sharding-coverage": (
        "# multi-device-path\n"
        "import jax\n"
        "step = jax.jit(lambda x: x + 1)\n"
    ),
    "drain-discipline": (
        "class Prefetcher:\n"
        "    def close(self):\n"
        "        pass\n"
        "def consume(it):\n"
        "    p = Prefetcher()\n"
        "    for _ in it:\n"
        "        pass\n"
    ),
    "blocking-under-lock": (
        "import threading\n"
        "import time\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0  # guarded-by: _lock\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            self._helper()\n"
        "    def _helper(self):\n"
        "        time.sleep(1)\n"
    ),
    "journal-schema": (
        "def f(journal):\n"
        "    journal.record('model_swap', generaton=2, step=4096)\n"
    ),
}


def test_cli_exits_nonzero_on_each_seeded_rule_violation(tmp_path, capsys):
    """Acceptance: `make check-invariants` fails on a violation of EACH
    registered rule."""
    assert set(_SEEDED_VIOLATIONS) == set(RULE_NAMES)
    for rule, text in _SEEDED_VIOLATIONS.items():
        bad = tmp_path / f"{rule.replace('-', '_')}.py"
        bad.write_text(text)
        rc = analysis_main([str(bad)])
        out = capsys.readouterr().out
        assert rc == 1, f"seeded {rule} violation not caught"
        assert f"[{rule}]" in out


def test_cli_rule_filter_and_listing(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(_SEEDED_VIOLATIONS["thread-hygiene"])
    assert analysis_main([str(bad), "--rule", "rpc-deadline"]) == 0
    assert analysis_main([str(bad), "--rule", "thread-hygiene"]) == 1
    capsys.readouterr()
    assert analysis_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in RULE_NAMES:
        assert rule in listed


def test_run_checks_reports_unparseable_files(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    found = run_checks([str(tmp_path)], ALL_RULES.values())
    assert len(found) == 1 and found[0].rule == "parse"


def test_cli_refuses_zero_file_scan(tmp_path, capsys):
    """An OK over zero scanned files would be a false green gate."""
    empty = tmp_path / "empty_dir"
    empty.mkdir()
    assert analysis_main([str(empty)]) == 2
    assert "no .py files" in capsys.readouterr().err


def test_run_checks_reports_undecodable_files(tmp_path):
    bad = tmp_path / "latin.py"
    bad.write_bytes(b"# caf\xe9\nx = 1\n")
    found = run_checks([str(tmp_path)], ALL_RULES.values())
    assert len(found) == 1 and found[0].rule == "parse"
    assert "could not read" in found[0].message


def test_list_rules_has_descriptions(capsys):
    assert analysis_main(["--list-rules"]) == 0
    for line in capsys.readouterr().out.strip().splitlines():
        rule, _, description = line.partition(":")
        assert description.strip(), f"rule {rule} listed without a description"


def test_default_scan_scope_includes_model_zoo():
    from elasticdl_tpu.analysis.__main__ import default_paths

    paths = default_paths()
    assert any(p.rstrip("/").endswith("elasticdl_tpu") for p in paths)
    assert any(p.rstrip("/").endswith("model_zoo") for p in paths)


# ---------------------------------------------------------------------------
# JSON output + baseline allowlist (incremental gating)
# ---------------------------------------------------------------------------


def _planted_host_sync(tmp_path):
    bad = tmp_path / "planted.py"
    bad.write_text(_SEEDED_VIOLATIONS["jit-host-sync"])
    return bad


def test_cli_json_format_is_machine_readable(tmp_path, capsys):
    import json

    bad = _planted_host_sync(tmp_path)
    rc = analysis_main([str(bad), "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["files_scanned"] == 1
    assert data["suppressed"] == 0
    assert set(data["rules"]) == set(RULE_NAMES)
    (finding,) = data["findings"]
    assert finding["rule"] == "jit-host-sync"
    assert finding["path"] == str(bad)
    assert finding["line"] == 4 and "message" in finding and "col" in finding


def test_cli_json_counts_noqa_suppressions(tmp_path, capsys):
    import json

    bad = tmp_path / "suppressed.py"
    bad.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    print(x)  # noqa-invariant: jit-host-sync\n"
        "    return x\n"
    )
    rc = analysis_main([str(bad), "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0 and data["findings"] == []
    assert data["suppressed"] == 1
    assert data["suppressed_by_rule"] == {"jit-host-sync": 1}


def test_cli_baseline_allowlists_known_findings(tmp_path, capsys):
    """A new rule gates incrementally: snapshot today's findings as the
    baseline, and only NEW findings fail the gate."""
    import json

    bad = _planted_host_sync(tmp_path)
    assert analysis_main([str(bad), "--format", "json"]) == 1
    baseline = tmp_path / "baseline.json"
    baseline.write_text(capsys.readouterr().out)  # the json IS the baseline

    assert analysis_main([str(bad), "--baseline", str(baseline)]) == 0
    capsys.readouterr()

    # A new violation not in the baseline still fails.
    bad.write_text(
        _SEEDED_VIOLATIONS["jit-host-sync"]
        + "\n\n@jax.jit\ndef step2(x):\n    return x.item()\n"
    )
    rc = analysis_main([str(bad), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1
    assert ".item()" in out and "print" not in out


def test_cli_baseline_unreadable_is_usage_error(tmp_path, capsys):
    missing = tmp_path / "nope.json"
    assert analysis_main(["--baseline", str(missing)]) == 2
    capsys.readouterr()
    garbage = tmp_path / "garbage.json"
    garbage.write_text("not json")
    assert analysis_main(["--baseline", str(garbage)]) == 2
    assert "baseline" in capsys.readouterr().err


def test_invariant_report_renders_per_rule_table(tmp_path, capsys):
    import json
    import sys

    sys.path.insert(0, "scripts")
    try:
        import invariant_report
    finally:
        sys.path.pop(0)

    bad = _planted_host_sync(tmp_path)
    analysis_main([str(bad), "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    table = invariant_report.render(data)
    lines = table.splitlines()
    assert lines[0].split() == ["rule", "findings", "suppressed"]
    row = next(l for l in lines if l.startswith("jit-host-sync"))
    assert row.split() == ["jit-host-sync", "1", "0"]
    assert any("1 files scanned" in l for l in lines)
    # Counts alone don't locate anything: the finding's path:line:col
    # text rides along so `make lint` output stays actionable.
    assert any(
        l.startswith(f"{bad}:4:") and "[jit-host-sync]" in l for l in lines
    )


def test_invariant_report_survives_missing_or_invalid_json(tmp_path, capsys):
    """The analyzer may exit 2 BEFORE writing JSON (usage error): the
    report chaser must not bury that one-line error under a traceback."""
    import sys

    sys.path.insert(0, "scripts")
    try:
        import invariant_report
    finally:
        sys.path.pop(0)

    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert invariant_report.main([str(empty)]) == 0
    assert "no findings JSON" in capsys.readouterr().out
    assert invariant_report.main([str(tmp_path / "missing.json")]) == 0


def test_cli_baseline_basename_entry_does_not_allowlist_other_dirs(tmp_path):
    """A bare-basename baseline entry ('trainer.py', no directory) must
    not suppress violations in every same-named file in the tree."""
    import json

    for sub in ("a", "b"):
        d = tmp_path / sub
        d.mkdir()
        (d / "trainer.py").write_text(_SEEDED_VIOLATIONS["jit-host-sync"])
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps(
        {"findings": [{"rule": "jit-host-sync", "path": "trainer.py"}]}
    ))
    assert analysis_main(
        [str(tmp_path), "--baseline", str(baseline)]
    ) == 1  # both violations survive the bare-basename entry
    # With the directory component the entry anchors to ONE file.
    baseline.write_text(json.dumps(
        {"findings": [{"rule": "jit-host-sync", "path": "a/trainer.py"}]}
    ))
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = analysis_main(
            [str(tmp_path), "--baseline", str(baseline), "--format", "json"]
        )
    data = json.loads(buf.getvalue())
    assert rc == 1 and len(data["findings"]) == 1
    assert data["findings"][0]["path"].endswith("b/trainer.py")
    assert data["suppressed"] == 1


# ---------------------------------------------------------------------------
# drain-discipline (protocol_rules.py): constructed resources reach
# teardown on every path
# ---------------------------------------------------------------------------

_PREFETCHER = """
class Prefetcher:
    def __init__(self):
        self._threads = []

    def close(self):
        pass

"""


def test_drain_discipline_flags_never_drained_resource():
    found = violations(
        _PREFETCHER
        + """
def consume(it):
    p = Prefetcher()
    for _ in it:
        pass
""",
        "drain-discipline",
    )
    assert len(found) == 1
    assert "close" in found[0].message
    assert "never reaches" in found[0].message


def test_drain_discipline_flags_straight_line_only_teardown():
    """close() after the loop body leaks on ANY exception in the loop —
    the replica_main.py bug class this rule exists for."""
    found = violations(
        _PREFETCHER
        + """
def consume(it):
    p = Prefetcher()
    for _ in it:
        pass
    p.close()
""",
        "drain-discipline",
    )
    assert len(found) == 1
    assert "straight-line" in found[0].message


def test_drain_discipline_accepts_try_finally():
    assert (
        violations(
            _PREFETCHER
            + """
def consume(it):
    p = Prefetcher()
    try:
        for _ in it:
            pass
    finally:
        p.close()
""",
            "drain-discipline",
        )
        == []
    )


def test_drain_discipline_accepts_with_use():
    assert (
        violations(
            _PREFETCHER
            + """
def consume(it):
    p = Prefetcher()
    with p:
        pass
""",
            "drain-discipline",
        )
        == []
    )


def test_drain_discipline_accepts_ownership_transfer():
    """Returning / handing off the resource transfers the teardown
    obligation — the task_data_service.get_batches() shape."""
    assert (
        violations(
            _PREFETCHER
            + """
def make():
    p = Prefetcher()
    return p

def hand(registry):
    p = Prefetcher()
    registry.adopt(p)
""",
            "drain-discipline",
        )
        == []
    )


def test_drain_discipline_builder_chain_and_receiver_use():
    """`Cls(...).start()` still resolves to the constructed class, and
    calling methods / reading attrs on the tracked name is NOT an
    ownership transfer (the replica_main.py `port = frontend.start()`
    false-negative shape)."""
    found = violations(
        _PREFETCHER
        + """
def serve(it):
    p = Prefetcher().start()
    port = p.port
    for _ in it:
        pass
    p.close()
""",
        "drain-discipline",
    )
    assert len(found) == 1 and "straight-line" in found[0].message


def test_drain_discipline_field_store_needs_owner_teardown():
    found = violations(
        _PREFETCHER
        + """
class Holder:
    def __init__(self):
        self._p = Prefetcher()
""",
        "drain-discipline",
    )
    assert len(found) == 1
    clean = violations(
        _PREFETCHER
        + """
class Holder:
    def __init__(self):
        self._p = Prefetcher()

    def close(self):
        self._p.close()
""",
        "drain-discipline",
    )
    assert clean == []


def test_drain_discipline_suppression():
    assert (
        violations(
            _PREFETCHER
            + """
def consume(it):
    p = Prefetcher()  # noqa-invariant: drain-discipline
    for _ in it:
        pass
""",
            "drain-discipline",
        )
        == []
    )


# ---------------------------------------------------------------------------
# blocking-under-lock (protocol_rules.py): no RPC / sleep / file I/O /
# joins reachable while holding a guarded-by lock
# ---------------------------------------------------------------------------

_LOCKED_CLASS_HEAD = """
import threading
import time


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = 0  # guarded-by: _lock
"""


def test_blocking_under_lock_flags_direct_sleep():
    found = violations(
        _LOCKED_CLASS_HEAD
        + """
    def tick(self):
        with self._lock:
            self._state += 1
            time.sleep(0.5)
""",
        "blocking-under-lock",
    )
    assert len(found) == 1
    assert "time.sleep" in found[0].message
    assert "Service._lock" in found[0].message


def test_blocking_under_lock_flags_transitive_same_file():
    """The sleep is one call below the critical section — reachability,
    not syntax, is what the rule checks."""
    found = violations(
        _LOCKED_CLASS_HEAD
        + """
    def tick(self):
        with self._lock:
            self._helper()

    def _helper(self):
        time.sleep(0.5)
""",
        "blocking-under-lock",
    )
    assert len(found) == 1
    assert "time.sleep" in found[0].message


def test_blocking_under_lock_accepts_work_outside_critical_section():
    assert (
        violations(
            _LOCKED_CLASS_HEAD
            + """
    def tick(self):
        with self._lock:
            self._state += 1
        time.sleep(0.5)
        self._helper()

    def _helper(self):
        time.sleep(0.5)
""",
            "blocking-under-lock",
        )
        == []
    )


def test_blocking_under_lock_flags_locked_suffix_method():
    """`*_locked` methods run under their class's lock by contract."""
    found = violations(
        _LOCKED_CLASS_HEAD
        + """
    def _flush_locked(self):
        with open("/tmp/x", "w") as f:
            f.write("x")
""",
        "blocking-under-lock",
    )
    assert len(found) == 1
    assert "file I/O" in found[0].message


def test_blocking_under_lock_suppression():
    assert (
        violations(
            _LOCKED_CLASS_HEAD
            + """
    def tick(self):
        with self._lock:
            time.sleep(0.5)  # noqa-invariant: blocking-under-lock
""",
            "blocking-under-lock",
        )
        == []
    )


def test_blocking_under_lock_cross_module_chain(tmp_path):
    """THE whole-program acceptance fixture: the lock is in one module,
    the sleep two calls below it in another — only cross-module call
    resolution can connect them."""
    (tmp_path / "helpers.py").write_text(
        "import time\n"
        "\n"
        "def deep():\n"
        "    time.sleep(0.5)\n"
        "\n"
        "def poll():\n"
        "    deep()\n"
    )
    (tmp_path / "svc.py").write_text(
        "import threading\n"
        "\n"
        "import helpers\n"
        "\n"
        "class Service:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = 0  # guarded-by: _lock\n"
        "\n"
        "    def tick(self):\n"
        "        with self._lock:\n"
        "            helpers.poll()\n"
    )
    found = run_checks([str(tmp_path)], [ALL_RULES["blocking-under-lock"]])
    assert len(found) == 1
    assert found[0].path.endswith("svc.py")
    assert "time.sleep" in found[0].message
    assert "via" in found[0].message  # the call chain is named


def test_cross_module_tracedness_reaches_jax_rules(tmp_path):
    """Tracedness propagates over imports: a helper that only a jitted
    fn in ANOTHER module calls is traced, so its host sync is flagged."""
    (tmp_path / "lib.py").write_text(
        "def helper(x):\n"
        "    print(x)\n"
        "    return x\n"
    )
    (tmp_path / "step.py").write_text(
        "import jax\n"
        "\n"
        "from lib import helper\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return helper(x)\n"
    )
    found = run_checks([str(tmp_path)], [ALL_RULES["jit-host-sync"]])
    assert any(
        v.path.endswith("lib.py") and "print" in v.message for v in found
    )


# ---------------------------------------------------------------------------
# journal-schema (protocol_rules.py): emission sites match the
# validate_journal.py registry field-for-field
# ---------------------------------------------------------------------------


def test_journal_schema_flags_misspelled_field():
    found = violations(
        """
        def f(journal):
            journal.record("model_swap", generaton=2, step=4096)
        """,
        "journal-schema",
    )
    assert any("generaton" in v.message for v in found)
    assert any("missing required" in v.message for v in found)


def test_journal_schema_flags_unknown_event():
    found = violations(
        """
        def f(journal):
            journal.record("totally_unknown_event", a=1)
        """,
        "journal-schema",
    )
    assert len(found) == 1 and "unknown journal event" in found[0].message


def test_journal_schema_flags_missing_required_field():
    found = violations(
        """
        def f(journal):
            journal.record("rendezvous", rendezvous_id=1)
        """,
        "journal-schema",
    )
    assert len(found) == 1
    assert "world_size" in found[0].message


def test_journal_schema_flags_nonliteral_event_name():
    found = violations(
        """
        def f(journal, name):
            journal.record(name, a=1)
        """,
        "journal-schema",
    )
    assert len(found) == 1 and "non-literal" in found[0].message


def test_journal_schema_accepts_registered_site():
    assert (
        violations(
            """
            def f(journal):
                journal.record(
                    "model_swap", generation=2, step=4096,
                    old_generation=1, outcome="committed",
                )
            """,
            "journal-schema",
        )
        == []
    )


def test_journal_schema_checks_dict_event_payloads():
    """`record(**payload)` is invisible at the call — the gate moves to
    the dict(event=...) / {"event": ...} build site."""
    found = violations(
        """
        def f():
            return dict(event="task_dispatch", task_id=1, worker_id=0)
        """,
        "journal-schema",
    )
    assert len(found) == 1 and "trace_id" in found[0].message
    found = violations(
        """
        def f():
            return {"event": "stream_watermark", "stream": "s",
                    "offset": 1, "pending_rangez": 2}
        """,
        "journal-schema",
    )
    assert len(found) == 1 and "pending_rangez" in found[0].message


def test_journal_schema_record_span_checks_extras_only():
    assert (
        violations(
            """
            def f(tracer):
                tracer.record_span("step.execute", duration_s=1.0,
                                   task_id=3, worker_id=1)
            """,
            "journal-schema",
        )
        == []
    )
    found = violations(
        """
        def f(tracer):
            tracer.record_span("step.execute", duration_s=1.0,
                               tsak_id=3)
        """,
        "journal-schema",
    )
    assert len(found) == 1 and "tsak_id" in found[0].message


def test_journal_schema_suppression():
    assert (
        violations(
            """
            def f(journal):
                journal.record("demo_event", a=1)  # noqa-invariant: journal-schema
            """,
            "journal-schema",
        )
        == []
    )


def test_check_sources_routes_through_ast_rule(tmp_path):
    """Regression pin for the --check-sources upgrade: a misspelled
    FIELD on a known event fails the gate now; the retired name-only
    grep passed it (the event name is registered)."""
    import importlib.util
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "validate_journal_for_analysis_test",
        os.path.join(repo_root, "scripts", "validate_journal.py"),
    )
    validator = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(validator)

    drifting = tmp_path / "drifting.py"
    drifting.write_text(
        'journal.record("model_swap", generaton=2, step=4096)\n'
    )
    assert "model_swap" in validator.KNOWN_EVENTS  # grep saw no drift…
    assert validator.scan_sources(str(tmp_path)) == []  # …and still doesn't
    assert validator._check_sources(str(tmp_path)) == 1  # the AST rule does
    problems, scanned = validator.scan_sources_counted(str(tmp_path))
    assert scanned == 1
    assert any("generaton" in message for _p, _l, message in problems)


def test_journal_optional_registry_covers_every_known_event():
    """The field contract only bites when every event has an (even
    empty) optional entry — a gap would silently disable extras
    checking for that event."""
    import importlib.util
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "validate_journal_for_registry_test",
        os.path.join(repo_root, "scripts", "validate_journal.py"),
    )
    validator = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(validator)
    assert set(validator.EVENT_OPTIONAL_FIELDS) == set(validator.KNOWN_EVENTS)


# ---------------------------------------------------------------------------
# Whole-program index: CLI stats, timing plumbing, runtime budget
# ---------------------------------------------------------------------------


def test_cli_reports_program_graph_stats(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert analysis_main([str(clean)]) == 0
    out = capsys.readouterr().out
    assert "program graph:" in out
    assert "fixpoint iteration" in out


def test_cli_json_includes_timing_and_graph(tmp_path, capsys):
    import json

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert analysis_main([str(clean), "--format", "json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert "program-index" in data["timing"]
    for rule in RULE_NAMES:
        assert rule in data["timing"]
    assert data["graph"]["modules"] == 1
    assert data["graph"]["fixpoint_iterations"] >= 1


def test_invariant_report_renders_timing_and_graph():
    import sys

    sys.path.insert(0, "scripts")
    try:
        import invariant_report
    finally:
        sys.path.pop(0)

    rendered = invariant_report.render(
        {
            "findings": [],
            "suppressed": 0,
            "suppressed_by_rule": {},
            "files_scanned": 3,
            "rules": ["drain-discipline"],
            "timing": {"program-index": 0.5, "drain-discipline": 0.25},
            "graph": {"modules": 3, "edges": 11, "fixpoint_iterations": 2},
        }
    )
    assert "timing:" in rendered
    assert "program-index 0.50s" in rendered
    assert "total 0.75s" in rendered
    assert "program graph: 3 modules, 11 edges, 2 fixpoint iteration(s)" in rendered


def test_serving_and_data_trees_are_invariant_clean():
    """The sweep that motivated this analyzer: the serving and data
    planes (where the drained-on-every-path bugs lived) gate clean."""
    import os

    import elasticdl_tpu

    pkg = os.path.dirname(os.path.abspath(elasticdl_tpu.__file__))
    assert analysis_main(
        [os.path.join(pkg, "serving"), os.path.join(pkg, "data")]
    ) == 0


def test_analyzer_full_sweep_stays_under_budget():
    """The whole-program pass (index + 15 rules over the full package)
    must stay cheap enough for `make lint` / pre-commit use."""
    import time

    from elasticdl_tpu.analysis.__main__ import default_paths
    from elasticdl_tpu.analysis.core import scan

    start = time.perf_counter()
    report = scan(default_paths(), ALL_RULES.values())
    elapsed = time.perf_counter() - start
    assert report.files, "budget test scanned nothing"
    assert elapsed < 60.0, f"analyzer sweep took {elapsed:.1f}s"
