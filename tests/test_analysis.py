"""Unit tests for the invariant analyzer (elasticdl_tpu.analysis).

One must-pass + must-fail fixture pair per rule, the inline-suppression
contract, and the two repo-level acceptance gates:

- the production tree is invariant-clean (`python -m elasticdl_tpu.analysis`
  exits 0) — this test IS the tier-1 wiring of `make check-invariants`;
- a seeded violation of each of the five rules makes the CLI exit
  non-zero.
"""

import textwrap

from elasticdl_tpu.analysis.__main__ import main as analysis_main
from elasticdl_tpu.analysis.core import SourceFile, run_checks
from elasticdl_tpu.analysis.rules import ALL_RULES, RULE_NAMES


def violations(text, rule, path="fixture.py"):
    source = SourceFile.parse(path, textwrap.dedent(text))
    found = [
        v
        for v in ALL_RULES[rule](source)
        if not source.suppressed(v.rule, v.line)
    ]
    assert all(v.rule == rule for v in found)
    return found


# ---------------------------------------------------------------------------
# rpc-deadline
# ---------------------------------------------------------------------------


def test_rpc_deadline_flags_raw_stub_call():
    found = violations(
        """
        def f(self, req):
            return self._stub.get_task(req)
        """,
        "rpc-deadline",
    )
    assert len(found) == 1 and "timeout" in found[0].message


def test_rpc_deadline_flags_getattr_dispatch():
    found = violations(
        """
        def f(stub, method, req):
            return getattr(stub, method)(req)
        """,
        "rpc-deadline",
    )
    assert len(found) == 1


def test_rpc_deadline_accepts_explicit_timeout_and_wrappers():
    found = violations(
        """
        def f(self, req):
            self._stub.get_task(req, timeout=10.0)
            return call_with_retry(
                getattr(self._stub, "get_task"), req,
                method="get_task", policy=IDEMPOTENT_POLICY,
            )
        """,
        "rpc-deadline",
    )
    assert found == []


# ---------------------------------------------------------------------------
# idempotency
# ---------------------------------------------------------------------------


def test_idempotency_flags_retried_result_report():
    found = violations(
        """
        def f(self, req):
            self._call_idempotent("report_task_result", req)
        """,
        "idempotency",
    )
    assert len(found) == 1 and "report_task_result" in found[0].message


def test_idempotency_flags_call_with_retry_on_eval_report():
    found = violations(
        """
        def f(fn, req):
            call_with_retry(fn, req, "report_evaluation_metrics",
                            IDEMPOTENT_POLICY)
        """,
        "idempotency",
    )
    assert len(found) == 1


def test_idempotency_accepts_no_retry_policies():
    found = violations(
        """
        def f(self, fn, req):
            call_with_retry(fn, req, "report_task_result",
                            NON_IDEMPOTENT_POLICY)
            call_with_retry(fn, req, "report_task_result",
                            self._no_retry_policy)
            call_with_retry(fn, req, "report_task_result",
                            RetryPolicy(max_attempts=1))
            self._call_idempotent("get_task", req)
        """,
        "idempotency",
    )
    assert found == []


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_determinism_flags_wall_clock_and_unseeded_rng():
    found = violations(
        """
        # deterministic-replay-path
        import random, time, datetime

        def f():
            a = time.time()
            b = random.random()
            c = datetime.now()
            d = random.Random()
            return a, b, c, d
        """,
        "determinism",
    )
    assert len(found) == 4


def test_determinism_accepts_monotonic_and_seeded_rng():
    found = violations(
        """
        # deterministic-replay-path
        import random, time

        def f(seed):
            a = time.monotonic()
            b = random.Random(seed).random()
            time.sleep(0.1)
            return a, b
        """,
        "determinism",
    )
    assert found == []


def test_determinism_applies_by_path_suffix():
    text = "import time\nx = time.time()\n"
    assert violations(text, "determinism",
                      path="elasticdl_tpu/common/faults.py")
    assert not violations(text, "determinism", path="somewhere_else.py")


def test_determinism_allows_seeded_rng_reads_inside_backoff():
    # The real backoff jitter pattern from grpc_utils must stay legal.
    found = violations(
        """
        # deterministic-replay-path
        import random

        def backoff(salt, method, attempt):
            return random.Random(f"{salt}:{method}:{attempt}").random()
        """,
        "determinism",
    )
    assert found == []


# ---------------------------------------------------------------------------
# thread-hygiene
# ---------------------------------------------------------------------------


def test_thread_hygiene_flags_missing_name_and_daemon():
    found = violations(
        """
        import threading

        def f(target):
            threading.Thread(target=target)
            threading.Thread(target=target, daemon=True)
            threading.Thread(target=target, name="ok")
        """,
        "thread-hygiene",
    )
    assert len(found) == 3
    assert "name, daemon" in found[0].message


def test_thread_hygiene_accepts_named_daemon_threads():
    found = violations(
        """
        import threading
        from threading import Thread

        def f(target):
            threading.Thread(target=target, name="w", daemon=True)
            Thread(target=target, name="w2", daemon=False)
        """,
        "thread-hygiene",
    )
    assert found == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = """
import threading


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._free = 0  # no annotation: unguarded

    def good(self):
        with self._lock:
            self._items.append(1)
            self._count += 1
        self._free = 9

    def good_via_locked_helper(self):
        with self._lock:
            self._refill_locked()

    def _refill_locked(self):
        self._items.extend([1, 2])
        self._items[0] = 3

    def bad_assign(self):
        self._count = 5

    def bad_mutator(self):
        self._items.append(1)

    def bad_subscript(self):
        self._items[0] = 1

    def bad_nested_thread_target(self):
        with self._lock:
            def target():
                self._items.pop()  # lock NOT held when target() runs
            return target
"""


def test_lock_discipline_flags_off_lock_mutations_only():
    found = violations(_LOCKED_CLASS, "lock-discipline")
    lines = {v.line for v in found}
    bad_methods = {"bad_assign", "bad_mutator", "bad_subscript"}
    assert len(found) == 4  # three bad_* methods + the nested closure
    assert all(
        any(m in v.message for m in bad_methods | {"bad_nested_thread_target"})
        for v in found
    )
    assert lines  # every violation is anchored to a line


def test_lock_discipline_dataclass_fields_and_named_locks():
    found = violations(
        """
        import threading
        from dataclasses import dataclass, field


        @dataclass
        class Stats:
            calls: int = 0  # guarded-by: _meta_lock
            _meta_lock: threading.Lock = field(default_factory=threading.Lock)

            def good(self):
                with self._meta_lock:
                    self.calls += 1

            def bad(self):
                self.calls += 1

            def wrong_lock(self):
                with self._other:
                    self.calls += 1
        """,
        "lock-discipline",
    )
    assert len(found) == 2
    assert all("_meta_lock" in v.message for v in found)


def test_lock_discipline_standalone_block_for_inherited_fields():
    found = violations(
        """
        class Sub(Base):
            def __init__(self):
                super().__init__()
                # guarded-by: _lock: _handles, _size

            def bad(self):
                self._size = 3

            def good(self):
                with self._lock:
                    self._handles = []
        """,
        "lock-discipline",
    )
    assert len(found) == 1 and "_size" in found[0].message


# ---------------------------------------------------------------------------
# metric-label-cardinality
# ---------------------------------------------------------------------------


def test_metric_cardinality_flags_unbounded_labelnames():
    found = violations(
        """
        def f(obs):
            obs.counter("t_total", "h", labelnames=("task_id", "type"))
            obs.histogram("d_seconds", "h", labelnames=["pod_name"])
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 2
    assert "task_id" in found[0].message and "journal" in found[0].message


def test_metric_cardinality_flags_unbounded_label_kwargs():
    found = violations(
        """
        def f(metric, task, pod):
            metric.inc(task_id=task.id)
            metric.labels(worker_id=3).observe(0.1)
            metric.set(1.0, host=pod.ip)
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 3


def test_metric_cardinality_flags_dynamic_metric_names():
    found = violations(
        """
        def f(obs, task):
            obs.counter(f"task_{task.id}_total", "h")
            obs.gauge("prefix_" + task.name, "h")
        """,
        "metric-label-cardinality",
    )
    assert len(found) == 2
    assert "dynamic metric name" in found[0].message


def test_metric_cardinality_ignores_non_metric_lookalikes():
    """collections.Counter arithmetic and unrelated .counter()/.histogram()
    methods must not trip the rule — only registry-shaped receivers do."""
    found = violations(
        """
        import collections

        def f(a, b, dataframe, name):
            total = collections.Counter(a + b)
            dataframe.histogram(f"col_{name}")
            stats = a.counter("x" + name)
            return total, stats
        """,
        "metric-label-cardinality",
    )
    assert found == []


def test_metric_cardinality_accepts_bounded_labels_and_journal_fields():
    found = violations(
        """
        def f(obs, journal, task):
            c = obs.counter(
                "elasticdl_task_requeues_total", "h",
                labelnames=("reason", "type"),
            )
            c.inc(reason="timeout", type="TRAINING")
            obs.histogram("d_seconds", "h", labelnames=("kind",))
            # Unbounded identifiers ride the JOURNAL, which is fine.
            journal.record("task_requeue", task_id=task.id, pod="w-3")
        """,
        "metric-label-cardinality",
    )
    assert found == []


# ---------------------------------------------------------------------------
# Suppression
# ---------------------------------------------------------------------------


def test_noqa_invariant_suppresses_by_rule_and_star():
    found = violations(
        """
        import threading

        def f(target):
            threading.Thread(target=target)  # noqa-invariant: thread-hygiene
            threading.Thread(target=target)  # noqa-invariant: *
            threading.Thread(target=target)  # noqa-invariant: rpc-deadline
        """,
        "thread-hygiene",
    )
    assert len(found) == 1  # only the wrong-rule suppression still flags


# ---------------------------------------------------------------------------
# Repo-level gates (this is the tier-1 wiring of `make check-invariants`)
# ---------------------------------------------------------------------------


def test_production_tree_is_invariant_clean(capsys):
    assert analysis_main([]) == 0
    assert "OK" in capsys.readouterr().out


def test_production_annotations_actually_engage():
    """Guard against the analyzer rotting into a no-op: the TaskManager
    must expose guarded fields the lock-discipline rule sees."""
    import ast

    from elasticdl_tpu.analysis.rules import _collect_guarded_fields
    from elasticdl_tpu.master import task_manager

    source = SourceFile.parse(task_manager.__file__)
    guarded = {}
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef) and node.name == "TaskManager":
            guarded = _collect_guarded_fields(source, node)
    assert "_todo" in guarded and guarded["_todo"] == "_lock"
    assert "_doing" in guarded


_SEEDED_VIOLATIONS = {
    "rpc-deadline": "def f(s, r):\n    return s._stub.get(r)\n",
    "idempotency": (
        "def f(s, r):\n"
        "    s._call_idempotent('report_task_result', r)\n"
    ),
    "determinism": (
        "# deterministic-replay-path\n"
        "import time\n"
        "def f():\n"
        "    return time.time()\n"
    ),
    "thread-hygiene": (
        "import threading\n"
        "def f(t):\n"
        "    threading.Thread(target=t)\n"
    ),
    "lock-discipline": (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0  # guarded-by: _lock\n"
        "    def bad(self):\n"
        "        self._x = 1\n"
    ),
    "metric-label-cardinality": (
        "def f(obs, task):\n"
        "    c = obs.counter('t_total', 'h', labelnames=('task_id',))\n"
        "    c.inc(task_id=task.id)\n"
    ),
}


def test_cli_exits_nonzero_on_each_seeded_rule_violation(tmp_path, capsys):
    """Acceptance: `make check-invariants` fails on a violation of EACH
    registered rule."""
    assert set(_SEEDED_VIOLATIONS) == set(RULE_NAMES)
    for rule, text in _SEEDED_VIOLATIONS.items():
        bad = tmp_path / f"{rule.replace('-', '_')}.py"
        bad.write_text(text)
        rc = analysis_main([str(bad)])
        out = capsys.readouterr().out
        assert rc == 1, f"seeded {rule} violation not caught"
        assert f"[{rule}]" in out


def test_cli_rule_filter_and_listing(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(_SEEDED_VIOLATIONS["thread-hygiene"])
    assert analysis_main([str(bad), "--rule", "rpc-deadline"]) == 0
    assert analysis_main([str(bad), "--rule", "thread-hygiene"]) == 1
    capsys.readouterr()
    assert analysis_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in RULE_NAMES:
        assert rule in listed


def test_run_checks_reports_unparseable_files(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    found = run_checks([str(tmp_path)], ALL_RULES.values())
    assert len(found) == 1 and found[0].rule == "parse"


def test_cli_refuses_zero_file_scan(tmp_path, capsys):
    """An OK over zero scanned files would be a false green gate."""
    empty = tmp_path / "empty_dir"
    empty.mkdir()
    assert analysis_main([str(empty)]) == 2
    assert "no .py files" in capsys.readouterr().err


def test_run_checks_reports_undecodable_files(tmp_path):
    bad = tmp_path / "latin.py"
    bad.write_bytes(b"# caf\xe9\nx = 1\n")
    found = run_checks([str(tmp_path)], ALL_RULES.values())
    assert len(found) == 1 and found[0].rule == "parse"
    assert "could not read" in found[0].message


def test_list_rules_has_descriptions(capsys):
    assert analysis_main(["--list-rules"]) == 0
    for line in capsys.readouterr().out.strip().splitlines():
        rule, _, description = line.partition(":")
        assert description.strip(), f"rule {rule} listed without a description"
