"""Step-anatomy plane tests (obs/stepstats.py + PR-8 wiring).

Covers:

- StepAnatomy: phase exclusivity (nesting raises), compile-vs-execute
  booking via real jit retrace detection, retrace counters keyed by
  jitted function, MFU math against the analytic FLOPs table, roofline
  ``bound:`` verdicts, snapshot round-trip through the telemetry
  sanitizer;
- the roofline constants / FLOPs formulas staying in lockstep with
  bench.py (single-truth rule, enforced here);
- telemetry snapshot size budget: an oversized snapshot degrades by
  trimming anatomy windows OLDEST-first, never by dropping the core
  liveness/step fields;
- aggregator: ``step_anatomy`` journal events, fleet phase-fraction
  gauges, straggler evidence upgraded with the dominant phase;
- StepProfiler ``profile_window`` journal events (open/close with the
  trace dir obs.report points at);
- scripts/bench_regress.py: selftest, the synthetic beyond-spread
  regression exiting non-zero with a schema-valid ``bench_regress``
  journal event, untracked rows never gating;
- the check-invariants seeded-violation gate over the new
  instrumentation call sites (trace-purity + metric-label-cardinality);
- the ISSUE acceptance e2e: master + 3 heartbeating workers over real
  gRPC where one worker is artificially data-starved — the straggler
  journal evidence names ``data_wait`` as the dominant phase, and
  ``obs.report`` over that journal attributes it with phase fractions
  summing to ~1.0.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.obs import stepstats
from elasticdl_tpu.obs.stepstats import (
    PHASES,
    RetraceWatcher,
    StepAnatomy,
)
from elasticdl_tpu.obs.telemetry import (
    StragglerDetector,
    TelemetryAggregator,
    WorkerTelemetry,
    sanitize_snapshot,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, seconds):
        self.t += seconds


def _fed_anatomy(worker_id=0, data_wait=0.0, stage=0.0, execute=0.0,
                 bookkeep=0.0, examples=0, steps=1, windows=1):
    """A StepAnatomy with deterministic phase seconds via a fake clock."""
    clock = _Clock()
    anatomy = StepAnatomy(worker_id=worker_id, clock=clock)
    for _ in range(windows):
        if data_wait:
            with anatomy.phase("data_wait"):
                clock.advance(data_wait)
        if stage:
            with anatomy.phase("stage"):
                clock.advance(stage)
        with anatomy.dispatch(steps, examples):
            clock.advance(execute)
        if bookkeep:
            with anatomy.phase("bookkeep"):
                clock.advance(bookkeep)
        anatomy.close_window()
    return anatomy


# ---------------------------------------------------------------------------
# StepAnatomy core
# ---------------------------------------------------------------------------


def test_phase_exclusivity_and_accounting():
    clock = _Clock()
    anatomy = StepAnatomy(worker_id=1, clock=clock)
    with anatomy.phase("data_wait"):
        clock.advance(2.0)
    with anatomy.phase("stage"):
        clock.advance(0.5)
    with anatomy.dispatch(4, 256):
        clock.advance(1.5)
    window = anatomy.close_window()
    assert window["data_wait"] == pytest.approx(2.0)
    assert window["stage"] == pytest.approx(0.5)
    assert window["execute"] == pytest.approx(1.5)
    assert window["steps"] == 4 and window["examples"] == 256
    # Exclusive by contract: nesting is a caller bug and raises.
    with pytest.raises(RuntimeError, match="exclusive"):
        with anatomy.phase("data_wait"):
            with anatomy.phase("execute"):
                pass
    with pytest.raises(RuntimeError, match="exclusive"):
        with anatomy.phase("stage"):
            with anatomy.dispatch(1):
                pass
    with pytest.raises(ValueError):
        with anatomy.phase("no_such_phase"):
            pass
    # The failed opens above must not have corrupted the accounting.
    with anatomy.phase("bookkeep"):
        clock.advance(0.25)
    window = anatomy.close_window()
    assert window["bookkeep"] == pytest.approx(0.25)
    totals = anatomy.totals()
    assert sum(totals.values()) == pytest.approx(4.25)


def test_phase_fractions_sum_to_one():
    anatomy = _fed_anatomy(data_wait=6.0, execute=1.0, bookkeep=0.5,
                           examples=64)
    fractions = stepstats.phase_fractions(anatomy.totals())
    assert sum(fractions.values()) == pytest.approx(1.0, abs=0.01)
    assert max(fractions, key=fractions.get) == "data_wait"


def test_retrace_counting_books_compile_vs_execute():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x * 2)
    anatomy = StepAnatomy(worker_id=0)
    anatomy.watch_jits(lambda: {"train_step": fn})
    with anatomy.dispatch(1, 8):
        fn(jnp.ones((4,)))  # first compile
    first = anatomy.close_window()
    assert "compile" in first and "execute" not in first
    assert first["compiles"] == 1
    with anatomy.dispatch(1, 8):
        fn(jnp.ones((4,)))  # cached executable
    second = anatomy.close_window()
    assert "execute" in second and "compile" not in second
    with anatomy.dispatch(1, 8):
        fn(jnp.ones((8,)))  # new shape -> RETRACE
    third = anatomy.close_window()
    assert "compile" in third
    snap = anatomy.snapshot()
    assert snap["compiles"] == {"train_step": 2}
    assert snap["retraces"] == 1  # compiles beyond the first


def test_retrace_watcher_tolerates_lazy_and_broken_providers():
    watcher = RetraceWatcher()
    watcher.watch(lambda: None)
    watcher.watch(lambda: {"unbuilt": None, "odd": object()})

    def exploding():
        raise RuntimeError("trainer not initialized yet")

    watcher.watch(exploding)
    assert watcher.poll() == {}
    assert watcher.retraces_total() == 0


def test_mfu_math_matches_flops_table():
    # 4096 transformer examples in 2.0s of pure execute.
    anatomy = _fed_anatomy(execute=2.0, examples=4096, steps=4)
    anatomy.set_model("transformer_lm")
    snap = anatomy.snapshot()
    flops = stepstats.MODEL_FLOPS["transformer_lm"]["train_flops_per_example"]
    expected = (4096 / 2.0) * flops / stepstats.PEAK_BF16_FLOPS
    assert snap["mfu"] == pytest.approx(expected, rel=1e-3)
    assert snap["bound"] == "compute"


def test_roofline_verdicts():
    # Host-starved: data_wait dominates regardless of model.
    host = stepstats.roofline(
        1000.0, {"data_wait": 0.7, "execute": 0.3}, "resnet50"
    )
    assert host["bound"] == "host"
    # DeepFM at ~1M samples/s: the BENCH_r04 sparse-row-count wall.
    sparse = stepstats.roofline(975_000.0, {"execute": 1.0}, "deepfm")
    assert sparse["bound"] == "sparse-row"
    assert sparse["floor_frac"] == pytest.approx(0.634, abs=0.01)
    # ResNet-50 at its measured rate: bandwidth-bound, not MXU-bound.
    hbm = stepstats.roofline(2_665.0, {"execute": 1.0}, "resnet50")
    assert hbm["bound"] == "hbm"
    assert hbm["bw_frac"] > hbm["mfu"]
    # No FLOPs row -> no verdict invented.
    assert "bound" not in stepstats.roofline(10.0, {"execute": 1.0}, None)


def test_roofline_constants_match_bench():
    """Single-truth rule: stepstats' chip ceilings and analytic FLOPs
    must never drift from bench.py's roofline accounting."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_test", os.path.join(REPO_ROOT, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert stepstats.PEAK_BF16_FLOPS == bench.PEAK_BF16_FLOPS
    assert stepstats.HBM_BYTES_PER_SEC == bench.HBM_BYTES_PER_SEC
    assert stepstats.SPARSE_FLOOR_NS_PER_ROW == bench.SPARSE_FLOOR_NS_PER_ROW
    assert stepstats.TRANSFORMER_BENCH == bench.TRANSFORMER_BENCH
    assert stepstats.transformer_flops_per_token() == pytest.approx(
        bench._transformer_flops_per_token()
    )
    resnet = stepstats.MODEL_FLOPS["resnet50"]
    assert resnet["train_flops_per_example"] == pytest.approx(12.3e9)
    assert resnet["hbm_bytes_per_example"] == pytest.approx(21.5e9 / 128)
    assert stepstats.MODEL_FLOPS["deepfm"]["sparse_rows_per_example"] == 26


def test_infer_model_key():
    assert stepstats.infer_model_key(
        "model_zoo.deepfm.deepfm_functional_api.custom_model"
    ) == "deepfm"
    assert stepstats.infer_model_key("/mz/resnet50/resnet50_subclass.py") == (
        "resnet50"
    )
    assert stepstats.infer_model_key("transformer_lm.custom_model") == (
        "transformer_lm"
    )
    assert stepstats.infer_model_key("census_wide_deep") is None


def test_snapshot_round_trip_through_sanitizer():
    anatomy = _fed_anatomy(worker_id=7, data_wait=1.0, stage=0.25,
                           execute=3.0, examples=512, windows=3)
    telemetry = WorkerTelemetry(worker_id=7)
    telemetry.bind_anatomy(anatomy)
    telemetry.record_steps(4, duration_s=0.04, records=512)
    clean = sanitize_snapshot(json.loads(telemetry.snapshot_json()))
    assert clean is not None
    anatomy_clean = clean["anatomy"]
    assert anatomy_clean["totals"]["data_wait"] == pytest.approx(3.0)
    assert anatomy_clean["totals"]["execute"] == pytest.approx(9.0)
    assert len(anatomy_clean["windows"]) == 3
    assert anatomy_clean["steps"] == 3 and anatomy_clean["examples"] == 1536
    # Wire junk: unknown keys drop, wrong-typed anatomy degrades to
    # absent WITHOUT rejecting the snapshot's core fields.
    assert stepstats.sanitize_anatomy({"totals": {"data_wait": "NaN-ish"}}) \
        is None
    assert stepstats.sanitize_anatomy("not a dict") is None
    hostile = json.loads(telemetry.snapshot_json())
    hostile["anatomy"] = {"bound": "rm -rf /", "junk": 1}
    clean = sanitize_snapshot(hostile)
    assert clean is not None and "anatomy" not in clean
    assert "step_p50_s" in clean
    partial = stepstats.sanitize_anatomy(
        {"totals": {"execute": 1.0, "nonsense": 2.0}, "bound": "hbm",
         "retraces": 3, "compiles": {"train_step": 2, 5: "x"}}
    )
    assert partial == {
        "totals": {"execute": 1.0}, "bound": "hbm", "retraces": 3,
        "compiles": {"train_step": 2},
    }


def test_oversized_snapshot_trims_anatomy_oldest_first(monkeypatch):
    """Satellite: near the 4 KiB heartbeat bound the snapshot sheds
    anatomy windows oldest-first (then the whole sub-dict) — the core
    liveness/step fields always deliver."""
    from elasticdl_tpu.obs import telemetry as telemetry_mod

    anatomy = _fed_anatomy(worker_id=3, data_wait=0.5, execute=1.0,
                           examples=64, windows=5)
    telemetry = WorkerTelemetry(worker_id=3)
    telemetry.bind_anatomy(anatomy)
    telemetry.set_rendezvous(2)
    telemetry.record_steps(4, duration_s=0.04, records=64)
    full = telemetry.snapshot()
    assert len(full["anatomy"]["windows"]) == 5
    newest = full["anatomy"]["windows"][-1]
    # Budget that fits the core snapshot plus ~2 anatomy windows.
    core = dict(full)
    core.pop("anatomy")
    budget = len(json.dumps(core, separators=(",", ":")).encode()) + 220
    monkeypatch.setattr(telemetry_mod, "MAX_SNAPSHOT_BYTES", budget)
    payload = telemetry.snapshot_json()
    assert len(payload.encode()) <= budget
    degraded = json.loads(payload)
    # Core liveness/step fields survive intact.
    for field in ("worker_id", "ts", "steps_total", "step_p50_s",
                  "rendezvous_id", "examples_per_s"):
        assert field in degraded, field
    # Anatomy degraded window-wise, newest window retained first.
    kept = degraded["anatomy"]["windows"]
    assert 0 < len(kept) < 5
    assert kept[-1] == newest
    # An impossibly small budget still ships totals (windows dropped)
    # or, at worst, the core snapshot with no anatomy at all.
    monkeypatch.setattr(
        telemetry_mod, "MAX_SNAPSHOT_BYTES",
        len(json.dumps(core, separators=(",", ":")).encode()) + 10,
    )
    degraded = json.loads(telemetry.snapshot_json())
    assert "anatomy" not in degraded
    assert degraded["steps_total"] == 4
    # The sanitizer accepts every rung of the ladder.
    assert sanitize_snapshot(degraded) is not None


def test_fleet_attribution_unit():
    snapshots = {
        0: {"anatomy": {"totals": {"data_wait": 1.0, "execute": 9.0}}},
        1: {"anatomy": {"totals": {"data_wait": 1.2, "execute": 8.8}}},
        2: {"anatomy": {"totals": {"data_wait": 8.0, "execute": 2.0}}},
        3: {},  # no anatomy: excluded, not a crash
    }
    attribution = stepstats.fleet_attribution(snapshots)
    assert attribution["bottleneck"] == "execute"
    assert sum(attribution["fractions"].values()) == pytest.approx(
        1.0, abs=0.01
    )
    assert attribution["workers"][2]["dominant_phase"] == "data_wait"
    assert 3 not in attribution["workers"]
    empty = stepstats.fleet_attribution({0: {}})
    assert empty["bottleneck"] is None and empty["fractions"] == {}


# ---------------------------------------------------------------------------
# Aggregator wiring: journal events, gauges, straggler evidence
# ---------------------------------------------------------------------------


def _wire_snap(wid, p50, data_wait, execute, retraces=0):
    return json.dumps(
        {
            "v": 1, "worker_id": wid, "ts": time.time(),
            "step_p50_s": p50, "step_p95_s": p50 * 1.2,
            "anatomy": {
                "totals": {"data_wait": data_wait, "execute": execute},
                "steps": 32, "examples": 2048, "retraces": retraces,
                "windows": [
                    {"steps": 32, "data_wait": data_wait,
                     "execute": execute}
                ],
            },
        }
    )


def test_aggregator_journals_step_anatomy_and_phase_gauges(
    obs_registry_snapshot,
):
    aggregator = TelemetryAggregator(journal_interval_s=0.0)
    marker = time.time() - 1
    aggregator.ingest(0, _wire_snap(0, 0.01, 1.0, 9.0, retraces=2))
    aggregator.ingest(1, _wire_snap(1, 0.01, 2.0, 8.0))
    events = [
        e for e in obs.journal().tail(100)
        if e["event"] == "step_anatomy" and e["ts"] >= marker
    ]
    assert len(events) == 2
    event = events[0]
    assert event["worker_id"] == 0
    assert event["totals"] == {"data_wait": 1.0, "execute": 9.0}
    assert event["dominant_phase"] == "execute"
    assert sum(event["fractions"].values()) == pytest.approx(1.0, abs=0.01)
    assert "windows" not in event  # heartbeat-only bulk
    # worker_telemetry events stay lean (no anatomy duplicate).
    telem = [
        e for e in obs.journal().tail(100)
        if e["event"] == "worker_telemetry" and e["ts"] >= marker
    ]
    assert telem and all("anatomy" not in e for e in telem)
    # Fleet gauges: bounded phase label only.
    registry = obs.registry()
    fraction = registry.get("elasticdl_worker_phase_fraction")
    assert fraction.value(phase="execute") == pytest.approx(0.85, abs=0.01)
    assert fraction.value(phase="data_wait") == pytest.approx(0.15, abs=0.01)
    assert registry.get("elasticdl_worker_retraces").value() == 2


def test_straggler_evidence_names_dominant_phase(obs_registry_snapshot):
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        journal_interval_s=1e9,
    )
    marker = time.time() - 1
    for wid in range(3):
        aggregator.ingest(wid, _wire_snap(wid, 0.01, 0.5, 9.5))
    for _ in range(3):
        aggregator.ingest(3, _wire_snap(3, 0.9, 9.0, 1.0))
    detected = [
        e for e in obs.journal().tail(100)
        if e["event"] == "straggler_detected" and e["ts"] >= marker
    ]
    assert detected and detected[-1]["worker_id"] == 3
    assert detected[-1]["dominant_phase"] == "data_wait"
    assert detected[-1]["phase_ratio"] > 5  # vs the fleet's ~5% median
    attribution = aggregator.fleet_attribution()
    assert attribution["workers"][3]["dominant_phase"] == "data_wait"


def test_note_phase_seconds_books_after_the_fact():
    anatomy = StepAnatomy(worker_id=0)
    anatomy.note_phase_seconds("data_wait", 2.5)
    anatomy.note_phase_seconds("data_wait", -1.0)  # clamped, not subtracted
    window = anatomy.close_window()
    assert window["data_wait"] == pytest.approx(2.5)
    with pytest.raises(ValueError):
        anatomy.note_phase_seconds("idle", 1.0)


def test_journal_anatomy_helper(obs_registry_snapshot):
    marker = time.time()
    record = stepstats.journal_anatomy(
        4, {"totals": {"data_wait": 3.0, "execute": 1.0}, "steps": 8,
            "windows": [{"steps": 8}]}
    )
    assert record["worker_id"] == 4
    assert record["dominant_phase"] == "data_wait"
    assert "windows" not in record
    assert stepstats.journal_anatomy(4, {}) is None
    events = [
        e for e in obs.journal().tail(20)
        if e["event"] == "step_anatomy" and e.get("worker_id") == 4
        and e["ts"] >= marker
    ]
    assert len(events) == 1


def test_fleet_attribution_cache_invalidates_on_ingest(
    obs_registry_snapshot,
):
    aggregator = TelemetryAggregator(journal_interval_s=1e9)
    aggregator.ingest(0, _wire_snap(0, 0.01, 1.0, 9.0))
    first = aggregator.fleet_attribution()
    assert aggregator.fleet_attribution() is first  # memoized per ingest
    aggregator.ingest(1, _wire_snap(1, 0.01, 9.0, 1.0))
    second = aggregator.fleet_attribution()
    assert second is not first
    assert second["fractions"]["data_wait"] == pytest.approx(0.5, abs=0.01)


def test_report_tolerates_degenerate_step_anatomy(tmp_path):
    """Forensics over arbitrary journals: zero-valued or garbage totals
    skip the worker instead of killing the whole postmortem CLI."""
    from elasticdl_tpu.obs import report

    events = [
        {"ts": 1.0, "event": "master_start", "job_name": "j"},
        {"ts": 2.0, "event": "step_anatomy", "worker_id": 0,
         "totals": {"data_wait": 0.0}},
        {"ts": 2.5, "event": "step_anatomy", "worker_id": 1,
         "totals": "garbage"},
        {"ts": 3.0, "event": "step_anatomy", "worker_id": 2,
         "totals": {"execute": 2.0}},
    ]
    summary = report.summarize(events)
    assert list(summary["compute"]["workers"]) == [2]
    report.render_report(summary)  # must not raise
    # All-degenerate journals simply have no compute section.
    summary = report.summarize(events[:3])
    assert "compute" not in summary
    report.render_report(summary)


# ---------------------------------------------------------------------------
# StepProfiler -> profile_window journal events
# ---------------------------------------------------------------------------


def test_profiler_journals_profile_window(tmp_path):
    from elasticdl_tpu.common.profiler import StepProfiler

    marker = time.time() - 1
    profiler = StepProfiler(str(tmp_path), "1,2", worker_id=5)
    profiler.before_steps(0)  # step 1 is in [1, 2): trace opens
    profiler.after_steps(1)   # last in-window step done: trace closes
    events = [
        e for e in obs.journal().tail(50)
        if e["event"] == "profile_window" and e["ts"] >= marker
    ]
    actions = [e["action"] for e in events]
    assert actions == ["open", "close"], events
    for event in events:
        assert event["worker_id"] == 5
        assert event["step_start"] == 1 and event["step_end"] == 2
        assert event["trace_dir"].endswith("worker_5")


# ---------------------------------------------------------------------------
# bench_regress gate
# ---------------------------------------------------------------------------


def _run_bench_regress(*argv, timeout=120):
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "bench_regress.py"), *argv],
        capture_output=True, text=True, timeout=timeout,
        cwd=REPO_ROOT, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_bench_regress_selftest():
    result = _run_bench_regress("--selftest")
    assert result.returncode == 0, result.stderr + result.stdout


def test_bench_regress_synthetic_regression_exits_nonzero(tmp_path):
    """ISSUE acceptance: a synthetic beyond-spread regression exits
    non-zero AND journals a schema-valid bench_regress event."""
    result = _run_bench_regress(
        "--synthetic", "regress", "--journal-dir", str(tmp_path)
    )
    assert result.returncode == 1, result.stderr + result.stdout
    assert "REGRESSED" in result.stdout
    journal_path = tmp_path / "events.jsonl"
    assert journal_path.exists()
    events = [
        json.loads(line)
        for line in journal_path.read_text().splitlines() if line
    ]
    regress = [e for e in events if e["event"] == "bench_regress"]
    assert len(regress) == 1
    assert regress[0]["verdict"] == "regressed"
    assert regress[0]["regressed"] == 1
    validator = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
         str(journal_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert validator.returncode == 0, validator.stderr


def test_bench_regress_synthetic_ok_passes():
    result = _run_bench_regress("--synthetic", "ok")
    assert result.returncode == 0, result.stderr + result.stdout
    assert "bench-regress: OK" in result.stdout


def test_bench_regress_fails_closed_on_crashed_bench():
    """A bench that emits rows then dies must NOT publish a passing
    claim — the gate fails on the bench's own exit code."""
    fake_bench = (
        f"{sys.executable} -c \"import json; "
        "print(json.dumps({'metric': "
        "'deepfm_train_samples_per_sec_per_chip', 'value': 87639.0})); "
        "raise SystemExit(3)\""
    )
    result = _run_bench_regress("--cmd", fake_bench)
    assert result.returncode == 1, result.stderr + result.stdout
    assert "BENCH_ERROR" in result.stdout


def test_bench_regress_fails_closed_on_dropped_metric(tmp_path):
    """A tracked baseline metric missing from the run gates — a metric
    that silently stops being emitted can never regress otherwise."""
    run = tmp_path / "partial.jsonl"
    run.write_text(json.dumps(
        {"metric": "deepfm_train_samples_per_sec_per_chip",
         "value": 87639.0}
    ) + "\n")
    result = _run_bench_regress("--input", str(run))
    assert result.returncode == 1, result.stderr + result.stdout
    assert "missing" in result.stdout


def test_bench_regress_judge_skips_untracked_rows():
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)

    baseline = {"m_tracked": 100.0, "m_untracked": 100.0}
    rows = [
        {"metric": "m_tracked", "value": 100.0},
        {"metric": "m_untracked", "value": 1.0, "tracked": False},
        {"metric": "m_unknown", "value": 5.0},
    ]
    result = bench_regress.judge(rows, baseline)
    assert result["verdict"] == "ok" and result["regressed"] == 0
    verdicts = {d["metric"]: d["verdict"] for d in result["details"]}
    assert verdicts == {"m_tracked": "ok", "m_untracked": "untracked"}
    rows[0]["value"] = 10.0
    assert bench_regress.judge(rows, baseline)["verdict"] == "regressed"


# ---------------------------------------------------------------------------
# Invariant-rule coverage of the new instrumentation call sites
# ---------------------------------------------------------------------------


def test_new_call_sites_pass_purity_and_cardinality_rules():
    """Satellite: the new instrumentation keeps (a) obs calls out of
    traced code and (b) per-worker/per-function names out of metric
    labels — and both rules still bite on seeded violations, so the
    clean pass is not vacuous."""
    from elasticdl_tpu.analysis.core import SourceFile, run_checks
    from elasticdl_tpu.analysis.jax_rules import check_trace_purity
    from elasticdl_tpu.analysis.rules import check_metric_label_cardinality

    new_call_sites = [
        os.path.join(REPO_ROOT, rel)
        for rel in (
            "elasticdl_tpu/obs/stepstats.py",
            "elasticdl_tpu/obs/telemetry.py",
            "elasticdl_tpu/obs/tracing.py",
            "elasticdl_tpu/obs/trace.py",
            "elasticdl_tpu/common/profiler.py",
            "elasticdl_tpu/worker/collective_worker.py",
            "elasticdl_tpu/worker/worker.py",
            "elasticdl_tpu/worker/master_client.py",
            "elasticdl_tpu/master/servicer.py",
            "elasticdl_tpu/master/task_manager.py",
            "elasticdl_tpu/parallel/elastic.py",
            "elasticdl_tpu/serving/ledger.py",
            "elasticdl_tpu/serving/frontend.py",
            "elasticdl_tpu/serving/batcher.py",
            "elasticdl_tpu/serving/replica_main.py",
            "elasticdl_tpu/obs/slo.py",
            "elasticdl_tpu/obs/report.py",
            "elasticdl_tpu/obs/top.py",
            "scripts/bench_regress.py",
            "scripts/loadgen.py",
        )
    ]
    violations = run_checks(
        new_call_sites, [check_trace_purity, check_metric_label_cardinality]
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    seeded_purity = SourceFile.parse(
        "seeded_purity.py",
        "import jax\n"
        "@jax.jit\n"
        "def step(x, anatomy):\n"
        "    anatomy.journal.record('step_anatomy', worker_id=1)\n"
        "    return x\n",
    )
    assert check_trace_purity(seeded_purity), (
        "trace-purity no longer catches journal calls under jit"
    )
    seeded_cardinality = SourceFile.parse(
        "seeded_card.py",
        "from elasticdl_tpu import obs\n"
        "obs.gauge('anatomy_phase_seconds', 'h',\n"
        "          labelnames=('worker_id',))\n",
    )
    assert check_metric_label_cardinality(seeded_cardinality), (
        "cardinality rule no longer catches worker_id labels"
    )


# ---------------------------------------------------------------------------
# Acceptance e2e: data-starved worker attributed end to end
# ---------------------------------------------------------------------------


def test_data_starved_straggler_attribution_end_to_end(
    obs_registry_snapshot, tmp_path
):
    """ISSUE acceptance: master + 3 heartbeating workers over real gRPC;
    one worker is artificially data-starved (slow steps, anatomy
    dominated by data_wait).  The straggler journal evidence names
    data_wait, and obs.report over the journal attributes it with
    phase fractions summing to ~1.0."""
    from elasticdl_tpu.common.grpc_utils import RetryPolicy
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.servicer import (
        MasterServicer,
        start_master_server,
    )
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.obs import report
    from elasticdl_tpu.parallel.elastic import HeartbeatReporter, WorldInfo
    from elasticdl_tpu.worker.master_client import MasterClient

    test_start = time.time() - 1
    task_manager = TaskManager(
        training_shards={"shard": 64}, records_per_task=64
    )
    rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 23456)
    rendezvous.set_worker_hosts(
        [(0, "127.0.0.1"), (1, "127.0.0.1"), (2, "127.0.0.1")]
    )
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        current_workers_fn=lambda: [w for w, _h in rendezvous.world()],
    )
    servicer = MasterServicer(
        task_manager=task_manager,
        rendezvous_server=rendezvous,
        telemetry=aggregator,
    )
    server, port = start_master_server(servicer, port=0)
    policy = RetryPolicy(
        timeout_s=5.0, max_attempts=3, base_backoff_s=0.01,
        max_backoff_s=0.05, jitter=0.0, total_budget_s=30.0,
        wait_for_ready=True,
    )
    clients = [
        MasterClient(f"localhost:{port}", worker_id=wid, retry_policy=policy)
        for wid in range(3)
    ]
    # Worker 2 is DATA-STARVED: slow steps whose anatomy shows the time
    # going to data_wait, not the device.  Healthy workers are
    # execute-dominant.
    telemetries = {}
    for wid in range(3):
        starved = wid == 2
        telemetry = WorkerTelemetry(wid, step_window=4)
        anatomy = _fed_anatomy(
            worker_id=wid,
            data_wait=6.0 if starved else 0.1,
            stage=0.05,
            execute=0.5 if starved else 0.9,
            bookkeep=0.05,
            examples=256,
            windows=3,
        )
        telemetry.bind_anatomy(anatomy)
        per_step = 0.5 if starved else 0.01
        for _ in range(4):
            telemetry.record_steps(4, duration_s=4 * per_step, records=64)
        telemetries[wid] = telemetry
    reporters = [
        HeartbeatReporter(
            clients[wid],
            WorldInfo(rank=wid, world_size=3, rendezvous_id=1,
                      coordinator_addr=""),
            host="127.0.0.1",
            interval_s=0.05,
            telemetry=telemetries[wid],
        )
        for wid in range(3)
    ]
    try:
        for reporter in reporters:
            reporter.start()
        deadline = time.time() + 60
        while time.time() < deadline and 2 not in aggregator.stragglers():
            time.sleep(0.02)
        assert 2 in aggregator.stragglers(), "starved worker never flagged"

        detected = [
            e for e in obs.journal().tail(500)
            if e["event"] == "straggler_detected" and e["ts"] >= test_start
        ]
        assert detected and detected[-1]["worker_id"] == 2
        # The upgraded evidence: not just "slow" — slow because of
        # data_wait, quantified against the fleet.
        assert detected[-1]["dominant_phase"] == "data_wait"
        assert detected[-1]["phase_ratio"] > 2
        assert aggregator.fleet_attribution()["workers"][2][
            "dominant_phase"
        ] == "data_wait"
    finally:
        for reporter in reporters:
            reporter.stop()
        for client in clients:
            client.close()
        server.stop(grace=None)

    # ---- obs.report over the e2e's journal -----------------------------
    journal_path = tmp_path / "events.jsonl"
    with open(journal_path, "w", encoding="utf-8") as f:
        for event in obs.journal().tail(1000):
            if event["ts"] >= test_start:
                f.write(json.dumps(event) + "\n")
    summary = report.summarize(report.load_events(str(journal_path)))
    compute = summary["compute"]
    assert sum(compute["fractions"].values()) == pytest.approx(1.0, abs=0.02)
    worker = compute["workers"][2]
    assert worker["dominant_phase"] == "data_wait"
    assert sum(worker["fractions"].values()) == pytest.approx(1.0, abs=0.02)
    assert compute["workers"][0]["dominant_phase"] == "execute"
    attribution = summary["straggler_attribution"]
    assert attribution[-1]["worker_id"] == 2
    assert attribution[-1]["dominant_phase"] == "data_wait"
    rendered = report.render_report(summary)
    assert "compute-phase attribution" in rendered
    assert "straggler worker 2" in rendered
    assert "data_wait" in rendered
    # The e2e journal schema-validates (step_anatomy etc. registered).
    validator = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
         str(journal_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert validator.returncode == 0, validator.stderr
