"""Wide&Deep + DeepFM zoo model tests (BASELINE configs 3-4) through the
sharded-embedding (PS-mode) trainer on the 8-device mesh."""

import numpy as np
import pytest

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from model_zoo import datasets


def _batches(zoo, n=64, mb=16, seed=0):
    reader = datasets.synthetic_ctr_reader(
        n=n, num_dense=zoo.NUM_DENSE, num_categorical=zoo.NUM_CAT,
        vocab_size=100, seed=seed,
    )
    from elasticdl_tpu.data.dataset import Dataset, _stack
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task = pb.Task(task_id=1, shard_name="s", start=0, end=n)
    records = list(
        zoo.dataset_fn(
            Dataset.from_generator(lambda: reader.read_records(task)),
            "training",
            reader.metadata,
        )
    )
    for i in range(0, n, mb):
        feats, labels = _stack(records[i : i + mb])
        yield feats, labels


@pytest.mark.parametrize("model_def", ["wide_and_deep", "deepfm"])
def test_ctr_model_trains_on_sharded_mesh(model_def):
    if model_def == "wide_and_deep":
        from model_zoo.wide_and_deep import wide_and_deep as zoo
    else:
        from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100),
        zoo.loss,
        zoo.optimizer(lr=0.01),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(lr=0.01),
    )
    losses = []
    for epoch in range(6):
        for feats, labels in _batches(zoo, n=64, mb=16):
            losses.append(float(trainer.train_step(feats, labels)))
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    # Tables actually sharded across all 8 devices.
    state = trainer.state
    table = next(iter(state.tables.values()))
    assert len(table.sharding.device_set) == 8
    # Eval produces logits + finite metrics.
    feats, labels = next(_batches(zoo, n=16, mb=16))
    out = trainer.eval_step(feats)
    assert out.shape == (16,) and np.isfinite(out).all()
    metrics = {
        name: fn(out, labels) for name, fn in zoo.eval_metrics_fn().items()
    }
    assert 0.0 <= metrics["auc"] <= 1.0


def test_deepfm_split_table_layout_trains():
    """The strict-mode large-table layout (BASELINE.md table-scale
    probe): split_tables=True builds TWO embedding tables (linear dim-1
    + fm dim-8, the reference's layout) and still learns."""
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100, split_tables=True),
        zoo.loss,
        zoo.optimizer(lr=0.01),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(lr=0.01),
    )
    losses = []
    for epoch in range(6):
        for feats, labels in _batches(zoo, n=64, mb=16):
            losses.append(float(trainer.train_step(feats, labels)))
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    state = trainer.state
    assert len(state.tables) == 2, list(state.tables)
    dims = sorted(
        trainer._table_specs[k].dim for k in state.tables
    )
    assert dims == [1, zoo.custom_model().embedding_dim]


def test_deepfm_auto_layout_selection():
    """Auto layout: merged table except under strict per-step apply at
    >SPLIT_TABLE_ROWS rows (the measured destination-block crossover)."""
    from model_zoo.deepfm import deepfm_functional_api as zoo

    big_vocab = zoo.SPLIT_TABLE_ROWS // zoo.NUM_CAT + 1
    assert zoo.custom_model(vocab_size=100)._split(100 * zoo.NUM_CAT) is False
    strict_big = zoo.custom_model(vocab_size=big_vocab, sparse_apply_every=1)
    assert strict_big._split(big_vocab * zoo.NUM_CAT) is True
    windowed_big = zoo.custom_model(
        vocab_size=big_vocab, sparse_apply_every=16
    )
    assert windowed_big._split(big_vocab * zoo.NUM_CAT) is False
    forced = zoo.custom_model(vocab_size=100, split_tables=True)
    assert forced._split(100 * zoo.NUM_CAT) is True
    # 'auto' resolves inside custom_model from the model's own vocab,
    # with the trainer's threshold: strict+merged small, windowed+merged
    # big — auto never reaches the strict-large split regime.
    auto_small = zoo.custom_model(vocab_size=100, sparse_apply_every="auto")
    assert auto_small.sparse_apply_every == 1
    assert auto_small._split(100 * zoo.NUM_CAT) is False
    auto_big = zoo.custom_model(
        vocab_size=big_vocab, sparse_apply_every="auto"
    )
    from elasticdl_tpu.parallel.ps_trainer import AUTO_APPLY_W

    assert auto_big.sparse_apply_every == AUTO_APPLY_W
    assert auto_big._split(big_vocab * zoo.NUM_CAT) is False
    # Forced split layout doubles the resident rows (linear + fm), and
    # auto resolves from the SAME count the trainer will see at init —
    # half the threshold vocab crosses into windowed when split.
    half_vocab = zoo.SPLIT_TABLE_ROWS // (2 * zoo.NUM_CAT) + 1
    auto_split = zoo.custom_model(
        vocab_size=half_vocab, split_tables=True, sparse_apply_every="auto"
    )
    assert auto_split.sparse_apply_every == AUTO_APPLY_W
    auto_merged = zoo.custom_model(
        vocab_size=half_vocab, sparse_apply_every="auto"
    )
    assert auto_merged.sparse_apply_every == 1
