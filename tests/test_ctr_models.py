"""Wide&Deep + DeepFM zoo model tests (BASELINE configs 3-4) through the
sharded-embedding (PS-mode) trainer on the 8-device mesh."""

import numpy as np
import pytest

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from model_zoo import datasets


def _batches(zoo, n=64, mb=16, seed=0):
    reader = datasets.synthetic_ctr_reader(
        n=n, num_dense=zoo.NUM_DENSE, num_categorical=zoo.NUM_CAT,
        vocab_size=100, seed=seed,
    )
    from elasticdl_tpu.data.dataset import Dataset, _stack
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task = pb.Task(task_id=1, shard_name="s", start=0, end=n)
    records = list(
        zoo.dataset_fn(
            Dataset.from_generator(lambda: reader.read_records(task)),
            "training",
            reader.metadata,
        )
    )
    for i in range(0, n, mb):
        feats, labels = _stack(records[i : i + mb])
        yield feats, labels


@pytest.mark.parametrize("model_def", ["wide_and_deep", "deepfm"])
def test_ctr_model_trains_on_sharded_mesh(model_def):
    if model_def == "wide_and_deep":
        from model_zoo.wide_and_deep import wide_and_deep as zoo
    else:
        from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100),
        zoo.loss,
        zoo.optimizer(lr=0.01),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(lr=0.01),
    )
    losses = []
    for epoch in range(6):
        for feats, labels in _batches(zoo, n=64, mb=16):
            losses.append(float(trainer.train_step(feats, labels)))
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    # Tables actually sharded across all 8 devices.
    state = trainer.state
    table = next(iter(state.tables.values()))
    assert len(table.sharding.device_set) == 8
    # Eval produces logits + finite metrics.
    feats, labels = next(_batches(zoo, n=16, mb=16))
    out = trainer.eval_step(feats)
    assert out.shape == (16,) and np.isfinite(out).all()
    metrics = {
        name: fn(out, labels) for name, fn in zoo.eval_metrics_fn().items()
    }
    assert 0.0 <= metrics["auc"] <= 1.0
