"""`elasticdl evaluate` / `elasticdl predict` under cluster strategies —
real multi-process worlds (round-1 weak #10: these modes were only ever
tested in Local mode).

The evaluate job doubles as the cluster TensorBoard e2e: metrics
aggregated by the master's EvaluationService land in event files the TB
reader can load.
"""

import pytest

# Tier-1 fast gate runs `-m 'not slow'` (see Makefile test-fast).
pytestmark = [pytest.mark.slow, pytest.mark.e2e]

import glob
import os

import pytest

from elasticdl_tpu.client import api

WORKER_ENV = {
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "ELASTICDL_FORCE_PLATFORM": "cpu",
    "JAX_PLATFORMS": "cpu",
}


@pytest.fixture
def worker_env(monkeypatch):
    monkeypatch.setenv("ELASTICDL_FORCE_PLATFORM", "cpu")
    monkeypatch.setenv(
        "ELASTICDL_WORKER_ENV",
        ";".join(f"{k}={v}" for k, v in WORKER_ENV.items()),
    )


def _read_scalars(log_dir):
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    acc = EventAccumulator(log_dir)
    acc.Reload()
    return {
        tag: [(e.step, e.value) for e in acc.Scalars(tag)]
        for tag in acc.Tags()["scalars"]
    }


def test_evaluate_under_allreduce_two_workers(tmp_path, worker_env):
    """Evaluation-only job through a 2-process world: the version-0 round
    runs through trigger_evaluation, workers gather outputs collectively,
    and the master aggregates metrics (asserted via the TB event file)."""
    log_dir = str(tmp_path / "tb")
    rc = api.evaluate(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "mnist.mnist_functional_api",
            "--validation_data", "synthetic://mnist?n=128&seed=1",
            "--records_per_task", "64",
            "--minibatch_size", "16",
            "--num_workers", "2",
            "--distribution_strategy", "AllreduceStrategy",
            f"--checkpoint_dir={tmp_path / 'ckpt'}",
            "--job_name", "evaljob",
            "--tensorboard_log_dir", log_dir,
        ]
    )
    assert rc == 0
    scalars = _read_scalars(log_dir)
    eval_tags = [t for t in scalars if t.startswith("eval/")]
    assert eval_tags, f"no eval metrics written: {scalars.keys()}"
    # All 128 validation examples were aggregated in the version-0 round.
    assert any(
        scalars[t][0][0] == 0 for t in eval_tags
    ), "metrics not recorded at model version 0"


def test_predict_under_ps_two_workers(tmp_path, worker_env):
    """Prediction-only job through a 2-process PS-mode world (sharded
    tables): every prediction record is processed and the job completes."""
    rc = api.predict(
        [
            "--model_zoo", "model_zoo",
            "--model_def", "deepfm.deepfm_functional_api",
            "--prediction_data", "synthetic://criteo?n=128&vocab=100",
            "--model_params", "vocab_size=100",
            "--records_per_task", "64",
            "--minibatch_size", "16",
            "--num_workers", "2",
            "--distribution_strategy", "ParameterServerStrategy",
            f"--checkpoint_dir={tmp_path / 'ckpt'}",
            "--job_name", "predictjob",
        ]
    )
    assert rc == 0
