"""Evaluation service aggregation tests.

Parity surface: elasticdl/python/tests/evaluation_service_test.py in the
reference (round scheduling + metric aggregation from worker reports).
"""

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.task_manager import TaskManager


def metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=-1) == labels
        )
    }


def report(service, version, outputs, labels):
    service.report_evaluation_metrics(
        version,
        [tensor_utils.ndarray_to_pb(np.asarray(outputs), name="output")],
        [tensor_utils.ndarray_to_pb(np.asarray(labels))],
    )


def make_service(eval_records=20, records_per_task=10):
    manager = TaskManager(
        training_shards={"t": 10},
        evaluation_shards={"v": eval_records},
        records_per_task=records_per_task,
    )
    return EvaluationService(manager, eval_metrics_fn=metrics_fn), manager


def test_round_aggregates_all_reports():
    service, _ = make_service()  # 2 eval tasks expected per round
    service.trigger_evaluation(model_version=3)
    out1 = np.array([[0.9, 0.1], [0.2, 0.8]])
    out2 = np.array([[0.7, 0.3]])
    report(service, 3, out1, np.array([0, 1]))
    assert service.latest_metrics == {}  # round not complete yet
    report(service, 3, out2, np.array([1]))
    assert service.latest_metrics == {"accuracy": 2.0 / 3.0}


def test_duplicate_report_after_finalize_is_dropped():
    """At-least-once retry can deliver a round's report twice; the stray
    duplicate must not overwrite the full round's metrics (not at arrival,
    and not later via finalize())."""
    service, _ = make_service()
    service.trigger_evaluation(model_version=5)
    good = np.array([[0.9, 0.1], [0.2, 0.8]])
    report(service, 5, good, np.array([0, 1]))
    report(service, 5, good, np.array([0, 1]))  # completes the round: acc=1.0
    assert service.latest_metrics == {"accuracy": 1.0}
    # Late duplicate with all-wrong labels.
    report(service, 5, good, np.array([1, 0]))
    assert service.latest_metrics == {"accuracy": 1.0}
    service.finalize()  # must not resurrect the dropped duplicate
    assert service.latest_metrics == {"accuracy": 1.0}
