"""Evaluation service aggregation tests.

Parity surface: elasticdl/python/tests/evaluation_service_test.py in the
reference (round scheduling + metric aggregation from worker reports).
"""

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.task_manager import TaskManager


def metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=-1) == labels
        )
    }


def report(service, version, outputs, labels, task_id=0):
    service.report_evaluation_metrics(
        version,
        [tensor_utils.ndarray_to_pb(np.asarray(outputs), name="output")],
        [tensor_utils.ndarray_to_pb(np.asarray(labels))],
        task_id=task_id,
    )


def make_service(eval_records=20, records_per_task=10):
    manager = TaskManager(
        training_shards={"t": 10},
        evaluation_shards={"v": eval_records},
        records_per_task=records_per_task,
    )
    return EvaluationService(manager, eval_metrics_fn=metrics_fn), manager


def _eval_tasks(manager, n):
    """Pull the round's EVALUATION tasks (they interleave at the front)."""
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    tasks = [manager.get(0) for _ in range(n)]
    assert all(t.type == pb.EVALUATION for t in tasks)
    return tasks


def test_round_aggregates_all_reports():
    """Rounds finalize on TASK completions, and a task may flush SEVERAL
    chunked metric reports before completing (the worker's eval-memory
    bound) — all chunks must aggregate."""
    service, manager = make_service()  # 2 eval tasks per round
    service.trigger_evaluation(model_version=3)
    t1, t2 = _eval_tasks(manager, 2)
    # Task 1 flushes two chunks, then completes.
    report(service, 3, np.array([[0.9, 0.1]]), np.array([0]), t1.task_id)
    report(service, 3, np.array([[0.2, 0.8]]), np.array([1]), t1.task_id)
    manager.report(t1.task_id, True, 0)
    assert service.latest_metrics == {}  # round not complete yet
    report(service, 3, np.array([[0.7, 0.3]]), np.array([1]), t2.task_id)
    manager.report(t2.task_id, True, 0)
    assert service.latest_metrics == {"accuracy": 2.0 / 3.0}


def test_duplicate_report_after_finalize_is_dropped():
    """At-least-once retry can deliver a round's reports twice; the stray
    duplicates must not overwrite the full round's metrics (not at
    arrival, and not later via finalize())."""
    service, manager = make_service()
    service.trigger_evaluation(model_version=5)
    t1, t2 = _eval_tasks(manager, 2)
    good = np.array([[0.9, 0.1], [0.2, 0.8]])
    report(service, 5, good, np.array([0, 1]), t1.task_id)
    manager.report(t1.task_id, True, 0)
    report(service, 5, good, np.array([0, 1]), t2.task_id)
    manager.report(t2.task_id, True, 0)  # completes the round: acc=1.0
    assert service.latest_metrics == {"accuracy": 1.0}
    # Late duplicate with all-wrong labels (and a stray completion).
    report(service, 5, good, np.array([1, 0]))
    assert service.latest_metrics == {"accuracy": 1.0}
    service.finalize()  # must not resurrect the dropped duplicate
    assert service.latest_metrics == {"accuracy": 1.0}


def test_dead_attempt_chunks_never_promoted():
    """At-least-once retry during eval: a failed attempt's PARTIAL chunks
    must not double-count rows — each attempt has a fresh task id, and
    only the completing attempt's staged chunks promote into the round."""
    service, manager = make_service()
    service.trigger_evaluation(model_version=9)
    t1, t2 = _eval_tasks(manager, 2)
    good = np.array([[0.9, 0.1], [0.2, 0.8]])
    bad = np.array([[0.1, 0.9], [0.8, 0.2]])  # all-wrong attempt chunks
    # Attempt 1 of task 1 flushes a chunk, then DIES (report failure).
    report(service, 9, bad, np.array([0, 1]), t1.task_id)
    manager.report(t1.task_id, False, 0)
    # The retry (fresh id) redoes the task from scratch.
    retry = manager.get(1)
    assert retry.task_id != t1.task_id
    report(service, 9, good, np.array([0, 1]), retry.task_id)
    manager.report(retry.task_id, True, 1)
    report(service, 9, good, np.array([0, 1]), t2.task_id)
    manager.report(t2.task_id, True, 0)
    # Dead attempt's rows excluded: accuracy is computed on 4 rows, all
    # correct — not dragged down by the stale chunk.
    assert service.latest_metrics == {"accuracy": 1.0}
