"""Native <-> Python ETRF codec parity tests.

The C++ codec (native/recordfile.cc) must be byte-identical with the
pure-Python reference implementation (data/recordfile.py) in both
directions: files written by either are read by both, CRC corruption is
detected by both, and range semantics (clamping, empty) match.
"""

import os

import numpy as np
import pytest

from elasticdl_tpu import native
from elasticdl_tpu.data import recordfile

pytestmark = pytest.mark.skipif(
    native.record_file() is None,
    reason="no C++ toolchain; native codec unavailable",
)

RECORDS = [
    b"hello",
    b"",
    b"x" * 5000,
    np.arange(64, dtype=np.int32).tobytes(),
    b"\x00\xff" * 33,
]


def test_python_written_native_read(tmp_path):
    path = str(tmp_path / "py.etrf")
    recordfile.write_records(path, RECORDS)  # pure-Python writer
    codec = native.record_file()
    assert codec.count_records(path) == len(RECORDS)
    assert list(codec.read_range(path, 0, len(RECORDS))) == RECORDS
    # Range semantics: clamping + interior slice + empty.
    assert list(codec.read_range(path, 2, 4)) == RECORDS[2:4]
    assert list(codec.read_range(path, -3, 99)) == RECORDS
    assert list(codec.read_range(path, 4, 4)) == []


def test_native_written_python_read(tmp_path):
    path = str(tmp_path / "native.etrf")
    codec = native.record_file()
    assert codec.write_records(path, RECORDS) == len(RECORDS)
    # Force the pure-Python read path for the parity check.
    assert recordfile._count_records_py(path) == len(RECORDS)
    assert list(recordfile._read_range_py(path, 0, len(RECORDS))) == RECORDS


def test_native_written_byte_identical_to_python(tmp_path):
    py_path = str(tmp_path / "py.etrf")
    native_path = str(tmp_path / "native.etrf")
    recordfile.write_records(py_path, RECORDS)
    native.record_file().write_records(native_path, RECORDS)
    with open(py_path, "rb") as a, open(native_path, "rb") as b:
        assert a.read() == b.read()


def test_crc_corruption_detected_by_both(tmp_path):
    path = str(tmp_path / "corrupt.etrf")
    recordfile.write_records(path, [b"payload-one", b"payload-two"])
    # Flip one payload byte of record 0 (after 8B header + 8B record head).
    with open(path, "r+b") as f:
        f.seek(8 + 8 + 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(IOError, match="CRC"):
        list(native.record_file().read_range(path, 0, 2))
    with pytest.raises(recordfile.RecordFileError, match="CRC"):
        list(recordfile._read_range_py(path, 0, 2))


def test_corrupt_length_field_is_an_error_not_an_overflow(tmp_path):
    """A flipped bit in a record's LENGTH field must surface as a clean
    error: the native reader bounds every record against the caller's
    buffer before writing (a naive implementation heap-overflows here)."""
    path = str(tmp_path / "len.etrf")
    recordfile.write_records(path, [b"abcdef", b"ghijkl"])
    with open(path, "r+b") as f:
        f.seek(8)  # record 0's u32 length field
        f.write((6 | 0x40000000).to_bytes(4, "little"))
    with pytest.raises(IOError, match="length|truncated"):
        list(native.record_file().read_range(path, 0, 2))


def test_bad_files_rejected(tmp_path):
    codec = native.record_file()
    garbage = tmp_path / "garbage.bin"
    garbage.write_bytes(b"not a record file at all")
    with pytest.raises(IOError):
        codec.count_records(str(garbage))
    with pytest.raises(IOError):
        codec.count_records(str(tmp_path / "missing.etrf"))


def test_reader_dispatches_to_native(tmp_path, monkeypatch):
    """data/recordfile.py's public functions use the native codec when
    built — the docstring's promise, previously unimplemented."""
    path = str(tmp_path / "dispatch.etrf")
    recordfile.write_records(path, RECORDS)
    calls = []
    codec = native.record_file()
    real = codec.read_range

    def spy(path, start, end):
        calls.append((start, end))
        return real(path, start, end)

    monkeypatch.setattr(codec, "read_range", spy)
    assert list(recordfile.read_range(path, 1, 3)) == RECORDS[1:3]
    assert calls == [(1, 3)]
    # Escape hatch: the env var forces the Python codec.
    monkeypatch.setenv("ELASTICDL_DISABLE_NATIVE", "1")
    assert list(recordfile.read_range(path, 1, 3)) == RECORDS[1:3]
    assert calls == [(1, 3)]
