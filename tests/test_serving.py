"""Model-export-for-serving tests (reference: model_handler
get_model_to_export — SURVEY.md §3.6).

Done-criterion from the round-1 review: `--output` produces an artifact a
fresh process can serve with bit-identical eval outputs — including
PS-mode's mesh-sharded embedding tables, which must be materialized into
the artifact without the exporter holding a full table in memory.
"""

import json
import os
import subprocess
import sys

import numpy as np

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from elasticdl_tpu.serving import export_model, load_for_serving
from test_ctr_models import _batches


def _trained_deepfm(steps=4):
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100),
        zoo.loss,
        zoo.optimizer(lr=0.01),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(lr=0.01),
    )
    batches = list(_batches(zoo, n=64, mb=16))
    for feats, labels in batches[:steps]:
        trainer.train_step(feats, labels)
    return zoo, trainer, batches


def test_export_then_serve_bit_identical(tmp_path):
    zoo, trainer, batches = _trained_deepfm()
    out_dir = str(tmp_path / "export")
    export_model(
        trainer,
        out_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
        chunk_rows=7,  # force multi-chunk streaming of every table
    )
    # Artifact layout: signature + variables + one file per table.
    sig = json.loads((tmp_path / "export" / "signature.json").read_text())
    assert sig["format"].startswith("elasticdl_tpu_serving/")
    assert len(sig["tables"]) >= 1
    for meta in sig["tables"]:
        assert os.path.exists(os.path.join(out_dir, meta["file"]))

    served = load_for_serving(out_dir)
    feats, _ = batches[0]
    # vs the trainer's mesh-jitted eval: numerically equivalent (XLA
    # reduction order differs between the 8-device program and the
    # single-host serving apply, so exact bits can't match).
    expected = trainer.eval_step(feats)
    got = np.asarray(served.predict(feats))
    np.testing.assert_allclose(np.asarray(expected), got, rtol=1e-5)
    # Serving is deterministic: repeat predictions are bit-identical.
    np.testing.assert_array_equal(got, np.asarray(served.predict(feats)))

    # Logical [vocab, dim] view for external consumers.
    logical = served.logical_tables()
    for meta in sig["tables"]:
        assert logical[meta["key"]].shape == (
            meta["vocab_size"],
            meta["dim"],
        )


def test_serving_in_fresh_process(tmp_path):
    """The artifact is self-contained: a brand-new interpreter (no trainer,
    no mesh) loads it and predicts BIT-IDENTICALLY to in-process serving."""
    zoo, trainer, batches = _trained_deepfm(steps=2)
    out_dir = str(tmp_path / "export")
    export_model(
        trainer,
        out_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    feats, _ = batches[0]
    expected = np.asarray(load_for_serving(out_dir).predict(feats))
    np.savez(tmp_path / "feats.npz", **feats)

    script = f"""
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize may force TPU
import numpy as np
from elasticdl_tpu.serving import load_for_serving
served = load_for_serving({out_dir!r})
feats = dict(np.load({str(tmp_path / 'feats.npz')!r}))
out = np.asarray(served.predict(feats))
np.save({str(tmp_path / 'out.npy')!r}, out)
"""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "ELASTICDL_FORCE_PLATFORM": "cpu",
    }
    subprocess.run(
        [sys.executable, "-c", script],
        check=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
        timeout=300,
    )
    got = np.load(tmp_path / "out.npy")
    np.testing.assert_array_equal(expected, got)


def test_export_records_resolved_model_params(tmp_path):
    """Flag-dependent model structure must survive the serving
    round-trip: save_model records the RESOLVED model params (the job
    flags model_utils injects — sparse_apply_every, use_bf16), so a
    reload rebuilds the exact trained structure.  The real-world hazard:
    DeepFM trained at >10M rows with --sparse_apply_every=16 uses the
    MERGED table layout; an artifact recording only the raw
    --model_params would rebuild the SPLIT layout at load and fail on
    missing parameters."""
    import json as _json

    from elasticdl_tpu.client.api import save_model
    from elasticdl_tpu.common.args import parse_master_args

    zoo, trainer, batches = _trained_deepfm()
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        "--training_data=synthetic://criteo?n=64&vocab=100",
        "--model_params=vocab_size=100",
        "--sparse_apply_every=16",
    ])
    out_dir = str(tmp_path / "export")
    save_model(trainer, out_dir, args)
    sig = _json.loads((tmp_path / "export" / "signature.json").read_text())
    recorded = sig["model_params"]
    assert "sparse_apply_every=16" in recorded, recorded
    assert "vocab_size=100" in recorded, recorded
    # And the reload consumes them: the rebuilt model sees the flag.
    served = load_for_serving(out_dir)
    assert served._model.sparse_apply_every == 16
    feats, _ = batches[0]
    got = np.asarray(served.predict(feats))
    expected = np.asarray(trainer.eval_step(feats))
    np.testing.assert_allclose(expected, got, rtol=1e-5)


def test_format_dict_params_round_trip():
    from elasticdl_tpu.common.args import (
        format_dict_params,
        parse_dict_params,
    )

    params = {"vocab_size": 100, "use_bf16": True, "lr": 0.5,
              "mode": "auto", "split_tables": False}
    assert parse_dict_params(format_dict_params(params)) == params
    # '=' inside a string value round-trips (parse splits items on ','
    # then on the FIRST '=') — a URL-valued param must not abort the
    # end-of-training export (round-4 ADVICE).
    url_params = {"init_from": "gs://bkt/ckpt?ver=3", "vocab_size": 7}
    assert parse_dict_params(format_dict_params(url_params)) == url_params
    import pytest as _pytest

    # ',' is genuinely non-round-trippable: it splits the item list.
    with _pytest.raises(ValueError):
        format_dict_params({"bad": "a,b"})


# ---------------------------------------------------------------------------
# Serving plane: micro-batcher, hot-swap runtime, elastic fleet e2e (PR 13)
# ---------------------------------------------------------------------------

import importlib.util
import shutil
import threading
import time

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.serving.batcher import (
    BatcherConfig,
    MicroBatcher,
    QueueFullError,
    RequestError,
    bucket_for,
    bucket_sizes,
    pad_features,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, "scripts", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    # Registered so dataclass string annotations (`from __future__ import
    # annotations`) can resolve against the module's namespace.
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_bucket_math():
    assert bucket_sizes(64) == (1, 2, 4, 8, 16, 32, 64)
    assert bucket_sizes(48) == (1, 2, 4, 8, 16, 32, 48)
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    assert bucket_for(8, (1, 2, 4, 8)) == 8
    feats = {"dense": np.ones((3, 2), np.float32),
             "cat": np.ones((3, 4), np.int32)}
    padded = pad_features(feats, 8)
    assert padded["dense"].shape == (8, 2)
    assert padded["cat"].dtype == np.int32
    assert np.array_equal(padded["dense"][:3], feats["dense"])
    assert not padded["dense"][3:].any()
    # Exact-size arrays pass through untouched.
    assert pad_features(feats, 3)["dense"] is feats["dense"]


def test_batcher_size_trigger_beats_latency_budget(
    journal_file, obs_registry_snapshot
):
    """The race the batcher exists to arbitrate: a FULL batch dispatches
    immediately (long before the latency budget), while a lone request
    dispatches at the budget (long before a full batch would form)."""
    dispatches = []

    def execute(features, n_valid):
        rows = features["x"].shape[0]
        dispatches.append((rows, n_valid))
        return np.arange(rows, dtype=np.float32)

    # Budget deliberately huge: only the size trigger can fire fast.
    batcher = MicroBatcher(
        execute,
        BatcherConfig(max_batch_size=4, max_wait_us=2_000_000,
                      queue_limit=16),
    ).start()
    try:
        t0 = time.monotonic()
        out = batcher.predict({"x": np.zeros((4, 1), np.float32)})
        full_elapsed = time.monotonic() - t0
        assert full_elapsed < 1.0, "full batch waited on the latency budget"
        np.testing.assert_array_equal(out, np.arange(4, dtype=np.float32))
        assert dispatches[-1] == (4, 4)
    finally:
        batcher.stop()

    # Budget small: a lone 1-row request must NOT wait for 4 rows.
    dispatches.clear()
    batcher = MicroBatcher(
        execute,
        BatcherConfig(max_batch_size=4, max_wait_us=50_000, queue_limit=16),
    ).start()
    try:
        t0 = time.monotonic()
        out = batcher.predict({"x": np.zeros((1, 1), np.float32)})
        lone_elapsed = time.monotonic() - t0
        assert 0.04 <= lone_elapsed < 1.5, lone_elapsed
        # Padded to bucket 1, one valid row, pad rows sliced off.
        assert dispatches[-1] == (1, 1)
        assert out.shape[0] == 1
    finally:
        batcher.stop()


def test_batcher_sheds_on_full_queue(journal_file, obs_registry_snapshot):
    """Admission past queue_limit is an immediate, journaled rejection —
    never a silent unbounded backlog."""
    gate = threading.Event()
    executing = threading.Event()

    def execute(features, n_valid):
        executing.set()
        gate.wait(timeout=30)
        return np.zeros(features["x"].shape[0], np.float32)

    shed_rows = []
    batcher = MicroBatcher(
        execute,
        BatcherConfig(max_batch_size=1, max_wait_us=100, queue_limit=2),
        on_shed=lambda rows: shed_rows.append(rows),
    ).start()
    try:
        first = batcher.submit({"x": np.zeros((1, 1), np.float32)})
        assert executing.wait(timeout=10)  # batcher thread is wedged
        queued = [
            batcher.submit({"x": np.zeros((1, 1), np.float32)})
            for _ in range(2)
        ]
        assert batcher.queue_depth() == 2
        with pytest.raises(QueueFullError):
            batcher.submit({"x": np.zeros((1, 1), np.float32)})
        assert shed_rows == [1]
        gate.set()
        for req in [first] + queued:
            assert req.wait(timeout=30).shape == (1,)
    finally:
        gate.set()
        batcher.stop()
    shed = [e for e in _events(journal_file) if e["event"] == "request_shed"]
    assert len(shed) == 1
    assert shed[0]["reason"] == "queue_full"
    assert shed[0]["queue_limit"] == 2


def test_batcher_drops_expired_deadline(journal_file, obs_registry_snapshot):
    """A request whose deadline expired while queued is dropped at
    dispatch (its device slot would be wasted work) and the ledger
    callback sees outcome='dropped'."""
    gate = threading.Event()
    executing = threading.Event()
    outcomes = []

    def execute(features, n_valid):
        executing.set()
        gate.wait(timeout=30)
        return np.zeros(features["x"].shape[0], np.float32)

    batcher = MicroBatcher(
        execute,
        BatcherConfig(max_batch_size=1, max_wait_us=100, queue_limit=8),
        on_request=lambda phases, outcome, rows: outcomes.append(outcome),
    ).start()
    try:
        batcher.submit({"x": np.zeros((1, 1), np.float32)})
        assert executing.wait(timeout=10)
        doomed = batcher.submit(
            {"x": np.zeros((1, 1), np.float32)}, deadline_s=0.01
        )
        time.sleep(0.1)
        gate.set()
        with pytest.raises(RequestError, match="deadline"):
            doomed.wait(timeout=30)
    finally:
        gate.set()
        batcher.stop()
    assert "dropped" in outcomes and "served" in outcomes
    shed = [e for e in _events(journal_file) if e["event"] == "request_shed"]
    assert any(e["reason"] == "deadline" for e in shed)


def _exported_deepfm(tmp_path, steps=2):
    """Train, export, and return (model_dir, feats, expected) where
    expected is the trainer's mesh-jitted eval at export time."""
    zoo, trainer, batches = _trained_deepfm(steps=steps)
    out_dir = str(tmp_path / "gen1")
    export_model(
        trainer, out_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    feats, _ = batches[0]
    feats = {k: np.asarray(v) for k, v in feats.items()}
    return trainer, batches, out_dir, feats, np.asarray(trainer.eval_step(feats))


def test_replica_padded_buckets_no_retrace(tmp_path, obs_registry_snapshot):
    """After bucket warmup, live traffic of every batch size <= max
    reuses a cached executable — the RetraceWatcher (PR 8) sees ZERO new
    compiles across the whole size sweep."""
    from elasticdl_tpu.obs.stepstats import RetraceWatcher
    from elasticdl_tpu.serving.runtime import ServingReplica

    _, _, model_dir, feats, expected = _exported_deepfm(tmp_path)
    replica = ServingReplica(model_dir, model_zoo="model_zoo")
    buckets = bucket_sizes(16)
    watcher = RetraceWatcher()
    watcher.watch(replica.jitted_entrypoints)
    replica.warmup({k: v[:1] for k, v in feats.items()}, buckets)
    warm_compiles = watcher.poll().get("serve_step", 0)
    assert warm_compiles == len(buckets)
    full = replica.execute(feats, n_valid=16)
    for rows in (1, 2, 3, 5, 7, 11, 16):
        sub = {k: v[:rows] for k, v in feats.items()}
        # Padding rows never perturb real rows: padded up to the SAME
        # compiled shape, the sub-batch rows are BIT-identical to the
        # full batch's (same executable, same reduction order).
        np.testing.assert_array_equal(
            replica.execute(pad_features(sub, 16), n_valid=rows)[:rows],
            full[:rows],
        )
        # Across buckets the executable differs, so only numeric
        # equivalence is promised (XLA reduction order per shape).
        out = replica.execute(
            pad_features(sub, bucket_for(rows, buckets)), n_valid=rows
        )
        np.testing.assert_allclose(out[:rows], full[:rows], rtol=1e-5)
    assert watcher.poll() == {}, "padded-bucket traffic retraced"
    np.testing.assert_allclose(
        replica.execute(feats, n_valid=16), expected, rtol=1e-5
    )


def test_hot_swap_equivalence(tmp_path, journal_file, obs_registry_snapshot):
    """Each generation's served outputs match THAT generation's trainer
    eval; the swap is atomic (generation id bumps, old drains to zero)
    and journaled with the schema-registered model_swap event."""
    from elasticdl_tpu.serving.runtime import ServingReplica

    trainer, batches, gen1_dir, feats, expected1 = _exported_deepfm(tmp_path)
    for f, labels in batches[2:4]:
        trainer.train_step(f, labels)
    gen2_dir = str(tmp_path / "gen2")
    export_model(
        trainer, gen2_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    expected2 = np.asarray(trainer.eval_step(feats))

    replica = ServingReplica(gen1_dir, model_zoo="model_zoo")
    assert replica.generation.gen_id == 1
    got1 = replica.execute(feats, n_valid=16)
    np.testing.assert_allclose(got1, expected1, rtol=1e-5)
    # Serving determinism: repeats are bit-identical.
    np.testing.assert_array_equal(got1, replica.execute(feats, n_valid=16))

    replica.reload(gen2_dir)
    assert replica.generation.gen_id == 2
    got2 = replica.execute(feats, n_valid=16)
    np.testing.assert_allclose(got2, expected2, rtol=1e-5)
    assert not np.array_equal(got1, got2), "swap served stale weights"

    swaps = [e for e in _events(journal_file) if e["event"] == "model_swap"]
    assert len(swaps) == 1
    assert swaps[0]["generation"] == 2
    assert swaps[0]["old_generation"] == 1
    assert swaps[0]["undrained"] == 0


def test_reload_corrupt_artifact_keeps_serving(
    tmp_path, journal_file, obs_registry_snapshot
):
    """Reload hardening (continuous-loop degradation ladder): a corrupt
    artifact fails the reload BEFORE the generation pointer moves — no
    half-built generation — while live traffic rides the old generation
    through the failure with zero dropped requests, and the rollback is
    journaled.  A good artifact then swaps in normally."""
    from elasticdl_tpu.serving.runtime import ServingReplica

    trainer, batches, gen1_dir, feats, expected1 = _exported_deepfm(tmp_path)
    for f, labels in batches[2:4]:
        trainer.train_step(f, labels)
    gen2_dir = str(tmp_path / "gen2")
    export_model(
        trainer, gen2_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    # Corrupt the new artifact's variables mid-pipeline (a torn copy).
    corrupt = str(tmp_path / "gen2_corrupt")
    shutil.copytree(gen2_dir, corrupt)
    with open(os.path.join(corrupt, "variables.pkl"), "r+b") as fh:
        fh.truncate(os.path.getsize(os.path.join(corrupt, "variables.pkl")) // 2)

    replica = ServingReplica(gen1_dir, model_zoo="model_zoo")
    old_gen = replica.generation
    baseline = replica.execute(feats, n_valid=16)

    served = []
    errors = []
    stop = threading.Event()

    def loadgen():
        while not stop.is_set():
            try:
                served.append(replica.execute(feats, n_valid=16))
            except Exception as exc:  # pragma: no cover - the failure mode
                errors.append(exc)
                return

    thread = threading.Thread(target=loadgen, daemon=True)
    thread.start()
    try:
        with pytest.raises(Exception):
            replica.reload(corrupt)
        # Pointer untouched: SAME generation object, still answering.
        assert replica.generation is old_gen
        assert replica.generation.gen_id == 1
        np.testing.assert_array_equal(
            replica.execute(feats, n_valid=16), baseline
        )
    finally:
        stop.set()
        thread.join(timeout=30)
    assert not errors, f"requests dropped during failed reload: {errors}"
    assert len(served) > 0
    for out in served:
        np.testing.assert_array_equal(out, baseline)

    # The rollback is journaled; a good artifact still swaps in after.
    swaps = [e for e in _events(journal_file) if e["event"] == "model_swap"]
    assert [s["outcome"] for s in swaps] == ["rolled_back"]
    assert swaps[0]["kind"] == "full"
    assert swaps[0]["generation"] == 1 and swaps[0]["model_dir"] == corrupt
    replica.reload(gen2_dir)
    assert replica.generation.gen_id > 1
    np.testing.assert_allclose(
        replica.execute(feats, n_valid=16),
        np.asarray(trainer.eval_step(feats)),
        rtol=1e-5,
    )
    swaps = [e for e in _events(journal_file) if e["event"] == "model_swap"]
    assert swaps[-1]["outcome"] == "applied" and swaps[-1]["undrained"] == 0


@pytest.mark.slow
@pytest.mark.e2e
def test_serving_fleet_e2e(tmp_path, obs_registry_snapshot):
    """The ISSUE acceptance run: a supervised 2-replica fleet sustains
    deterministic load with bounded tail latency across (a) a LIVE
    hot-swap — zero in-flight requests dropped — and (b) a replica
    SIGKILL the supervisor repairs with a fresh replica while the
    survivor keeps serving.  The shared journal schema-validates."""
    from elasticdl_tpu.serving.frontend import PredictClient, encode_features
    from elasticdl_tpu.serving.supervisor import (
        start_serving_fleet,
        wait_for_replicas,
    )

    loadgen = _load_script("loadgen")
    validator = _load_script("validate_journal")

    trainer, batches, gen1_dir, feats, expected1 = _exported_deepfm(tmp_path)
    for f, labels in batches[2:4]:
        trainer.train_step(f, labels)
    gen2_dir = str(tmp_path / "gen2")
    export_model(
        trainer, gen2_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    expected2 = np.asarray(trainer.eval_step(feats))

    serve_dir = str(tmp_path / "serve")
    os.makedirs(serve_dir)
    warm = str(tmp_path / "warm.npz")
    with open(warm, "wb") as fh:
        fh.write(encode_features({k: v[:1] for k, v in feats.items()}))
    env = {"JAX_PLATFORMS": "cpu", "ELASTICDL_FORCE_PLATFORM": "cpu"}
    manager = start_serving_fleet(
        2, gen1_dir, serve_dir,
        worker_env=env,
        model_zoo="model_zoo",
        max_batch_size=16,
        max_wait_us=1000,
        telemetry_interval_s=0.5,
        warmup_features=warm,
    )
    clients = {}
    try:
        live = wait_for_replicas(serve_dir, 2, timeout_s=300)
        clients = {
            r["replica_id"]: PredictClient(
                f"127.0.0.1:{r['port']}", deadline_s=60.0
            )
            for r in live
        }
        rid_swap, rid_kill = sorted(clients)
        # Same artifact + same compiled path: replicas agree bit-for-bit.
        outs = [clients[rid].predict(feats) for rid in sorted(clients)]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_allclose(outs[0], expected1, rtol=1e-5)

        # -- (a) live hot-swap under load: zero dropped in-flight -------
        stream = loadgen.RequestStream(loadgen.StreamConfig(seed=3))
        predict = loadgen.round_robin_predict(
            [clients[rid].predict for rid in sorted(clients)]
        )
        box = {}

        def _drive():
            box["result"] = loadgen.run_closed_loop(
                predict, stream, num_requests=80, concurrency=4
            )

        driver = threading.Thread(
            target=_drive, name="e2e-loadgen", daemon=True
        )
        driver.start()
        time.sleep(0.5)  # swap lands mid-run, in-flight traffic live
        swap_stats = clients[rid_swap].reload(gen2_dir)
        assert swap_stats["generation"] == 2
        driver.join(timeout=300)
        result = box["result"]
        summary = result.summary()
        assert summary["served"] == 80, summary  # ZERO dropped/shed
        assert summary["availability_ratio"] == 1.0, summary
        assert 0 < summary["latency"]["p99_ms"] < 10_000, summary
        assert summary["qps"] > 0, summary
        # Post-swap: swapped replica serves gen2, survivor still gen1.
        np.testing.assert_allclose(
            clients[rid_swap].predict(feats), expected2, rtol=1e-5
        )
        np.testing.assert_allclose(
            clients[rid_kill].predict(feats), expected1, rtol=1e-5
        )

        # -- (b) SIGKILL -> supervisor repairs with a FRESH replica -----
        manager.kill_worker(rid_kill, sig=9)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            ids = manager.current_worker_ids()
            if rid_kill not in ids and len(ids) == 2:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("supervisor never replaced the killed "
                                 f"replica: {manager.current_worker_ids()}")
        live2 = wait_for_replicas(serve_dir, 2, timeout_s=300)
        fresh = [
            r for r in live2 if r["replica_id"] not in (rid_swap, rid_kill)
        ]
        assert len(fresh) == 1, live2  # fresh id, never reused
        fresh_client = PredictClient(
            f"127.0.0.1:{fresh[0]['port']}", deadline_s=60.0
        )
        clients[fresh[0]["replica_id"]] = fresh_client
        np.testing.assert_allclose(
            fresh_client.predict(feats), expected1, rtol=1e-5
        )
        after = loadgen.run_closed_loop(
            loadgen.round_robin_predict(
                [clients[rid_swap].predict, fresh_client.predict]
            ),
            stream, num_requests=40, concurrency=4,
        )
        assert after.summary()["served"] == 40, after.summary()
        stats = fresh_client.stats()
        assert stats["ledger"]["availability_ratio"] >= 0.99, stats
        assert stats["generation"] == 1
    finally:
        for client in clients.values():
            client.close()
        manager.stop()
        obs.journal().configure(None)

    journal_path = os.path.join(serve_dir, "events.jsonl")
    assert validator.validate_file(journal_path) == []
    seen = {e["event"] for e in _events(journal_path)}
    assert {
        "serving_fleet_start", "serving_replica_start", "serving_telemetry",
        "model_swap", "worker_churn", "compile_plan",
    } <= seen, seen


def test_obs_top_serving_fold():
    """`obs.top --serving` folds the journal tail latest-wins per replica
    and degrades to an explicit note against training-only journals."""
    from elasticdl_tpu.obs import top

    events = [
        {"event": "worker_telemetry", "worker_id": 0, "ts": 90.0},
        {"event": "serving_telemetry", "replica_id": 2, "ts": 95.0,
         "generation": 1, "step": 3, "qps": 10.0, "p50_ms": 1.0,
         "p99_ms": 2.0, "queue_depth": 0, "inflight": 1,
         "availability_ratio": 1.0, "served": 50, "shed": 0, "errors": 0},
        {"event": "serving_telemetry", "replica_id": 1, "ts": 99.0,
         "generation": 2, "step": 7, "qps": 123.4, "p50_ms": 0.5,
         "p99_ms": 4.5, "queue_depth": 3, "inflight": 2,
         "availability_ratio": 0.98, "served": 700, "shed": 14,
         "errors": 0},
        # Later snapshot for replica 2 must win over the earlier one.
        {"event": "serving_telemetry", "replica_id": 2, "ts": 100.0,
         "generation": 2, "step": 9, "qps": 55.0, "p50_ms": 1.1,
         "p99_ms": 3.3, "queue_depth": 1, "inflight": 0,
         "availability_ratio": 1.0, "served": 90, "shed": 0, "errors": 1},
    ]
    rows = top.serving_rows(events, now=101.0)
    assert [r["replica"] for r in rows] == [1, 2]  # sorted by id
    by_id = {r["replica"]: r for r in rows}
    assert by_id[2]["generation"] == 2 and by_id[2]["served"] == 90
    assert by_id[2]["age_s"] == 1.0
    assert by_id[1]["availability_pct"] == "98"

    frame = top.render_serving(rows, {"elasticdl_serving_qps": 178.4},
                               addr="host:9100")
    assert "REPLICA" in frame and "GEN" in frame and "P99(ms)" in frame
    assert "123.4" in frame and "host:9100" in frame
    assert "training-only" not in frame

    empty = top.render_serving(top.serving_rows([{"event": "job_start"}]),
                               {})
    assert "training-only master" in empty
