"""Model-export-for-serving tests (reference: model_handler
get_model_to_export — SURVEY.md §3.6).

Done-criterion from the round-1 review: `--output` produces an artifact a
fresh process can serve with bit-identical eval outputs — including
PS-mode's mesh-sharded embedding tables, which must be materialized into
the artifact without the exporter holding a full table in memory.
"""

import json
import os
import subprocess
import sys

import numpy as np

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
from elasticdl_tpu.serving import export_model, load_for_serving
from test_ctr_models import _batches


def _trained_deepfm(steps=4):
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=100),
        zoo.loss,
        zoo.optimizer(lr=0.01),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(lr=0.01),
    )
    batches = list(_batches(zoo, n=64, mb=16))
    for feats, labels in batches[:steps]:
        trainer.train_step(feats, labels)
    return zoo, trainer, batches


def test_export_then_serve_bit_identical(tmp_path):
    zoo, trainer, batches = _trained_deepfm()
    out_dir = str(tmp_path / "export")
    export_model(
        trainer,
        out_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
        chunk_rows=7,  # force multi-chunk streaming of every table
    )
    # Artifact layout: signature + variables + one file per table.
    sig = json.loads((tmp_path / "export" / "signature.json").read_text())
    assert sig["format"].startswith("elasticdl_tpu_serving/")
    assert len(sig["tables"]) >= 1
    for meta in sig["tables"]:
        assert os.path.exists(os.path.join(out_dir, meta["file"]))

    served = load_for_serving(out_dir)
    feats, _ = batches[0]
    # vs the trainer's mesh-jitted eval: numerically equivalent (XLA
    # reduction order differs between the 8-device program and the
    # single-host serving apply, so exact bits can't match).
    expected = trainer.eval_step(feats)
    got = np.asarray(served.predict(feats))
    np.testing.assert_allclose(np.asarray(expected), got, rtol=1e-5)
    # Serving is deterministic: repeat predictions are bit-identical.
    np.testing.assert_array_equal(got, np.asarray(served.predict(feats)))

    # Logical [vocab, dim] view for external consumers.
    logical = served.logical_tables()
    for meta in sig["tables"]:
        assert logical[meta["key"]].shape == (
            meta["vocab_size"],
            meta["dim"],
        )


def test_serving_in_fresh_process(tmp_path):
    """The artifact is self-contained: a brand-new interpreter (no trainer,
    no mesh) loads it and predicts BIT-IDENTICALLY to in-process serving."""
    zoo, trainer, batches = _trained_deepfm(steps=2)
    out_dir = str(tmp_path / "export")
    export_model(
        trainer,
        out_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    feats, _ = batches[0]
    expected = np.asarray(load_for_serving(out_dir).predict(feats))
    np.savez(tmp_path / "feats.npz", **feats)

    script = f"""
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize may force TPU
import numpy as np
from elasticdl_tpu.serving import load_for_serving
served = load_for_serving({out_dir!r})
feats = dict(np.load({str(tmp_path / 'feats.npz')!r}))
out = np.asarray(served.predict(feats))
np.save({str(tmp_path / 'out.npy')!r}, out)
"""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "ELASTICDL_FORCE_PLATFORM": "cpu",
    }
    subprocess.run(
        [sys.executable, "-c", script],
        check=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
        timeout=300,
    )
    got = np.load(tmp_path / "out.npy")
    np.testing.assert_array_equal(expected, got)


def test_export_records_resolved_model_params(tmp_path):
    """Flag-dependent model structure must survive the serving
    round-trip: save_model records the RESOLVED model params (the job
    flags model_utils injects — sparse_apply_every, use_bf16), so a
    reload rebuilds the exact trained structure.  The real-world hazard:
    DeepFM trained at >10M rows with --sparse_apply_every=16 uses the
    MERGED table layout; an artifact recording only the raw
    --model_params would rebuild the SPLIT layout at load and fail on
    missing parameters."""
    import json as _json

    from elasticdl_tpu.client.api import save_model
    from elasticdl_tpu.common.args import parse_master_args

    zoo, trainer, batches = _trained_deepfm()
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        "--training_data=synthetic://criteo?n=64&vocab=100",
        "--model_params=vocab_size=100",
        "--sparse_apply_every=16",
    ])
    out_dir = str(tmp_path / "export")
    save_model(trainer, out_dir, args)
    sig = _json.loads((tmp_path / "export" / "signature.json").read_text())
    recorded = sig["model_params"]
    assert "sparse_apply_every=16" in recorded, recorded
    assert "vocab_size=100" in recorded, recorded
    # And the reload consumes them: the rebuilt model sees the flag.
    served = load_for_serving(out_dir)
    assert served._model.sparse_apply_every == 16
    feats, _ = batches[0]
    got = np.asarray(served.predict(feats))
    expected = np.asarray(trainer.eval_step(feats))
    np.testing.assert_allclose(expected, got, rtol=1e-5)


def test_format_dict_params_round_trip():
    from elasticdl_tpu.common.args import (
        format_dict_params,
        parse_dict_params,
    )

    params = {"vocab_size": 100, "use_bf16": True, "lr": 0.5,
              "mode": "auto", "split_tables": False}
    assert parse_dict_params(format_dict_params(params)) == params
    # '=' inside a string value round-trips (parse splits items on ','
    # then on the FIRST '=') — a URL-valued param must not abort the
    # end-of-training export (round-4 ADVICE).
    url_params = {"init_from": "gs://bkt/ckpt?ver=3", "vocab_size": 7}
    assert parse_dict_params(format_dict_params(url_params)) == url_params
    import pytest as _pytest

    # ',' is genuinely non-round-trippable: it splits the item list.
    with _pytest.raises(ValueError):
        format_dict_params({"bad": "a,b"})
