"""CIFAR-10 ResNet-20 zoo model tests (BASELINE config 2).

Covers the batch-norm (mutable model_state) path through both trainers —
the mnist DNN has no non-trainable state, so this is the coverage for it.
"""

import numpy as np

from elasticdl_tpu.parallel import DataParallelTrainer, MeshConfig, build_mesh
from elasticdl_tpu.worker.trainer import Trainer
from model_zoo.cifar10 import cifar10_functional_api as zoo
from model_zoo import datasets


def _batch(n=16, seed=0):
    reader = datasets.synthetic_cifar10_reader(n=n, seed=seed)
    records = [
        r
        for r in zoo.dataset_fn(
            _as_dataset(reader), "training", reader.metadata
        )
    ]
    feats = np.stack([r[0] for r in records])
    labels = np.stack([r[1] for r in records])
    return feats, labels


def _as_dataset(reader):
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task = pb.Task(task_id=1, shard_name="cifar-synth", start=0, end=1 << 30)
    return Dataset.from_generator(lambda: reader.read_records(task))


def test_resnet20_trains_and_updates_batch_stats():
    trainer = Trainer(
        zoo.custom_model(use_bf16=False), zoo.loss, zoo.optimizer(lr=0.05)
    )
    feats, labels = _batch(16)
    losses = [float(trainer.train_step(feats, labels)) for _ in range(8)]
    assert losses[-1] < losses[0]
    state = trainer.state
    assert "batch_stats" in state.model_state
    # Running stats actually moved away from init.
    leaves = [np.asarray(x) for x in __import__("jax").tree.leaves(
        state.model_state["batch_stats"])]
    assert any(np.abs(leaf).sum() > 0 for leaf in leaves)


def test_resnet20_dp_matches_single_device():
    mesh = build_mesh(MeshConfig())
    dp = DataParallelTrainer(
        zoo.custom_model(use_bf16=False), zoo.loss, zoo.optimizer(), mesh, seed=0
    )
    single = Trainer(
        zoo.custom_model(use_bf16=False), zoo.loss, zoo.optimizer(), seed=0
    )
    feats, labels = _batch(16, seed=1)
    # Reduction-order differences through batch-norm rsqrt amplify float
    # drift step over step; the first step must agree tightly, later steps
    # within growing slack.
    for step, rtol in enumerate((1e-3, 8e-3, 3e-2)):
        dp_loss = dp.train_step(feats, labels)
        s_loss = single.train_step(feats, labels)
        np.testing.assert_allclose(
            float(dp_loss), float(s_loss), rtol=rtol, atol=1e-4,
            err_msg=f"step {step}",
        )


def test_resnet20_bf16_forward_finite():
    trainer = Trainer(zoo.custom_model(use_bf16=True), zoo.loss, zoo.optimizer())
    feats, labels = _batch(8)
    loss = trainer.train_step(feats, labels)
    assert np.isfinite(float(loss))
    outputs = trainer.eval_step(feats)
    assert outputs.dtype == np.float32 and outputs.shape == (8, 10)
