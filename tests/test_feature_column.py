"""Feature-column glue tests (preprocessing/feature_column.py).

Mirrors the reference's elasticdl_preprocessing feature-column tests:
golden per-column behavior, disjoint offset spaces, crossed-column
determinism, and end-to-end consumption by layers.Embedding.
"""

import numpy as np
import pytest

from elasticdl_tpu.preprocessing import Normalizer
from elasticdl_tpu.preprocessing.feature_column import (
    FeatureLayer,
    bucketized_column,
    categorical_column_with_hash_bucket,
    categorical_column_with_identity,
    categorical_column_with_vocabulary_list,
    crossed_column,
    embedding_column,
    numeric_column,
    shared_embedding_columns,
)

RAW = {
    "age": np.asarray([22.0, 41.0, 65.0], np.float32),
    "income": np.asarray([1000.0, 5000.0, 0.0], np.float32),
    "education": np.asarray(["BA", "PhD", "unknown-token"]),
    "city": np.asarray(["sf", "nyc", "sf"]),
}


def test_numeric_column_normalizes():
    col = numeric_column("income", Normalizer.from_stats(2000.0, 2000.0))
    values = col.values(RAW)
    np.testing.assert_allclose(values[:, 0], [-0.5, 1.5, -1.0])
    assert values.shape == (3, 1)


def test_bucketized_column_uses_raw_values():
    age = numeric_column("age", Normalizer.from_stats(40.0, 10.0))
    col = bucketized_column(age, [25.0, 50.0])
    # Bucketizes raw ages, not normalized ones.
    np.testing.assert_array_equal(col.ids(RAW), [0, 1, 2])
    assert col.num_ids == 3


def test_vocab_column_oov():
    col = categorical_column_with_vocabulary_list(
        "education", ["BA", "MS", "PhD"], num_oov_indices=1
    )
    # OOV bucket is id 0; vocab starts at 1.
    np.testing.assert_array_equal(col.ids(RAW), [1, 3, 0])
    assert col.num_ids == 4


def test_hash_and_identity_columns_in_range():
    hashed = categorical_column_with_hash_bucket("city", 16)
    ids = hashed.ids(RAW)
    assert ids.shape == (3,) and (0 <= ids).all() and (ids < 16).all()
    assert ids[0] == ids[2]  # same string, same bucket

    ident = categorical_column_with_identity("age", 70)
    np.testing.assert_array_equal(ident.ids(RAW), [22, 41, 65])


def test_crossed_column_deterministic_and_order_sensitive():
    cross = crossed_column(["education", "city"], 32)
    ids = cross.ids(RAW)
    assert ids.shape == (3,) and (0 <= ids).all() and (ids < 32).all()
    np.testing.assert_array_equal(ids, cross.ids(RAW))  # stable
    assert cross.key == "education_x_city"


def test_feature_layer_offsets_are_disjoint():
    edu = categorical_column_with_vocabulary_list(
        "education", ["BA", "MS", "PhD"]
    )
    city = categorical_column_with_hash_bucket("city", 16)
    layer = FeatureLayer(
        [
            numeric_column("income"),
            embedding_column(edu, 8),
            embedding_column(city, 8),
        ]
    )
    out = layer(RAW)
    assert set(out) == {"dense", "cat"}
    assert out["dense"].shape == (3, 1)
    assert out["cat"].shape == (3, 2)
    # Column 0 in [0, 4); column 1 offset into [4, 20).
    assert (out["cat"][:, 0] < 4).all()
    assert (out["cat"][:, 1] >= 4).all() and (out["cat"][:, 1] < 20).all()
    assert layer.total_id_space() == 20
    assert layer.embedding_specs() == {"default": (20, 8)}


def test_feature_layer_groups_and_mixed_dim_rejected():
    edu = categorical_column_with_vocabulary_list("education", ["BA"])
    city = categorical_column_with_hash_bucket("city", 8)
    layer = FeatureLayer(
        shared_embedding_columns([edu, city], 4, group="wide")
        + [embedding_column(categorical_column_with_identity("age", 70), 8)]
    )
    out = layer(RAW)
    assert set(out) == {"cat", "cat_wide"}
    specs = layer.embedding_specs()
    assert specs["wide"] == (2 + 8, 4)
    assert specs["default"] == (70, 8)

    with pytest.raises(ValueError, match="mixes dimensions"):
        FeatureLayer(
            [embedding_column(edu, 4), embedding_column(city, 8)]
        )


def test_feature_layer_feeds_embedding_layer():
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.layers import Embedding

    edu = categorical_column_with_vocabulary_list(
        "education", ["BA", "MS", "PhD"]
    )
    city = categorical_column_with_hash_bucket("city", 16)
    layer = FeatureLayer(
        [numeric_column("age"), embedding_column(edu, 4),
         embedding_column(city, 4)]
    )
    inputs = layer(RAW)
    vocab, dim = layer.embedding_specs()["default"]

    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, features):
            emb = Embedding(vocab, dim, combiner="sum")(features["cat"])
            x = jnp.concatenate([emb, features["dense"]], axis=-1)
            return nn.Dense(1)(x)[..., 0]

    model = Tiny()
    variables = model.init(jax.random.PRNGKey(0), inputs)
    out = model.apply(variables, inputs)
    assert out.shape == (3,) and np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# End-to-end: the declarative census variant trains on the sharded mesh.
# ---------------------------------------------------------------------------


def _census_fc_batches(n=64, mb=16, seed=0):
    from elasticdl_tpu.data.dataset import Dataset, _stack
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from model_zoo import datasets
    from model_zoo.census import census_feature_columns as zoo

    reader = datasets.synthetic_census_reader(n=n, seed=seed)
    task = pb.Task(task_id=1, shard_name="s", start=0, end=n)
    records = list(
        zoo.dataset_fn(
            Dataset.from_generator(lambda: reader.read_records(task)),
            "training",
            None,
        )
    )
    for i in range(0, n, mb):
        yield _stack(records[i : i + mb])


def test_census_feature_column_model_trains():
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.census import census_feature_columns as zoo

    mesh = build_mesh(MeshConfig())
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    losses = []
    for epoch in range(8):
        for feats, labels in _census_fc_batches(n=64, mb=16, seed=epoch % 2):
            losses.append(float(trainer.train_step(feats, labels)))
    assert losses[-1] < losses[0] * 0.9, (
        f"no learning: {losses[:2]} -> {losses[-2:]}"
    )
    feats, labels = next(_census_fc_batches(n=16, mb=16, seed=9))
    out = np.asarray(trainer.eval_step(feats))
    metrics = {
        name: fn(out, labels) for name, fn in zoo.eval_metrics_fn().items()
    }
    assert 0.0 <= metrics["auc"] <= 1.0


def test_feature_layer_train_serve_consistency():
    """The FeatureLayer used by dataset_fn is the serving transform: the
    same raw batch transformed twice is bit-identical."""
    from model_zoo import datasets
    from model_zoo.census import census_feature_columns as zoo

    reader = datasets.synthetic_census_reader(n=4, seed=3)
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task = pb.Task(task_id=1, shard_name="s", start=0, end=4)
    raws = [raw for raw, _ in reader.read_records(task)]
    batch = {k: np.asarray([r[k] for r in raws]) for k in raws[0]}
    once, twice = zoo.FEATURES(batch), zoo.FEATURES(dict(batch))
    for key in once:
        np.testing.assert_array_equal(once[key], twice[key])
