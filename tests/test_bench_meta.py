"""Host-side tests of bench.py's measurement machinery (the benches
themselves need the real chip; the steadiness statistics and roofline
accounting they report must not).  VERDICT round-3 #4/#5."""

import json

import numpy as np
import pytest

import bench


def test_median_spread_basics():
    median, spread = bench._median_spread([1.0, 2.0, 4.0], 8.0)
    # rates 8, 4, 2 -> median 4, spread (8-2)/4
    assert median == 4.0
    assert spread == pytest.approx(1.5)


def test_trimmed_median_spread_drops_one_outlier_each_side():
    # One contended run (10x slow) must not blow up the spread.
    times = [1.0, 1.02, 0.98, 1.01, 10.0, 0.99, 1.0]
    median, spread = bench._trimmed_median_spread(times, 100.0)
    assert 95 < median < 105
    assert spread < 0.1
    with pytest.raises(AssertionError):
        bench._trimmed_median_spread([1.0] * 4, 1.0)


def test_roofline_fields_every_tracked_metric():
    """Every SELF_BASELINE metric emits a roofline anchor, and the
    fractions are sane at the recorded baseline values."""
    for metric, value in bench.SELF_BASELINE.items():
        fields = bench._roofline_fields(metric, value)
        assert fields, f"no roofline fields for {metric}"
        fracs = [
            v for k, v in fields.items()
            if k in ("mfu", "bw_frac", "floor_frac", "host_parse_frac",
                     "device_frac")
        ]
        assert fracs, f"no fraction field for {metric}: {fields}"
        for frac in fracs:
            assert 0.0 < frac <= 1.2, (metric, fields)


def test_transformer_flops_model():
    # d512 L4 V32k mlp4 T2048 causal: lm_head 2dV = 33.6M/token; the
    # 4 layers add ~33.6M more (24d^2 + 4d*T/2 each).
    per_token = bench._transformer_flops_per_token()
    assert 60e6 < per_token < 75e6, per_token


def test_emit_json_contract(capsys):
    bench._emit(
        "transformer_lm_tokens_per_sec_per_chip", 242_000.0,
        "tokens/sec/chip", 0.01, tracked=False,
    )
    row = json.loads(capsys.readouterr().out.strip())
    assert row["metric"] == "transformer_lm_tokens_per_sec_per_chip"
    assert row["unit"] == "tokens/sec/chip"
    assert row["tracked"] is False
    assert 0 < row["mfu"] < 1
    assert row["vs_baseline"] == pytest.approx(242_000.0 / 241_046.0, rel=1e-3)


def test_final_emit_carries_every_metric(capsys):
    """The driver's BENCH_r{N}.json preserves only the parsed FINAL line;
    final=True must fold every previously emitted row into `all` so the
    artifact alone reconstructs the round (VERDICT round-4 weak #1)."""
    bench._EMITTED.clear()
    bench._emit(
        "resnet50_images_per_sec_per_chip", 2_665.0, "images/sec/chip",
        0.01,
    )
    bench._emit(
        "deepfm_26m_strict_samples_per_sec_per_chip", 272_953.0,
        "samples/sec/chip", 0.01,
    )
    bench._emit(
        "deepfm_train_samples_per_sec_per_chip", 975_000.0,
        "samples/sec/chip", 0.001, final=True,
    )
    lines = capsys.readouterr().out.strip().splitlines()
    assert "all" not in json.loads(lines[0])
    final = json.loads(lines[-1])
    assert set(final["all"]) == {
        "resnet50_images_per_sec_per_chip",
        "deepfm_26m_strict_samples_per_sec_per_chip",
        "deepfm_train_samples_per_sec_per_chip",
    }
    resnet = final["all"]["resnet50_images_per_sec_per_chip"]
    assert resnet["value"] == 2_665.0
    assert resnet["unit"] == "images/sec/chip"
    assert "vs_baseline" in resnet and "spread" in resnet
    strict = final["all"]["deepfm_26m_strict_samples_per_sec_per_chip"]
    assert strict["bound"] == "table-stream"
    # The headline row itself is in `all` too — one artifact, whole round.
    assert final["all"]["deepfm_train_samples_per_sec_per_chip"][
        "value"
    ] == final["value"]
    bench._EMITTED.clear()


def test_ring_roofline_reads_ring_bench_config():
    """_roofline_fields' ring FLOP accounting must follow RING_BENCH (the
    dict bench_ring_engine also reads) — a divergent copy would silently
    emit a wrong mfu (round-4 ADVICE)."""
    base = bench._roofline_fields(
        "ring_attention_tokens_per_sec_per_chip", 1_977_558.0
    )
    orig = dict(bench.RING_BENCH)
    try:
        bench.RING_BENCH["t_local"] = orig["t_local"] * 2
        doubled = bench._roofline_fields(
            "ring_attention_tokens_per_sec_per_chip", 1_977_558.0
        )
    finally:
        bench.RING_BENCH.clear()
        bench.RING_BENCH.update(orig)
    # FLOPs/group scale with t_local^2 but tokens/group only with
    # t_local -> achieved flops at fixed token rate doubles.
    assert doubled["mfu"] == pytest.approx(2 * base["mfu"], rel=0.02)


def test_backend_probe_prints_contract(capfd):
    """The fail-fast backend probe (bench._require_live_backend) must
    emit its explanatory line BEFORE touching the backend — that line
    is what makes a tunnel-outage hard-exit diagnosable from the
    driver's recorded output tail.  The probe itself is injected: a
    host-side meta test must never initialize the live backend (a dead
    tunnel would hard-exit the whole pytest process).  capfd, not
    capsys: faulthandler's watchdog needs a real stderr descriptor."""
    bench._require_live_backend(timeout_s=120, probe_fn=lambda: 1)
    out = capfd.readouterr().out
    assert "bench_backend_probe" in out.splitlines()[0]
    assert "backend live: 1" in out


def test_ring_bench_harness_import():
    """bench_ring_engine loads scripts/exp_ring_perf.py by file path; pin
    the coupling (module loads, exposes run_variant, parses the exact
    variant string the bench builds) without touching a device."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "exp_ring_perf_for_test",
        os.path.join(
            os.path.dirname(__file__), os.pardir, "scripts",
            "exp_ring_perf.py",
        ),
    )
    harness = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(harness)
    assert callable(harness.run_variant)
    cfg = harness.parse("t2048_b4_r4_pallas_i32")
    assert (cfg["t"], cfg["b"], cfg["r"], cfg["engine"], cfg["inner"]) == (
        2048, 4, 4, "pallas", 32,
    )
