"""Chaos e2e for the continuous train->serve loop (ISSUE 16 acceptance).

One deterministic in-process driver runs the whole loop on a virtual
clock: an unbounded synthetic click stream with a mid-run rate spike,
the master's streaming dispatcher, three training "workers", the delta
publisher, and a serving replica advanced by a DeltaWatcher under live
loadgen traffic — while the fault plane injects every new site:

  stream.source       schedule-based stall (wedged upstream pipe)
  worker churn        trained-but-unreported tasks requeued
  master SIGKILL      dispatcher rebuilt from the journal mid-stream
  ckpt.delta          torn delta write, quarantined by the consumer
  serving.delta_apply failed apply, atomic rollback, retried next poll

Everything is virtual time (`SyntheticClickStream.advance` +
`faults.due`), so the run replays bit-exactly.  The acceptance
assertions at the bottom are the ISSUE's: redo debt exact, zero dropped
requests, quarantine + rollback journaled, freshness SLO breached then
defended, journal schema-valid.
"""

import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.checkpoint.delta import DeltaExporter
from elasticdl_tpu.common import faults
from elasticdl_tpu.data.stream import SyntheticClickStream
from elasticdl_tpu.master.stream import StreamingTaskManager
from elasticdl_tpu.obs.freshness import FreshnessTracker
from test_serving import _trained_deepfm

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

pytestmark = [pytest.mark.slow, pytest.mark.e2e]


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def journal_file(tmp_path):
    path = obs.init_journal(str(tmp_path))
    try:
        yield path
    finally:
        obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_journal",
        os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules["validate_journal"] = module
    spec.loader.exec_module(module)
    return module


def _merged_cover(ranges):
    merged = []
    for lo, hi in sorted(ranges):
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [tuple(r) for r in merged]


def test_continuous_loop_chaos_e2e(
    tmp_path, journal_file, obs_registry_snapshot
):
    from elasticdl_tpu.serving.continuous import DeltaWatcher
    from elasticdl_tpu.serving.runtime import ServingReplica

    # ------------------------------------------------------------------
    # World: deepfm trainer, pub dir, 6s virtual run at 0.25s ticks.
    # Phase 1 produces 400 rec/s for 4s, then a 4x spike forever.
    # ------------------------------------------------------------------
    zoo, trainer, batches = _trained_deepfm(steps=2)
    pool = batches  # task range -> deterministic minibatch
    pub_dir = str(tmp_path / "pub")
    exporter = DeltaExporter(
        pub_dir,
        model_zoo="model_zoo",
        model_def="deepfm.deepfm_functional_api",
        model_params="vocab_size=100",
    )
    stream = SyntheticClickStream(
        [(4.0, 400.0), (2.0, 1600.0)], name="clicks"
    )
    manager = StreamingTaskManager(
        stream, records_per_task=64, lookahead_tasks=8
    )
    tracker = FreshnessTracker(slo_s=1.5)
    faults.install(
        "stream.source:latency=1.0@t2.0,"
        " ckpt.delta:truncate@2,"
        " serving.delta_apply:error=injected@3"
    )

    DT = 0.25
    train_counts = {}

    def train(task):
        feats, labels = pool[(task.start // 64) % len(pool)]
        trainer.train_step(feats, labels)
        key = (task.start, task.end)
        train_counts[key] = train_counts.get(key, 0) + 1

    def drain_worker(worker_id, budget=64):
        for _ in range(budget):
            task = manager.get(worker_id)
            if task.task_id < 0:
                return
            train(task)
            manager.report(task.task_id, True, worker_id=worker_id)

    replica = None
    watcher = None
    served, serve_errors = [], []
    stop_loadgen = threading.Event()
    feats = {k: np.asarray(v) for k, v in batches[0][0].items()}

    def loadgen():
        while not stop_loadgen.is_set():
            try:
                served.append(np.asarray(replica.execute(feats, n_valid=16)))
            except Exception as exc:  # any dip is a test failure
                serve_errors.append(exc)
                return
            time.sleep(0.001)

    loadgen_thread = threading.Thread(target=loadgen, daemon=True)

    def publish_delta():
        delta_dir = exporter.publish_delta(
            trainer, event_time=manager.watermark_event_time()
        )
        if delta_dir is not None:
            tracker.note_published(
                exporter.head_step, manager.watermark_event_time()
            )
        return delta_dir

    churned = []
    rolled_back_seen = False
    killed_inflight = []

    try:
        for i in range(24):
            stream.advance(DT)
            now = stream.elapsed_s
            # Schedule-based source stall: the driver owns the timeline,
            # so it converts due specs into stream.stall itself.
            for spec in faults.due("stream.source", now):
                if spec.kind == "latency":
                    stream.stall(float(spec.arg or 1.0))

            if i == 17:
                # Master SIGKILL mid-stream: some tasks are dispatched
                # (in flight, never trained, never reported) when the
                # process dies.  The journal is all that survives.
                for w in (0, 1):
                    task = manager.get(w)
                    if task.task_id >= 0:
                        killed_inflight.append((task.start, task.end))
                assert killed_inflight, "kill tick dispatched nothing"
                watermark_before = manager.watermark
                del manager
                manager = StreamingTaskManager.resume_from_journal(
                    _events(journal_file),
                    stream,
                    records_per_task=64,
                    lookahead_tasks=8,
                )
                assert manager.watermark == watermark_before

            if i == 3:  # t=1.0: seed the chain, bring serving up
                full_dir = exporter.publish_full(
                    trainer, event_time=manager.watermark_event_time()
                )
                tracker.note_published(
                    exporter.head_step, manager.watermark_event_time()
                )
                replica = ServingReplica(full_dir, model_zoo="model_zoo")
                watcher = DeltaWatcher(replica, pub_dir, freshness=tracker)
                gen = replica.generation
                tracker.note_served(gen.gen_id, gen.step, gen.event_time)
                loadgen_thread.start()
            elif i == 7:  # t=2.0: first delta (applies cleanly)
                assert publish_delta() is not None
            elif i == 13:  # t=3.5: second delta (torn by ckpt.delta@2);
                # the source stall froze the cut frontier until ~t=3.0,
                # so this is the first publish with fresh training on it
                assert publish_delta() is not None
            elif i == 15:  # t=4.0: compaction repairs the quarantine gap
                compacted = exporter.compact()
                assert compacted is not None
                tracker.note_published(
                    exporter.head_step, manager.watermark_event_time()
                )
            elif i == 18:  # t=4.75: post-resume delta (applies cleanly)
                assert publish_delta() is not None
            elif i == 20:  # t=5.25: delta whose apply faults then retries
                assert publish_delta() is not None

            if i == 5:
                # Worker churn: worker 2 trains tasks but is SIGKILLed
                # before reporting — recover_tasks requeues them, and the
                # replay is the ONLY redo debt this run may carry.
                for _ in range(2):
                    task = manager.get(2)
                    if task.task_id < 0:
                        break
                    train(task)
                    churned.append((task.start, task.end))
                assert churned, "churn tick dispatched nothing"
                assert manager.recover_tasks(2) == len(churned)

            for w in (0, 1, 2):
                drain_worker(w)

            if watcher is not None:
                summary = watcher.poll_once()
                if summary["failed"] is not None:
                    rolled_back_seen = True
            tracker.note_watermark(manager.watermark_event_time())
            tracker.evaluate(now)

        # --------------------------------------------------------------
        # Drain: close the source, train the tail, publish the final
        # state, and let serving catch all the way up.
        # --------------------------------------------------------------
        stream.close()
        for _ in range(100):
            if manager.finished():
                break
            for w in (0, 1, 2):
                drain_worker(w)
        assert manager.finished()
        publish_delta()
        for _ in range(4):
            if replica.generation.step == exporter.head_step:
                break
            watcher.poll_once()
        tracker.note_watermark(manager.watermark_event_time())
        tracker.evaluate(stream.elapsed_s)
    finally:
        stop_loadgen.set()
        if loadgen_thread.is_alive():
            loadgen_thread.join(timeout=30)

    # ------------------------------------------------------------------
    # Acceptance: redo debt exact — every record trained, duplicates are
    # EXACTLY the churn-requeued ranges (master kill added none: its
    # in-flight tasks were never trained, so the resume re-cut them and
    # they trained once).
    # ------------------------------------------------------------------
    total = stream.available()
    counts = manager.stream_counts()
    assert counts["watermark"] == total
    assert counts["pending_ranges"] == 0
    assert _merged_cover(train_counts) == [(0, total)]
    duplicates = {r: c for r, c in train_counts.items() if c > 1}
    assert duplicates == {r: 2 for r in churned}
    for r in killed_inflight:
        assert train_counts[r] == 1

    # Serving never dipped: live traffic rode every swap, rollback, and
    # reload without a single dropped request.
    assert not serve_errors
    assert len(served) > 0
    assert rolled_back_seen, "delta_apply fault never exercised rollback"
    np.testing.assert_allclose(
        np.asarray(replica.execute(feats, n_valid=16)),
        np.asarray(trainer.eval_step(feats)),
        rtol=1e-5,
    )
    assert replica.generation.step == exporter.head_step

    # Freshness SLO: breached under injected faults, defended by the end.
    assert not tracker.breached
    assert tracker.lag_s(stream.elapsed_s) <= tracker.slo_s

    # ------------------------------------------------------------------
    # Journal: the run's whole story, schema-valid end to end.
    # ------------------------------------------------------------------
    events = _events(journal_file)
    validator = _load_validator()
    assert validator.validate_file(journal_file) == []

    watermarks = [
        e["offset"] for e in events if e["event"] == "stream_watermark"
    ]
    assert watermarks == sorted(watermarks)
    assert watermarks[-1] == total

    quarantined = [
        e for e in events if e["event"] == "checkpoint_quarantined"
    ]
    assert any("torn write" in e["reason"] for e in quarantined)

    swaps = [e for e in events if e["event"] == "model_swap"]
    outcomes = [s["outcome"] for s in swaps]
    assert "rolled_back" in outcomes
    assert outcomes[-1] == "applied"
    assert all(
        s["undrained"] == 0 for s in swaps if s["outcome"] == "applied"
    )

    resumes = [e for e in events if e["event"] == "task_progress_resume"]
    assert any(e.get("stream") == "clicks" for e in resumes)

    slo_events = [e for e in events if e["event"] == "freshness_slo"]
    assert [e["state"] for e in slo_events][:1] == ["breach"]
    assert slo_events[-1]["state"] == "clear"

    requeues = [
        e for e in events
        if e["event"] == "task_requeue"
        and e.get("reason") == "worker_churn"
    ]
    assert sum(len(e["task_ids"]) for e in requeues) == len(churned)
    assert not any(e["event"] == "request_shed" for e in events)
