"""Concurrency stress tests for the master's shared state.

Parity: SURVEY.md §5 "race detection" — the reference leans on Go's
`-race` for its Go half and gRPC's thread model for Python; the rebuild's
prescription is threading stress tests over the lock-guarded master
state.  These hammer the TaskManager / rendezvous / evaluation service
from many threads concurrently and assert the invariants the elastic
design depends on:

- every record is trained at-least-once and ACCOUNTED exactly once per
  successful task report (no double-count, no loss) even with workers
  racing recover_tasks (churn) mid-flight;
- a task id is never dispatched twice concurrently;
- rendezvous re-declarations racing heartbeats/rank polls never corrupt
  world state or deadlock.
"""

import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.analysis import runtime as lockcheck
from elasticdl_tpu.analysis.runtime import CheckedLock
from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elasticdl_pb2 as pb

N_RECORDS = 6400
RECORDS_PER_TASK = 64


def test_many_workers_race_dispatch_and_churn():
    """16 worker threads pull/report tasks while a churn thread keeps
    recovering random workers' in-flight tasks.  The job must finish with
    every record counted exactly once per successful completion."""
    manager = TaskManager(
        training_shards={"s": N_RECORDS},
        records_per_task=RECORDS_PER_TASK,
        num_epochs=1,
    )
    seen_task_ids = set()
    seen_lock = threading.Lock()
    duplicate_dispatch = []
    errors = []
    stop_churn = threading.Event()

    def worker(worker_id):
        try:
            while True:
                task = manager.get(worker_id)
                if task.task_id == -1 and task.type != pb.WAIT:
                    return
                if task.type == pb.WAIT:
                    time.sleep(0.001)
                    continue
                with seen_lock:
                    if task.task_id in seen_task_ids:
                        duplicate_dispatch.append(task.task_id)
                    seen_task_ids.add(task.task_id)
                # Simulate work; some reports race churn recovery and are
                # dropped by the manager as unknown — that's the design.
                time.sleep(0.0005)
                manager.report(task.task_id, success=True, worker_id=worker_id)
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    def churn():
        rng = np.random.RandomState(0)
        while not stop_churn.is_set():
            manager.recover_tasks(int(rng.randint(0, 16)))
            time.sleep(0.002)

    workers = [
        threading.Thread(
            target=worker, args=(i,), name=f"stress-worker-{i}", daemon=True
        )
        for i in range(16)
    ]
    churn_thread = threading.Thread(
        target=churn, name="stress-churn", daemon=True
    )
    for t in workers:
        t.start()
    churn_thread.start()
    for t in workers:
        t.join(timeout=120)
        assert not t.is_alive(), "worker thread wedged (deadlock?)"
    stop_churn.set()
    churn_thread.join(timeout=10)

    assert not errors, errors
    assert not duplicate_dispatch, (
        f"task ids dispatched twice: {duplicate_dispatch[:5]}"
    )
    assert manager.finished()
    # At-least-once with exact accounting: every record finished >= once,
    # and the counter equals successful completions x task size (churned
    # re-runs count again — by design — but never fractionally).
    assert manager.finished_record_count >= N_RECORDS
    assert manager.finished_record_count % RECORDS_PER_TASK == 0


def test_rendezvous_redeclare_races_rank_polls():
    """World re-declarations racing get_comm_rank/report_liveness from
    many threads: every response must be internally consistent (a rank
    within world_size, coordinator resolved only for full worlds)."""
    rdv = ElasticRendezvous(coordinator_port_fn=lambda host: 5000)
    stop = threading.Event()
    errors = []

    def redeclare():
        i = 0
        while not stop.is_set():
            i += 1
            ids = list(range(i % 3, i % 3 + 4))
            rdv.set_worker_hosts([(wid, "") for wid in ids])
            time.sleep(0.0005)

    def poll(wid):
        try:
            while not stop.is_set():
                rdv.report_liveness(wid, f"10.0.0.{wid}", 0)
                resp = rdv.get_comm_rank(wid, f"10.0.0.{wid}")
                assert -1 <= resp.rank_id < max(1, resp.world_size)
                if resp.coordinator_addr:
                    host = resp.coordinator_addr.split(":")[0]
                    assert host.startswith("10.0.0."), resp.coordinator_addr
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=redeclare, name="rdv-redeclare", daemon=True)
    ] + [
        threading.Thread(
            target=poll, args=(wid,), name=f"rdv-poll-{wid}", daemon=True
        )
        for wid in range(7)
    ]
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert not errors, errors


def test_timeout_recovery_races_reports():
    """Aggressive task timeouts racing successful reports: tasks may be
    requeued and re-run (at-least-once), but the job completes and the
    accounting stays whole-task granular."""
    manager = TaskManager(
        training_shards={"s": 1280},
        records_per_task=64,
        num_epochs=1,
        task_timeout_s=0.01,  # everything times out aggressively
    )
    errors = []

    def worker(worker_id):
        try:
            while True:
                task = manager.get(worker_id)
                if task.task_id == -1 and task.type != pb.WAIT:
                    return
                if task.type == pb.WAIT:
                    time.sleep(0.001)
                    continue
                time.sleep(0.005)  # often longer than the timeout
                manager.report(task.task_id, success=True, worker_id=worker_id)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(
            target=worker, args=(i,), name=f"timeout-worker-{i}", daemon=True
        )
        for i in range(8)
    ]
    for t in threads:
        t.start()
    # Timeout recovery runs inside the dispatch path itself (get() calls
    # _recover_timed_out_locked), so the workers drive it by racing.
    deadline = time.time() + 120
    while not manager.finished():
        assert time.time() < deadline, "stress job never finished"
        time.sleep(0.002)
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errors, errors
    assert manager.finished_record_count >= 1280
    assert manager.finished_record_count % 64 == 0


# ---------------------------------------------------------------------------
# Runtime lock-order race detector (elasticdl_tpu.analysis.runtime).
#
# The static lock-discipline rule (make check-invariants) proves guarded
# fields mutate under their lock; these tests exercise the dynamic half:
# ELASTICDL_LOCKCHECK=1 swaps every control-plane lock for an instrumented
# CheckedLock that records per-thread acquisition order.
# ---------------------------------------------------------------------------


@pytest.fixture
def lockcheck_enabled(monkeypatch):
    monkeypatch.setenv(lockcheck.ENV_VAR, "1")
    lockcheck.reset()
    yield
    lockcheck.reset()


def test_lockcheck_detects_deliberate_inversion(lockcheck_enabled):
    """Acceptance gate: a seeded lock-order inversion is caught.  The
    detector flags cycles in the acquisition-order *graph*, so one thread
    acquiring A->B then B->A suffices — the test can never deadlock."""
    a, b = CheckedLock("demo.A"), CheckedLock("demo.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    found = lockcheck.inversions()
    assert found, "inverted acquisition order was not detected"
    assert found[0].first == "demo.B" and found[0].second == "demo.A"
    with pytest.raises(AssertionError):
        lockcheck.assert_clean()


def test_lockcheck_detects_self_deadlock_attempt(lockcheck_enabled):
    """Re-acquiring a held (non-reentrant) lock is recorded BEFORE the
    block, so a wedged process's report still names the culprit."""
    lock = CheckedLock("demo.self")
    assert lock.acquire()
    assert not lock.acquire(timeout=0.05)  # would deadlock; times out
    lock.release()
    assert any(
        "self-deadlock" in inv.witness for inv in lockcheck.inversions()
    )


def test_lockcheck_flags_long_holds(lockcheck_enabled, monkeypatch):
    monkeypatch.setenv(lockcheck.HOLD_ENV_VAR, "0.01")
    lock = CheckedLock("demo.slow")
    with lock:
        time.sleep(0.05)
    report = lockcheck.report()
    assert report["long_holds"] and report["long_holds"][0].lock == "demo.slow"
    assert report["max_hold_s"]["demo.slow"] >= 0.05
    # Long holds are advisory: the default race gate stays green.
    lockcheck.assert_clean()


def test_dispatch_churn_stress_runs_clean_under_lockcheck(lockcheck_enabled):
    """The real TaskManager, hammered by the dispatch/churn stress above,
    with its lock instrumented: zero inversions, and the instrumentation
    actually engaged (acquisitions were recorded)."""
    test_many_workers_race_dispatch_and_churn()
    report = lockcheck.report()
    assert report["acquisitions"] > 0, "lockcheck never engaged"
    lockcheck.assert_clean()


def test_rendezvous_stress_runs_clean_under_lockcheck(lockcheck_enabled):
    test_rendezvous_redeclare_races_rank_polls()
    report = lockcheck.report()
    assert report["acquisitions"] > 0, "lockcheck never engaged"
    lockcheck.assert_clean()


def test_traced_purity_canary_static_and_runtime_agree(lockcheck_enabled):
    """Cross-check the STATIC trace-purity rule against the RUNTIME lock
    detector on one shared scenario: a deliberately impure jitted fn
    that acquires a lock under trace.

    The static analyzer must flag the source; the runtime detector must
    observe that the acquisition really happens exactly once — at trace
    time — and never again on the cached-executable path.  That is the
    precise failure mode the rule's message describes ("runs once at
    trace time, guards nothing at runtime")."""
    import textwrap

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.analysis.core import SourceFile
    from elasticdl_tpu.analysis.rules import ALL_RULES

    # Static half: the analyzer flags the planted impurity.
    canary_src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def impure_step(x):
            with STEP_LOCK:
                return x + 1
        """
    )
    source = SourceFile.parse("purity_canary.py", canary_src)
    found = ALL_RULES["trace-purity"](source)
    assert len(found) == 1, found
    assert "STEP_LOCK" in found[0].message
    assert "trace time" in found[0].message

    # Runtime half: the same impurity shape with an instrumented lock.
    step_lock = CheckedLock("canary.step_lock")

    @jax.jit
    def impure_step(x):
        with step_lock:
            return x + 1

    before = lockcheck.report()["acquisitions"]
    impure_step(jnp.zeros((4,), jnp.float32)).block_until_ready()
    traced = lockcheck.report()["acquisitions"]
    assert traced == before + 1, "lock not observed during tracing"
    impure_step(jnp.ones((4,), jnp.float32)).block_until_ready()
    assert lockcheck.report()["acquisitions"] == traced, (
        "cached-executable call re-acquired the lock — tracing semantics "
        "changed; the static rule's 'once at trace time' claim is stale"
    )
    lockcheck.assert_clean()


def test_traced_purity_canary_pure_step_is_silent_both_ways(
    lockcheck_enabled,
):
    """The agreeing negative: a pure jitted step trips neither the
    static rule nor the runtime detector."""
    import textwrap

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.analysis.core import SourceFile
    from elasticdl_tpu.analysis.rules import ALL_RULES

    pure_src = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def pure_step(x):
            return jnp.sum(x * x)
        """
    )
    source = SourceFile.parse("purity_canary_ok.py", pure_src)
    assert ALL_RULES["trace-purity"](source) == []

    before = lockcheck.report()["acquisitions"]

    @jax.jit
    def pure_step(x):
        return jnp.sum(x * x)

    pure_step(jnp.ones((4,), jnp.float32)).block_until_ready()
    assert lockcheck.report()["acquisitions"] == before
    lockcheck.assert_clean()


def test_lockcheck_distinguishes_same_named_instances(lockcheck_enabled):
    """Two services of the same class share a lock NAME but not identity:
    holding instance A's lock while taking instance B's must not read as
    a self-deadlock or an ordering edge (false positive on correct code)."""
    a, b = CheckedLock("TaskManager._lock"), CheckedLock("TaskManager._lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    lockcheck.assert_clean()
