"""Transformer LM (long-context config) tests: single-device and
context-parallel (ring attention over the model axis) training, plus
parity between the two."""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
from model_zoo import datasets
from model_zoo.transformer import transformer_lm as zoo


def _batches(n=64, mb=16, seq_len=64, seed=0):
    from elasticdl_tpu.data.dataset import Dataset, _stack
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    reader = datasets.synthetic_lm_reader(
        n=n, seq_len=seq_len, vocab=zoo.VOCAB, seed=seed
    )
    task = pb.Task(task_id=1, shard_name="s", start=0, end=n)
    records = list(
        zoo.dataset_fn(
            Dataset.from_generator(lambda: reader.read_records(task)),
            "training",
            None,
        )
    )
    for i in range(0, n, mb):
        yield _stack(records[i : i + mb])


def test_lm_trains_single_device():
    mesh = build_mesh(MeshConfig(data=1, model=1),
                      devices=jax.devices()[:1])
    trainer = DataParallelTrainer(
        zoo.custom_model(d_model=64, num_layers=2),
        zoo.loss, zoo.optimizer(), mesh,
    )
    losses = []
    for epoch in range(4):
        for tokens, labels in _batches(seed=epoch % 2):
            losses.append(float(trainer.train_step(tokens, labels)))
    assert losses[-1] < losses[0] * 0.7, (
        f"no learning: {losses[:2]} -> {losses[-2:]}"
    )


def test_lm_trains_context_parallel():
    """dp=2 x cp=4: batch over `data`, sequence ring over `model`."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    trainer = DataParallelTrainer(
        zoo.custom_model(d_model=64, num_layers=2, mesh=mesh),
        zoo.loss, zoo.optimizer(), mesh,
    )
    losses = []
    for epoch in range(4):
        for tokens, labels in _batches(seed=epoch % 2):
            losses.append(float(trainer.train_step(tokens, labels)))
    assert losses[-1] < losses[0] * 0.7, (
        f"no learning: {losses[:2]} -> {losses[-2:]}"
    )


def test_cp_and_single_device_agree():
    """Same init, same batch: the context-parallel forward must match the
    single-device forward (ring attention is exact, not approximate)."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    tokens, _ = next(_batches(n=8, mb=8, seq_len=64))
    tokens = jnp.asarray(tokens)

    single = zoo.custom_model(d_model=64, use_bf16=False)
    ringed = zoo.custom_model(d_model=64, use_bf16=False, mesh=mesh)
    variables = single.init(jax.random.PRNGKey(0), tokens)
    out_single = single.apply(variables, tokens)
    out_ring = ringed.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(out_single), np.asarray(out_ring), atol=2e-4, rtol=2e-4
    )


import pytest as _pytest


@_pytest.mark.parametrize("extra_model_params", ["", ",model_axis_mode=tp"])
def test_lm_cluster_e2e_cp_and_tp(tmp_path, monkeypatch, extra_model_params):
    """Full cluster path, parametrized over what the model axis carries:
    2 worker processes x 2 CPU devices = a 4-device world,
    --mesh_model_axis=2 -> mesh 2x2 (data x model).

    - default (cp): the sequence ring spans PROCESS boundaries;
    - model_axis_mode=tp: GSPMD's tensor-parallel collectives run across
      processes instead.

    Both must train every record and write a checkpoint, and the worker
    logs must show the mesh genuinely reached the model (without it the
    model silently degrades to the single-device layout)."""
    import os

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.constants import Mode
    from elasticdl_tpu.master.job_runner import run_allreduce_job

    monkeypatch.setenv("ELASTICDL_FORCE_PLATFORM", "cpu")
    monkeypatch.setenv(
        "ELASTICDL_WORKER_ENV",
        ";".join(
            f"{k}={v}"
            for k, v in {
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "ELASTICDL_FORCE_PLATFORM": "cpu",
                "JAX_PLATFORMS": "cpu",
            }.items()
        ),
    )
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=transformer.transformer_lm",
        "--model_params=d_model=32,num_layers=1,num_heads=2"
        + extra_model_params,
        "--training_data=synthetic://lm?n=64&len=32",
        "--records_per_task=32",
        "--minibatch_size=8",
        "--num_workers=2",
        "--mesh_model_axis=2",
        "--distribution_strategy=AllreduceStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=4",
        "--num_epochs=1",
    ])
    rc = run_allreduce_job(args, Mode.TRAINING)
    assert rc == 0
    assert any(p.startswith("step_") for p in os.listdir(tmp_path / "ckpt"))
    # The mesh reached the model in every worker (see build_model's log).
    log_root = next(
        tmp_path / "ckpt" / d
        for d in os.listdir(tmp_path / "ckpt")
        if d.endswith("_worker_logs")
    )
    logs = "".join(
        open(log_root / f).read() for f in os.listdir(log_root)
    )
    assert "Mesh-aware model: forwarding mesh" in logs



def test_pallas_attn_impl_matches_xla():
    """attn_impl='pallas' (interpret mode on CPU) must match the XLA
    blockwise implementation through the full model."""
    tokens, _ = next(_batches(n=4, mb=4, seq_len=32))
    tokens = jnp.asarray(tokens)
    xla_model = zoo.custom_model(d_model=32, num_heads=2, num_layers=1,
                                 use_bf16=False, attn_impl="xla")
    pls_model = zoo.custom_model(d_model=32, num_heads=2, num_layers=1,
                                 use_bf16=False, attn_impl="pallas")
    variables = xla_model.init(jax.random.PRNGKey(0), tokens)
    out_x = xla_model.apply(variables, tokens)
    out_p = pls_model.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_p), atol=2e-4, rtol=2e-4
    )


def test_zigzag_cp_matches_single_device():
    """cp_layout='zigzag' (balanced causal ring) must be numerically
    identical to the single-device forward."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    tokens, _ = next(_batches(n=8, mb=8, seq_len=64))
    tokens = jnp.asarray(tokens)
    single = zoo.custom_model(d_model=64, use_bf16=False)
    zigzag = zoo.custom_model(d_model=64, use_bf16=False, mesh=mesh,
                              cp_layout="zigzag")
    variables = single.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        np.asarray(single.apply(variables, tokens)),
        np.asarray(zigzag.apply(variables, tokens)),
        atol=2e-4, rtol=2e-4,
    )


def test_remat_matches_and_trains():
    """remat=True must not change the math (same loss trajectory) while
    rematerializing block activations."""
    mesh = build_mesh(MeshConfig(data=1, model=1),
                      devices=jax.devices()[:1])
    batches = list(_batches(n=32, mb=8, seq_len=32))

    def run(remat):
        trainer = DataParallelTrainer(
            zoo.custom_model(d_model=32, num_heads=2, num_layers=2,
                             use_bf16=False, remat=remat),
            zoo.loss, zoo.optimizer(), mesh,
        )
        return [float(trainer.train_step(t, l)) for t, l in batches]

    plain, remat = run(False), run(True)
    np.testing.assert_allclose(plain, remat, rtol=1e-4, atol=1e-5)


def test_cp_worker_kill_elastic_recovery(tmp_path, monkeypatch):
    """Elasticity composes with sequence parallelism: kill a worker in a
    context-parallel (2 procs x 2 devices, ring over model axis) job —
    the world re-forms (budget 0 => shrinks to 1 fresh proc, mesh 1x2,
    the ring shrinks with it), restores from checkpoint, and every
    record still trains (asserted by the shared driver in conftest)."""
    from elasticdl_tpu.common.args import parse_master_args
    from tests.conftest import run_kill_recovery_job

    worker_env = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ELASTICDL_FORCE_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
    }
    monkeypatch.setenv("ELASTICDL_FORCE_PLATFORM", "cpu")
    n_records = 512
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=transformer.transformer_lm",
        "--model_params=d_model=32,num_layers=1,num_heads=2",
        f"--training_data=synthetic://lm?n={n_records}&len=32",
        "--records_per_task=32",
        "--minibatch_size=4",
        "--num_workers=2",
        "--mesh_model_axis=2",
        "--max_worker_restarts=0",
        "--distribution_strategy=AllreduceStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=4",
        "--num_epochs=1",
    ])
    run_kill_recovery_job(
        args, n_records, worker_env, str(tmp_path / "logs"),
        wait_timeout=600,
    )


def test_tp_matches_single_device_and_trains():
    """model_axis_mode='tp': heads + MLP hidden shard over the model
    axis (Megatron-style, GSPMD splits the matmuls).  Same params =>
    same outputs as single-device; training through the trainer learns."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    tokens, _ = next(_batches(n=8, mb=8, seq_len=64))
    tokens = jnp.asarray(tokens)

    single = zoo.custom_model(d_model=64, use_bf16=False)
    tp = zoo.custom_model(d_model=64, use_bf16=False, mesh=mesh,
                          model_axis_mode="tp")
    variables = single.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        np.asarray(single.apply(variables, tokens)),
        np.asarray(tp.apply(variables, tokens)),
        atol=2e-4, rtol=2e-4,
    )

    trainer = DataParallelTrainer(
        zoo.custom_model(d_model=64, num_layers=2, mesh=mesh,
                         model_axis_mode="tp"),
        zoo.loss, zoo.optimizer(), mesh,
    )
    losses = []
    for epoch in range(4):
        for toks, labels in _batches(seed=epoch % 2):
            losses.append(float(trainer.train_step(toks, labels)))
    assert losses[-1] < losses[0] * 0.7, (
        f"no learning: {losses[:2]} -> {losses[-2:]}"
    )


def test_model_axis_mode_validated():
    import pytest

    mesh = build_mesh(MeshConfig(data=2, model=4))
    model = zoo.custom_model(d_model=32, mesh=mesh, model_axis_mode="typo")
    tokens = jnp.zeros((4, 32), jnp.int32)
    with pytest.raises(ValueError, match="model_axis_mode"):
        model.init(jax.random.PRNGKey(0), tokens)


def test_bf16_logits_head_parity_and_checkpoint_names():
    """logits_compute='bf16' (MXU-native head: bf16 operands, f32
    accumulate/out) must produce the same parameter tree as the f32 head
    (checkpoint-interchangeable) and logits within bf16 rounding of it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from model_zoo.transformer import transformer_lm as zoo

    kwargs = dict(vocab=128, d_model=64, num_heads=2, num_layers=1,
                  max_len=32)
    f32 = zoo.custom_model(**kwargs)
    bf16 = zoo.custom_model(logits_compute="bf16", **kwargs)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, size=(2, 32)), jnp.int32
    )
    v32 = f32.init(jax.random.PRNGKey(0), tokens)
    v16 = bf16.init(jax.random.PRNGKey(0), tokens)
    paths32 = {p for p, _ in jax.tree_util.tree_flatten_with_path(v32)[0]}
    paths16 = {p for p, _ in jax.tree_util.tree_flatten_with_path(v16)[0]}
    assert paths32 == paths16
    out32 = f32.apply(v32, tokens)
    out16 = bf16.apply(v32, tokens)  # SAME params through the bf16 head
    assert out16.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out16), np.asarray(out32), rtol=0.05, atol=0.05
    )
