"""Pallas-engined ring attention (VERDICT round-2 #3: fuse the flash
kernel into the ring steps).

Exact-parity pinning against dense numerics and against the XLA ring
engine on the 8-device CPU mesh (kernels run in Pallas interpret mode
off-TPU), forward AND gradients, both layouts.  The lse-space step
recombination and the ring-aware custom VJP (KV and their grads rotate
together) are the new machinery under test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from elasticdl_tpu.parallel.ring_attention import ring_self_attention
from tests.test_ring_attention import _qkv, dense_attention


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_ring_matches_dense(causal):
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=4, t=64)
    out = ring_self_attention(mesh, q, k, v, causal=causal, impl="pallas")
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_ring_matches_xla_ring(causal):
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=5)
    a = ring_self_attention(mesh, q, k, v, causal=causal, impl="pallas")
    b_ = ring_self_attention(mesh, q, k, v, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


def test_pallas_ring_zigzag_matches_dense():
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=64, seed=9)
    out = ring_self_attention(
        mesh, q, k, v, causal=True, layout="zigzag", impl="pallas"
    )
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_ring_gradients_match_dense():
    """The ring-aware custom VJP: dq accumulates across steps, dk/dv ride
    the rotation home — grads must equal dense attention's."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=7)
    spec = P(DATA_AXIS, MODEL_AXIS, None, None)
    sharding = NamedSharding(mesh, spec)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    def ring_loss(q, k, v):
        out = ring_self_attention(mesh, q, k, v, causal=True, impl="pallas")
        return jnp.sum(out ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-4
        )


def test_pallas_ring_zigzag_gradients_match_dense():
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=11)

    def ring_loss(q, k, v):
        out = ring_self_attention(
            mesh, q, k, v, causal=True, layout="zigzag", impl="pallas"
        )
        return jnp.sum(out ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-4
        )


def test_pallas_ring_bf16_inputs():
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=13, dtype=jnp.bfloat16)
    out = ring_self_attention(mesh, q, k, v, causal=True, impl="pallas")
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def _capture_ring_warnings():
    """StringIO handler on the exact logger (the repo logger binds its
    own stderr handler with propagate=False, so caplog/capfd miss it)."""
    import contextlib
    import io
    import logging

    @contextlib.contextmanager
    def cm():
        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        lg = logging.getLogger("elasticdl_tpu.parallel.ring_attention")
        lg.addHandler(handler)
        try:
            yield buf
        finally:
            lg.removeHandler(handler)

    return cm()


def test_auto_mode_vmem_fallback_warns():
    """attn impl=auto falling back to the XLA engine because of the
    scoped-VMEM budget (NOT a shape-capability limit) must say so and
    name the LIBTPU flag that unlocks the kernel (VERDICT round-3 #8)."""
    from elasticdl_tpu.parallel.ring_attention import _ring_dispatch

    # T=32768, D=64: alignment fine, KV block 16 MiB f32 > the 8 MiB
    # auto-mode budget -> xla fallback.  Outside shard_map the follow-on
    # ring call fails on the unbound axis — the warning fires first, at
    # impl-selection time, which is all this test pins.
    q = jnp.zeros((1, 32768, 1, 64), jnp.float32)
    with _capture_ring_warnings() as buf:
        try:
            _ring_dispatch(q, q, q, axis_name="model", causal=False)
        except Exception:
            pass
    assert "xla_tpu_scoped_vmem_limit_kib" in buf.getvalue()


def test_auto_mode_small_shape_no_vmem_warning():
    """In-budget shapes select the Pallas engine with no VMEM warning."""
    from elasticdl_tpu.parallel.ring_attention import _ring_dispatch

    q = jnp.zeros((1, 64, 1, 64), jnp.float32)
    with _capture_ring_warnings() as buf:
        try:
            _ring_dispatch(q, q, q, axis_name="model", causal=False)
        except Exception:
            pass
    assert "xla_tpu_scoped_vmem_limit_kib" not in buf.getvalue()


def test_supports_honors_configured_vmem_flag(monkeypatch):
    """auto-mode's VMEM bound follows the OPERATOR'S configured budget:
    with LIBTPU_INIT_ARGS raising the scoped-VMEM limit, supports()
    accepts the long-T shapes the flag exists for instead of silently
    falling back to the XLA engine (round 4)."""
    from elasticdl_tpu.ops.flash_attention import supports

    # Flag-free: T=16384 D=64 sits exactly at the 8 MiB KV cap; T=32768
    # exceeds it.
    monkeypatch.delenv("LIBTPU_INIT_ARGS", raising=False)
    assert supports(16384, 64)
    assert not supports(32768, 64)
    # Operator raises the budget 4x -> the 16 MiB KV block now fits.
    monkeypatch.setenv(
        "LIBTPU_INIT_ARGS", "--xla_tpu_scoped_vmem_limit_kib=65536"
    )
    assert supports(32768, 64)
    assert not supports(262144, 64)  # still bounded
    # Malformed/unrelated args fall back to the default budget.
    monkeypatch.setenv("LIBTPU_INIT_ARGS", "--some_other_flag=1")
    assert not supports(32768, 64)
