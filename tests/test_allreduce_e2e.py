"""AllReduce-mode end-to-end tests: a real multi-process jax.distributed
world over localhost, driven by the master's process manager.

Parity surface: the reference's elasticity e2e (SURVEY.md §4) — run a job
across worker processes, kill one mid-job, assert the job still completes
with every record trained (at-least-once task semantics).
"""

import pytest

# Tier-1 fast gate runs `-m 'not slow'` (see Makefile test-fast).
pytestmark = [pytest.mark.slow, pytest.mark.e2e]

import os
import time

import pytest

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.master.job_runner import run_allreduce_job
from elasticdl_tpu.master.main import start_master
from elasticdl_tpu.master.pod_manager import (
    LocalProcessManager,
    worker_argv_from_args,
)
from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous

WORKER_ENV = {
    # Workers run single-CPU-device processes (override the test harness's
    # 8 virtual devices); the world then has one device per process.
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "ELASTICDL_FORCE_PLATFORM": "cpu",
    "JAX_PLATFORMS": "cpu",
}


def job_args(tmp_path, n_records, records_per_task, minibatch, num_workers,
             max_restarts=3, extra=()):
    return parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=mnist.mnist_functional_api",
        f"--training_data=synthetic://mnist?n={n_records}",
        f"--records_per_task={records_per_task}",
        f"--minibatch_size={minibatch}",
        f"--num_workers={num_workers}",
        f"--max_worker_restarts={max_restarts}",
        "--distribution_strategy=AllreduceStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=5",
        *extra,
    ])


@pytest.fixture
def worker_env(monkeypatch):
    monkeypatch.setenv("ELASTICDL_FORCE_PLATFORM", "cpu")
    monkeypatch.setenv(
        "ELASTICDL_WORKER_ENV",
        ";".join(f"{k}={v}" for k, v in WORKER_ENV.items()),
    )


def test_allreduce_two_workers_end_to_end(tmp_path, worker_env):
    args = job_args(
        tmp_path, n_records=96, records_per_task=32, minibatch=8, num_workers=2,
        extra=("--validation_data=synthetic://mnist?n=32",),
    )
    rc = run_allreduce_job(args, Mode.TRAINING)
    assert rc == 0
    # A checkpoint was written by rank 0.
    assert any(p.startswith("step_") for p in os.listdir(tmp_path / "ckpt"))


def test_worker_kill_then_scale_up_when_capacity_returns(tmp_path, worker_env):
    """Elastic rejoin e2e (real processes): kill a worker with the restart
    budget exhausted — the world shrinks to 1 — then signal returned
    capacity through the capacity-file oracle; the world grows back to 2
    and every record still trains exactly-at-least-once."""
    n_records = 4096
    args = job_args(
        tmp_path, n_records=n_records, records_per_task=256, minibatch=4,
        num_workers=2, max_restarts=0,
    )
    capacity_file = tmp_path / "capacity"
    capacity_file.write_text("0")

    def capacity_check(needed):
        try:
            return max(0, min(needed, int(capacity_file.read_text() or 0)))
        except (OSError, ValueError):
            return 0

    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
        scale_up_check_fn=capacity_check,
    )
    try:
        manager.start()
        deadline = time.time() + 240
        while master.task_manager.finished_record_count < n_records // 16:
            assert time.time() < deadline, "no progress before kill"
            assert not master.task_manager.finished(), "job finished too fast"
            time.sleep(0.05)
        victims = manager.current_worker_ids()
        manager.kill_worker(victims[1])
        # Budget 0: the world shrinks to a single fresh worker.
        deadline = time.time() + 240
        while len(manager.current_worker_ids()) != 1 or (
            manager.current_worker_ids() == victims[:1]
        ):
            assert time.time() < deadline, "world never shrank"
            time.sleep(0.05)
        shrunk = manager.current_worker_ids()
        # Capacity returns: the manager must grow the world back to 2.
        capacity_file.write_text("1")
        deadline = time.time() + 240
        while len(manager.current_worker_ids()) != 2:
            assert time.time() < deadline, "world never grew back"
            assert not master.task_manager.finished(), (
                "job finished before scale-up could be observed"
            )
            time.sleep(0.05)
        grown = manager.current_worker_ids()
        assert len(grown) == 2 and not set(grown) & set(shrunk)
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        assert master.task_manager.finished_record_count == n_records
    finally:
        manager.stop()
        master.stop()


def test_worker_kill_elastic_recovery(tmp_path, worker_env):
    """Kill a worker mid-job: world re-forms (restart budget 0 => shrink to
    one fresh worker), state restores from checkpoint, all records still
    train (asserted by the shared driver in conftest)."""
    from tests.conftest import run_kill_recovery_job

    n_records = 4096
    args = job_args(
        tmp_path, n_records=n_records, records_per_task=256, minibatch=4,
        num_workers=2, max_restarts=0,
        # Persistent compile cache: the re-formed world's compiles are
        # disk hits (the recovery-time shave measured in BASELINE.md).
        extra=(f"--jax_compilation_cache_dir={tmp_path / 'jaxcache'}",),
    )
    metrics = run_kill_recovery_job(
        args, n_records, WORKER_ENV, str(tmp_path / "logs")
    )
    assert metrics["replayed_records"] <= 2 * 256  # <= both workers' tasks
