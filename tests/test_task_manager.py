"""Task manager unit tests.

Parity surface: elasticdl/python/tests/task_manager_test.py in the reference
(shard creation, get/report/recover semantics, epoch boundaries).
"""

import threading

from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elasticdl_pb2 as pb


def make_manager(**kwargs):
    defaults = dict(
        training_shards={"f1": 30, "f2": 15},
        records_per_task=10,
        num_epochs=1,
    )
    defaults.update(kwargs)
    return TaskManager(**defaults)


def drain(manager, worker_id=0, succeed=True):
    tasks = []
    while True:
        task = manager.get(worker_id)
        if task.task_id == -1 and task.type != pb.WAIT:
            break
        if task.type == pb.WAIT:
            break
        tasks.append(task)
        manager.report(task.task_id, succeed, worker_id)
    return tasks


class TestShardCreation:
    def test_task_count_and_ranges(self):
        manager = make_manager()
        tasks = drain(manager)
        # f1: [0,10),[10,20),[20,30); f2: [0,10),[10,15)
        assert len(tasks) == 5
        ranges = sorted((t.shard_name, t.start, t.end) for t in tasks)
        assert ranges == [
            ("f1", 0, 10),
            ("f1", 10, 20),
            ("f1", 20, 30),
            ("f2", 0, 10),
            ("f2", 10, 15),
        ]

    def test_uneven_tail_shard(self):
        manager = TaskManager(training_shards={"x": 7}, records_per_task=3)
        tasks = drain(manager)
        assert [(t.start, t.end) for t in tasks] == [(0, 3), (3, 6), (6, 7)]

    def test_shard_with_offset(self):
        manager = TaskManager(training_shards={"x": (100, 5)}, records_per_task=10)
        tasks = drain(manager)
        assert [(t.start, t.end) for t in tasks] == [(100, 105)]


class TestDispatchSemantics:
    def test_task_ids_unique_and_positive(self):
        manager = make_manager()
        seen = set()
        task = manager.get(0)
        while task.task_id != -1:
            assert task.task_id not in seen
            seen.add(task.task_id)
            manager.report(task.task_id, True, 0)
            task = manager.get(0)
        assert len(seen) == 5

    def test_wait_while_tasks_in_flight(self):
        manager = TaskManager(training_shards={"x": 10}, records_per_task=10)
        task = manager.get(0)
        assert task.task_id > 0
        # Queue empty but task in flight: second worker told to WAIT.
        waiting = manager.get(1)
        assert waiting.type == pb.WAIT and waiting.task_id == -1
        manager.report(task.task_id, True, 0)
        done = manager.get(1)
        assert done.task_id == -1 and done.type != pb.WAIT

    def test_failed_task_requeued(self):
        manager = TaskManager(training_shards={"x": 10}, records_per_task=10)
        task = manager.get(0)
        manager.report(task.task_id, False, 0)
        retry = manager.get(1)
        assert (retry.shard_name, retry.start, retry.end) == ("x", 0, 10)
        assert retry.task_id != task.task_id

    def test_report_unknown_task(self):
        manager = make_manager()
        assert manager.report(9999, True, 0) is False

    def test_finished_record_count(self):
        manager = make_manager()
        drain(manager)
        assert manager.finished_record_count == 45


class TestRecovery:
    def test_recover_tasks_of_dead_worker(self):
        manager = TaskManager(training_shards={"x": 30}, records_per_task=10)
        t0 = manager.get(0)
        t1 = manager.get(0)
        t2 = manager.get(1)
        assert manager.counts()["doing"] == 3
        recovered = manager.recover_tasks(0)
        assert recovered == 2
        # Worker 1 finishes everything, including the recovered ranges.
        manager.report(t2.task_id, True, 1)
        remaining = drain(manager, worker_id=1)
        got = sorted((t.start, t.end) for t in remaining)
        assert got == sorted([(t0.start, t0.end), (t1.start, t1.end)])
        assert manager.finished()

    def test_recovered_record_count_accounting(self):
        """Replay accounting (the elasticity lost-work metric): exact
        TRAINING ranges of recovered/retried tasks, eval tasks excluded."""
        manager = TaskManager(
            training_shards={"x": 30},
            evaluation_shards={"x": 10},
            records_per_task=10,
        )
        manager.create_evaluation_tasks(0)
        grabbed = [manager.get(0) for _ in range(4)]  # mixed train + eval
        n_train = sum(
            t.end - t.start for t in grabbed if t.type == pb.TRAINING
        )
        n_eval = sum(
            t.end - t.start for t in grabbed if t.type == pb.EVALUATION
        )
        assert n_train and n_eval, "fixture must mix task types"
        assert manager.recovered_record_count == 0
        assert manager.recover_tasks(0) == 4
        # Only the TRAINING ranges count as replayed records.
        assert manager.recovered_record_count == n_train

        # Failed-task retry path counts too (same guard).
        t = manager.get(1)
        while t is not None and t.type != pb.TRAINING:
            manager.report(t.task_id, True, 1)
            t = manager.get(1)
        before = manager.recovered_record_count
        manager.report(t.task_id, False, 1)
        assert manager.recovered_record_count == before + (t.end - t.start)

    def test_task_timeout_recovery(self):
        manager = TaskManager(
            training_shards={"x": 10}, records_per_task=10, task_timeout_s=0.001
        )
        stale = manager.get(0)
        import time

        time.sleep(0.01)
        # Next get() sweeps the timed-out task back and hands it over.
        fresh = manager.get(1)
        assert (fresh.start, fresh.end) == (stale.start, stale.end)
        # The stale report is now a no-op.
        assert manager.report(stale.task_id, True, 0) is False


class TestEpochs:
    def test_multi_epoch_generation(self):
        manager = TaskManager(
            training_shards={"x": 20}, records_per_task=10, num_epochs=3
        )
        epochs = []
        task = manager.get(0)
        while task.task_id != -1:
            epochs.append(task.epoch)
            manager.report(task.task_id, True, 0)
            task = manager.get(0)
        assert epochs == [0, 0, 1, 1, 2, 2]
        assert manager.finished()

    def test_done_callback_fires_once_at_end(self):
        fired = []
        manager = TaskManager(
            training_shards={"x": 20}, records_per_task=10, num_epochs=2
        )
        manager.add_tasks_done_callback(lambda: fired.append(1))
        drain_all(manager)
        assert fired == [1]


def drain_all(manager):
    task = manager.get(0)
    while task.task_id != -1 or task.type == pb.WAIT:
        if task.task_id != -1:
            manager.report(task.task_id, True, 0)
        task = manager.get(0)


class TestEvaluationTasks:
    def test_eval_tasks_interleave_at_front(self):
        manager = TaskManager(
            training_shards={"x": 20},
            evaluation_shards={"v": 10},
            records_per_task=10,
        )
        count = manager.create_evaluation_tasks(model_version=7)
        assert count == 1
        task = manager.get(0)
        assert task.type == pb.EVALUATION
        assert task.model_version == 7
        assert task.shard_name == "v"


class TestCheckpoint:
    def test_roundtrip_mid_epoch(self):
        manager = TaskManager(
            training_shards={"x": 40}, records_per_task=10, num_epochs=2
        )
        t = manager.get(0)
        manager.report(t.task_id, True, 0)
        in_flight = manager.get(0)  # left in doing: must reappear after resume

        resumed = TaskManager.from_checkpoint(manager.to_checkpoint())
        ranges = [(task.start, task.end) for task in drain_all_collect(resumed)]
        # 3 remaining tasks of epoch 0 (incl. the in-flight one) + 4 of epoch 1
        assert len(ranges) == 7
        assert (in_flight.start, in_flight.end) in ranges

    def test_concurrent_get_report(self):
        manager = TaskManager(training_shards={"x": 1000}, records_per_task=10)
        errors = []

        def run(worker_id):
            try:
                while True:
                    task = manager.get(worker_id)
                    if task.task_id == -1 and task.type != pb.WAIT:
                        return
                    if task.task_id != -1:
                        manager.report(task.task_id, True, worker_id)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(
                target=run, args=(i,), name=f"tm-worker-{i}", daemon=True
            )
            for i in range(8)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert not errors
        assert manager.finished()
        assert manager.finished_record_count == 1000


def drain_all_collect(manager):
    tasks = []
    task = manager.get(0)
    while task.task_id != -1 or task.type == pb.WAIT:
        if task.task_id != -1:
            tasks.append(task)
            manager.report(task.task_id, True, 0)
        task = manager.get(0)
    return tasks


class TestRetryBudget:
    def test_poison_task_dropped_after_max_retries(self):
        manager = TaskManager(
            training_shards={"x": 20}, records_per_task=10, max_task_retries=2
        )
        # Fail the same range 3 times: 2 retries allowed, then dropped.
        for _ in range(3):
            task = manager.get(0)
            assert (task.start, task.end) == (0, 10)
            manager.report(task.task_id, False, 0)
        failed = manager.permanently_failed_tasks()
        assert len(failed) == 1
        assert (failed[0].start, failed[0].end) == (0, 10)
        # The job still completes with the remaining range.
        rest = manager.get(0)
        assert (rest.start, rest.end) == (10, 20)
        manager.report(rest.task_id, True, 0)
        assert manager.finished()

    def test_callback_may_reenter_task_manager(self):
        manager = TaskManager(training_shards={"x": 10}, records_per_task=10)
        seen = []
        manager.add_tasks_done_callback(
            lambda: seen.append(manager.to_checkpoint())
        )
        task = manager.get(0)
        manager.report(task.task_id, True, 0)  # must not deadlock
        assert len(seen) == 1

    def test_exec_counters_aggregate(self):
        manager = TaskManager(training_shards={"x": 20}, records_per_task=10)
        for _ in range(2):
            task = manager.get(0)
            manager.report(task.task_id, True, 0, exec_counters={"batch_count": 5})
        assert manager.exec_counters() == {"batch_count": 10}

    def test_oov_counter_reaches_master_and_warns(self):
        """A task report carrying oov_lookup_count aggregates like any exec
        counter AND raises a master-log warning — the production alarm
        path for the fixed-vocab OOV contract (docs/design.md)."""
        import io
        import logging

        from elasticdl_tpu.common.constants import TaskExecCounterKey

        manager = TaskManager(training_shards={"x": 20}, records_per_task=10)
        stream = io.StringIO()
        handler = logging.StreamHandler(stream)
        logging.getLogger("elasticdl_tpu.master.task_manager").addHandler(
            handler
        )
        try:
            task = manager.get(0)
            manager.report(
                task.task_id, True, 0,
                exec_counters={TaskExecCounterKey.OOV_LOOKUP_COUNT: 42},
            )
        finally:
            logging.getLogger(
                "elasticdl_tpu.master.task_manager"
            ).removeHandler(handler)
        assert manager.exec_counters()[
            TaskExecCounterKey.OOV_LOOKUP_COUNT
        ] == 42
        assert "out-of-vocabulary" in stream.getvalue()


class TestFinalizationRace:
    def test_second_worker_waits_during_done_callbacks(self):
        """While done-callbacks queue final-eval/train-end tasks, a second
        worker polling get() must receive WAIT, not the job-done sentinel."""
        manager = TaskManager(
            training_shards={"x": 10},
            evaluation_shards={"v": 10},
            records_per_task=10,
        )
        seen_during_callback = []

        def queue_final_eval():
            # Simulate EvaluationService.trigger_evaluation at end of job;
            # poll from a "second worker" while the callback runs.
            seen_during_callback.append(manager.get(1))
            manager.create_evaluation_tasks(model_version=7)

        manager.add_tasks_done_callback(queue_final_eval)
        task = manager.get(0)
        manager.report(task.task_id, True, 0)
        # Poll during callback answered WAIT, not job-complete.
        assert seen_during_callback[0].type == pb.WAIT
        # The final eval task queued by the callback is served afterwards.
        final = manager.get(1)
        assert final.type == pb.EVALUATION and final.model_version == 7
        manager.report(final.task_id, True, 1)
        assert manager.get(1).task_id == -1

    def test_get_fires_done_callbacks_when_no_tasks(self):
        """A job with zero training tasks still runs its done-callbacks
        (via get) before workers see job-complete."""
        manager = TaskManager(training_shards={}, records_per_task=10)
        fired = []
        manager.add_tasks_done_callback(lambda: fired.append(True))
        first = manager.get(0)
        assert first.type == pb.WAIT and fired == [True]
        assert manager.get(0).task_id == -1
