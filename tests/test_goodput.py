"""Goodput ledger + postmortem report tests (elasticdl_tpu/obs/goodput,
obs/report, the obs.top goodput header, and the journal schema drift
gate).

Covers the ISSUE 5 acceptance surface:

- ledger state machine: exclusive phases, zero-length/same-phase edges,
  monotonic-clock regression clamping, restart-resume seeding, and exact
  requeue-redo accounting;
- per-rescale cost records (detection/rendezvous/redo components,
  superseded back-to-back churn);
- the report tool: timeline covers wall-clock, outage attribution
  between master generations, /metrics join;
- an end-to-end: a real LocalProcessManager fleet with one induced
  rescale, scraped over /metrics, whose replayed report agrees with the
  live elasticdl_goodput_ratio gauge.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.obs import goodput
from elasticdl_tpu.obs import report as report_mod
from elasticdl_tpu.obs import top

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
GOLDEN = os.path.join(TESTS_DIR, "golden_journal.jsonl")


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, seconds):
        self.t += seconds


@pytest.fixture
def journal_file(tmp_path):
    """Point the process journal at a per-test file (the ledger journals
    its edges there) and detach afterwards."""
    path = obs.init_journal(str(tmp_path))
    yield path
    obs.journal().configure(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# Ledger state machine
# ---------------------------------------------------------------------------


def test_transitions_accumulate_and_journal(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    record = ledger.transition("idle", cause="master_start")
    assert record["from"] == "" and record["to"] == "idle"
    clock.advance(2.0)
    record = ledger.transition("rendezvous", cause="world_declared")
    assert record["from"] == "idle" and record["seconds"] == 2.0
    clock.advance(3.0)
    ledger.transition("training", cause="task_dispatch")
    clock.advance(5.0)
    seconds = ledger.phase_seconds()
    assert seconds["idle"] == 2.0
    assert seconds["rendezvous"] == 3.0
    assert seconds["training"] == 5.0  # open phase counts its elapsed
    assert ledger.goodput_ratio() == pytest.approx(0.5)
    kinds = [e["event"] for e in _events(journal_file)]
    assert kinds.count("phase_transition") == 3


def test_same_phase_transition_is_noop(journal_file):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.transition("training")
    clock.advance(1.0)
    assert ledger.transition("training", cause="again") is None
    clock.advance(1.0)
    assert ledger.phase_seconds()["training"] == 2.0  # one unbroken span
    with pytest.raises(ValueError):
        ledger.transition("not_a_phase")


def test_clock_regression_clamps_to_zero(journal_file):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.transition("training")
    clock.t -= 10.0  # a regressing clock must not charge negative time
    record = ledger.transition("idle")
    assert record["seconds"] == 0.0
    assert ledger.phase_seconds()["training"] == 0.0
    assert ledger.goodput_ratio() >= 0.0


def test_phase_context_restores_previous(journal_file):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.transition("training")
    clock.advance(1.0)
    with ledger.phase("checkpoint_save", cause="cadence"):
        clock.advance(4.0)
        # Nested same-phase frames are free: no spurious edges.
        with ledger.phase("checkpoint_save"):
            clock.advance(1.0)
    assert ledger.current_phase() == "training"
    seconds = ledger.phase_seconds()
    assert seconds["checkpoint_save"] == 5.0
    assert seconds["training"] == 1.0


def test_redo_accounting_is_exact(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.note_dispatch()
    assert ledger.current_phase() == "training"
    ledger.note_requeue(128, "worker_churn", tasks=2)
    ledger.note_dispatch()
    assert ledger.current_phase() == "requeue_redo"
    clock.advance(2.0)
    ledger.note_task_done(64)
    assert ledger.current_phase() == "requeue_redo"  # 64 of 128 repaid
    clock.advance(2.0)
    ledger.note_task_done(64)
    assert ledger.current_phase() == "training"  # debt exactly repaid
    counts = ledger.counts()
    assert counts["records_redone"] == 128
    assert counts["redo_pending"] == 0
    assert ledger.phase_seconds()["requeue_redo"] == 4.0
    # Non-training completions never repay training debt.
    ledger.note_requeue(32, "failure")
    ledger.note_task_done(1000, training=False)
    assert ledger.counts()["redo_pending"] == 32


def test_rescale_cost_components(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.note_dispatch()
    ledger.on_rescale_detected("worker_churn", old_size=2)
    assert ledger.current_phase() == "rendezvous"
    ledger.note_requeue(64, "worker_churn", tasks=1)
    clock.advance(2.0)
    ledger.on_drain_complete(2)
    clock.advance(1.0)
    ledger.on_world_declared(2, 2)
    clock.advance(5.0)
    ledger.on_world_formed(2)
    ledger.note_dispatch()
    clock.advance(8.0)
    ledger.note_task_done(64)  # redo repaid with a formed world: closes
    costs = [
        e for e in _events(journal_file) if e["event"] == "rescale_cost"
    ]
    assert len(costs) == 1
    cost = costs[0]
    assert cost["cause"] == "worker_churn"
    assert cost["old_size"] == 2 and cost["new_size"] == 2
    assert cost["detection_s"] == pytest.approx(2.0)
    assert cost["rendezvous_s"] == pytest.approx(6.0)
    assert cost["redo_s"] == pytest.approx(8.0)
    assert cost["total_s"] == pytest.approx(16.0)
    assert cost["redo_records"] == 64 and cost["redo_tasks"] == 1
    assert cost["rendezvous_id"] == 2 and cost["superseded"] is False


def test_back_to_back_churn_supersedes_open_rescale(
    journal_file, obs_registry_snapshot
):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.on_rescale_detected("worker_churn", old_size=3)
    clock.advance(1.0)
    ledger.on_rescale_detected("worker_churn", old_size=2)
    clock.advance(1.0)
    ledger.on_world_declared(5, 2)
    ledger.note_dispatch()
    ledger.note_task_done(0)
    costs = [
        e for e in _events(journal_file) if e["event"] == "rescale_cost"
    ]
    assert [c["superseded"] for c in costs] == [True, False]
    assert [c["seq"] for c in costs] == [1, 2]


def test_straggler_flips_training_to_degraded(journal_file):
    ledger = goodput.GoodputLedger(clock=FakeClock())
    ledger.note_dispatch()
    ledger.on_straggler(7, True)
    assert ledger.current_phase() == "degraded_straggler"
    ledger.on_straggler(8, True)
    ledger.on_straggler(7, False)
    assert ledger.current_phase() == "degraded_straggler"  # 8 still flagged
    ledger.on_straggler(8, False)
    assert ledger.current_phase() == "training"
    # New dispatches while degraded land in the degraded phase.
    ledger.on_straggler(9, True)
    ledger.transition("idle")
    ledger.note_dispatch()
    assert ledger.current_phase() == "degraded_straggler"


def test_finish_emits_goodput_summary(journal_file, obs_registry_snapshot):
    clock = FakeClock()
    ledger = goodput.GoodputLedger(clock=clock)
    ledger.note_dispatch()
    clock.advance(9.0)
    ledger.transition("rendezvous")
    clock.advance(1.0)
    ledger.finish("job_complete")
    ledger.finish("job_complete")  # idempotent: one summary only
    summaries = [
        e for e in _events(journal_file) if e["event"] == "goodput_summary"
    ]
    assert len(summaries) == 1
    summary = summaries[0]
    assert summary["outcome"] == "job_complete"
    assert summary["goodput_ratio"] == pytest.approx(0.9)
    assert summary["wall_s"] == pytest.approx(10.0)
    assert summary["phases"] == {"training": 9.0, "rendezvous": 1.0}
    assert ledger.current_phase() == "idle"


def test_seed_from_journal_restores_cumulative_seconds(
    tmp_path, obs_registry_snapshot
):
    path = obs.init_journal(str(tmp_path))
    try:
        clock = FakeClock()
        first = goodput.GoodputLedger(clock=clock)
        first.transition("idle")
        clock.advance(2.0)
        first.transition("training")
        clock.advance(8.0)
        first.transition("rendezvous")  # closes training at 8s
        # SIGKILL here: rendezvous never closes; a replacement seeds what
        # WAS accounted and its own accounting continues from there.
        # 3 edges journaled, but the opening from="" edge closed nothing:
        # only the 2 closed-phase transitions seed.
        replacement = goodput.GoodputLedger(clock=clock)
        assert replacement.seed_from_journal(path) == 2
        seconds = replacement.phase_seconds()
        assert seconds["idle"] == 2.0
        assert seconds["training"] == 8.0
        clock.advance(2.0)  # the outage gap: unaccounted by the live
        replacement.transition("training")  # ledger (the report owns it)
        clock.advance(10.0)
        assert replacement.phase_seconds()["training"] == 18.0
        assert replacement.goodput_ratio() == pytest.approx(18.0 / 20.0)
        # Foreign/unreadable journals seed nothing.
        fresh = goodput.GoodputLedger(clock=clock)
        assert fresh.seed_from_journal(str(tmp_path / "nope.jsonl")) == 0
        assert sum(fresh.phase_seconds().values()) == 0.0
        # Pre-rotation accounting (events.jsonl.1) seeds too.
        with open(path + ".1", "w") as f:
            f.write(
                '{"ts": 1.0, "event": "phase_transition", "from": '
                '"training", "to": "idle", "seconds": 100.0}\n'
            )
        rotated_aware = goodput.GoodputLedger(clock=clock)
        assert rotated_aware.seed_from_journal(path) == 3
        assert rotated_aware.phase_seconds()["training"] == 108.0
    finally:
        obs.journal().configure(None)


# ---------------------------------------------------------------------------
# Report tool
# ---------------------------------------------------------------------------


def test_report_golden_outage_attribution_and_sums():
    summary = report_mod.summarize(report_mod.load_events(GOLDEN))
    wall = summary["wall_s"]
    assert wall == pytest.approx(90.1)
    assert sum(summary["phases"].values()) == pytest.approx(wall, rel=0.02)
    assert summary["generations"] == 2
    assert len(summary["outages"]) == 1
    assert summary["outage_s"] == pytest.approx(12.0)
    assert summary["phases"]["training"] == pytest.approx(46.0)
    assert summary["goodput_ratio"] == pytest.approx(52.0 / 90.1, rel=1e-3)
    (rescale,) = summary["rescales"]
    assert rescale["cause"] == "worker_churn"
    assert rescale["detection_s"] + rescale["rendezvous_s"] + rescale[
        "redo_s"
    ] == pytest.approx(rescale["total_s"])
    text = report_mod.render_report(summary)
    assert "master outage: 12.0s" in text
    assert "worker_churn" in text and "redo of 64 requeued records" in text


def test_report_selftest_and_cli_json_scrape(tmp_path, capsys):
    assert report_mod.selftest(GOLDEN) == 0
    scrape = tmp_path / "metrics.txt"
    scrape.write_text(
        "# TYPE elasticdl_goodput_ratio gauge\n"
        "elasticdl_goodput_ratio 0.58\n"
    )
    out_json = tmp_path / "summary.json"
    assert report_mod.main(
        [GOLDEN, "--json", str(out_json), "--scrape", str(scrape)]
    ) == 0
    printed = capsys.readouterr().out
    assert "goodput 57.7%" in printed
    assert "elasticdl_goodput_ratio: 0.58" in printed
    summary = json.loads(out_json.read_text())
    assert summary["metrics_goodput_ratio"] == 0.58
    assert abs(summary["goodput_ratio_delta"]) < 0.01
    # Malformed trailing line (torn write at SIGKILL) is dropped, not fatal.
    torn = tmp_path / "torn.jsonl"
    with open(GOLDEN) as f:
        torn.write_text(f.read() + '{"ts": 1754000091.0, "event": "tru')
    assert report_mod.summarize(report_mod.load_events(str(torn)))[
        "wall_s"
    ] == pytest.approx(90.1)


# ---------------------------------------------------------------------------
# obs.top goodput header (satellite)
# ---------------------------------------------------------------------------

_TOP_METRICS = (
    "elasticdl_world_size 2\n"
    "elasticdl_goodput_ratio 0.873\n"
    'elasticdl_goodput_current_phase{phase="training"} 1\n'
    'elasticdl_goodput_current_phase{phase="idle"} 0\n'
    "elasticdl_goodput_last_rescale_seconds 93.0\n"
    'elasticdl_records_redone_total{reason="worker_churn"} 128\n'
)


def test_top_goodput_header_row():
    header = top.goodput_header(_TOP_METRICS)
    assert "goodput=87.3%" in header
    assert "phase=training" in header
    assert "last_rescale=93.0s" in header
    assert "redone=128rec" in header
    frame = top.render(
        [], top.parse_metrics(_TOP_METRICS), "m:9090", job_header=header
    )
    assert "goodput=87.3%" in frame


def test_top_degrades_without_goodput_or_journal():
    # Old master: no goodput gauges -> no header row, never a raise.
    assert top.goodput_header("elasticdl_world_size 2\n") == ""
    frame = top.render(
        [],
        {"elasticdl_world_size": 2.0},
        "m:9090",
        job_header="",
        notes=["(journal endpoint unavailable: HTTP Error 404)"],
    )
    assert "journal endpoint unavailable" in frame
    assert "world=2" in frame


# ---------------------------------------------------------------------------
# Journal schema drift gate (satellite)
# ---------------------------------------------------------------------------


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_journal",
        os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_journal_source_scan_repo_clean_and_detects_drift(tmp_path):
    validator = _load_validator()
    assert validator.scan_sources(
        os.path.join(REPO_ROOT, "elasticdl_tpu")
    ) == []
    drifting = tmp_path / "drifting.py"
    drifting.write_text(
        'obs.journal().record("totally_new_event", x=1)\n'
        'events.append(dict(event="another_unregistered", y=2))\n'
        'obs.journal().record("rendezvous", rendezvous_id=1)\n'
    )
    unknown = {
        event for _p, _l, event in validator.scan_sources(str(tmp_path))
    }
    assert unknown == {"totally_new_event", "another_unregistered"}
    # Field-level drift: the event name is registered, the field is
    # misspelled — the AST-backed gate catches what the retired
    # name-only grep passed.
    drifting.write_text(
        'obs.journal().record("rendezvous", rendezvous_id=1,\n'
        '                     world_size=2, coordinater=0)\n'
    )
    assert validator.scan_sources(str(tmp_path)) == []  # name is known
    problems, scanned = validator.scan_sources_counted(str(tmp_path))
    assert scanned == 1
    assert any("coordinater" in message for _p, _l, message in problems)
    assert validator._check_sources(str(tmp_path)) == 1
    # A scan that matched zero files must FAIL, not pass vacuously
    # (wrong cwd would otherwise silently disable the drift gate).
    empty = tmp_path / "empty"
    empty.mkdir()
    assert validator._check_sources(str(empty)) == 2
    assert validator._check_sources(str(tmp_path / "missing")) == 2


def test_golden_journal_passes_schema_validation():
    validator = _load_validator()
    assert validator.validate_file(GOLDEN) == []


# ---------------------------------------------------------------------------
# End-to-end: real fleet, one induced rescale, /metrics vs report
# ---------------------------------------------------------------------------


def test_rescale_e2e_report_and_metrics_agree(tmp_path, obs_registry_snapshot):
    """A master-side control plane (task manager + rendezvous + real
    LocalProcessManager fleet) runs a job with one induced worker-churn
    rescale.  The journal replay and the live /metrics gauge must tell
    the same goodput story, and the rescale must be attributed into
    detection/rendezvous/redo components."""
    from elasticdl_tpu.master.pod_manager import LocalProcessManager
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.obs.exporter import MetricsExporter
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    journal_path = obs.init_journal(str(tmp_path))
    ledger = goodput.reset_ledger()
    sleeper = tmp_path / "sleeper.py"
    sleeper.write_text("import time\ntime.sleep(120)\n")
    exporter = None
    manager = None
    try:
        obs.journal().record("master_start", job_name="goodput-e2e", port=0)
        ledger.transition("idle", cause="master_start")
        task_manager = TaskManager(
            training_shards={"shard": 512}, records_per_task=64
        )
        rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 29123)
        manager = LocalProcessManager(
            num_workers=2,
            worker_argv_fn=lambda wid: [sys.executable, str(sleeper)],
            rendezvous=rendezvous,
            task_manager=task_manager,
            max_restarts=2,
            job_finished_fn=task_manager.finished,
            poll_interval_s=0.05,
        )
        exporter = MetricsExporter(port=0).start()
        manager.start()

        # Real training time: an in-process "fleet" (worker id 99, not a
        # supervised process, so churn never requeues ITS task) works the
        # queue while the supervised sleepers provide the churn surface.
        def work_one(min_s=0.15):
            task = task_manager.get(99)
            if task.task_id == -1:
                if task.type == pb.WAIT:
                    time.sleep(0.02)
                    return True
                return False
            time.sleep(min_s)
            task_manager.report(task.task_id, True, worker_id=99)
            return True

        for _ in range(3):
            assert work_one(0.15)

        # Induce the rescale: a task is in flight on the victim when it
        # dies, so the churn requeues real records (the redo debt).
        victims = manager.current_worker_ids()
        assert len(victims) == 2
        inflight = task_manager.get(victims[1])
        assert inflight.task_id >= 0
        manager.kill_worker(victims[1])
        deadline = time.time() + 60
        while time.time() < deadline:
            ids = manager.current_worker_ids()
            if ids and not set(ids) & set(victims):
                break
            time.sleep(0.02)
        else:
            pytest.fail("world never re-formed after the kill")

        while work_one():
            pass
        assert task_manager.finished()
        manager.stop()
        ledger.finish("job_complete")

        # --- live gauge, scraped over real HTTP -----------------------
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=10
        ) as response:
            text = response.read().decode()
        live_ratio = report_mod.parse_metric_value(
            text, "elasticdl_goodput_ratio"
        )
        assert live_ratio is not None and 0.0 < live_ratio <= 1.0
        assert 'elasticdl_phase_seconds_total{phase="training"} ' in text
        assert 'elasticdl_rescale_cost_seconds_count{component="total"} ' in text
        assert 'elasticdl_records_redone_total{reason="worker_churn"} ' in text
        # The top satellite renders its goodput header from this scrape.
        assert "goodput=" in top.goodput_header(text)

        # --- journal replay -------------------------------------------
        summary = report_mod.summarize(report_mod.load_events(journal_path))
        wall = summary["wall_s"]
        assert wall > 1.0
        assert sum(summary["phases"].values()) == pytest.approx(
            wall, rel=0.02
        )
        assert summary["phases"].get("training", 0.0) > 0.0
        rescales = [r for r in summary["rescales"] if not r["superseded"]]
        assert len(rescales) == 1
        rescale = rescales[0]
        assert rescale["cause"] == "worker_churn"
        assert rescale["redo_records"] == 64
        assert rescale["detection_s"] + rescale["rendezvous_s"] + rescale[
            "redo_s"
        ] == pytest.approx(rescale["total_s"], abs=0.01)
        # Live gauge vs replay: same story within the acceptance bound
        # (small drift = idle seconds accrued between finish and scrape).
        assert live_ratio == pytest.approx(
            summary["goodput_ratio"], abs=0.05
        )
        assert report_mod.selftest(journal_path) == 0

        # --- and the journal passes schema validation -----------------
        check = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
                journal_path,
            ],
            capture_output=True,
            text=True,
        )
        assert check.returncode == 0, check.stderr
    finally:
        if manager is not None:
            manager.stop()
        if exporter is not None:
            exporter.stop()
        obs.journal().configure(None)
        goodput.reset_ledger()
