"""Touched-rows (scatter/lazy) sparse optimizers vs the streaming path.

Round-3 scale fix: the streaming moment updates are O(local-table) per
step, which collapsed DeepFM at the north-star 26M-row table (VERDICT
round 2, #1).  The scatter path (packed.dedup_representatives + gather/
update/scatter of touched rows) must preserve the exact sparse-apply
contract the golden tests pin (parity: the reference's Eigen
`*SparseApply` kernels, elasticdl/pkg/kernel/capi via pkg/optimizer):

- duplicate ids contribute their SUMMED gradient, one slot update;
- rows whose summed gradient is exactly zero are untouched (no decay);
- out-of-bounds ids (negative padding, >= vocab) are dropped.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel import sparse_optim
from elasticdl_tpu.parallel.packed import PackedSpec


def _ids_with_edges(rng, vocab, n):
    """ids covering every edge: duplicates, negatives, >= vocab OOB."""
    ids = rng.randint(0, vocab, size=n).astype(np.int32)
    ids[0] = ids[1]  # duplicate pair
    ids[2] = -1  # padding id
    ids[3] = vocab + 1000  # OOB high
    return ids


def test_dedup_representatives_matches_numpy():
    spec = PackedSpec(64, 8)
    rng = np.random.RandomState(0)
    n = 24
    ids = _ids_with_edges(rng, 64, n)
    grads = rng.randn(n, 8).astype(np.float32)
    # Make one valid row sum exactly to zero (cancelling duplicates).
    ids[4] = ids[5] = 50
    grads[5] = -grads[4]

    safe, gsum, touched = pk.dedup_representatives(
        spec, jnp.asarray(ids), jnp.asarray(grads)
    )
    safe, gsum, touched = map(np.asarray, (safe, gsum, touched))

    valid = (ids >= 0) & (ids < spec.vocab_padded)
    # Exactly one representative per distinct valid id with nonzero sum.
    for row in np.unique(ids[valid]):
        expect = grads[ids == row].sum(axis=0)
        reprs = np.flatnonzero(touched & (ids == row))
        if np.allclose(expect, 0):
            assert reprs.size == 0, f"zero-sum row {row} must stay untouched"
        else:
            assert reprs.size == 1, f"row {row} needs exactly one representative"
            np.testing.assert_allclose(gsum[reprs[0]], expect, rtol=1e-6)
            assert safe[reprs[0]] == row
    # Invalid positions never touched.
    assert not touched[~valid].any()


_OPTS = {
    "momentum": lambda mode: sparse_optim.momentum(0.1, mu=0.9, mode=mode),
    "nesterov": lambda mode: sparse_optim.momentum(
        0.1, mu=0.9, nesterov=True, mode=mode
    ),
    "adagrad": lambda mode: sparse_optim.adagrad(0.1, mode=mode),
    "adam": lambda mode: sparse_optim.adam(0.01, mode=mode),
    "adam_global": lambda mode: sparse_optim.adam(
        0.01, mode=mode, bias_correction="global"
    ),
}


@pytest.mark.parametrize("name", sorted(_OPTS))
@pytest.mark.parametrize("vocab,dim", [(64, 8), (100, 4), (33, 5)])
def test_scatter_matches_stream_multi_step(name, vocab, dim):
    """Both paths produce the same table and slots over several steps with
    duplicate / zero-sum / padding / OOB ids in the mix."""
    rng = np.random.RandomState(7)
    table0 = rng.randn(vocab, dim).astype(np.float32)

    results = {}
    for mode in ("stream", "scatter"):
        opt = _OPTS[name](mode)
        table = jnp.asarray(table0)
        slots = opt.init_slots_logical(table)
        for step in range(4):
            srng = np.random.RandomState(100 + step)
            n = 20
            ids = _ids_with_edges(srng, vocab, n)
            grads = srng.randn(n, dim).astype(np.float32)
            ids[4] = ids[5] = 7
            grads[5] = -grads[4]  # row 7 sums to zero -> untouched
            table, slots = opt.apply_logical(
                table, slots, jnp.asarray(ids), jnp.asarray(grads)
            )
        results[mode] = (np.asarray(table), {k: np.asarray(v) for k, v in slots.items()})

    t_stream, s_stream = results["stream"]
    t_scatter, s_scatter = results["scatter"]
    np.testing.assert_allclose(t_scatter, t_stream, rtol=1e-5, atol=1e-6)
    assert sorted(s_stream) == sorted(s_scatter)
    for key in s_stream:
        np.testing.assert_allclose(
            s_scatter[key], s_stream[key], rtol=1e-5, atol=1e-6,
            err_msg=f"slot {key} diverged",
        )


@pytest.mark.parametrize("name", ["sgd", "momentum", "adagrad", "adam"])
def test_apply_acc_matches_apply(name):
    """One apply_acc from an accumulated-gradient table == one apply from
    the raw (ids, grads) batch — the contract the windowed sparse-apply
    (ps_trainer sparse_apply_every) is built on."""
    vocab, dim = 64, 8
    spec = PackedSpec(vocab, dim)
    rng = np.random.RandomState(11)
    table0 = rng.randn(vocab, dim).astype(np.float32)
    ids = _ids_with_edges(rng, vocab, 20)
    grads = rng.randn(20, dim).astype(np.float32)

    opts = {
        "sgd": sparse_optim.sgd(0.1),
        "momentum": sparse_optim.momentum(0.1),
        "adagrad": sparse_optim.adagrad(0.1),
        "adam": sparse_optim.adam(0.01),
    }
    opt = opts[name]
    packed = pk.pack(spec, jnp.asarray(table0))
    slots = opt.init_slots(spec, packed)

    t_apply, s_apply = opt.apply(
        spec, packed, slots, jnp.asarray(ids), jnp.asarray(grads)
    )
    acc = pk.grad_accumulate(
        spec, packed, jnp.asarray(ids), jnp.asarray(grads)
    )
    t_acc, s_acc = opt.apply_acc(spec, packed, slots, acc)
    np.testing.assert_allclose(
        np.asarray(t_acc), np.asarray(t_apply), rtol=1e-6, atol=1e-7
    )
    for key in s_apply:
        np.testing.assert_allclose(
            np.asarray(s_acc[key]), np.asarray(s_apply[key]),
            rtol=1e-6, atol=1e-7,
        )


def test_adam_global_bias_correction():
    """bias_correction='global' drops the per-row t slot and corrects with
    one shared apply counter (the reference Go Adam's behaviour)."""
    vocab, dim = 32, 8
    spec = PackedSpec(vocab, dim)
    rng = np.random.RandomState(5)
    table0 = rng.randn(vocab, dim).astype(np.float32)
    opt = sparse_optim.adam(0.01, bias_correction="global")
    packed = pk.pack(spec, jnp.asarray(table0))
    slots = opt.init_slots(spec, packed)
    assert "t" not in slots and "t_global" in slots

    ids = np.array([3, 3, 9], np.int32)
    grads = rng.randn(3, dim).astype(np.float32)
    packed1, slots1 = opt.apply(
        spec, packed, slots, jnp.asarray(ids), jnp.asarray(grads)
    )
    assert float(slots1["t_global"]) == 1.0
    # First apply: every touched row corrected by 1/(1-beta) exactly like
    # per-row mode's first touch, so the tables must agree on step 1.
    per_row = sparse_optim.adam(0.01, bias_correction="per_row")
    pr_packed1, _ = per_row.apply(
        spec, packed, per_row.init_slots(spec, packed),
        jnp.asarray(ids), jnp.asarray(grads),
    )
    np.testing.assert_allclose(
        np.asarray(packed1), np.asarray(pr_packed1), rtol=1e-6, atol=1e-7
    )
    # Untouched rows stay bit-identical.
    np.testing.assert_array_equal(
        np.asarray(pk.unpack(spec, packed1))[0], table0[0]
    )
    # Scatter mode agrees with stream mode under global correction too.
    sc = sparse_optim.adam(0.01, bias_correction="global", mode="scatter")
    sc_packed1, sc_slots1 = sc.apply(
        spec, packed, sc.init_slots(spec, packed),
        jnp.asarray(ids), jnp.asarray(grads),
    )
    np.testing.assert_allclose(
        np.asarray(sc_packed1), np.asarray(packed1), rtol=1e-5, atol=1e-6
    )
    assert float(sc_slots1["t_global"]) == 1.0


def test_auto_mode_picks_stream_small_scatter_large():
    spec_small = PackedSpec(1000, 8)  # num_blocks = 63
    spec_large = PackedSpec(2_000_000, 8)  # num_blocks = 125k
    n = 256
    assert not sparse_optim._use_scatter(spec_small, n, "auto")
    assert sparse_optim._use_scatter(spec_large, n, "auto")
    assert sparse_optim._use_scatter(spec_small, n, "scatter")
    assert not sparse_optim._use_scatter(spec_large, n, "stream")
    with pytest.raises(ValueError):
        sparse_optim._use_scatter(spec_small, n, "bogus")


def test_scatter_mode_under_jit_and_grad_shapes():
    """The scatter path must be jittable with static shapes (it runs
    inside the PS train step's lax.scan window)."""
    import jax

    spec = PackedSpec(64, 8)
    opt = sparse_optim.adam(0.01, mode="scatter")
    table = jnp.asarray(np.random.RandomState(3).randn(64, 8), jnp.float32)
    packed = pk.pack(spec, table)
    slots = opt.init_slots(spec, packed)

    @jax.jit
    def step(packed, slots, ids, grads):
        return opt.apply(spec, packed, slots, ids, grads)

    ids = jnp.asarray(np.array([1, 1, 5, -1, 70], np.int32))
    grads = jnp.asarray(np.random.RandomState(4).randn(5, 8), jnp.float32)
    new_packed, new_slots = step(packed, slots, ids, grads)
    assert new_packed.shape == packed.shape
    assert np.isfinite(np.asarray(new_packed)).all()
    # Row 1 stepped once (duplicates dedup), row 5 once, padding dropped.
    t = np.asarray(pk.unpack(spec, new_slots["t"]))[:, 0]
    assert t[1] == 1 and t[5] == 1 and t.sum() == 2
