"""Packed (lane-tiled) table storage: layout math and op semantics.

The packed layout is the round-2 answer to TPU tiling of narrow
[vocab, dim] tables (see parallel/packed.py docstring for the measured
motivation).  These tests pin the logical<->packed mapping and the
gather-free lookup/scatter paths against plain numpy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec


@pytest.mark.parametrize("vocab,dim", [(32, 8), (100, 4), (7, 1), (33, 5), (16, 200)])
def test_pack_unpack_roundtrip(vocab, dim):
    spec = PackedSpec(vocab, dim)
    table = np.random.RandomState(0).rand(vocab, dim).astype(np.float32)
    packed = pk.pack(spec, table)
    assert packed.shape == spec.packed_shape
    np.testing.assert_array_equal(np.asarray(pk.unpack(spec, packed)), table)


@pytest.mark.parametrize("vocab,dim", [(32, 8), (100, 4), (64, 16), (33, 5)])
def test_lookup_matches_logical_take(vocab, dim):
    spec = PackedSpec(vocab, dim)
    rng = np.random.RandomState(1)
    table = rng.rand(vocab, dim).astype(np.float32)
    ids = rng.randint(0, vocab, size=(50,)).astype(np.int32)
    out = pk.lookup(spec, pk.pack(spec, table), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)


def test_scatter_add_sums_duplicates():
    spec = PackedSpec(32, 8)
    rng = np.random.RandomState(2)
    table = rng.rand(32, 8).astype(np.float32)
    ids = np.array([3, 7, 3, 3, 0], np.int32)
    updates = rng.rand(5, 8).astype(np.float32)
    packed = pk.scatter_add(spec, pk.pack(spec, table), jnp.asarray(ids), jnp.asarray(updates))
    expected = table.copy()
    for i, u in zip(ids, updates):
        expected[i] += u
    np.testing.assert_allclose(np.asarray(pk.unpack(spec, packed)), expected, rtol=1e-5)


def test_grad_accumulate_and_touched_mask():
    spec = PackedSpec(32, 8)
    rng = np.random.RandomState(3)
    packed_like = jnp.zeros(spec.packed_shape, jnp.float32)
    ids = np.array([1, 1, 30], np.int32)
    grads = rng.rand(3, 8).astype(np.float32)
    # Make row 30's summed grad exactly zero (two cancelling occurrences).
    ids = np.array([1, 1, 30, 30], np.int32)
    grads = np.concatenate([grads, -grads[2:3]], axis=0)
    acc = pk.grad_accumulate(spec, packed_like, jnp.asarray(ids), jnp.asarray(grads))
    logical = np.asarray(pk.unpack(spec, acc))
    np.testing.assert_allclose(logical[1], grads[0] + grads[1], rtol=1e-6)
    np.testing.assert_allclose(logical[30], 0.0, atol=1e-7)
    touched = np.asarray(pk.touched_mask(spec, acc)).reshape(-1)
    assert touched[1] and not touched[30] and not touched[0]


@pytest.mark.parametrize("vocab,dim", [(32, 8), (16, 200)])
def test_scatter_add_drops_negative_ids(vocab, dim):
    """Regression: JAX scatters WRAP negative indices numpy-style (while
    dropping positive OOB), so an unmasked padding id of -1 used to add
    its grad into the LAST storage block.  Padding ids must be dropped."""
    spec = PackedSpec(vocab, dim)
    table = np.zeros((vocab, dim), np.float32)
    ids = np.array([-1, -5, vocab + 9], np.int32)
    updates = np.ones((3, dim), np.float32)
    packed = pk.scatter_add(
        spec, pk.pack(spec, table), jnp.asarray(ids), jnp.asarray(updates)
    )
    np.testing.assert_array_equal(np.asarray(pk.unpack(spec, packed)), table)
    acc = pk.grad_accumulate(
        spec, jnp.zeros(spec.packed_shape, jnp.float32), jnp.asarray(ids),
        jnp.asarray(updates),
    )
    assert not np.asarray(pk.touched_mask(spec, acc)).any()


def test_wide_rows_pass_through():
    """dim >= 128 needs no packing: R == 1, lookup is a plain row gather."""
    spec = PackedSpec(16, 200)
    assert spec.rows_per_block == 1
    assert spec.packed_shape == (16, 256)
    rng = np.random.RandomState(4)
    table = rng.rand(16, 200).astype(np.float32)
    ids = np.array([5, 3, 5], np.int32)
    out = pk.lookup(spec, pk.pack(spec, table), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)


def test_train_window_matches_sequential_steps():
    """K steps via one scanned window == K single staged steps (losses and
    final table bit-identical)."""
    import optax
    from elasticdl_tpu.parallel import MeshConfig, build_mesh, sparse_optim
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from tests.test_embedding import SparseModel, _loss

    rng = np.random.RandomState(7)
    batches = []
    for _ in range(3):
        ids = rng.randint(0, 32, size=(16, 3)).astype(np.int32)
        labels = rng.randint(0, 4, size=16).astype(np.int32)
        batches.append((ids, labels, np.ones((16,), np.float32)))

    def make():
        return ShardedEmbeddingTrainer(
            SparseModel(), _loss, optax.sgd(0.1), build_mesh(MeshConfig()),
            embedding_optimizer=sparse_optim.adam(0.01), seed=0,
        )

    t_seq = make()
    t_seq.ensure_initialized(batches[0][0])
    seq_losses = [
        float(t_seq.train_step_staged(t_seq.stage_batch(*b))) for b in batches
    ]

    t_win = make()
    t_win.ensure_initialized(batches[0][0])
    win_losses = np.asarray(t_win.train_window(t_win.stage_window(batches)))

    np.testing.assert_allclose(win_losses, seq_losses, rtol=1e-6)
    assert t_win.step == t_seq.step == 3
    sv, wv = t_seq.get_variables_numpy(), t_win.get_variables_numpy()
    for key in sv:
        np.testing.assert_allclose(wv[key], sv[key], rtol=1e-6, atol=1e-7)
