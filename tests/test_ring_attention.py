"""Ring attention (sequence/context parallelism) tests.

Golden parity: ring attention over the 8-device mesh must match plain
single-device softmax attention — full and causal — to fp tolerance,
including through the backward pass (grads flow through ppermute).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel import MeshConfig, build_mesh
from elasticdl_tpu.parallel.mesh import MODEL_AXIS
from elasticdl_tpu.parallel.ring_attention import (
    blockwise_attention,
    ring_attention,
    ring_self_attention,
)


def dense_attention(q, k, v, causal=False):
    """O(T^2)-materialized reference numerics."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tk)[None, :] > jnp.arange(tq)[:, None]
        scores = jnp.where(mask[None, None], -jnp.inf, scores)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _qkv(b=2, t=32, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((b, t, h, d)).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_dense(causal):
    q, k, v = _qkv()
    out = blockwise_attention(q, k, v, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense_on_mesh(causal):
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=4, t=64)
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5
    )


def test_ring_full_context_axis():
    """Sequence over ALL 8 devices (data=1): the deepest ring."""
    mesh = build_mesh(MeshConfig(data=1, model=8))
    q, k, v = _qkv(b=1, t=64, seed=3)
    out = ring_self_attention(mesh, q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match_dense():
    """Backward through the ring (ppermute transposes to the reverse
    rotation) must produce the same input grads as dense attention."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    from elasticdl_tpu.parallel import compile as pc
    from elasticdl_tpu.parallel.mesh import DATA_AXIS

    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=7)
    spec = P(DATA_AXIS, MODEL_AXIS, None, None)
    # check off: this jax version's replication checker rejects the
    # causal ring's lax.cond skip under transposition ("branches of
    # cond produced mismatched replication types ... pass
    # check_rep=False") — the numerics under test are unaffected.
    ring = pc.shard_map_call(
        partial(ring_attention, axis_name=MODEL_AXIS, causal=True),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    def ring_loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-4
        )


def test_ring_bf16_inputs():
    """bf16 q/k/v accumulate in f32 (flash numerics) — outputs stay
    close to the f32 dense reference."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=5, dtype=jnp.bfloat16)
    out = ring_self_attention(mesh, q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
    )


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_kv_chunking_matches_dense(causal):
    """T > kv_chunk exercises the chunked scan path; parity must hold."""
    q, k, v = _qkv(t=64, seed=11)
    out = blockwise_attention(q, k, v, causal=causal, kv_chunk=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_zigzag_layout_matches_dense(causal):
    """Balanced causal layout: shard i holds chunks (i, 2N-1-i); the
    wrapper permutes in/out, so results must equal dense attention in
    natural order."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=64, seed=13)
    out = ring_self_attention(mesh, q, k, v, causal=causal, layout="zigzag")
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_order_roundtrip():
    from elasticdl_tpu.parallel.ring_attention import (
        inverse_order,
        zigzag_order,
    )

    order = zigzag_order(32, 4)
    inv = inverse_order(order)
    np.testing.assert_array_equal(np.sort(order), np.arange(32))
    np.testing.assert_array_equal(order[inv], np.arange(32))
    # Shard 0 of 4 holds chunks 0 and 7 (of 8).
    assert list(order[:4]) == [0, 1, 2, 3]
    assert list(order[4:8]) == [28, 29, 30, 31]
    with pytest.raises(ValueError, match="chunks"):
        zigzag_order(30, 4)


def test_zigzag_gradients_match_dense():
    """Zigzag changes the differentiated graph (no cond skip, plus the
    in/out permutation gathers) — backward must still match dense."""
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, k, v = _qkv(b=2, t=32, seed=17)

    def zig_loss(q, k, v):
        out = ring_self_attention(mesh, q, k, v, causal=True,
                                  layout="zigzag")
        return jnp.sum(out ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_zig = jax.grad(zig_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_zig, g_dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-4
        )


def test_zigzag_rejects_cross_attention_lengths():
    mesh = build_mesh(MeshConfig(data=2, model=4))
    q, _, _ = _qkv(b=2, t=32, seed=1)
    k, _, _ = _qkv(b=2, t=64, seed=2)
    with pytest.raises(ValueError, match="equal q/k/v sequence lengths"):
        ring_self_attention(mesh, q, k, k, causal=True, layout="zigzag")
