"""Tensor codec tests (numpy/JAX <-> proto)."""

import numpy as np
import pytest

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.proto import elasticdl_pb2 as pb


@pytest.mark.parametrize(
    "dtype", [np.float32, np.float64, np.int32, np.int64, np.bool_, np.float16]
)
def test_roundtrip_dtypes(dtype):
    rng = np.random.default_rng(0)
    array = rng.standard_normal((3, 4)).astype(dtype)
    tensor = tensor_utils.ndarray_to_pb(array, name="w")
    out = tensor_utils.pb_to_ndarray(tensor)
    assert out.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(out, array)
    assert tensor.name == "w"


def test_bfloat16_roundtrip():
    import ml_dtypes

    array = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 4)
    out = tensor_utils.pb_to_ndarray(tensor_utils.ndarray_to_pb(array))
    assert out.dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out, array)


def test_jax_array_roundtrip():
    import jax.numpy as jnp

    array = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    out = tensor_utils.pb_to_ndarray(tensor_utils.ndarray_to_pb(array))
    np.testing.assert_allclose(out, np.asarray(array))


def test_indexed_slices_roundtrip():
    values = np.ones((2, 8), dtype=np.float32)
    indices = np.array([3, 17], dtype=np.int64)
    tensor = tensor_utils.ndarray_to_pb(values, name="emb", indices=indices)
    out_values, out_indices = tensor_utils.pb_to_indexed_slices(tensor)
    np.testing.assert_array_equal(out_values, values)
    np.testing.assert_array_equal(out_indices, indices)


def test_unsupported_dtype_raises():
    with pytest.raises(ValueError):
        tensor_utils.np_dtype_to_pb(np.complex64)
    with pytest.raises(ValueError):
        tensor_utils.pb_dtype_to_np(pb.DT_INVALID)
