"""PS-mode (sharded embedding) multi-process end-to-end test.

The table is vocab-sharded ACROSS worker processes here — this exercises
the cross-process gather in lookups, the scatter in sparse apply, and the
collective checkpoint gather, none of which single-process tests can see.
"""

import pytest

# Tier-1 fast gate runs `-m 'not slow'` (see Makefile test-fast).
pytestmark = [pytest.mark.slow, pytest.mark.e2e]

import os
import time

import numpy as np

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.master.main import start_master
from elasticdl_tpu.master.pod_manager import (
    LocalProcessManager,
    worker_argv_from_args,
)
from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous

WORKER_ENV = {
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "ELASTICDL_FORCE_PLATFORM": "cpu",
    "JAX_PLATFORMS": "cpu",
}


def test_ps_mode_kill_worker_restores_sharded_checkpoint(tmp_path):
    """The flagship elastic-restore path end to end: a 2-process PS world
    checkpoints shard-wise (shards_p0of2 + shards_p1of2), a worker is
    killed with the restart budget exhausted, and the re-formed
    1-process world restores the SAME shard files under its new sharding
    (world-size-agnostic restore) and finishes every record."""
    n_records = 1024
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        f"--training_data=synthetic://criteo?n={n_records}&vocab=100",
        "--model_params=vocab_size=100",
        "--records_per_task=128",
        "--minibatch_size=4",
        "--num_workers=2",
        "--distribution_strategy=ParameterServerStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=8",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        # Wait for real progress AND a 2-process sharded checkpoint.
        deadline = time.time() + 300
        def two_proc_ckpt():
            root = tmp_path / "ckpt"
            if not root.exists():
                return False
            return any(
                (root / d / "shards_p1of2.npz").exists()
                for d in os.listdir(root)
                if d.startswith("step_") and ".tmp" not in d
            )
        while not two_proc_ckpt():
            assert time.time() < deadline, "no 2-proc checkpoint written"
            assert not master.task_manager.finished(), "finished too fast"
            time.sleep(0.1)
        victims = manager.current_worker_ids()
        manager.kill_worker(victims[1])
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        assert master.task_manager.finished_record_count == n_records
        # The world actually shrank and trained on after restoring the
        # 2-process checkpoint into a 1-process layout.
        assert len(manager.current_worker_ids()) == 1
        logs = "".join(
            open(os.path.join(tmp_path / "logs", f)).read()
            for f in os.listdir(tmp_path / "logs")
        )
        assert "restore sharded checkpoint" in logs
    finally:
        manager.stop()
        master.stop()


def test_ps_mode_two_workers_two_devices_each(tmp_path):
    """2 processes x 2 virtual devices: tables shard across FOUR devices
    spanning process boundaries — the closest the CPU harness gets to the
    v5e multi-chip layout (VERDICT weak #4).  Exercises cross-process
    gathers with multi-device processes, per-process sharded checkpoints
    whose shard files each carry multiple device intervals, and the
    data-axis batch split within each process."""
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        "--training_data=synthetic://criteo?n=128&vocab=128",
        "--model_params=vocab_size=128",
        "--records_per_task=64",
        "--minibatch_size=8",
        "--num_workers=2",
        "--distribution_strategy=ParameterServerStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=4",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env={
            **WORKER_ENV,
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        assert manager._restarts_used == 0, (
            "2x2 PS world crashed; check worker logs"
        )
        ckpts = sorted(
            p for p in os.listdir(tmp_path / "ckpt") if p.startswith("step_")
        )
        assert ckpts
        step_dir = tmp_path / "ckpt" / ckpts[-1]
        # Each process wrote its own shard file covering ITS devices'
        # row intervals (2 per table with 2 local devices).
        files = sorted(os.listdir(step_dir))
        assert "shards_p0of2.npz" in files and "shards_p1of2.npz" in files
        npz = np.load(step_dir / "shards_p0of2.npz")
        table_entries = [k for k in npz.files if k.startswith("table|")]
        assert table_entries, "process 0 wrote no table rows"
    finally:
        manager.stop()
        master.stop()


def test_ps_mode_two_workers_trains_and_checkpoints(tmp_path):
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        "--training_data=synthetic://criteo?n=128&vocab=100",
        "--model_params=vocab_size=100",
        "--records_per_task=64",
        "--minibatch_size=8",
        "--num_workers=2",
        "--distribution_strategy=ParameterServerStrategy",
        f"--checkpoint_dir={tmp_path / 'ckpt'}",
        "--checkpoint_steps=4",
        f"--output={tmp_path / 'export'}",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        # No crash-churn: the 2-process world survived the whole job.
        assert manager._restarts_used == 0, (
            "PS-mode world crashed and re-formed; check worker logs"
        )
        ckpts = [
            p for p in os.listdir(tmp_path / "ckpt") if p.startswith("step_")
        ]
        assert ckpts, "no sharded checkpoint written"
        # PS mode checkpoints shard-wise: each of the 2 processes wrote its
        # own rows; no host-complete state pickle exists anywhere.
        step_dir = tmp_path / "ckpt" / sorted(ckpts)[-1]
        files = sorted(os.listdir(step_dir))
        assert "manifest.json" in files and "dense.pkl" in files
        assert "shards_p0of2.npz" in files and "shards_p1of2.npz" in files
        assert "state.pkl" not in files
        # Job-end export ran collectively across the 2-process world
        # (table materialization gathers rows from both processes) and
        # produced a loadable servable artifact.
        from elasticdl_tpu.serving import load_for_serving

        served = load_for_serving(str(tmp_path / "export"))
        assert len(served.signature["tables"]) >= 1
        from model_zoo.deepfm import deepfm_functional_api as zoo

        feats = {
            "dense": np.zeros((2, zoo.NUM_DENSE), np.float32),
            "cat": np.zeros((2, zoo.NUM_CAT), np.int32),
        }
        out = np.asarray(served.predict(feats))
        assert out.shape == (2,) and np.isfinite(out).all()
    finally:
        manager.stop()
        master.stop()


def test_table_shards_are_disjoint_per_device():
    """HBM-scaling contract (VERDICT round-1 weak #4): each device of the
    mesh holds ONLY its interval of a table — per-device bytes are
    total/N, nothing is replicated."""
    import numpy as np

    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer
    from model_zoo.deepfm import deepfm_functional_api as zoo

    mesh = build_mesh(MeshConfig(data=4, model=2))
    vocab = 2048  # 26 fields x 2048 = 53248 logical rows
    trainer = ShardedEmbeddingTrainer(
        zoo.custom_model(vocab_size=vocab),
        zoo.loss,
        zoo.optimizer(),
        mesh,
        embedding_optimizer=zoo.embedding_optimizer(),
    )
    rng = np.random.RandomState(0)
    features = {
        "dense": rng.rand(16, zoo.NUM_DENSE).astype(np.float32),
        "cat": rng.randint(0, vocab, size=(16, zoo.NUM_CAT)).astype(
            np.int32
        ),
    }
    trainer.ensure_initialized(features)
    n_dev = len(mesh.devices.flatten())
    checked = 0
    for path, leaf in trainer.state.tables.items():
        shards = leaf.addressable_shards
        assert len(shards) == n_dev
        per_dev = [s.data.size for s in shards]
        # Every device holds exactly 1/N of the rows — no replication.
        assert sum(per_dev) == leaf.size, (path, per_dev)
        assert max(per_dev) == leaf.size // n_dev, (path, per_dev)
        # And the shards tile the row space exactly: starts form the
        # full arithmetic progression (disjoint AND covering).
        starts = sorted(s.index[0].start or 0 for s in shards)
        rows = leaf.shape[0]
        assert starts == [i * (rows // n_dev) for i in range(n_dev)], starts
        checked += 1
    # DeepFM ships ONE merged table (linear lane 0 + fm lanes) since the
    # round-3 scatter-cost fix — see model_zoo/deepfm.
    assert checked == len(trainer.state.tables) == 1


def test_ps_mode_oov_count_reaches_master(tmp_path):
    """The aggregated OOV metric end-to-end (round-5 VERDICT weak #5):
    data drawn from a 100-id vocabulary into a model built with
    vocab_size=50 — every id >= 50 is OOV by the fixed-vocab contract —
    must be counted device-side, ride the task exec counters over gRPC,
    and land in the master's aggregate."""
    from elasticdl_tpu.common.constants import TaskExecCounterKey

    n_records = 256
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        f"--training_data=synthetic://criteo?n={n_records}&vocab=100",
        "--model_params=vocab_size=50",
        "--records_per_task=128",
        "--minibatch_size=8",
        "--num_workers=1",
        "--distribution_strategy=ParameterServerStrategy",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=1,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        counters = master.task_manager.exec_counters()
        # ~half the 26 cat ids per record draw >= 50; statistically
        # certain to be far above zero over 256 records.
        assert counters.get(TaskExecCounterKey.OOV_LOOKUP_COUNT, 0) > 100, counters
    finally:
        manager.stop()
        master.stop()


def test_ps_mode_windowed_sparse_apply_cluster(tmp_path):
    """--sparse_apply_every=4 through the REAL master/worker gRPC world:
    the headline large-table configuration's flag must round-trip
    client -> master -> worker, grow the dispatch window to a multiple
    of W (collective_worker), run the chunked apply, and finish every
    record.  Trainer-level windowed semantics are pinned in
    test_sparse_window; this is the cluster wiring."""
    n_records = 512
    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=deepfm.deepfm_functional_api",
        f"--training_data=synthetic://criteo?n={n_records}&vocab=100",
        "--model_params=vocab_size=100",
        "--records_per_task=128",
        "--minibatch_size=8",
        "--num_workers=2",
        "--distribution_strategy=ParameterServerStrategy",
        "--sparse_apply_every=4",
    ])
    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        assert manager.wait(timeout=480) is True
        assert master.task_manager.finished()
        assert master.task_manager.finished_record_count == n_records
    finally:
        manager.stop()
        master.stop()
